"""Fused high-rate processor: the north-star hot path end to end.

Per micro-batch: one bulk binary frame from the broker -> zero-copy
columnar decode (np.frombuffer) -> ONE fused device dispatch
(Bloom-validate + HLL-count, models.fused) -> columnar side-store append
-> ack. Replaces the reference's 3-RTT-per-event loop (reference
attendance_processor.py:100-136) at the other end of the batching
spectrum from AttendanceProcessor (which keeps the JSON wire format and
the generic SketchStore API).

Execution backends, selected by config:
  * single chip (num_shards * num_replicas == 1): bit-packed Bloom words
    + HLL banks resident on one device, one fused jitted dispatch per
    frame with a combined byte-packed input transfer ((4 + w) bytes per
    event: uint32 key + narrow bank id, models.fused.fused_step_bytes).
  * sharded (product > 1): the same sketches partitioned over a
    (dp, sp) jax.sharding.Mesh via parallel.ShardedSketchEngine —
    hash-range Bloom/HLL shards, AND-across-shards queries, register-max
    replica sync; the multi-chip scale-out the reference gets from
    Pulsar Shared-subscription competing consumers
    (attendance_processor.py:30-34) plus a sketch capacity no single
    Redis node would hold (BASELINE.md bench config #4).

Ack ordering under pipelining (SURVEY.md §7 hard part f): dispatches are
enqueued asynchronously so host decode of batch N+1 overlaps device
execution of batch N, but a frame is acknowledged only after its batch's
device outputs are materialized — an in-flight deque of (frame, outputs)
drains as results become ready, preserving the reference's
ack-after-commit at-least-once contract (attendance_processor.py:132).
Replays after a crash are harmless: scatter-OR/scatter-max sketches and
the read-time-dedup columnar store are all idempotent (SURVEY.md §5).

Checkpoint/resume (SURVEY.md §5): when config.snapshot_dir is set, the
pipeline restores sketch + store state on construction and snapshots
every config.snapshot_every_batches frames. Snapshots are ack BARRIERS:
a frame is acknowledged only at the first checkpoint after its outputs
commit, so every acknowledged event is durably in a snapshot — a crash
loses nothing (unacked frames redeliver; replay into idempotent sinks is
free). This replaces the reference's reliance on external-service
durability (Redis RDB / Cassandra sstables / Pulsar cursor,
attendance_processor.py:56-72,90-92 re-entrancy).
"""

from __future__ import annotations

import json
import logging
import os
import threading
import time
from collections import deque
from pathlib import Path
from typing import Dict, Optional

import jax
import numpy as np

from attendance_tpu import obs
from attendance_tpu.config import Config
from attendance_tpu.models.bloom import bloom_add_packed
from attendance_tpu.models.fused import (
    bank_wire_dtype, init_state, make_jitted_step_bytes,
    make_jitted_step_delta, make_jitted_step_seg, make_jitted_step_words,
    delta_scan, pack_bytes, pack_delta, pack_seg, pack_words,
    pick_delta_width)
from attendance_tpu.models.hll import (
    best_histogram, estimate_from_histogram)
from attendance_tpu.pipeline.codec import decode_frame
from attendance_tpu.pipeline.processor import ProcessorMetrics
from attendance_tpu.storage.columnar_store import ColumnarEventStore
from attendance_tpu.transport import (
    PoisonTracker, acknowledge_all, handle_poison, make_client)
from attendance_tpu.transport.memory_broker import ReceiveTimeout
from attendance_tpu.utils.profiling import (
    annotate_trace, maybe_annotate, maybe_trace)

logger = logging.getLogger(__name__)

_INFLIGHT_DEPTH = 8  # dispatched-but-unacked batches before forcing a sync
DEFAULT_SNAPSHOT_EVERY = 64  # barrier cadence when only snapshot_dir is set
# Canonical chunked-preload helper lives next to the scatter it feeds
# (models.bloom); re-exported here for the pipeline's callers (bench).
from attendance_tpu.models.bloom import (  # noqa: E402,F401
    PRELOAD_CHUNK, chunked_preload)

SKETCH_SNAPSHOT = "fused_sketch.npz"
EVENTS_SNAPSHOT = "fused_events.npz"
EVENTS_SEGMENTS = "fused_events_segs"
CHAIN_MANIFEST = "CHAIN.json"  # fsync'd base+delta chain manifest
_SNAP_QUEUE_DEPTH = 2  # staged delta captures in flight (double buffer)


def _verify_npz_structure(path, orig_exc) -> None:
    """Full structural read of an npz (zip-CRC verification of every
    entry, the SHARED integrity.structural_npz_check — restore and
    scrub must reach the same verdict for the same file): the fallback
    discriminator between a stale recorded digest (benign crash
    window) and real storage rot. Re-raises the original classified
    error when the file does not parse clean."""
    from attendance_tpu.utils.integrity import structural_npz_check

    if structural_npz_check(path) is not None:
        raise orig_exc from None


def read_chain_state(snap_dir, *, expect_m_bits: Optional[int] = None,
                     expect_precision: Optional[int] = None,
                     stop_before: Optional[str] = None,
                     verified: Optional[dict] = None) -> dict:
    """Merge-on-read over a snapshot directory: the base npz plus every
    CHAIN.json-listed delta, applied in order. Shared by
    :meth:`FusedPipeline.restore` and the query plane's separate-process
    chain readers (serve/chain) — one loader, one crash contract.

    Every observable state is self-consistent under the chain's write
    protocol (delta files are fsync'd before the manifest names them;
    a full base resets the manifest BEFORE deleting superseded deltas),
    so a reader racing the writer sees either the old chain or the new
    one. The one benign race — a named delta deleted by compaction
    between our manifest read and file open — surfaces as the
    ValueError below, which chain readers handle by re-reading the
    manifest and retrying.

    Integrity: every file with a CHAIN.json-recorded digest is
    verified before it is trusted; failures raise a classified
    :class:`utils.integrity.ChainIntegrityError` (kinds:
    ``digest_mismatch`` / ``missing`` / ``torn_manifest`` /
    ``unreadable``) — the input to the repair ladder (quarantine ->
    truncate -> peer re-assert -> fresh base) instead of an opaque
    numpy error or a silent wrong restore. ``stop_before`` truncates
    the applied chain just before the named delta (the repair path's
    "apply every delta before the corrupt one"). ``verified`` is an
    optional caller-owned ``{file name: digest}`` cache: deltas are
    immutable and the base is replace-only, so a (name, digest) pair
    that verified once need not be re-hashed on every reload — the
    serve-plane chain reader passes a persistent dict (without it,
    each delta publish would re-read and re-digest the whole chain,
    possibly-large base included). Each file is still verified at
    least once per (name, digest) per cache lifetime.

    Raises FileNotFoundError when no base snapshot exists."""
    from attendance_tpu.utils.integrity import (
        ChainIntegrityError, file_digest, verify_file)

    snap_dir = Path(snap_dir)
    path = snap_dir / SKETCH_SNAPSHOT
    if not path.exists():
        if (snap_dir / CHAIN_MANIFEST).exists():
            # A manifest with no base is CORRUPTION (rot/GC of the
            # base, or a crash inside a base-lost repair), not a
            # never-checkpointed directory: classify it so restore
            # enters the repair ladder (peer re-assert can rebuild)
            # instead of silently starting fresh.
            raise ChainIntegrityError(
                "missing", path,
                "chain manifest exists but the base snapshot is "
                "absent")
        raise FileNotFoundError(f"no base snapshot at {path}")
    chain: list = []
    chain_digests: dict = {}
    base_digest = ""
    chain_path = snap_dir / CHAIN_MANIFEST
    if chain_path.exists():
        try:
            chain_doc = json.loads(chain_path.read_text())
        except ValueError as exc:  # torn JSON or non-UTF8 bytes
            raise ChainIntegrityError("torn_manifest", chain_path,
                                      str(exc)) from exc
        chain = list(chain_doc.get("deltas", []))
        chain_digests = dict(chain_doc.get("digests", {}))
        base_digest = chain_doc.get("base_digest", "")
    if base_digest and not (verified is not None and verified.get(
            SKETCH_SNAPSHOT) == base_digest):
        try:
            verify_file(path, base_digest)
            if verified is not None:
                verified[SKETCH_SNAPSHOT] = base_digest
        except ChainIntegrityError as exc:
            if exc.kind != "digest_mismatch":
                raise
            # The ONE legit mismatch: a crash between the base's
            # in-place replace and the chain-manifest reset leaves
            # CHAIN.json recording the OLD base's digest (the same
            # window the chain_seq staleness fence below exists for).
            # Distinguish it from rot STRUCTURALLY — the npz zip's
            # per-entry CRCs catch bit flips and truncation — and
            # proceed when clean, RECOMPUTING the digest so restore
            # records (and the next manifest write persists) the
            # digest of the base actually on disk; carrying the stale
            # one forward would re-trip this warning on every later
            # read and downgrade real base rot to the structural
            # check forever.
            _verify_npz_structure(path, exc)
            base_digest = file_digest(path)
            if verified is not None:
                verified[SKETCH_SNAPSHOT] = base_digest
            logger.warning(
                "base snapshot digest differs from CHAIN.json but the "
                "file verifies structurally: treating as the "
                "crash-before-manifest-reset window, not rot (stale "
                "deltas are fenced by chain_seq; digest re-recorded)")
    try:
        base_npz = np.load(path)
    except Exception as exc:  # noqa: BLE001 — classify, never opaque
        raise ChainIntegrityError(
            "unreadable", path,
            f"{type(exc).__name__}: {exc}") from exc
    with base_npz as data:
        try:
            manifest = json.loads(bytes(data["manifest"]).decode())
            bits = np.array(data["bloom_words"])
            regs = np.array(data["hll_regs"], dtype=np.uint8)
            counts = np.array(data["counts"] if "counts" in data
                              else np.zeros((2, 2), np.uint32))
        except Exception as exc:  # noqa: BLE001 — legacy base rot
            raise ChainIntegrityError(
                "unreadable", path,
                f"{type(exc).__name__}: {exc}") from exc
        if (expect_m_bits is not None
                and manifest["m_bits"] != expect_m_bits):
            raise ValueError(
                f"snapshot filter is {manifest['m_bits']} bits but "
                f"config derives {expect_m_bits} — capacity/"
                "error-rate/layout changed since the snapshot")
        if (expect_precision is not None
                and manifest["precision"] != expect_precision):
            raise ValueError(
                f"snapshot HLL precision is {manifest['precision']} "
                f"but config requests {expect_precision} — "
                "register banks are not convertible across precisions")
    bank_of_raw = manifest["bank_of"]
    events = manifest["events"]
    # Staleness fence (see _write_snapshot_files): a crash between
    # a full base's in-place replace and the chain-manifest reset
    # leaves the old delta list naming files OLDER than the base —
    # every legit delta's sequence number exceeds the chain_seq
    # its base recorded. Applying a stale one would regress
    # registers and shear bank_of off the register banks. Bases
    # from before this field never coexist with a chain manifest.
    base_seq = int(manifest.get("chain_seq", -1))
    applied: list = []
    for name in chain:
        if name == stop_before:
            break  # repair truncation: chain good only up to here
        dpath = snap_dir / name
        if int(name.split("-")[1].split(".")[0]) <= base_seq:
            # Stale (older than the restored base, the crash-window
            # leftovers the chain_seq fence exists for): skipped
            # BEFORE verification — rot in a file restore would never
            # apply must not trigger a repair that truncates away the
            # newer good deltas behind it.
            continue
        if name in chain_digests:
            if not (verified is not None
                    and verified.get(name) == chain_digests[name]):
                verify_file(dpath, chain_digests[name])
                if verified is not None:
                    verified[name] = chain_digests[name]
        elif not dpath.exists():
            raise ChainIntegrityError(
                "missing", dpath,
                f"chain manifest names {name} but the delta file is "
                "absent — snapshot directory is corrupt")
        try:
            delta_npz = np.load(dpath)
        except FileNotFoundError as exc:
            # The file vanished between the (possibly cache-skipped)
            # verification and the open — the benign compaction race,
            # which chain readers retry. Classify as 'missing', never
            # 'unreadable' (that reads as permanent rot).
            raise ChainIntegrityError(
                "missing", dpath,
                "vanished between manifest read and open "
                "(compaction race, or a genuinely broken chain)"
            ) from exc
        except Exception as exc:  # noqa: BLE001 — legacy delta rot
            raise ChainIntegrityError(
                "unreadable", dpath,
                f"{type(exc).__name__}: {exc}") from exc
        with delta_npz as d:
            try:
                dman = json.loads(bytes(d["manifest"]).decode())
                d_idx = np.asarray(d["bank_idx"], np.int64)
                d_rows = np.asarray(d["regs_rows"])
                d_counts = np.array(d["counts"], np.uint32)
            except Exception as exc:  # noqa: BLE001
                raise ChainIntegrityError(
                    "unreadable", dpath,
                    f"{type(exc).__name__}: {exc}") from exc
            nb = int(dman.get("num_banks", regs.shape[0]))
            if nb > regs.shape[0]:
                grown = np.zeros((nb, regs.shape[1]), np.uint8)
                grown[:regs.shape[0]] = regs
                regs = grown
            if len(d_idx):
                if int(d_idx.max()) >= regs.shape[0]:
                    raise ValueError(
                        f"delta {name} writes bank {int(d_idx.max())}"
                        f" but the chain only restored "
                        f"{regs.shape[0]} banks — chain is corrupt")
                regs[d_idx] = d_rows
            counts = d_counts
            bank_of_raw = dman["bank_of"]
            events = dman["events"]
        applied.append(name)
    # The bank map must be consistent with the register banks it
    # routes into — a stale/hand-edited manifest that references
    # banks beyond the restored array would silently misroute
    # every PFADD for those days. Fail loudly instead.
    bank_vals = [int(b) for b in bank_of_raw.values()]
    if bank_vals:
        if len(set(bank_vals)) != len(bank_vals):
            raise ValueError(
                "snapshot manifest maps two days to one HLL bank"
                " — manifest is corrupt")
        if max(bank_vals) >= regs.shape[0]:
            raise ValueError(
                f"snapshot manifest references bank "
                f"{max(bank_vals)} but only {regs.shape[0]} "
                "register banks were restored — manifest and "
                "registers are from different snapshots")
    return dict(bits=bits, regs=regs, counts=counts,
                bank_of=bank_of_raw, events=events, applied=applied,
                manifest=manifest, base_digest=base_digest,
                digests={n: chain_digests[n] for n in applied
                         if n in chain_digests})


class _StaleBaseError(RuntimeError):
    """A staged delta failed the no-durable-base guard — pure
    bookkeeping, no disk was touched, so it must not extend the
    writer's disk-backoff meter (after an ENOSPC base failure the
    queued deltas insta-fail on this guard; charging each one a full
    capped backoff starves the hot loop into its idle timeout while a
    healthy backlog still queues)."""


class _ScatterValidity:
    """Lazy original-order view of the seg/delta wires' permuted
    validity.

    Holds the (possibly still in-flight) device vector plus the packed
    lane -> original index permutation; materializes ``out[perm] = v``
    only when a reader asks (store compaction, snapshot) — the hot loop
    never pays the scatter, and the device sync stays as lazy as the
    raw jax array the store keeps for the other wires.

    Single-chip packs put all n real lanes first (``lanes=None``); the
    sharded engine's per-replica packs leave the real lanes at each
    slice's front, so the caller passes their explicit ``lanes``
    positions (len n, aligned with ``perm``).
    """

    __slots__ = ("_valid", "_perm", "_n", "_lanes")

    def __init__(self, valid, perm, n: int, lanes=None):
        self._valid, self._perm, self._n = valid, perm, n
        self._lanes = lanes

    def __len__(self) -> int:
        return self._n

    def __array__(self, dtype=None, copy=None):
        v = np.asarray(self._valid)
        v = v[:self._n] if self._lanes is None else v[self._lanes]
        out = np.empty(self._n, v.dtype)
        out[self._perm] = v
        if dtype is not None and np.dtype(dtype) != out.dtype:
            out = out.astype(dtype)
        return out


class FusedPipeline:
    SUBSCRIPTION = "attendance_fused"

    def __init__(self, config: Optional[Config] = None, *,
                 client=None, store: Optional[ColumnarEventStore] = None,
                 num_banks: int = 256, mesh=None):
        self.config = config or Config()
        # Live telemetry (obs/): created here iff a telemetry flag is
        # set, BEFORE the transport below so broker queues register
        # their depth gauges. With the flags unset every hook in this
        # class is one `is not None` branch (profiling.py discipline).
        self._obs = obs.ensure(self.config)
        # Span tracer (obs/tracing.py): one more capture-once handle —
        # a metrics-only run holds None here and pays one branch.
        self._tracer = (self._obs.tracer if self._obs is not None
                        else None)
        if self._obs is not None:
            self._h_dequeue = self._obs.stage("dequeue_wait")
            self._h_decode = self._obs.stage("decode")
            self._h_dispatch = self._obs.stage("dispatch")
            self._h_device = self._obs.stage("device_wait")
            self._h_snap_write = self._obs.stage("snapshot_write")
            self._h_snap_blocked = self._obs.stage("snapshot_blocked")
        # Attribution plane (obs/profiler.py, ISSUE 15). Three
        # capture-once handles, each one `is not None` branch when
        # off: _stage_mark lets the sampling profiler attribute every
        # stack sample to the stage this thread is in (marked at the
        # SAME transitions the stage histograms already time),
        # _recomp is the jitted-dispatch shape-fingerprint tracker
        # (recompile storms from unpadded shapes were invisible), and
        # the dispatch-gap histogram records device idle between
        # consecutive dispatch enqueues — the honest "device outruns
        # transport" number.
        self._recomp = (self._obs.recompiles if self._obs is not None
                        else None)
        prof = (self._obs.profiler if self._obs is not None else None)
        self._stage_mark = prof.stages if prof is not None else None
        self._h_gap = None
        self._last_dispatch_t = 0.0
        # Dispatch-thread occupancy split (ISSUE 14 carried item,
        # measured instead of guessed): wall seconds this thread spent
        # in decode / device dispatch / the temporal host passes /
        # blocked on device results, since the current run() started.
        # Exported as attendance_dispatch_thread_busy_fraction
        # callback gauges — scrape-time division, zero hot-loop cost
        # beyond the accumulations process_frame already times.
        self._busy = {"decode": 0.0, "device_dispatch": 0.0,
                      "temporal": 0.0, "device_wait": 0.0}
        self._busy_anchor = time.perf_counter()
        self._last_dequeue_s = 0.0  # run-loop receive wait, per batch
        self._dw_accum = 0.0  # device_wait since the last flight rec
        self._c_xfer: Dict[tuple, object] = {}
        if self._obs is not None:
            self._h_gap = self._obs.registry.histogram(
                "attendance_dispatch_gap_seconds",
                help="Host-side gap between consecutive device "
                "dispatch enqueues (device idle opportunity: the "
                "transport/host side is what fills it)")
            import weakref
            ref = weakref.ref(self)

            def _busy_reader(component: str):
                def read() -> float:
                    pipe = ref()
                    if pipe is None:
                        return float("nan")
                    wall = time.perf_counter() - pipe._busy_anchor
                    return (pipe._busy[component] / wall
                            if wall > 0 else 0.0)
                return read

            components = ("decode", "device_dispatch", "device_wait")
            if getattr(self.config, "temporal_period_s", 0.0) > 0:
                components += ("temporal",)
            for component in components:
                self._obs.registry.gauge(
                    "attendance_dispatch_thread_busy_fraction",
                    help="Dispatch-thread occupancy split since the "
                    "current run started (the measurement behind the "
                    "lane-style temporal-worker decision)",
                    component=component).set_function(
                        _busy_reader(component))
        self._last_wire = ""
        # Fault plane (chaos/): install the injector BEFORE transport
        # and store construction so both seams pick it up; None (the
        # default) keeps every hook at one branch.
        from attendance_tpu import chaos
        self._chaos = chaos.ensure(self.config)
        # Metrics exist before the transport: the classic consumer's
        # chunk-decode wrapper settles poison payloads itself and must
        # count them into THIS pipeline's nack/dead-letter totals.
        self.metrics = ProcessorMetrics()
        self.client = client or make_client(self.config)
        if getattr(self.config, "ingress_lanes", 0) > 0:
            # Striped ingress plane (pipeline.lanes): N lane sessions
            # + bridge workers behind the one-consumer call shape this
            # run loop speaks; acks (incl. the snapshot writer's group
            # commits) route back to each owning lane's session. With
            # --ingress-wire=shm the client IS the shm ring client
            # (make_client), so each lane maps its own ring file.
            from attendance_tpu.pipeline.lanes import StripedConsumer
            self.consumer = StripedConsumer(
                self.config, self.client, self.config.pulsar_topic,
                self.SUBSCRIPTION, obs=self._obs)
        else:
            self.consumer = self.client.subscribe(
                self.config.pulsar_topic, self.SUBSCRIPTION)
            if (getattr(self.config, "json_chunk_decode", True)
                    and getattr(self.config, "ingress_wire",
                                "auto") != "shm"
                    and hasattr(self.consumer, "receive_many_raw")):
                # Classic-consumer chunk decode (ISSUE 11 satellite):
                # per-event JSON wires coalesce into one batched
                # decode + one device dispatch per chunk instead of
                # one per message; bulk binary frames pass through
                # byte-identically (shm skips the wrapper — its slots
                # are always planar frames already).
                from attendance_tpu.pipeline.lanes import (
                    JsonChunkConsumer)
                self.consumer = JsonChunkConsumer(
                    self.consumer, self.config, obs=self._obs,
                    metrics=self.metrics)
        from attendance_tpu.storage import wrap_store
        self.store = wrap_store(store or ColumnarEventStore(),
                                self.config, sink="columnar")
        # Poison retries bounded by the frame's OWN failure count, not
        # the broker redelivery count (which reconnect/takeover
        # requeues inflate for healthy frames).
        self._poison = PoisonTracker()
        self.sharded = (self.config.num_shards
                        * self.config.num_replicas) > 1
        if self.sharded:
            if self.config.wire_format == "bytes":
                logger.warning(
                    "--wire-format=bytes has no effect with num_shards/"
                    "num_replicas > 1: the sharded engine carries wide "
                    "frames as separate key/bank arrays instead")
            from attendance_tpu.parallel.multihost import (
                init_distributed, make_multihost_mesh)
            from attendance_tpu.parallel.sharded import ShardedSketchEngine
            if mesh is None:
                init_distributed()  # no-op outside a cluster environment
                mesh = make_multihost_mesh(self.config.num_shards,
                                           self.config.num_replicas)
            self.engine = ShardedSketchEngine(
                mesh,
                capacity=self.config.bloom_filter_capacity,
                error_rate=self.config.bloom_filter_error_rate,
                num_banks=num_banks,
                precision=self.config.hll_precision,
                layout="blocked",
                replica_sync=self.config.replica_sync)
            self.params = self.engine.params
        else:
            self.engine = None
            self.state, self.params = init_state(
                capacity=self.config.bloom_filter_capacity,
                error_rate=self.config.bloom_filter_error_rate,
                # The fused packed step requires the blocked layout (one
                # 512-bit block per key); a "flat" request is honored by
                # the generic TpuSketchStore path, not here.
                layout="blocked",
                num_banks=num_banks,
                precision=self.config.hll_precision)
            self._bank_dtype = bank_wire_dtype(num_banks)
            self._step = make_jitted_step_bytes(
                self.params, np.dtype(self._bank_dtype).itemsize,
                self.config.hll_precision)
            # Word-packed (4-byte/event) step programs, one per key
            # width; _kw_hint grows monotonically so a stable key
            # population compiles at most a couple of widths.
            self._word_steps: Dict[int, object] = {}
            # Segmented bit-packed (kb bits/event) step programs, one
            # per (key width, padded shape, bank count).
            self._seg_steps: Dict[tuple, object] = {}
            # Delta-coded (db bits/event) step programs. The delta
            # width is data-dependent (the frame's widest sorted-key
            # gap), so _db_hint grows monotonically and widths round up
            # to even values — a stable population compiles a couple of
            # programs, not one per frame.
            self._delta_steps: Dict[tuple, object] = {}
            self._preload = jax.jit(
                lambda bits, keys: bloom_add_packed(bits, keys,
                                                    self.params),
                donate_argnums=(0,))
        # Native host runtime (fused decode+LUT+pack pass), shared by
        # BOTH engines — the mesh's per-replica seg/delta packs run the
        # same native passes as the single-chip wires; None falls back
        # to the numpy path transparently. _native_skip adaptively
        # bypasses doomed native attempts when the stream steadily
        # contains days the dense LUT cannot cover (see
        # _dispatch_single / _dispatch_sharded_narrow).
        from attendance_tpu.native import load as load_native
        self._native = load_native()
        self._native_skip = 0
        # Wire-selection state shared by BOTH engines (the mesh rides
        # the same ladder and width hints as the single chip):
        # monotonic key-width hint (bounds compile churn), delta-width
        # hint with outlier decay (every extra bit is link bytes), and
        # the adaptive ladder for auto mode (see _auto_wire):
        # 0 = word (cheapest host pack), 1 = seg, 2 = delta (narrowest
        # link). Which resource binds depends on the moment's link
        # rate vs host contention, so auto adapts per frame from
        # observed backpressure instead of committing to either.
        self._kw_hint = 1
        self._db_hint = 1
        self._db_slack = 0
        self._db_seen = 1
        self._auto_level = 0
        self._auto_pressure = 0
        self._drain_waited = False
        # One-time notice when a FORCED word wire cannot be honored
        # (key+bank bits exceed a word) and frames degrade to the
        # bytes wire — without it only wire_dwell reveals the switch.
        self._warned_word_degrade = False
        self._profiling = bool(self.config.profile_dir)
        # Bank allocation: days AND temporal buckets share one map and
        # one register array. The allocator is a monotonic counter
        # plus a free list — the temporal ring's evictions recycle
        # bank rows, so "next bank = len(map)" stopped being sound.
        self._bank_of: Dict[int, int] = {}
        self._next_bank = 0
        self._free_banks: list = []
        # Dense day->bank lookup: maps days in [base, base + LUT) with one
        # O(n) fancy-index instead of an O(n log n) np.unique per batch.
        self._day_base: Optional[int] = None
        self._day_lut = np.full(self._LUT_SIZE, -1, np.int32)
        self._inflight = deque()
        # Snapshot/checkpoint wiring (dir empty = disabled). A set dir
        # with no interval still checkpoints (at a default cadence):
        # restoring on start but never snapshotting again would lose
        # every event acked after the restored snapshot on the next
        # crash.
        self._snap_dir = (Path(self.config.snapshot_dir)
                          if self.config.snapshot_dir else None)
        self._snap_every = (self.config.snapshot_every_batches
                            if self.config.snapshot_every_batches > 0
                            else DEFAULT_SNAPSHOT_EVERY)
        self._batches_at_snap = 0
        # Host copy of the packed Bloom words for the snapshot path:
        # the hot loop never writes the filter (the reference's loop
        # never BF.ADDs either — only the generator preloads), so one
        # read after the last preload serves every later snapshot
        # instead of a per-snapshot D2H of the whole filter.
        self._bloom_host: Optional[np.ndarray] = None
        # Incremental (delta) snapshot state — see _checkpoint_async.
        # _dirty_days is fed by the hot loop (one cheap pass per frame,
        # only when delta checkpointing is on) and drained at barriers
        # into the dirty-bank capture; the chain bookkeeping below is
        # owned by the background writer (serialized by its queue).
        self._snap_mode = getattr(self.config, "snapshot_mode", "delta")
        self._snap_compact_every = max(
            1, getattr(self.config, "snapshot_compact_every", 16))
        self._snap_dirty = (self._snap_dir is not None
                            and self._snap_mode == "delta")
        self._dirty_days: set = set()
        self._base_stale = True     # no durable base for this run yet
        self._writer_base_ok = False
        self._snap_chain: list = []  # delta files since the base
        self._delta_seq = 0
        # Integrity plane (utils/integrity): payload digests recorded
        # in CHAIN.json per durable file, verified before restore /
        # the chain readers trust them. integrity=False skips digest
        # computation at the writer (the bench's integrity-off
        # baseline); verification always runs when digests exist.
        self._integrity = bool(getattr(self.config, "integrity", True))
        self._snap_digests: Dict[str, str] = {}  # delta name -> sha256
        self._base_digest = ""
        self._regs_mirror: Optional[np.ndarray] = None
        self._snap_take = None  # jitted dirty-row capture (lazy)
        # Async snapshot writer (the BGSAVE analogue): ONE persistent
        # thread draining a bounded staging queue — two captures may be
        # in flight (double buffering: the loop swaps into the second
        # staging slot while the writer drains the first), each acked
        # only once ITS delta/base is durable (group commit per
        # barrier interval).
        self._snap_jobs: deque = deque()
        self._snap_cv = threading.Condition()
        self._snap_pending = 0
        # Consecutive background-write failures: drives the bounded
        # inter-attempt backoff (_writer_backoff_s) so a persistently
        # failing snapshot disk retries at a bounded cadence instead
        # of spinning the writer hot, plus the failure counter's SLO
        # hook (--slo snapshot_failures<=N).
        self._snap_fail_streak = 0
        self._snap_thread: Optional[threading.Thread] = None
        self._snap_io_lock = threading.Lock()
        self._snap_copy = None
        self._g_delta_bytes = self._g_chain_len = None
        if self._obs is not None and self._snap_dir is not None:
            self._g_delta_bytes = self._obs.registry.gauge(
                "attendance_snapshot_delta_bytes",
                help="Bytes of the last incremental snapshot delta")
            self._g_chain_len = self._obs.registry.gauge(
                "attendance_snapshot_chain_length",
                help="Delta files since the last full base snapshot")
        # Epoch-pinned read mirror (serve/): the snapshot plane's host
        # register state published as immutable epochs — the query
        # plane and the scrape-time health/audit gauges read from a
        # pinned epoch instead of racing the hot loop's donated device
        # arrays. Publication rides the paths that already hold host
        # copies (preload, restore, snapshot barriers), so the hot
        # loop itself never pays for it.
        from attendance_tpu.serve.mirror import ReadMirror
        self.read_mirror = ReadMirror()
        self._roster_size = 0
        self.query_server = None
        self.query_engine = None
        if self._obs is not None:
            self.read_mirror.register_gauges(self._obs)
        # Federation fence gossip (attendance_tpu/federation): when
        # this pipeline is a federated worker, every snapshot fence
        # publishes its dirty-bank delta (and full frames at preload/
        # restore/base) as CRDT merge frames. Constructed BEFORE
        # restore() so a takeover worker's restored chain reaches the
        # aggregator immediately. On a multi-process mesh only process
        # 0 gossips (it holds the replicated state the barriers write).
        self._fed = None
        self._events_restored = 0
        if getattr(self.config, "fed_worker", "") and \
                jax.process_index() == 0:
            from attendance_tpu.federation.gossip import FenceGossip
            self._fed = FenceGossip(
                self.config, client=self.client,
                m_bits=self.params.m_bits, k=self.params.k,
                obs=self._obs).start_heartbeat()
        # Temporal sketch plane (attendance_tpu/temporal): windowed
        # HLL bucket ring + watermarked reorder + CMS fraud kernel.
        # Buckets are ordinary bank_of entries (synthetic keys), so
        # the delta chain / epoch mirror / federation frames below
        # carry them with no new machinery. Constructed BEFORE
        # restore() so a restored chain re-seeds the ring. One
        # `is not None` branch on the hot path when off.
        self._temporal = None
        if getattr(self.config, "temporal_period_s", 0.0) > 0:
            from attendance_tpu.temporal.plane import TemporalPlane
            self._temporal = TemporalPlane(
                self.config,
                alloc_bank=self._register_temporal_bucket,
                free_buckets=self._free_temporal_buckets,
                mark_dirty=self._mark_temporal_dirty,
                dispatch_add=self._temporal_dispatch,
                obs=self._obs)
            self._t_add = None  # lazy jit (needs params at trace)
            self._t_clear = None
        if self._snap_dir is not None:
            self.restore()
        # Accuracy auditor (obs/audit.py): the hot loop only RECORDS
        # sampled shadow truth (one vectorized hash + a small set
        # update per frame); the measured gauges are scrape-time
        # callbacks that re-query the live filter — one branch per
        # frame when auditing is off.
        self._auditor = (self._obs.auditor if self._obs is not None
                         else None)
        if self._obs is not None:
            # Sketch-health gauges: lazy callbacks — device reads
            # (fill popcount, register histograms) happen only when a
            # scrape renders the registry, never on the hot path.
            from attendance_tpu.obs import health
            health.register_fused(self._obs, self)
            if self._auditor is not None:
                from attendance_tpu.obs.audit import register_fused_audit
                register_fused_audit(self._obs, self)
        serve_port = getattr(self.config, "serve_port", 0)
        if serve_port:
            # In-process query plane (serve/): a vectorized executor
            # over the read mirror behind a binary batch RPC port,
            # plus JSON routes on the live /metrics endpoint. Queries
            # never touch the device or the hot loop — they answer
            # from whatever epoch the barriers last published.
            from attendance_tpu.serve.engine import QueryEngine
            from attendance_tpu.serve.rpc import QueryServer
            ceiling = getattr(self.config,
                              "read_staleness_ceiling_s", 0.0)
            self.query_engine = QueryEngine(
                self.read_mirror, obs=self._obs,
                batch_max=getattr(self.config, "query_batch_max",
                                  1 << 16),
                staleness_ceiling_s=ceiling or None)
            self.query_server = QueryServer(
                self.query_engine,
                port=0 if serve_port < 0 else serve_port).start()
            if (self._obs is not None
                    and getattr(self._obs, "_server", None) is not None):
                from attendance_tpu.serve import http as serve_http
                serve_http.attach(self._obs._server, self.query_engine)
        # Control-plane knobs (attendance_tpu/control). The attributes
        # exist unconditionally — the hot path branches on them whether
        # or not a controller is attached; without one they are the
        # configured constants. `_audit_every` widens the audit shadow's
        # frame interval under ladder rung >= 1; `_temporal_paused`
        # gates the temporal host pass under rung >= 3.
        self._audit_every = 1
        self._temporal_paused = False
        self._admission = None
        self._admission_retire: list = []
        self._control = (getattr(self._obs, "control", None)
                         if self._obs is not None else None)
        if self._control is not None:
            self._control.attach(self)
            self._admission = self._control.admission

    _LUT_SIZE = 1 << 14  # covers ~44 years of calendar days from base
    _TRACE_ROLE = "fused-pipeline"

    # -- roster -------------------------------------------------------------
    def preload(self, keys) -> None:
        keys = np.asarray(keys, dtype=np.uint32)
        self._count_xfer("preload", "h2d", keys.nbytes)
        self._bloom_host = None  # invalidate the snapshot-path cache
        # The filter changed: any existing base snapshot no longer
        # covers it, so the next barrier must write a fresh full base
        # before deltas (which never carry Bloom words) may chain on.
        self._base_stale = True
        if self.sharded:
            self.engine.preload(keys)
        else:
            self.state = self.state._replace(bloom_bits=chunked_preload(
                self._preload, self.state.bloom_bits, keys))
        if self._auditor is not None:
            # The roster IS the filter's full membership (the hot loop
            # never BF.ADDs): its sampled subset is the shadow's
            # ground truth for both the false-negative probe and the
            # measured-FPR negative classification. Recorded strictly
            # AFTER the device preload: the FN probe re-queries the
            # live filter from the scrape thread, and shadowing keys
            # the filter does not hold yet reads the whole roster as
            # false negatives (seen under chaos-soak timing).
            self._auditor.record_roster(keys)
        if self._temporal is not None:
            # The window shadow classifies validity by roster
            # membership — same after-the-preload ordering note.
            self._temporal.record_roster(keys)
        self._roster_size = len(keys)
        if not self.sharded and (self.checkpointing
                                 or self.query_engine is not None
                                 or self._fed is not None):
            # Seed the first read epoch (and the snapshot path's host
            # filter cache) from the freshly preloaded state. Gated:
            # plain ingest runs must not pay a D2H here — on the
            # relay-tunneled platform one read of the donated-chain
            # state flips the process into a degraded dispatch mode
            # (see run()'s D2H note), so only runs that will read
            # host-side anyway (barriers, queries, gossip) take it,
            # pre-run where it is cheapest. The sharded engine
            # publishes its first epoch at the first barrier instead
            # (its state gather contains collectives).
            self._bloom_host = np.asarray(self.state.bloom_bits)
            self._publish_epoch(np.asarray(self.state.hll_regs),
                                np.asarray(self.state.counts),
                                bank_of=dict(self._bank_of))
            if self._fed is not None:
                # The preloaded filter must reach the aggregator
                # before any delta (deltas never carry Bloom words):
                # the federation's zero-false-negative story is the OR
                # of every shard's preload frame.
                self._fed.publish_full(
                    self._bloom_host, np.asarray(self.state.hll_regs),
                    np.asarray(self.state.counts),
                    dict(self._bank_of), self._events_total,
                    roster_size=self._roster_size)

    @property
    def _events_total(self) -> int:
        """Cumulative events INCLUDING a restored chain's total — what
        every durable manifest, read epoch, and gossip frame stamps.
        ``metrics.events`` alone restarts at 0 across a restore, which
        would make post-restore deltas look STALE (events <= the
        base's) to the chain loader's crash-window skip and regress
        recovered views on a second failover."""
        return self._events_restored + self.metrics.events

    # -- bank mapping -------------------------------------------------------
    def _num_banks(self) -> int:
        return (self.engine.num_banks if self.sharded
                else self.state.hll_regs.shape[0])

    def _grow_banks(self) -> None:
        if self.sharded:
            self.engine.grow_banks(self.engine.num_banks * 2)
            return
        regs = self.state.hll_regs
        grown = jax.numpy.zeros(
            (regs.shape[0] * 2, regs.shape[1]), regs.dtype)
        self.state = self.state._replace(
            hll_regs=grown.at[:regs.shape[0]].set(regs))
        new_dtype = bank_wire_dtype(regs.shape[0] * 2)
        if new_dtype is not self._bank_dtype:
            # Wire dtype widens past the sentinel limit: new step program.
            self._bank_dtype = new_dtype
            self._step = make_jitted_step_bytes(
                self.params, np.dtype(new_dtype).itemsize,
                self.config.hll_precision)

    def _alloc_bank(self) -> int:
        """Next free HLL bank row: the free list (rows recycled by
        temporal-ring evictions) first, else the monotonic counter,
        growing the register array on demand."""
        if self._free_banks:
            return self._free_banks.pop()
        bank = self._next_bank
        while bank >= self._num_banks():
            # Double the bank array (rare; one recompile per size).
            self._grow_banks()
        self._next_bank = bank + 1
        return bank

    def _register_day(self, day: int) -> int:
        bank = self._bank_of.get(day)
        if bank is not None:
            return bank
        bank = self._alloc_bank()
        self._bank_of[day] = bank
        if self._day_base is not None:
            off = day - self._day_base
            if 0 <= off < self._LUT_SIZE:
                self._day_lut[off] = bank
        return bank

    def _rebuild_lut(self, base: int) -> None:
        self._day_base = base
        self._day_lut.fill(-1)
        for day, bank in self._bank_of.items():
            off = day - base
            if 0 <= off < self._LUT_SIZE:
                self._day_lut[off] = bank

    def _banks_for(self, lecture_days: np.ndarray) -> np.ndarray:
        """Vectorized day->bank through the dense LUT.

        Hot path (every steady-state frame: all days already registered
        and inside the window): ONE uint32 subtract, a min/max guard,
        one np.take, one >=0 check — ~4 passes over int32 data, no
        boolean masking temporaries. The general path (new or
        out-of-window days — rare, calendar days are few and clustered)
        registers the missing days and re-resolves only the missed
        lanes."""
        days_u32 = np.ascontiguousarray(lecture_days, dtype=np.uint32)
        if self._day_base is None:
            self._rebuild_lut(int(days_u32.min()))
        # uint32 wraparound keeps day<base negative after the int32
        # reinterpret (calendar deltas never approach 2^31).
        off = (days_u32 - np.uint32(self._day_base)).view(np.int32)
        mn, mx = int(off.min()), int(off.max())
        if 0 <= mn and mx < self._LUT_SIZE:
            banks = np.take(self._day_lut, off)
            if banks.min() >= 0:
                return banks
        return self._banks_for_slow(days_u32.astype(np.int64))

    def _banks_for_slow(self, days: np.ndarray) -> np.ndarray:
        if int(days.min()) < self._day_base:
            self._rebuild_lut(int(days.min()))
        off = days - self._day_base
        in_range = (off >= 0) & (off < self._LUT_SIZE)
        banks = np.where(in_range,
                         self._day_lut[np.where(in_range, off, 0)], -1)
        misses = banks < 0
        if misses.any():
            for day in np.unique(days[misses]).tolist():
                self._register_day(int(day))
            # re-resolve only the missed lanes
            moff = days[misses] - self._day_base
            mok = (moff >= 0) & (moff < self._LUT_SIZE)
            fixed = np.where(mok, self._day_lut[np.where(mok, moff, 0)], -1)
            still = fixed < 0
            if still.any():  # outside the LUT window: scalar map
                vals = days[misses][still]
                fixed[still] = [self._bank_of[int(d)]
                                for d in vals.tolist()]
            banks[misses] = fixed
        return banks.astype(np.int32, copy=False)

    # -- temporal plane hooks ------------------------------------------------
    def _register_temporal_bucket(self, key: int) -> int:
        """Allocate one bank row for a temporal bucket key (the
        BucketRing's alloc callback). Rides the same allocator as
        days; the plane marks the key dirty on every frame that
        touches it, so recycled rows re-persist through the chain."""
        bank = self._alloc_bank()
        self._bank_of[key] = bank
        return bank

    def _free_temporal_buckets(self, keys, banks) -> None:
        """Evict rotated buckets: drop their keys from the bank map,
        zero the device rows, and recycle the rows via the free list
        (the BucketRing's eviction callback)."""
        for key in keys:
            self._bank_of.pop(key, None)
            self._dirty_days.discard(key)
        if self.sharded or not banks:
            return
        regs = self.state.hll_regs
        if self._t_clear is None:
            self._t_clear = jax.jit(
                lambda r, idx: r.at[idx].set(jax.numpy.uint8(0),
                                             mode="drop"),
                donate_argnums=(0,))
        padded = 8
        while padded < len(banks):
            padded *= 2
        idx = np.full(padded, regs.shape[0], np.int32)  # OOB = no-op
        idx[:len(banks)] = banks
        self.state = self.state._replace(
            hll_regs=self._t_clear(regs, jax.numpy.asarray(idx)))
        self._free_banks.extend(int(b) for b in banks)

    def _mark_temporal_dirty(self, keys) -> None:
        if self._snap_dirty:
            self._dirty_days.update(keys)

    def _temporal_dispatch(self, keys: np.ndarray,
                           banks: np.ndarray) -> None:
        """One fused Bloom-probe + windowed hll_add dispatch into the
        SHARED register array (bank -1 lanes drop). Joins the device
        queue after the frame's main step, so the barrier capture of
        dirty bucket rows orders after it — the PR 4 ack contract
        extends to window contributions for free."""
        if self._t_add is None:
            from attendance_tpu.models.bloom import (
                bloom_contains_words)
            from attendance_tpu.models.hll import hll_add
            params = self.params
            prec = self.config.hll_precision

            def _add(regs, words, ks, bs):
                valid = bloom_contains_words(words, ks, params)
                return hll_add(regs,
                               jax.numpy.where(valid, bs, -1), ks,
                               precision=prec)

            self._t_add = jax.jit(_add, donate_argnums=(0,))
        n = len(keys)
        padded = 256
        while padded < n:
            padded *= 2
        kbuf = np.zeros(padded, np.uint32)
        kbuf[:n] = keys
        bbuf = np.full(padded, -1, np.int32)
        bbuf[:n] = banks
        self._note_compile("temporal_window_add", padded)
        self.state = self.state._replace(hll_regs=self._t_add(
            self.state.hll_regs, self.state.bloom_bits,
            jax.numpy.asarray(kbuf), jax.numpy.asarray(bbuf)))

    def temporal_stats(self) -> Optional[Dict]:
        """The temporal plane's live counters (None when off)."""
        return (self._temporal.stats() if self._temporal is not None
                else None)

    def window_counts(self) -> Dict[int, int]:
        """PFCOUNT of every live temporal bucket in ONE device pass:
        {bucket key: unique-valid-student estimate} — the write-side
        twin of the query plane's window verbs (tests/soaks compare
        the two)."""
        from attendance_tpu.temporal.buckets import is_bucket_key
        keys = {k: b for k, b in self._bank_of.items()
                if is_bucket_key(k)}
        if not keys:
            return {}
        if self.sharded:
            ests = self.engine.count_all()
            return {k: int(ests[b]) for k, b in keys.items()}
        hists = np.asarray(best_histogram(self.state.hll_regs,
                                          self.config.hll_precision))
        return {k: int(round(estimate_from_histogram(
            hists[b], self.config.hll_precision)))
            for k, b in keys.items()}

    # -- hot loop -----------------------------------------------------------
    def process_frame(self, data: bytes):
        """Dispatch one bulk binary frame; returns the async validity."""
        obs_t = self._obs
        st = self._stage_mark
        if st is not None:
            st.set("decode")
        t0 = time.perf_counter()
        # Skip the embedded ground-truth column: validity is recomputed
        # on device and the store gets the computed vector. The codec
        # seam sniffs the wire (binary frames keep the exact zero-copy
        # decode; JSON payloads arrive via the json codec), so new
        # wires slot in as codecs, not hot-loop branches.
        cols = decode_frame(data, include_truth=False)
        t_dec = time.perf_counter() if obs_t is not None else 0.0
        if st is not None:
            st.set("dispatch")
        n = len(cols["student_id"])
        if n == 0:
            return None
        if obs_t is not None and self._last_dispatch_t:
            # Gap since the previous dispatch ENQUEUE completed: the
            # window the device could have been starving in. Host-side
            # by necessity, but dispatches are async (the device runs
            # behind the queue), so queue-feed gaps ARE the ceiling.
            # After the empty-frame return: an n == 0 frame dispatches
            # nothing, and observing its arrival would double-count
            # the same idle window against the next real frame.
            self._h_gap.observe(max(t_dec - self._last_dispatch_t,
                                    0.0))
        if self._snap_dirty:
            # Delta checkpointing: note which lecture days this frame
            # touches (barriers map them to dirty HLL banks). One
            # bincount-class pass, wire-agnostic — it sees the days
            # BEFORE dispatch, so even native packs that never
            # materialize a host bank array are covered.
            self._note_dirty(cols["lecture_day"])
        if self._auditor is not None and (
                self._audit_every <= 1
                or self.metrics.batches % self._audit_every == 0):
            # Shadow recording only — no device read, no sync; the
            # sampled ~1% of lanes feed the scrape-time measured
            # FPR / HLL-error callbacks (obs/audit.register_fused_audit).
            # Under degradation-ladder rung >= 1 the controller widens
            # `_audit_every` so the shadow thins to every Nth frame —
            # the measured gauges stay live, just over a sparser sample.
            self._auditor.observe_fused_frame(cols["student_id"],
                                              cols["lecture_day"])
        if self.sharded:
            sid = cols["student_id"]
            banks = self._banks_for(cols["lecture_day"])
            num_banks = self.engine.num_banks
            wire = self.config.wire_format
            if wire == "auto":
                # Same adaptive ladder as the single-chip path: the
                # backpressure signal (hot loop blocked on a full
                # in-flight deque) is wire-agnostic, and the mesh's
                # narrow wires trade host pack time for link bytes
                # exactly like the single-chip ones.
                wire = self._auto_wire()
            if wire in ("seg", "delta"):
                with maybe_annotate(self._profiling,
                                    "sharded_narrow_step"):
                    valid_n, lanes, orig = self._dispatch_sharded_narrow(
                        sid, banks, cols["lecture_day"], n, wire)
                # valid_n is in packed per-slice order; the lazy view
                # restores original order at read time (same contract
                # as the single-chip narrow wires below).
                stored = _ScatterValidity(valid_n, orig, n, lanes=lanes)
            else:
                kw = self._pick_kw(int(sid.max()).bit_length(), num_banks)
                with maybe_annotate(self._profiling, "sharded_fused_step"):
                    if kw + num_banks.bit_length() <= 32:
                        # Packed word wire onto the mesh: 4 B/event per
                        # chip instead of the 9 of keys + bank ids + mask.
                        self._kw_hint = kw
                        self._count_wire("word")
                        words = pack_words(sid, banks, kw,
                                           self.engine.padded_size(n))
                        self._note_compile("sharded_step_words", kw,
                                           len(words))
                        valid_n = self.engine.step_words(words, n, kw)
                    else:
                        # Separate key/bank/mask arrays (9 B/event).
                        self._note_word_degrade()
                        self._count_wire("arrays")
                        self._note_compile("sharded_step_arrays",
                                           self.engine.padded_size(n))
                        valid_n = self.engine.step(sid, banks)
                stored = valid_n
        else:
            padded = 256
            while padded < n:
                padded *= 2
            with maybe_annotate(self._profiling, "fused_step_dispatch"):
                valid, perm = self._dispatch_single(cols, n, padded)
            valid_n = valid[:n]
            # Segmented wire: the device answered in bank-sorted order.
            # Rows are stored in ORIGINAL order with a lazy validity
            # view that scatters the permuted vector back at read time —
            # compaction is off the hot path, and a per-frame host
            # gather of every column here measurably erases the narrow
            # wire's win. The jax slice (not the wrapper) is what flows
            # back to the ack chain, which probes .is_ready() on it.
            stored = (valid_n if perm is None
                      else _ScatterValidity(valid, perm, n))
        if isinstance(data, memoryview):
            # shm-ring frames: the slot recycles once its frame is
            # acked, but the append-only store references inserted
            # arrays forever — the stored columns must own their
            # bytes. (Decode and the device dispatch above consumed
            # the zero-copy views; this copies only the narrow stored
            # columns, off the wire's critical path.)
            cols = {k: np.array(v) for k, v in cols.items()}
        t_tmp = 0.0
        if self._temporal is not None and not self._temporal_paused:
            # Temporal sidecar: windowed adds dispatch with this
            # frame (order-free scatter-max, same ack barrier); the
            # reorder stage feeds the order-sensitive consumers.
            # Timed separately when telemetry is on — the dispatch-
            # thread busy-fraction gauge splits device dispatch from
            # these host passes (the lane-worker decision's number).
            if obs_t is None:
                self._temporal.observe_frame(cols)
            else:
                if st is not None:
                    st.set("temporal")
                t_tmp0 = time.perf_counter()
                self._temporal.observe_frame(cols)
                t_tmp = time.perf_counter() - t_tmp0
                if st is not None:
                    st.set("dispatch")
        self.store.insert_columns({**cols, "is_valid": stored})
        self.metrics.batches += 1
        self.metrics.events += n
        self.metrics.batch_sizes.append(n)
        t_end = time.perf_counter()
        self.metrics.device_seconds += t_end - t0
        if obs_t is not None:
            self._last_dispatch_t = t_end
            # Occupancy split feeding the busy-fraction gauges: the
            # temporal host passes are carved OUT of the dispatch
            # phase they currently ride inside.
            self._busy["decode"] += t_dec - t0
            self._busy["temporal"] += t_tmp
            self._busy["device_dispatch"] += (t_end - t_dec) - t_tmp
            obs_t.events.inc(n)
            obs_t.frames.inc()
            trace_hex = ""
            tr = self._tracer
            if tr is not None:
                # The batch span _run_loop activated; process_frame
                # called directly (tests, embedding) roots fresh spans.
                cur = tr.current()
                tid = cur.trace_id if cur is not None else tr.new_id()
                parent = cur.span_id if cur is not None else None
                tr.add_span("decode", t0, t_dec, trace_id=tid,
                            parent_id=parent, role=self._TRACE_ROLE,
                            args={"events": n})
                tr.add_span("dispatch", t_dec, t_end, trace_id=tid,
                            parent_id=parent, role=self._TRACE_ROLE,
                            args={"wire": self._last_wire})
                trace_hex = f"{tid:016x}"
            # Stage observations carry the trace id as an OpenMetrics
            # exemplar candidate: the exposition emits the window's
            # worst batch on its landing bucket, so a p99 breach links
            # straight into the span tree (empty id = no exemplar).
            self._h_decode.observe(t_dec - t0, trace_hex)
            self._h_dispatch.observe(t_end - t_dec, trace_hex)
            rec = dict(
                ts=round(time.time(), 6), events=n,
                wire=self._last_wire,
                decode_s=round(t_dec - t0, 6),
                dispatch_s=round(t_end - t_dec, 6),
                inflight=len(self._inflight))
            # Per-record stage self-times (ISSUE 15 satellite): a
            # SIGUSR1 dump is attributable on its own — dequeue wait
            # from the run loop, decode/dispatch from this frame,
            # device_wait accumulated from the drains since the last
            # record — without needing the separate trace file.
            dw, self._dw_accum = self._dw_accum, 0.0
            stages = {
                "dequeue_wait": round(self._last_dequeue_s, 6),
                "decode": round(t_dec - t0, 6),
                "dispatch": round((t_end - t_dec) - t_tmp, 6),
                "device_wait": round(dw, 6),
            }
            if self._temporal is not None:
                stages["temporal"] = round(t_tmp, 6)
            rec["stages"] = stages
            if trace_hex:
                # Cross-reference: a flight-recorder dump names the
                # trace each batch record belongs to, so wedged-run
                # forensics can jump from the ring straight into the
                # Perfetto span tree.
                rec["trace"] = trace_hex
            obs_t.record_batch(**rec)
        return valid_n

    def _word_step(self, kw: int):
        step = self._word_steps.get(kw)
        if step is None:
            step = self._word_steps[kw] = make_jitted_step_words(
                self.params, kw, self.config.hll_precision)
        return step

    def _seg_step(self, kb: int, padded: int, num_banks: int):
        key = (kb, padded, num_banks)
        step = self._seg_steps.get(key)
        if step is None:
            step = self._seg_steps[key] = make_jitted_step_seg(
                self.params, kb, padded, num_banks,
                self.config.hll_precision)
        return step

    def _decayed_db(self, width: int, needed: int) -> int:
        """Next delta-width hint after a frame packed at ``width``
        whose own minimum was ``needed``.

        Growth is immediate (width already includes it). Decay needs
        evidence: 16 consecutive frames with >= 3 bits of slack drop
        the hint to the widest width those frames actually needed —
        so one pathological frame widens the wire once, not forever,
        while steady populations never oscillate (the 3-bit guard band
        absorbs ordinary widest-gap jitter, and each decay step is a
        new compile, so it must be rare)."""
        if needed <= width - 3:
            self._db_slack += 1
            self._db_seen = max(self._db_seen, needed)
            if self._db_slack >= 16:
                from attendance_tpu.models.fused import pick_delta_width
                width = pick_delta_width(1, self._db_seen)
                self._db_slack, self._db_seen = 0, 1
        else:
            self._db_slack, self._db_seen = 0, 1
        return width

    def _delta_step(self, db: int, padded: int, num_banks: int):
        key = (db, padded, num_banks)
        step = self._delta_steps.get(key)
        if step is None:
            step = self._delta_steps[key] = make_jitted_step_delta(
                self.params, db, padded, num_banks,
                self.config.hll_precision)
        return step

    def _rescan_width(self, nat, sid, num_banks: int):
        """Real frame key width via the native max-key scan, plus the
        word-wire verdict for it. When the frame outgrows the word
        budget, the width is folded into the hint so subsequent frames
        take the cheap top-of-loop check straight to the bytes wire
        instead of re-paying a doomed hinted pack every frame."""
        frame_bits = nat.max_key(sid).bit_length()
        kw = self._pick_kw(frame_bits, num_banks)
        use_words = kw + num_banks.bit_length() <= 32
        if not use_words:
            self._kw_hint = max(self._kw_hint, frame_bits)
        return frame_bits, kw, use_words

    def _pick_kw(self, frame_bits: int, num_banks: int) -> int:
        """Key width for the word wire: the frame's own max-key bits,
        widened to the monotonic hint (fewer distinct compiled widths) —
        but the hint is DROPPED when, after bank growth, it no longer
        fits a word while the frame's own width still does. An outlier
        frame must not permanently force the wider fallback wire."""
        kw = max(frame_bits, 1)
        hinted = max(kw, self._kw_hint)
        return hinted if hinted + num_banks.bit_length() <= 32 else kw

    def _dispatch_single(self, cols: Dict[str, np.ndarray], n: int,
                         padded: int):
        """Pack one frame's (key, bank) lanes and dispatch the fused
        step; returns (valid, perm) where perm is the packed-lane ->
        original-index permutation of the segmented wire, or None for
        the order-preserving wires.

        Wire format choice: either the host->device link or the host
        pack is the e2e ceiling, and which one varies with link weather
        (see _auto_wire — config.wire_format "auto" adapts per frame).
        The wires, narrowest link to cheapest host: the DELTA-coded
        segmented stream (db bits/event — sorted-key gaps per bank);
        the bank-SEGMENTED bit-packed stream (kb bits/event — the bank
        id never crosses the link); ONE uint32 word per event — bank id
        folded into the key's spare high bits (4 bytes/event); the
        5-byte key+bank wire when key and bank bits don't fit one word.

        The pack itself runs in the native host runtime when available
        (one fused max-scan + LUT-map + pack pass, hostpipe.c); the
        numpy path is the behavior-identical fallback. On a native LUT
        miss (a day with no registered bank yet) the banks are resolved
        once through the numpy registration path; the native pack is
        retried only if that actually brought the missed day into the
        dense LUT window — out-of-window days (hashed non-calendar
        lecture ids) reuse the resolved banks in the numpy pack instead
        of paying a doomed second native pass.
        """
        sid, days = cols["student_id"], cols["lecture_day"]
        num_banks = self.state.hll_regs.shape[0]
        nat = self._native
        banks = None
        if nat is not None and self._native_skip > 0:
            # Recent frames carried out-of-LUT-window days: the native
            # pack would scan most of the frame just to abort. Skip it
            # for a while, re-probing periodically in case the stream's
            # day population shifted back to the dense window.
            self._native_skip -= 1
            nat = None
        wire = self.config.wire_format
        if wire == "auto" and nat is not None:
            wire = self._auto_wire()
        if wire in ("seg", "delta"):
            valid, perm, banks = self._dispatch_narrow(
                cols, n, padded, nat, wire,
                forced=self.config.wire_format != "auto")
            if valid is not None:
                return valid, perm
            # Seg wire unavailable for this frame (native bypass armed,
            # or a native allocation failure in auto mode): the legacy
            # wires below carry it, skipping the already-doomed native
            # attempt and reusing any banks the seg attempt resolved
            # (bank growth there also means num_banks must be re-read).
            nat = None
            num_banks = self.state.hll_regs.shape[0]
        if nat is not None:
            if self._day_base is None:
                self._rebuild_lut(int(days.min()))
            # Key width is the monotonic hint, trusted without a
            # per-frame max-key scan: the native pack detects overflow
            # itself (miss == -3), and only then is the real width
            # scanned and the pack retried — on this single-core host
            # every avoided pass over the frame is throughput.
            frame_bits = None
            for _attempt in (0, 1):
                kw = (max(self._kw_hint, 1) if frame_bits is None
                      else self._pick_kw(frame_bits, num_banks))
                use_words = (kw + num_banks.bit_length() <= 32
                             and wire != "bytes")
                if not use_words and frame_bits is None \
                        and wire != "bytes":
                    # The hint outgrew the word budget; the frame's own
                    # width may still fit (_pick_kw drops the hint).
                    frame_bits, kw, use_words = self._rescan_width(
                        nat, sid, num_banks)
                if use_words:
                    words, miss = nat.pack_words(
                        sid, days, self._day_lut, self._day_base, kw,
                        padded)
                    if miss == -3:  # hinted width overflowed: rescan
                        frame_bits, kw, use_words = self._rescan_width(
                            nat, sid, num_banks)
                        if use_words:
                            words, miss = nat.pack_words(
                                sid, days, self._day_lut,
                                self._day_base, kw, padded)
                if not use_words:
                    self._note_word_degrade()
                    words, miss = nat.pack_bytes(
                        sid, days, self._day_lut, self._day_base,
                        np.dtype(self._bank_dtype).itemsize, padded)
                if miss == -1:
                    if use_words:
                        self._kw_hint = kw
                        self._count_wire("word")
                        self._note_compile("step_words", kw,
                                           len(words))
                        self.state, valid = self._word_step(kw)(
                            self.state, jax.numpy.asarray(words))
                    else:
                        self._count_wire("bytes")
                        self._note_compile("step_bytes", len(words))
                        self.state, valid = self._step(
                            self.state, jax.numpy.asarray(words))
                    return valid, None
                if _attempt == 1:
                    # Missed again after full registration: this frame
                    # has a day the dense LUT cannot cover. Bypass
                    # native packing for the next frames (a stream with
                    # persistent out-of-window days would pay a doomed
                    # near-full scan per frame), re-probing later.
                    self._native_skip = 32
                    break
                # Unregistered day (or LUT window shift): resolve banks
                # once via the numpy path (registers days, may rebuild
                # the LUT or grow banks — hence re-picking kw above).
                banks = self._banks_for(days)
                num_banks = self.state.hll_regs.shape[0]
                # Retry natively only if the missed day actually landed
                # in the LUT window; otherwise it is unresolvable —
                # reuse the resolved banks and arm the bypass now.
                off = int(days[miss]) - self._day_base
                if not (0 <= off < self._LUT_SIZE
                        and self._day_lut[off] >= 0):
                    self._native_skip = 32
                    break
        # numpy pack: no native runtime, or days the dense LUT window
        # can't cover (hashed non-calendar lecture ids far from the
        # calendar window) — _banks_for_slow resolves those through the
        # dict map.
        if banks is None:
            banks = self._banks_for(days)
            num_banks = self.state.hll_regs.shape[0]
        kw = self._pick_kw(int(sid.max()).bit_length(), num_banks)
        if kw + num_banks.bit_length() <= 32 and wire != "bytes":
            self._kw_hint = kw
            self._count_wire("word")
            words = pack_words(sid, banks, kw, padded)
            self._note_compile("step_words", kw, len(words))
            self.state, valid = self._word_step(kw)(
                self.state, jax.numpy.asarray(words))
            return valid, None
        # ONE combined byte-packed transfer: B little-endian uint32
        # keys then B narrow bank ids (dtype max = padded lane) —
        # (4 + w) bytes/event on the link instead of 8.
        self._note_word_degrade()
        self._count_wire("bytes")
        buf = pack_bytes(sid, banks, self._bank_dtype, padded)
        self._note_compile("step_bytes", len(buf))
        self.state, valid = self._step(self.state, jax.numpy.asarray(buf))
        return valid, None

    def _dispatch_sharded_narrow(self, sid: np.ndarray, banks: np.ndarray,
                                 days: np.ndarray, n: int, mode: str):
        """Seg/delta wires over the mesh: split the batch into dp
        contiguous range slices, pack each independently at the
        engine's per-replica lane count, and ship ONE uint32[dp, words]
        array whose leading axis is dp-sharded — each replica's chip
        receives only its own packed buffer, the same bits-per-event
        link economy the single-chip ladder gets. Returns
        (valid, lanes, orig): ``valid`` is the device vector in packed
        per-slice order; ``lanes``/``orig`` map its real lanes back to
        original event order for the lazy store view.

        Each slice packs in the native host runtime when available
        (the same atp_pack_seg / atp_delta_scan + atp_bitpack passes
        the single-chip wires use — VERDICT r03 weak #5: the mesh used
        the numpy packers exactly in the slow-link regime where narrow
        wires matter). The caller already resolved ``banks`` (filling
        the day LUT), so a native LUT miss means an out-of-window day:
        that slice falls back to the numpy pack with the resolved
        banks, and persistent misses arm the same _native_skip bypass
        as the single-chip path."""
        engine = self.engine
        dp = engine.dp
        num_banks = engine.num_banks
        padded_local = engine.padded_size(n) // dp
        bounds = [min(n, r * padded_local) for r in range(dp + 1)]
        slices = [(sid[bounds[r]:bounds[r + 1]],
                   banks[bounds[r]:bounds[r + 1]],
                   days[bounds[r]:bounds[r + 1]]) for r in range(dp)]
        nat = self._native
        if nat is not None and self._native_skip > 0:
            self._native_skip -= 1
            nat = None
        if nat is not None and self._day_base is None:
            self._rebuild_lut(int(days.min()))
        if mode == "seg":
            width = min(max(int(sid.max()).bit_length(), 1,
                            self._kw_hint), 32)
            self._kw_hint = width
            scans = None
        else:
            # One shared delta width across replicas (the compiled step
            # is per-width): scan every slice first — native fused
            # LUT+sort+delta pass where possible, numpy otherwise (the
            # tuples are interchangeable) — then each slice's scan is
            # reused by its pack.
            scans = []
            for ks, bs, ds in slices:
                scan = None
                if nat is not None and len(ks):
                    scan, miss = nat.delta_scan(
                        ks, ds, self._day_lut, self._day_base, num_banks)
                    if scan is None and miss >= 0:
                        self._native_skip = 32
                if scan is None:
                    scan = delta_scan(ks, bs, num_banks)
                scans.append(scan)
            needed = max(s[-1] for s in scans)
            width = pick_delta_width(self._db_hint, needed)
            self._db_hint = self._decayed_db(width, needed)
        bufs = None
        lanes = np.empty(n, np.int64)
        orig = np.empty(n, np.int64)
        pos = 0
        tr = self._tracer
        for r, (ks, bs, ds) in enumerate(slices):
            t_pack = time.perf_counter() if tr is not None else 0.0
            buf = perm = None
            if mode == "seg":
                if nat is not None and len(ks):
                    buf, perm, miss = nat.pack_seg(
                        ks, ds, self._day_lut, self._day_base, width,
                        padded_local, num_banks)
                    if buf is None and miss >= 0:
                        self._native_skip = 32
                if buf is None:
                    buf, perm = pack_seg(ks, bs, width, padded_local,
                                         num_banks)
            else:
                perm = scans[r][0]
                if nat is not None:
                    buf = nat.bitpack_delta(scans[r], width,
                                            padded_local, num_banks)
                if buf is None:
                    buf, perm = pack_delta(ks, bs, width, padded_local,
                                           num_banks, scan=scans[r])
            if tr is not None:
                # Replica-labeled host-pack spans: which dp slice's
                # pack dominates the mesh batch (nests under the batch
                # span via the tracer's active-span stack).
                tr.add_span("pack", t_pack, time.perf_counter(),
                            trace_id=(tr.current().trace_id
                                      if tr.current() else tr.new_id()),
                            parent_id=(tr.current().span_id
                                       if tr.current() else None),
                            role=self._TRACE_ROLE,
                            args={"replica": r, "wire": mode,
                                  "events": len(ks)})
            if bufs is None:
                bufs = np.empty((dp, len(buf)), np.uint32)
            bufs[r] = buf
            m = len(ks)
            lanes[pos:pos + m] = r * padded_local + np.arange(m)
            orig[pos:pos + m] = bounds[r] + perm
            pos += m
        self._count_wire(mode)
        if self._obs is not None:
            engine.note_shard_events(
                [bounds[r + 1] - bounds[r] for r in range(dp)])
        self._note_compile(f"sharded_step_{mode}", width,
                           padded_local)
        valid = engine.step_narrow(bufs, mode, width, padded_local)
        return valid, lanes, orig

    def _note_word_degrade(self) -> None:
        """Log ONCE when ``--wire-format=word`` was requested but a
        frame's key + bank bits exceed 32 and it must ride the wide
        fallback wire instead (bytes single-chip, arrays on the mesh) —
        a forced format is otherwise silently unhonored (only
        wire_dwell would reveal it)."""
        if (self.config.wire_format == "word"
                and not self._warned_word_degrade):
            self._warned_word_degrade = True
            logger.warning(
                "--wire-format=word cannot be honored: key bits + bank "
                "bits exceed one 32-bit word; frames fall back to the "
                "%s wire (see metrics wire_dwell for the split)",
                "arrays" if self.sharded else "bytes")

    _WIRE_LADDER = ("word", "seg", "delta")

    def _count_wire(self, key: str) -> None:
        """Record one frame dispatched over ``key`` — called at the
        dispatch sites themselves, not at wire selection, so fallback
        frames (narrow wire unavailable, word wire not fitting) are
        attributed to the wire that actually carried them."""
        dwell = self.metrics.wire_dwell
        dwell[key] = dwell.get(key, 0) + 1
        self._last_wire = key
        if self._obs is not None:
            self._obs.wire(key).inc()

    def _note_compile(self, fn: str, *fingerprint) -> None:
        """Report one jitted dispatch's shape fingerprint to the
        recompile tracker (obs/profiler.RecompileTracker) — called at
        the dispatch sites themselves, like _count_wire, so the
        fingerprint describes the program variant that actually ran.
        Cost per frame: one set lookup; a NEW fingerprint is exactly
        one XLA trace+compile."""
        rc = self._recomp
        if rc is not None:
            rc.observe(fn, fingerprint)

    def _count_xfer(self, site: str, direction: str,
                    nbytes: int) -> None:
        """Count host<->device bytes at the gather seams (snapshot
        capture D2H, mirror gather D2H, roster preload H2D) —
        attendance_device_transfer_bytes_total{site=,direction=}."""
        if self._obs is None or nbytes <= 0:
            return
        key = (site, direction)
        c = self._c_xfer.get(key)
        if c is None:
            c = self._c_xfer[key] = self._obs.registry.counter(
                "attendance_device_transfer_bytes_total",
                help="Host<->device bytes moved at the snapshot/"
                "mirror gather seams", site=site, direction=direction)
        c.inc(int(nbytes))

    def _auto_wire(self) -> str:
        """Per-frame wire choice for auto mode, from observed
        backpressure.

        The binding resource shifts with conditions outside our
        control: when the host->device link is slow, fewer bits/event
        wins (delta < seg < word on the wire); when the link is fast,
        the heavier sort-based host packs of the narrow wires become
        the bottleneck instead (word < seg < delta on the host; all
        device steps are equal). Measured on the relay tunnel, the SAME
        workload flips between word-wins (~1GB/s bursts) and
        seg/delta-wins (~100MB/s sustained) across sessions — so auto
        watches the in-flight deque: persistently full means the
        device/link side is behind (narrow the wire, one ladder step),
        persistently draining means the host is behind (widen).
        Hysteresis keeps it from thrashing; a mid-stream switch is safe
        because every frame is a self-contained dispatch.

        Checkpointing holds frames until snapshot barriers, so depth
        stops signalling backpressure — adaptation freezes at the
        current level there.
        """
        if self.checkpointing:
            return self._WIRE_LADDER[self._auto_level]
        # Primary signal: the hot loop actually BLOCKED on a full deque
        # since the last frame (set by _drain_inflight) — the tunnel
        # completes transfers in bursts, so instantaneous depth
        # oscillates 0..full and washes out, while a forced wait is
        # unambiguous "device/link behind".
        if self._drain_waited:
            self._auto_pressure = min(self._auto_pressure + 1, 8)
        elif len(self._inflight) <= 1:
            self._auto_pressure = max(self._auto_pressure - 1, -8)
        self._drain_waited = False
        # Asymmetric hysteresis: a full deque means dispatches are
        # cheap to divert into a narrower pack (climb after 2 signals),
        # while descending costs re-paying link bytes — require
        # sustained drain (6 signals) before widening.
        if self._auto_pressure >= 2 and self._auto_level < 2:
            self._auto_level += 1
            self._auto_pressure = 0
        elif self._auto_pressure <= -6 and self._auto_level > 0:
            self._auto_level -= 1
            self._auto_pressure = 0
        return self._WIRE_LADDER[self._auto_level]

    def _dispatch_narrow(self, cols: Dict[str, np.ndarray], n: int,
                         padded: int, nat, mode: str, forced: bool):
        """Sub-word-wire dispatch (``mode`` = "delta" or "seg" — one
        LUT-miss/bypass protocol for both); returns (valid, perm, None)
        on success, or (None, None, banks_or_None) when this frame
        should fall back to the legacy wires (auto mode only: native
        bypass armed by persistent out-of-LUT-window days, or a native
        scratch-allocation failure) — banks carries any day->bank
        resolution already done so the caller doesn't resolve twice."""
        sid, days = cols["student_id"], cols["lecture_day"]
        num_banks = self.state.hll_regs.shape[0]
        banks = None
        if nat is not None:
            if self._day_base is None:
                self._rebuild_lut(int(days.min()))
            for _attempt in (0, 1):
                if mode == "seg":
                    # Trust the monotonic width hint; the pack detects
                    # overflow itself (miss == -3) and we rescan only
                    # then — same economy as the word path.
                    width = min(max(1, self._kw_hint), 32)
                    buf, perm, miss = nat.pack_seg(
                        sid, days, self._day_lut, self._day_base,
                        width, padded, num_banks)
                    if miss == -3:
                        width = min(max(nat.max_key(sid).bit_length(),
                                        1, self._kw_hint), 32)
                        buf, perm, miss = nat.pack_seg(
                            sid, days, self._day_lut, self._day_base,
                            width, padded, num_banks)
                else:
                    buf, perm, width, needed, miss = nat.pack_delta(
                        sid, days, self._day_lut, self._day_base,
                        self._db_hint, padded, num_banks)
                if miss == -1:
                    if mode == "seg":
                        self._kw_hint = width
                        step = self._seg_step(width, padded, num_banks)
                    else:
                        self._db_hint = self._decayed_db(width, needed)
                        step = self._delta_step(width, padded,
                                                num_banks)
                    self._count_wire(mode)
                    self._note_compile(f"step_{mode}", width, padded,
                                       num_banks)
                    self.state, valid = step(self.state,
                                             jax.numpy.asarray(buf))
                    return valid, perm, None
                if miss == -2:  # scratch alloc failed / too many banks
                    if not forced:
                        return None, None, banks
                    break
                if _attempt == 1:
                    # Missed again after full registration: persistent
                    # out-of-LUT-window days (see _dispatch_single).
                    self._native_skip = 32
                    if not forced:
                        return None, None, banks
                    break
                banks = self._banks_for(days)
                num_banks = self.state.hll_regs.shape[0]
                off = int(days[miss]) - self._day_base
                if not (0 <= off < self._LUT_SIZE
                        and self._day_lut[off] >= 0):
                    self._native_skip = 32
                    if not forced:
                        return None, None, banks
                    break
        # numpy packer: forced mode without (or past) the native
        # runtime. Sort-based — correct for any day population, but
        # slower than the fused native pass; auto mode prefers the
        # legacy wires in that situation.
        if banks is None:
            banks = self._banks_for(days)
            num_banks = self.state.hll_regs.shape[0]
        if mode == "seg":
            kb = min(max(int(sid.max()).bit_length(), 1, self._kw_hint),
                     32)
            self._kw_hint = kb
            buf, perm = pack_seg(sid, banks, kb, padded, num_banks)
            step = self._seg_step(kb, padded, num_banks)
        else:
            scan = delta_scan(sid, banks, num_banks)
            db = pick_delta_width(self._db_hint, scan[-1])
            self._db_hint = self._decayed_db(db, scan[-1])
            buf, perm = pack_delta(sid, banks, db, padded, num_banks,
                                   scan=scan)
            step = self._delta_step(db, padded, num_banks)
        self._count_wire(mode)
        self._note_compile(f"step_{mode}",
                           kb if mode == "seg" else db, padded,
                           num_banks)
        self.state, valid = step(self.state, jax.numpy.asarray(buf))
        return valid, perm, None

    # -- checkpointing ------------------------------------------------------
    @property
    def checkpointing(self) -> bool:
        return self._snap_dir is not None

    def snapshot(self) -> None:
        """Write sketch + store state to snapshot_dir, synchronously
        (explicit calls and the sharded/mesh path; the run loop's
        cadence barriers use the async writer, _checkpoint_async)."""
        if self._snap_dir is None:
            return
        self._flush_snapshots()  # serialize with any in-flight writer
        # State gather runs on EVERY process — on a multi-process mesh
        # it contains cross-process collectives, so skipping it on any
        # process would deadlock the lockstep.
        if self.sharded:
            bits, regs = self.engine.get_state()
            counts = self.engine.get_counts()
        else:
            if self._bloom_host is None:
                self._bloom_host = np.asarray(self.state.bloom_bits)
            bits = self._bloom_host
            regs = np.asarray(self.state.hll_regs)
            counts = np.asarray(self.state.counts)
        if self.sharded:
            self._bloom_host = np.asarray(bits)
        # A full snapshot covers every bank: the dirty set and the
        # delta chain restart from it (on every process — the flags
        # steer control flow and must not diverge across a mesh; a
        # write failure on process 0 crashes the lockstep anyway).
        self._dirty_days.clear()
        self._regs_mirror = np.array(regs, dtype=np.uint8, copy=True)
        self._publish_epoch(self._regs_mirror, counts,
                            bank_of=dict(self._bank_of))
        if self._fed is not None:
            self._fed.publish_full(
                np.asarray(bits), self._regs_mirror, counts,
                dict(self._bank_of), self._events_total,
                roster_size=self._roster_size)
        if jax.process_count() > 1 and jax.process_index() != 0:
            # Multi-controller lockstep (DCN cluster): every process
            # holds the same replicated state, so exactly one writes
            # it. Non-zero processes still honor the barrier semantics
            # (callers materialize outputs and ack) — they only skip
            # the duplicate FILE writes, which would race on a shared
            # snapshot_dir.
            self._base_stale = False
            self._writer_base_ok = True
            self._batches_at_snap = self.metrics.batches
            return
        with self._snap_io_lock:
            self._write_snapshot_files(bits, regs, counts,
                                       dict(self._bank_of),
                                       self._events_total, upto=None)
        # Only after the write: a raise above leaves the next barrier
        # still owing a full base.
        self._base_stale = False
        self._writer_base_ok = True
        self._batches_at_snap = self.metrics.batches

    def _write_snapshot_files(self, bits, regs, counts, bank_of: dict,
                              events: int, upto) -> None:
        """The file half of a snapshot (caller holds _snap_io_lock):
        sketch npz (atomic rename) + incremental event segments.
        Uncompressed: zlib costs ~40x the raw write on this one-core
        host and the write sits on the ack-latency path."""
        self._snap_dir.mkdir(parents=True, exist_ok=True)
        manifest = {
            "bank_of": {str(d): b for d, b in bank_of.items()},
            "m_bits": self.params.m_bits,
            "k": self.params.k,
            "precision": self.config.hll_precision,
            "events": events,
            # Staleness fence for the one crash window the in-place
            # base replace opens (new base lands, crash before the
            # chain-manifest reset): deltas numbered <= this are OLDER
            # than the base and must not be applied on top of it. The
            # delta sequence is monotonic across restarts (restore
            # scans the dir), unlike the per-process events counter.
            "chain_seq": self._delta_seq,
        }
        # Event segments FIRST: a crash between the two writes leaves
        # extra store rows whose frames are still unacked — replay
        # appends them again and read-time last-write-wins dedup folds
        # them, exactly like redelivery into Cassandra upsert.
        if hasattr(self.store, "save_segments"):
            self.store.save_segments(self._snap_dir / EVENTS_SEGMENTS,
                                     upto=upto)
        else:
            self.store.save(self._snap_dir / EVENTS_SNAPSHOT)
        from attendance_tpu.utils.integrity import (
            chaos_post_publish, chaos_pre_write, file_digest)

        chaos_pre_write("disk.chain")
        path = self._snap_dir / SKETCH_SNAPSHOT
        tmp = path.with_suffix(".tmp")
        with open(tmp, "wb") as f:
            np.savez(f, bloom_words=bits, hll_regs=regs, counts=counts,
                     manifest=np.frombuffer(
                         json.dumps(manifest).encode(), dtype=np.uint8))
            # fsync before the rename: the chain-manifest reset below
            # unlinks the delta files this base supersedes, so page-
            # cache durability is not enough for the base itself.
            f.flush()
            os.fsync(f.fileno())
        # Digest of the CLEAN bytes, streaming off the tmp file before
        # the publish (and before the chaos disk-rot hook can touch
        # the published copy) — what CHAIN.json records and every
        # reader verifies against.
        self._base_digest = (file_digest(tmp) if self._integrity
                             else "")
        tmp.replace(path)
        chaos_post_publish("disk.chain", path)
        # A full base supersedes any delta chain: reset the manifest
        # FIRST (restore must never apply stale deltas on top of this
        # newer base), then delete the superseded delta files.
        old = list(self._snap_chain)
        self._snap_chain = []
        self._snap_digests = {}
        self._write_chain_manifest()
        for name in old:
            try:
                (self._snap_dir / name).unlink()
            except OSError:
                pass

    def _write_chain_manifest(self) -> None:
        """Atomically publish the base+delta chain (caller holds
        _snap_io_lock) via the shared durable-manifest helper — the
        rename IS the snapshot's durability point (a delta file a
        crash orphaned before its manifest entry is ignored on
        restore, and its frames redeliver)."""
        from attendance_tpu.utils.snapshot import write_manifest_atomic

        doc = {"base": SKETCH_SNAPSHOT,
               "deltas": list(self._snap_chain)}
        if self._integrity:
            # Payload digests: what restore, the serve-plane chain
            # readers, and `scrub` verify each file against before
            # trusting it.
            doc["base_digest"] = self._base_digest
            doc["digests"] = {n: self._snap_digests[n]
                              for n in self._snap_chain
                              if n in self._snap_digests}
        write_manifest_atomic(self._snap_dir, doc, name=CHAIN_MANIFEST)

    def _write_delta_files(self, banks: np.ndarray, rows: np.ndarray,
                           counts, bank_of: dict, events: int,
                           num_banks: int, upto) -> int:
        """The file half of one incremental snapshot (caller holds
        _snap_io_lock): event segments first (extra rows from a crash
        before the manifest replay harmlessly through read-time
        dedup), then the fsync'd delta npz, then the manifest rename
        that makes the delta part of the restorable chain. Returns the
        delta file's size in bytes."""
        self._snap_dir.mkdir(parents=True, exist_ok=True)
        if hasattr(self.store, "save_segments"):
            self.store.save_segments(self._snap_dir / EVENTS_SEGMENTS,
                                     upto=upto)
        else:
            self.store.save(self._snap_dir / EVENTS_SNAPSHOT)
        from attendance_tpu.utils.snapshot import fsync_write_npz

        manifest = {
            "bank_of": {str(d): b for d, b in bank_of.items()},
            "events": events,
            "num_banks": num_banks,
        }
        self._delta_seq += 1
        name = f"delta-{self._delta_seq:04d}.npz"
        path = self._snap_dir / name
        # fsync'd (shared helper): durable BEFORE the manifest names it.
        digest = fsync_write_npz(path, dict(
            bank_idx=np.asarray(banks, np.int32),
            regs_rows=np.asarray(rows, np.uint8),
            counts=np.asarray(counts, np.uint32),
            manifest=np.frombuffer(
                json.dumps(manifest).encode(), dtype=np.uint8)))
        if self._integrity:
            self._snap_digests[name] = digest
        self._snap_chain.append(name)
        self._write_chain_manifest()
        return path.stat().st_size

    def _flush_snapshots(self) -> None:
        """Wait out every in-flight background snapshot write."""
        self._wait_snap_slots(0)

    def _wait_snap_slots(self, below: int) -> None:
        """Block until fewer than ``below`` + 1 staged writes remain
        (0 = queue fully drained), recording the wait as hot-loop
        snapshot backpressure."""
        with self._snap_cv:
            if self._snap_pending <= below:
                return
            t0 = time.perf_counter()
            while self._snap_pending > below:
                self._snap_cv.wait()
            blocked = time.perf_counter() - t0
        self.metrics.snapshot_blocked_s += blocked
        if self._obs is not None:
            self._h_snap_blocked.observe(blocked)

    # -- dirty-bank tracking (delta mode) ------------------------------------
    def _note_dirty(self, days: np.ndarray) -> None:
        """Record the lecture days one frame touches. Steady state
        (all days inside the dense LUT window) costs a min/max pair
        plus — only for multi-day frames — one bincount over the small
        offset range; single-day frames are O(1)."""
        days_u32 = np.ascontiguousarray(days, dtype=np.uint32)
        base = self._day_base
        if base is not None:
            off = (days_u32 - np.uint32(base)).view(np.int32)
            mn, mx = int(off.min()), int(off.max())
            if 0 <= mn and mx < self._LUT_SIZE:
                if mn == mx:
                    self._dirty_days.add(mn + base)
                else:
                    seen = np.bincount(off - mn, minlength=1)
                    self._dirty_days.update(
                        (np.nonzero(seen)[0] + (mn + base)).tolist())
                return
        self._dirty_days.update(
            np.unique(days_u32.astype(np.int64)).tolist())

    def _drain_dirty_banks(self) -> np.ndarray:
        """Swap out the dirty-day set and resolve it to sorted HLL bank
        indices (every dispatched day registered a bank; unregistered
        stragglers — e.g. days seen only in all-padding frames — are
        simply not dirty)."""
        days, self._dirty_days = self._dirty_days, set()
        banks = sorted(self._bank_of[d] for d in days
                       if d in self._bank_of)
        return np.asarray(banks, dtype=np.int32)

    @staticmethod
    def _pad_bank_index(banks: np.ndarray) -> np.ndarray:
        """Dirty-bank indices padded to a power of two (min 8) so a
        steady dirty population compiles a couple of gather shapes,
        not one per distinct dirty count. Pad rows gather bank 0 and
        are sliced off host-side."""
        padded = 8
        while padded < len(banks):
            padded *= 2
        idx = np.zeros(padded, np.int32)
        idx[:len(banks)] = banks
        return idx

    def _post_delta_bookkeeping(self, banks, rows, nbytes: int,
                                counts, bank_of: dict, events: int,
                                num_banks: int) -> None:
        """Shared tail of every delta write (async writer and mesh
        sync path): fold the rows into the host mirror, publish the
        gauges, and fold the chain into a fresh base when it reached
        the compaction cadence."""
        self._apply_mirror_rows(banks, rows, num_banks)
        if self._regs_mirror is not None:
            # The mirror now reflects this delta: publish it as the
            # next read epoch (the atomic swap readers pin against).
            self._publish_epoch(self._regs_mirror, counts,
                                bank_of=bank_of, events=events)
        if self._fed is not None:
            # Fence gossip: the SAME dirty-bank capture that just
            # became durable ships to the aggregator. A publisher
            # owing a full frame (an earlier gossip publish failed —
            # the aggregator may have missed banks) upgrades from the
            # host mirror instead; durability is never coupled to
            # gossip success in either direction.
            if self._fed.full_due and self._regs_mirror is not None \
                    and self._bloom_host is not None:
                self._fed.publish_full(
                    self._bloom_host, self._regs_mirror, counts,
                    bank_of, events, roster_size=self._roster_size)
            else:
                self._fed.publish_delta(
                    banks, rows, counts, bank_of, events, num_banks,
                    roster_size=self._roster_size)
        if self._g_delta_bytes is not None:
            self._g_delta_bytes.set(float(nbytes))
            self._g_chain_len.set(float(len(self._snap_chain)))
        if len(self._snap_chain) >= self._snap_compact_every:
            self._compact_chain(counts, bank_of, events)

    # -- background writer ---------------------------------------------------
    def _enqueue_snap(self, job: dict) -> None:
        with self._snap_cv:
            if self._snap_thread is None or not self._snap_thread.is_alive():
                import weakref
                self._snap_thread = threading.Thread(
                    target=FusedPipeline._snap_writer_main,
                    args=(weakref.ref(self), self._snap_cv,
                          self._snap_jobs),
                    name="snapshot-writer", daemon=True)
                self._snap_thread.start()
            self._snap_jobs.append(job)
            self._snap_pending += 1
            self._snap_cv.notify_all()

    def _stop_snap_writer(self) -> None:
        """Shut the writer down (cleanup path): sentinel + join, after
        the queue drained."""
        with self._snap_cv:
            t = self._snap_thread
            if t is None or not t.is_alive():
                return
            self._snap_jobs.append(None)
            self._snap_cv.notify_all()
        t.join(timeout=10.0)
        self._snap_thread = None

    @staticmethod
    def _snap_writer_main(pipe_ref, cv, jobs) -> None:
        """The persistent snapshot writer: drains staged captures in
        barrier order, makes each durable (D2H -> files -> manifest
        rename), and releases the interval's acks as ONE group commit.
        A failed write leaves its frames unacked (redelivery replays
        them into idempotent sinks) and forces the next barrier to
        write a fresh full base, restoring the chain invariant.

        Holds only a WEAKREF to the pipeline between jobs (plus a
        cleanup sentinel): a pipeline dropped without cleanup() is
        still collectable, and the parked thread notices within a
        second and exits instead of pinning the device state forever."""
        while True:
            with cv:
                while not jobs:
                    if pipe_ref() is None:
                        return  # pipeline collected: nothing to write
                    cv.wait(timeout=1.0)
                job = jobs.popleft()
            if job is None:
                return  # cleanup sentinel
            pipe = pipe_ref()
            if pipe is None:
                return  # frames stay unacked; process is tearing down
            backoff = pipe._writer_backoff_s()
            if backoff and (job["kind"] == "base"
                            or pipe._writer_base_ok):
                # Bounded backoff BETWEEN attempts after failures (the
                # queue slot was already released, so the hot loop
                # keeps overlapping; only durability lags). Deltas
                # staged behind a FAILED base skip it: they insta-fail
                # the no-durable-base guard without touching the disk,
                # and sleeping the capped backoff per doomed job
                # starves delivery into the idle timeout.
                time.sleep(backoff)
            pipe._run_snap_job_logged(job)

    def _writer_backoff_s(self) -> float:
        """Delay before the writer's next attempt: 0 while healthy,
        exponential from 50ms after consecutive failures, capped at
        5s — bounded, so recovery latency after the disk heals is
        bounded too."""
        streak = self._snap_fail_streak
        if streak <= 0:
            return 0.0
        return min(0.05 * 2 ** min(streak - 1, 7), 5.0)

    def _run_snap_job_logged(self, job: dict) -> None:
        t0 = time.perf_counter()
        inj = self._chaos
        st = self._stage_mark
        prev_stage = st.set("snapshot") if st is not None else None
        try:
            if inj is not None:
                stall = inj.stall_s("snapshot.writer")
                if stall:
                    time.sleep(stall)  # injected writer stall
                if inj.roll("snapshot.writer", "snap_fail"):
                    from attendance_tpu.chaos import ChaosFault
                    raise ChaosFault(
                        "chaos snap_fail at snapshot.writer")
            self._run_snap_job(job)
            acknowledge_all(self.consumer, job["msgs"])
            self._snap_fail_streak = 0
        except Exception as exc:
            self._base_stale = True
            import errno as _errno
            disk_full = (isinstance(exc, OSError)
                         and exc.errno == _errno.ENOSPC)
            if disk_full:
                # ENOSPC is not a transient hiccup: walking the
                # exponential ladder from 50ms re-attempts a FULL BASE
                # into a full disk several times before reaching a
                # sane cadence. Jump straight to the capped backoff
                # and count the condition distinctly so doctor/SLOs
                # can name it.
                self._snap_fail_streak = max(self._snap_fail_streak + 1,
                                             8)
            elif not isinstance(exc, _StaleBaseError):
                # Stale-base guard failures touched no disk: they
                # ride whatever backoff the REAL failure earned
                # without extending it.
                self._snap_fail_streak += 1
            if job["kind"] == "base":
                # The on-disk base is stale/absent: any delta job
                # already staged behind this one must NOT chain onto
                # it — the guard in _run_snap_job fails those jobs too
                # (their frames redeliver) until a fresh base lands.
                self._writer_base_ok = False
            obs_t = self._obs
            if obs_t is not None:
                obs_t.registry.counter(
                    "attendance_snapshot_write_failures_total",
                    help="Background snapshot writes that failed "
                    "(frames stay unacked; next barrier forces a "
                    "full base)").inc()
                if disk_full:
                    obs_t.registry.counter(
                        "attendance_snapshot_disk_full_total",
                        help="Snapshot writes refused with ENOSPC "
                        "(writer backs off at the capped cadence "
                        "until space frees; frames stay unacked)"
                    ).inc()
            if disk_full:
                logger.error(
                    "Snapshot disk is FULL (ENOSPC): frames stay "
                    "unacked, writer retries every %.1fs until space "
                    "frees", self._writer_backoff_s())
            else:
                logger.exception(
                    "Background snapshot failed (consecutive "
                    "failures: %d, next attempt in %.2fs)",
                    self._snap_fail_streak, self._writer_backoff_s())
        finally:
            if st is not None:
                st.restore(prev_stage)
            t_done = time.perf_counter()
            stall = t_done - t0
            self.metrics.snapshot_stalls.append(stall)
            if self._obs is not None:
                self._h_snap_write.observe(stall)
                if self._tracer is not None:
                    self._tracer.add_span(
                        "snapshot_write", t0, t_done,
                        trace_id=self._tracer.new_id(),
                        role=self._TRACE_ROLE,
                        args={"events_at": job["events"],
                              "kind": job["kind"]})
            with self._snap_cv:
                self._snap_pending -= 1
                self._snap_cv.notify_all()

    def _run_snap_job(self, job: dict) -> None:
        if job["kind"] == "base":
            regs_h, counts_h = jax.device_get(
                (job["regs"], job["counts"]))
            regs_h = np.asarray(regs_h)
            self._count_xfer("snapshot_capture", "d2h",
                             regs_h.nbytes
                             + np.asarray(counts_h).nbytes)
            with self._snap_io_lock:
                self._write_snapshot_files(
                    job["bloom"], regs_h, counts_h, job["bank_of"],
                    job["events"], job["upto"])
            self._regs_mirror = np.array(regs_h, dtype=np.uint8,
                                         copy=True)
            self._publish_epoch(self._regs_mirror, counts_h,
                                bank_of=job["bank_of"],
                                events=job["events"])
            if self._fed is not None:
                self._fed.publish_full(
                    job["bloom"], regs_h, counts_h, job["bank_of"],
                    job["events"], roster_size=self._roster_size)
            self._writer_base_ok = True
            if self._g_chain_len is not None:
                self._g_chain_len.set(0.0)
            return
        if not self._writer_base_ok:
            raise _StaleBaseError(
                "delta capture with no durable base (an earlier base "
                "write failed); frames stay unacked and the next "
                "barrier writes a full base")
        banks = job["banks"]
        rows_h, counts_h = jax.device_get((job["rows"], job["counts"]))
        self._count_xfer("snapshot_capture", "d2h",
                         np.asarray(rows_h).nbytes
                         + np.asarray(counts_h).nbytes)
        rows_h = np.asarray(rows_h)[:len(banks)]
        with self._snap_io_lock:
            nbytes = self._write_delta_files(
                banks, rows_h, counts_h, job["bank_of"], job["events"],
                job["num_banks"], job["upto"])
        self._post_delta_bookkeeping(banks, rows_h, nbytes, counts_h,
                                     job["bank_of"], job["events"],
                                     job["num_banks"])

    def _publish_epoch(self, regs_h: np.ndarray, counts_h,
                       *, bank_of: dict,
                       events: Optional[int] = None) -> None:
        """Publish one read epoch from host-side register state (cold
        paths and the snapshot writer only — never the hot loop). The
        shadow's per-day truth is snapshotted WITH the epoch so the
        read-path HLL audit compares estimate and truth from the same
        moment instead of charging barrier staleness to the sketch."""
        auditor = getattr(self, "_auditor", None)
        day_truth = (auditor.fused_day_truth()
                     if auditor is not None else None)
        self.read_mirror.publish(
            regs=regs_h,
            events=(self._events_total if events is None else events),
            bank_of=bank_of, params=self.params,
            precision=self.config.hll_precision,
            bloom_words=self._bloom_host,
            counts=np.asarray(counts_h) if counts_h is not None
            else None,
            roster_size=self._roster_size, day_truth=day_truth)

    def _gather_host_state(self):
        """(regs_h, counts_h) after flushing the writer, with
        ``_bloom_host`` refreshed — the cold-path device read the
        synchronous publishers share. Performs D2H: call from cold
        paths only (see run()'s D2H note)."""
        self._flush_snapshots()
        if self.sharded:
            bits, regs = self.engine.get_state()
            counts = self.engine.get_counts()
            self._bloom_host = np.asarray(bits)
            regs_h = np.asarray(regs, dtype=np.uint8)
            self._count_xfer("mirror_gather", "d2h",
                             self._bloom_host.nbytes + regs_h.nbytes)
            return regs_h, counts
        if self._bloom_host is None:
            self._bloom_host = np.asarray(self.state.bloom_bits)
            self._count_xfer("mirror_gather", "d2h",
                             self._bloom_host.nbytes)
        regs_h = np.asarray(self.state.hll_regs)
        self._count_xfer("mirror_gather", "d2h", regs_h.nbytes)
        return regs_h, np.asarray(self.state.counts)

    def publish_epoch(self) -> None:
        """Force one synchronous epoch publish from the CURRENT device
        state — for embedders/benches that serve queries without
        checkpointing (snapshot barriers are the normal publisher).
        Performs device reads: call from cold paths (setup, between
        runs), never mid-stream on relay-tunneled devices (see
        run()'s D2H note)."""
        regs_h, counts = self._gather_host_state()
        self._publish_epoch(regs_h, counts,
                            bank_of=dict(self._bank_of))

    def fed_flush(self) -> None:
        """Publish one FULL merge frame from the current state — the
        federated worker's end-of-run handshake (the aggregator holds
        this worker's complete contribution before the process exits).
        Cold path: performs device reads, like publish_epoch."""
        if self._fed is None:
            return
        regs_h, counts = self._gather_host_state()
        self._fed.publish_full(self._bloom_host, regs_h, counts,
                               dict(self._bank_of),
                               self._events_total,
                               roster_size=self._roster_size)

    def _apply_mirror_rows(self, banks, rows: np.ndarray,
                           num_banks: int) -> None:
        """Fold one delta into the writer's host register mirror (what
        compaction folds back into a base without any extra D2H)."""
        mirror = self._regs_mirror
        if mirror is None:
            return
        if num_banks > mirror.shape[0]:
            grown = np.zeros((num_banks, mirror.shape[1]), np.uint8)
            grown[:mirror.shape[0]] = mirror
            self._regs_mirror = mirror = grown
        if len(banks):
            mirror[np.asarray(banks, np.int64)] = rows

    def _compact_chain(self, counts_h, bank_of: dict,
                       events: int) -> None:
        """Fold the delta chain back into a full base snapshot — in
        the WRITER, off the hot path, from the host mirror (no device
        traffic). Also merges the store's on-disk event segments so a
        long checkpointed run's restore cost stays bounded."""
        if self._regs_mirror is None or self._bloom_host is None:
            return
        with self._snap_io_lock:
            self._write_snapshot_files(
                self._bloom_host, self._regs_mirror, counts_h,
                bank_of, events, upto=None)
            if hasattr(self.store, "compact_segments"):
                # Safe here: this writer thread is the only
                # save_segments caller, so the no-concurrent-writer
                # contract holds by construction.
                self.store.compact_segments(
                    self._snap_dir / EVENTS_SEGMENTS)
        if self._g_chain_len is not None:
            self._g_chain_len.set(0.0)

    def _checkpoint_async(self, force: bool) -> None:
        """The BGSAVE analogue (single-chip path): capture a consistent
        point and hand the writes to the background writer, acking the
        captured frames only once they are durable.

        The capture is a DEVICE-SIDE copy of the mutating state — in
        delta mode a gather of just the HLL banks dirtied since the
        last barrier (models.fused.snapshot_capture_rows; the Bloom
        filter is run-static, see _bloom_host), in barrier mode the
        full register state. Either way it joins the dispatch queue
        after every step of the frames being snapshotted, so when the
        writer's D2H of the capture completes, those steps completed —
        the ack barrier without stopping the hot loop. The reference
        gets this from Redis BGSAVE's copy-on-write fork (SURVEY.md
        §5); the TPU-native analogue snapshots the STATE, not the
        process, and the delta capture shrinks it to the touched
        banks.

        Up to _SNAP_QUEUE_DEPTH captures may be staged (double
        buffering); past that a barrier is DEFERRED (cadence
        self-regulates to writer throughput) unless ``force``
        (in-flight depth bound hit), which blocks for one slot and
        records the wait as metrics.snapshot_blocked_s."""
        depth = (_SNAP_QUEUE_DEPTH if self._snap_mode == "delta"
                 else 1)
        if self._snap_pending >= depth:
            if not force:
                return  # defer: re-checked on a later frame
            self._wait_snap_slots(depth - 1)
        if self._bloom_host is None:
            # One-time (run-static filter), in the MAIN thread: the
            # writer must never host-read the live donated state chain.
            self._bloom_host = np.asarray(self.state.bloom_bits)
        if self._snap_mode == "delta" and not self._base_stale:
            banks = self._drain_dirty_banks()
            idx = self._pad_bank_index(banks)
            if self._snap_take is None:
                from attendance_tpu.models.fused import (
                    make_jitted_snapshot_capture)
                self._snap_take = make_jitted_snapshot_capture()
            self._note_compile("snapshot_capture", len(idx))
            rows_c, counts_c = self._snap_take(self.state.hll_regs,
                                               jax.numpy.asarray(idx),
                                               self.state.counts)
            job = dict(kind="delta", banks=banks, rows=rows_c,
                       counts=counts_c,
                       num_banks=self.state.hll_regs.shape[0])
        else:
            if self._snap_copy is None:
                self._snap_copy = jax.jit(lambda r, c: (r | 0, c | 0))
            regs_c, counts_c = self._snap_copy(self.state.hll_regs,
                                               self.state.counts)
            # The base covers every bank: restart the dirty set and
            # chain from it. (If the write later fails, the writer
            # flips _base_stale back and the next barrier re-captures
            # everything in a fresh base.)
            self._dirty_days.clear()
            self._base_stale = False
            job = dict(kind="base", regs=regs_c, counts=counts_c,
                       bloom=self._bloom_host)
        job.update(
            upto=(self.store.mark()
                  if hasattr(self.store, "mark") else None),
            msgs=[m for m, _, _ in self._inflight],
            events=self._events_total,
            bank_of=dict(self._bank_of))
        self._inflight.clear()
        self._batches_at_snap = self.metrics.batches
        self._enqueue_snap(job)

    def restore(self) -> bool:
        """Load the latest snapshot from snapshot_dir, if one exists:
        the base npz plus — when a CHAIN.json manifest is present —
        every delta it names, applied in order (via the shared
        :func:`read_chain_state` merge-on-read loader the query
        plane's chain readers also use). Delta files on disk that the
        manifest does NOT name are crash orphans (written but never
        made durable by a manifest rename) and are ignored; their
        frames were never acked and redeliver."""
        if self._snap_dir is None:
            return False
        from attendance_tpu.utils.integrity import ChainIntegrityError
        repaired = False
        try:
            chain_state = read_chain_state(
                self._snap_dir, expect_m_bits=self.params.m_bits,
                expect_precision=self.config.hll_precision)
        except FileNotFoundError:
            return False
        except ChainIntegrityError as exc:
            # The repair ladder (never a crash loop): quarantine the
            # corrupt artifact, truncate the chain to the good prefix,
            # fold a peer re-assert of the lost banks when federated,
            # and owe a fresh full base at the next barrier.
            chain_state = self._repair_chain(exc)
            if chain_state is None:
                return False
            repaired = True
        bits = chain_state["bits"]
        regs = chain_state["regs"]
        counts = chain_state["counts"]
        bank_of_raw = chain_state["bank_of"]
        events = chain_state["events"]
        applied = chain_state["applied"]
        # Rebuild the bank allocator BEFORE pushing state to the
        # device: holes left by temporal-ring evictions become the
        # free list, and their restored rows must be ZEROED here — an
        # evicted bucket's device row was zeroed live but its dirty
        # mark was discarded with it, so the chain still holds the
        # dead bucket's registers; re-allocating such a hole without
        # this zero would scatter-max new keys onto stale state and
        # overcount (caught by review; covered by
        # test_restored_free_bank_reallocates_clean).
        used = set(int(b) for b in bank_of_raw.values())
        next_bank = (max(used) + 1) if used else 0
        free_banks = sorted(set(range(next_bank)) - used)
        if free_banks:
            regs = np.array(regs, dtype=np.uint8)
            regs[np.asarray(free_banks, np.int64)] = 0
        if self.sharded:
            self.engine.set_state(bits, regs)
            self.engine.set_counts(counts)
        else:
            self.state = self.state._replace(
                bloom_bits=jax.numpy.asarray(bits),
                hll_regs=jax.numpy.asarray(regs),
                counts=jax.numpy.asarray(counts))
            # The snapshot may hold more banks than this construction
            # (growth before the crash): re-derive the wire dtype and
            # step program from the RESTORED bank count, or bank ids
            # above the old sentinel would narrow-cast into the wrong
            # banks.
            new_dtype = bank_wire_dtype(regs.shape[0])
            if new_dtype is not self._bank_dtype:
                self._bank_dtype = new_dtype
                self._step = make_jitted_step_bytes(
                    self.params, np.dtype(new_dtype).itemsize,
                    self.config.hll_precision)
        self._bank_of = {int(d): b for d, b in bank_of_raw.items()}
        self._next_bank = next_bank
        self._free_banks = free_banks
        if self._temporal is not None:
            self._temporal.restore(self._bank_of)
        self._day_base = None
        self._day_lut.fill(-1)
        self._bloom_host = np.asarray(bits)
        # Resume the delta chain where the restored manifest left it
        # (stale skipped entries dropped — the next manifest write
        # stops naming them): memory state now equals base + applied
        # deltas, so new deltas append. The sequence counter also
        # skips past crash-orphaned delta files (present on disk,
        # absent from the manifest) so a new delta never overwrites
        # one a concurrent post-mortem may read.
        self._snap_chain = applied
        self._snap_digests = dict(chain_state.get("digests", {}))
        self._base_digest = chain_state.get("base_digest", "")
        self._dirty_days.clear()
        self._regs_mirror = np.array(regs, dtype=np.uint8, copy=True)
        self._publish_epoch(self._regs_mirror, counts,
                            bank_of=self._bank_of, events=events)
        self._events_restored = int(events)
        if self._fed is not None:
            # Takeover path: everything the dead peer made durable is
            # re-asserted to the aggregator under THIS (higher)
            # incarnation, and this worker's cumulative event counter
            # continues from the restored total (_events_total) —
            # frames the broker redelivers are processed (and counted)
            # exactly once on top of it, so the federation's
            # per-worker max-fold can never double-count a replay.
            self._fed.publish_full(
                self._bloom_host, self._regs_mirror, counts,
                dict(self._bank_of), int(events),
                roster_size=self._roster_size)
        if repaired:
            # The on-disk chain was truncated to the good prefix:
            # publish a manifest naming ONLY the survivors (readers
            # and scrub must stop tripping over the quarantined file)
            # and owe a fresh full base — the repaired in-memory state
            # is what that base persists. When the BASE itself was
            # quarantined there is nothing servable to name: leave the
            # manifest alone (manifest-without-base classifies as
            # corruption on a re-read, re-entering this ladder) and
            # let the fresh-base snapshot below publish both together.
            if (self._snap_dir / SKETCH_SNAPSHOT).exists():
                with self._snap_io_lock:
                    self._write_chain_manifest()
            self._base_stale = True
            self._writer_base_ok = False
        else:
            self._base_stale = False
            self._writer_base_ok = True
        self._delta_seq = max(
            (int(p.stem.split("-")[1])
             for p in self._snap_dir.glob("delta-*.npz")), default=0)
        segs_dir = self._snap_dir / EVENTS_SEGMENTS
        events_path = self._snap_dir / EVENTS_SNAPSHOT
        if hasattr(self.store, "load_segments") and segs_dir.is_dir():
            self._load_event_segments(segs_dir)
        elif events_path.exists():
            self.store.truncate()
            try:
                self.store.load(events_path)
            except Exception as exc:  # noqa: BLE001 — rot, classified
                from attendance_tpu.utils.integrity import (
                    quarantine_artifact)
                logger.error(
                    "events snapshot %s is unreadable (%s: %s) — "
                    "quarantining; its rows are lost locally "
                    "(detected, never silent)", events_path,
                    type(exc).__name__, exc)
                quarantine_artifact(events_path, reason="unreadable",
                                    detail=f"{type(exc).__name__}: "
                                    f"{exc}")
                self.store.truncate()
        if repaired:
            # Rebuild the clean chain NOW (step 3 of the ladder): a
            # fresh full base from the repaired state supersedes the
            # truncated chain, so readers/scrub see a verifying chain
            # immediately instead of waiting for the next barrier.
            # Safe post-restore: load_segments marked the restored
            # store blocks durable, so the base's save_segments call
            # writes nothing twice. A failed write (full disk mid-
            # repair) degrades to the normal owe-a-base path.
            try:
                self.snapshot()
            except Exception:
                logger.exception(
                    "fresh-base write after chain repair failed; the "
                    "next barrier retries a full base")
                self._base_stale = True
                self._writer_base_ok = False
        logger.info("Restored snapshot: %d events (%d deltas), "
                    "%d HLL banks%s", events, len(applied),
                    len(self._bank_of),
                    " [REPAIRED: corrupt artifact quarantined, fresh "
                    "base written]" if repaired else "")
        return True

    def _load_event_segments(self, segs_dir) -> None:
        """Classified event-segment restore: a rotted segment file is
        quarantined (the rows it carried are lost LOCALLY and loudly —
        the same detect-and-bound contract as spill-record rot; read-
        time dedup tolerates the gap) and the load retries over the
        survivors, instead of crashing restore with an opaque numpy
        error."""
        from attendance_tpu.utils.integrity import (
            quarantine_artifact, structural_npz_check)

        for attempt in range(2):
            self.store.truncate()
            try:
                if attempt == 0 and hasattr(self.store,
                                            "compact_segments"):
                    # Compact BEFORE loading (restore is the safe
                    # point — no writer is running yet): a long run's
                    # cadence segments merge into one on disk, and
                    # the load below then reads that single file
                    # instead of parsing every segment twice.
                    self.store.compact_segments(segs_dir)
                self.store.load_segments(segs_dir)
                return
            except Exception as exc:  # noqa: BLE001 — classify rot
                bad = [p for p in sorted(
                    Path(segs_dir).glob("segment-*.npz"))
                    if structural_npz_check(p) is not None]
                if not bad or attempt:
                    raise
                logger.error(
                    "event segment(s) %s failed structural "
                    "verification (%s: %s) — quarantining; their "
                    "rows are lost locally (detected, never silent)",
                    [p.name for p in bad], type(exc).__name__, exc)
                for p in bad:
                    quarantine_artifact(
                        p, reason="unreadable",
                        detail="event segment failed the zip-CRC "
                               "structural check")

    def _repair_chain(self, exc):
        """The detection->repair ladder for a corrupt snapshot chain
        (called by restore when read_chain_state classifies rot):

        1. **local quarantine** — the corrupt artifact moves into
           ``integrity-quarantine/`` with a sidecar naming why, and
           the chain is re-read truncated to the good prefix (a torn
           CHAIN.json degrades to base-only; a corrupt BASE leaves no
           local state at all);
        2. **peer re-assert** — under federation the aggregator's
           retained per-worker CRDT view already holds the banks the
           lost deltas carried (they were gossiped at their fences):
           request a full-state re-assert frame and fold it on top of
           the surviving local state;
        3. **fresh base** — restore's caller owes a full base at the
           next barrier, superseding the truncated chain.

        Returns a ``read_chain_state``-shaped dict, or None when no
        state is recoverable (corrupt base, no peer) — the caller
        starts empty, loudly, with the quarantined bytes preserved
        for triage instead of crash-looping on them."""
        from attendance_tpu.utils.integrity import (
            ChainIntegrityError, count_corrupt, file_digest,
            quarantine_artifact)

        state = None
        base_lost = False
        for _attempt in range(4):
            kind, path = exc.kind, exc.path
            logger.error(
                "snapshot chain at %s is corrupt (%s at %s)%s — "
                "quarantining and repairing", self._snap_dir, kind,
                path.name, f": {exc.detail}" if exc.detail else "")
            if quarantine_artifact(
                    path, reason=kind, detail=exc.detail,
                    expected_digest=getattr(exc, "expected",
                                            "")) is None:
                # Nothing on disk to move (the "missing" class):
                # still count it — the doctor/SLO alert surface must
                # see every detected corruption, not just the movable
                # ones.
                count_corrupt(kind)
            stop = None
            if path.name == SKETCH_SNAPSHOT:
                base_lost = True
            elif path.name != CHAIN_MANIFEST:
                stop = path.name
            if base_lost:
                break
            try:
                state = read_chain_state(
                    self._snap_dir, expect_m_bits=self.params.m_bits,
                    expect_precision=self.config.hll_precision,
                    stop_before=stop)
                break
            except ChainIntegrityError as exc2:
                exc = exc2
                continue
            except FileNotFoundError:
                break
        if state is not None and self._integrity \
                and not state.get("base_digest"):
            # A torn manifest took the recorded digests with it; the
            # base just parsed clean, so re-record its digest for the
            # truncated manifest the caller republishes.
            state["base_digest"] = file_digest(
                self._snap_dir / SKETCH_SNAPSHOT)
        reassert = None
        folded = False
        if self._fed is not None:
            reassert = self._fed.request_reassert()
        if reassert is not None:
            state, folded = self._fold_reassert_state(state, reassert)
        if folded:
            self._count_repair("peer")
        elif state is not None:
            self._count_repair("local")
            logger.warning(
                "chain repaired LOCALLY only (no federation peer to "
                "re-assert from): state truncated at the corrupt "
                "artifact — events acked into the lost suffix are "
                "not locally recoverable")
        else:
            logger.error(
                "chain at %s is unrepairable locally (base corrupt) "
                "and no peer re-assert is available — starting EMPTY; "
                "the corrupt bytes are preserved under "
                "integrity-quarantine/ for triage", self._snap_dir)
        return state

    def _count_repair(self, source: str) -> None:
        if self._obs is not None:
            self._obs.registry.counter(
                "attendance_chain_repairs_total",
                help="Corrupt-chain repairs (local truncation or "
                     "peer-assisted re-assert)", source=source).inc()

    def _fold_reassert_state(self, state, frame):
        """Fold a peer re-assert full frame (the aggregator's retained
        view of THIS worker's own contribution) over the surviving
        local chain state; builds the state from scratch when the
        base itself was lost. CRDT joins (Bloom-OR / register-max /
        counter-max) make the fold safe regardless of how much the
        local prefix and the re-assert overlap. Returns
        ``(state, folded)`` — folded=False means the frame was refused
        (geometry mismatch / unusable) and the caller must account
        the repair as local-only, not peer-assisted."""
        from attendance_tpu.federation.merge import encode_counts
        from attendance_tpu.models.bloom import bloom_or_words_np
        from attendance_tpu.models.fused import decode_counts

        if int(frame.m_bits) and \
                int(frame.m_bits) != self.params.m_bits:
            logger.error(
                "peer re-assert gossips a %s-bit filter, this worker "
                "runs %s bits — refusing the repair frame",
                frame.m_bits, self.params.m_bits)
            return state, False
        if int(frame.precision) != self.config.hll_precision:
            logger.error(
                "peer re-assert gossips precision %s, this worker "
                "runs %s — refusing the repair frame",
                frame.precision, self.config.hll_precision)
            return state, False

        f_regs = np.asarray(frame.arrays.get(
            "regs", np.zeros((0, 1 << self.config.hll_precision),
                             np.uint8)), np.uint8)
        f_counts = frame.arrays.get("counts")
        f_bloom = frame.arrays.get("bloom")
        if state is None:
            if f_bloom is None:
                logger.error("peer re-assert carries no Bloom words; "
                             "cannot rebuild a lost base from it")
                return None, False
            manifest = {
                "bank_of": {str(d): int(b)
                            for d, b in frame.bank_of.items()},
                "m_bits": self.params.m_bits, "k": self.params.k,
                "precision": self.config.hll_precision,
                "events": int(frame.events),
                "chain_seq": self._delta_seq,
            }
            state = dict(
                bits=np.asarray(f_bloom, np.uint32),
                regs=f_regs.copy(),
                counts=(np.asarray(f_counts, np.uint32)
                        if f_counts is not None
                        else np.zeros((2, 2), np.uint32)),
                bank_of={str(d): int(b)
                         for d, b in frame.bank_of.items()},
                events=int(frame.events), applied=[],
                manifest=manifest, base_digest="", digests={})
            logger.warning("rebuilt lost base entirely from the peer "
                           "re-assert (%d events, %d banks)",
                           state["events"], len(frame.bank_of))
            return state, True
        if f_bloom is not None:
            state["bits"] = bloom_or_words_np(
                np.asarray(state["bits"], np.uint32),
                np.asarray(f_bloom, np.uint32))
        bank_of = {int(d): int(b)
                   for d, b in state["bank_of"].items()}
        regs = np.asarray(state["regs"], np.uint8)
        for day, fb in frame.bank_of.items():
            if fb >= f_regs.shape[0]:
                continue
            row = f_regs[fb]
            sb = bank_of.get(int(day))
            if sb is None:
                sb = len(bank_of)
                if sb >= regs.shape[0]:
                    grown = np.zeros((max(sb + 1, regs.shape[0] * 2),
                                      regs.shape[1]), np.uint8)
                    grown[:regs.shape[0]] = regs
                    regs = grown
                bank_of[int(day)] = sb
                regs[sb] = row
            else:
                regs[sb] = np.maximum(regs[sb], row)
        state["regs"] = regs
        state["bank_of"] = {str(d): b for d, b in bank_of.items()}
        lv, li = decode_counts(np.asarray(state["counts"]))
        fv, fi = (decode_counts(np.asarray(f_counts))
                  if f_counts is not None else (0, 0))
        state["counts"] = encode_counts(max(lv, fv), max(li, fi))
        state["events"] = max(int(state["events"]), int(frame.events))
        logger.warning(
            "folded peer re-assert over the truncated chain: events "
            "%d, %d banks (lost deltas recovered from the "
            "aggregator's retained view)", state["events"],
            len(bank_of))
        return state, True

    def _checkpoint_and_ack(self) -> None:
        """Barrier: materialize all in-flight outputs, make them
        durable, then ack — every acknowledged frame is durably in the
        snapshot chain. The single-chip path routes through the async
        writer (delta capture + flush); the mesh path stays in the
        main thread because its state gathers contain collectives,
        but in delta mode it gathers only the dirty banks."""
        for _, valid, _ in self._inflight:
            if valid is not None:
                jax.block_until_ready(valid)
        if not self.sharded:
            self._checkpoint_async(force=True)  # acks when durable
            self._flush_snapshots()
            self._retire_spilled()
            return
        if self._snap_mode == "delta" and not self._base_stale:
            self._snapshot_sync_delta()
        else:
            self.snapshot()
        acknowledge_all(self.consumer,
                        [m for m, _, _ in self._inflight])
        self._inflight.clear()
        self._retire_spilled()

    def _snapshot_sync_delta(self) -> None:
        """Mesh-path incremental barrier: merge + gather ONLY the
        dirty banks' register rows on device (one small D2H instead of
        the full state), then write the delta synchronously. Gathers
        run on EVERY process (collectives); only process 0 writes."""
        self._flush_snapshots()
        banks = self._drain_dirty_banks()
        rows = self.engine.get_state_rows(
            self._pad_bank_index(banks))[:len(banks)]
        counts = self.engine.get_counts()
        self._count_xfer("snapshot_capture", "d2h",
                         np.asarray(rows).nbytes)
        self._batches_at_snap = self.metrics.batches
        if jax.process_count() > 1 and jax.process_index() != 0:
            return
        with self._snap_io_lock:
            nbytes = self._write_delta_files(
                banks, rows, counts, dict(self._bank_of),
                self._events_total, self.engine.num_banks, upto=None)
        self._post_delta_bookkeeping(banks, rows, nbytes, counts,
                                     dict(self._bank_of),
                                     self._events_total,
                                     self.engine.num_banks)

    # -- ingress-spill draining (control plane) -----------------------------
    def _drain_admission(self, limit: int = 16) -> int:
        """Replay up to ``limit`` admission-spilled frames through the
        normal frame path (dispatch thread only). Files are queued for
        retirement at the next snapshot barrier — crash in between
        re-adopts them next run (at-least-once, the same contract
        broker redelivery imposes)."""
        adm = self._admission
        if adm is None:
            return 0
        batch = adm.drain_batch(limit)
        for path, payload in batch:
            try:
                self.process_frame(payload)
            except Exception:
                # A frame that poisons on replay poisons forever: park
                # it aside (same quarantine posture as handle_poison)
                # rather than livelock the drain.
                logger.exception("Bad spilled frame %s", path)
                try:
                    path.rename(path.with_suffix(".poison"))
                except OSError:
                    pass
                continue
            self._admission_retire.append(path)
        if not self.checkpointing and self._admission_retire:
            # No barriers in this mode: processed is as durable as the
            # pipeline ever gets, so retire immediately.
            adm.retire(self._admission_retire)
            self._admission_retire.clear()
        return len(batch)

    def _retire_spilled(self) -> None:
        """Delete ingress-spill files whose replayed events the barrier
        that just completed now covers (durability handoff:
        spill file -> snapshot chain)."""
        if self._admission is not None and self._admission_retire:
            self._admission.retire(self._admission_retire)
            self._admission_retire.clear()

    # -- ack draining -------------------------------------------------------
    def _drain_inflight(self, block: int = 0) -> None:
        """Ack completed in-flight frames in dispatch order.

        ``block`` is how many not-yet-ready head entries to wait for
        (-1 = all).  On depth overflow the hot loop passes 1 — freeing
        exactly one slot instead of collapsing the whole host/device
        overlap with a full pipeline sync. With checkpointing on, acks
        only ever happen at snapshot barriers (_checkpoint_and_ack).
        """
        if self.checkpointing:
            return
        while self._inflight:
            msg, valid, span = self._inflight[0]
            if valid is not None:
                try:
                    ready = valid.is_ready()
                except AttributeError:  # non-jax array (empty frame)
                    ready = True
                if not ready:
                    if block == 0:
                        break
                    if block > 0:
                        # The hot loop is stalled on a full deque: the
                        # device/link side is definitively behind. This
                        # is _auto_wire's climb signal — instantaneous
                        # deque depth oscillates under the tunnel's
                        # bursty completion and washes out.
                        self._drain_waited = True
                    if self._obs is None:
                        jax.block_until_ready(valid)
                    else:
                        st = self._stage_mark
                        prev_stage = (st.set("device_wait")
                                      if st is not None else None)
                        t_w = time.perf_counter()
                        jax.block_until_ready(valid)
                        t_done = time.perf_counter()
                        if st is not None:
                            st.restore(prev_stage)
                        self._h_device.observe(t_done - t_w)
                        self._busy["device_wait"] += t_done - t_w
                        self._dw_accum += t_done - t_w
                        if self._tracer is not None and span is not None:
                            # device_wait lands AFTER its batch span
                            # closed (pipelining) — committed with
                            # explicit timestamps under the same trace.
                            self._tracer.add_span(
                                "device_wait", t_w, t_done,
                                trace_id=span.trace_id,
                                parent_id=span.span_id,
                                role=self._TRACE_ROLE)
                    if block > 0:
                        block -= 1
            self.consumer.acknowledge(msg)
            self._inflight.popleft()

    def run(self, max_events: Optional[int] = None,
            idle_timeout_s: float = 1.0) -> None:
        t_start = time.perf_counter()
        # The busy-fraction gauges describe the CURRENT run: reset the
        # split so an idle gap between runs doesn't dilute it. The
        # dispatch-gap cursor resets for the same reason — the first
        # frame of a later run must not record the whole inter-run
        # idle as one giant "gap", which would own the p99 forever.
        self._busy_anchor = t_start
        for k in self._busy:
            self._busy[k] = 0.0
        self._last_dispatch_t = 0.0
        self._last_dequeue_s = 0.0
        idle_since = time.monotonic()
        try:
            with maybe_trace(self.config.profile_dir):
                self._run_loop(max_events, idle_timeout_s, idle_since)
        except Exception:
            # The crash forensics surface: the ring holds the last N
            # per-batch records leading up to this exception.
            if self._obs is not None:
                self._obs.dump_flight("run-loop-exception")
            raise
        if self._admission is not None and self._admission.pending_count:
            # Every spilled frame was ACKED against its spill file's
            # durability — it must reach the sketch state (and the
            # final snapshot barrier below) before this run ends.
            while self._drain_admission(limit=64):
                pass
        if self._temporal is not None:
            # End of run: release the reorder buffer, rotate final
            # buckets, fold the staged CMS estimates. Before the
            # final barrier so a rotation's eviction bookkeeping
            # lands in the last manifest.
            self._temporal.flush()
        if self.checkpointing:
            if self._inflight or self._admission_retire:
                # Replayed spill frames force a barrier even with no
                # broker in-flight: their files may only retire once a
                # snapshot covers their events.
                self._checkpoint_and_ack()  # flushes the writer first
            else:
                self._flush_snapshots()  # acks from the last barrier
        self._drain_inflight(block=-1)
        self.metrics.wall_seconds = time.perf_counter() - t_start
        # NO device->host reads here: on this platform a single D2H of
        # the donated-chain state (even 8 bytes of counters) permanently
        # collapses async dispatch throughput ~50x for the rest of the
        # process. Validity totals live on device (state.counts) and are
        # fetched on demand via validity_counts(); the FPR estimate is
        # likewise deferred to callers that want it after their last
        # run. The metrics line defers both.
        if logger.isEnabledFor(logging.INFO):
            logger.info("Fused metrics: %s",
                        self.metrics.summary(None,
                                             include_validity=False))
        if getattr(self.config, "metrics_json", ""):
            # estimated_fpr stays None: computing it forces the D2H
            # read the platform note above forbids mid-process.
            self.metrics.write_json_line(self.config.metrics_json,
                                         fpr_is_lower_bound=True)
        if self._obs is not None:
            # One last SLO classification before the trace flush: a
            # run shorter than the engine's tick interval must still
            # judge its objectives (and log any firing alert).
            self._obs.finalize_slo("run-end")
            self._obs.flush_trace("run-end")
            self._obs.flush_profile("run-end")
            if self._recomp is not None:
                # Steady-state contract: warmup compiles end with the
                # first completed run loop — any NEW shape fingerprint
                # after this is a recompile leak doctor's
                # --recompile-ceiling gates at 0.
                self._recomp.mark_warm()

    def _begin_batch_span(self, msg, t_rx: float, t_got: float):
        """Per-batch span continuing the propagated trace; redelivered
        frames become ``retry`` siblings under the original publish
        span (Tracer.begin_consume holds the one definition both
        processors share)."""
        from attendance_tpu.transport import redelivery_count

        props = (msg.properties() if hasattr(msg, "properties")
                 else None)
        return self._tracer.begin_consume(
            props, redelivery_count(msg), role=self._TRACE_ROLE,
            start=t_rx, got=t_got, wait_name="dequeue_wait",
            args={"bytes": len(msg.data())})

    def _run_loop(self, max_events: Optional[int],
                  idle_timeout_s: float, idle_since: float) -> None:
        st = self._stage_mark
        while True:
            try:
                if st is not None:
                    st.set("dequeue")
                if self._obs is None:
                    msg = self.consumer.receive(timeout_millis=50)
                else:
                    t_rx = time.perf_counter()
                    msg = self.consumer.receive(timeout_millis=50)
                    t_got = time.perf_counter()
                    self._h_dequeue.observe(t_got - t_rx)
                    self._last_dequeue_s = t_got - t_rx
            except ReceiveTimeout:
                if self._temporal is not None:
                    # Watermark idle advancement: a silent stream
                    # must not pin the reorder buffer / final buckets
                    # open forever (--watermark-idle-s).
                    self._temporal.maybe_idle_flush()
                if self.checkpointing and self._inflight:
                    self._checkpoint_and_ack()
                self._drain_inflight(block=-1)
                if (self._admission is not None
                        and not self._admission.active
                        and self._admission.pending_count):
                    # Pressure cleared with frames parked in the
                    # ingress spill: replay them on THIS thread
                    # (process_frame is dispatch-thread-only). Their
                    # files retire at the next snapshot barrier.
                    if self._drain_admission(limit=16):
                        idle_since = time.monotonic()  # progress
                    if (self.checkpointing
                            and self.metrics.batches
                            - self._batches_at_snap >= self._snap_every):
                        self._checkpoint_and_ack()
                    continue
                if time.monotonic() - idle_since > idle_timeout_s:
                    break
                continue
            idle_since = time.monotonic()
            adm = self._admission
            if adm is not None and adm.active:
                # Admission control (control plane, shed rung): the
                # producer-facing edge. "spill" wrote the raw frame
                # durably (checksummed + fsync'd) — that durability is
                # what justifies the ack; "shed" nacks, so the broker's
                # retention is the backpressure. Either way the frame
                # skips decode/dispatch entirely: under pressure the
                # snapshot cadence (and with it read staleness) holds
                # instead of collapsing.
                decision = adm.admit(msg.data())
                if decision == "spill":
                    self.consumer.acknowledge(msg)
                    continue
                if decision == "shed":
                    self.consumer.negative_acknowledge(msg)
                    continue
                # "pass": the controller re-opened between the check
                # and the admit — process normally.
            span = (self._begin_batch_span(msg, t_rx, t_got)
                    if self._tracer is not None else None)
            try:
                if span is None:
                    valid = self.process_frame(msg.data())
                else:
                    # Activate: stage spans (decode/dispatch, sharded
                    # replica spans) nest under the batch span; the
                    # profiler annotation carries the trace_id into any
                    # concurrent jax.profiler trace (correlation).
                    with self._tracer.activate(span), annotate_trace(
                            self._profiling, span):
                        valid = self.process_frame(msg.data())
            except Exception:
                # Bounded retry, then dead-letter: an undecodable frame
                # nacked forever livelocks the subscription (the broker
                # redelivers immediately and receive() never times out).
                if span is not None:
                    self._tracer.end_span(span, error=True)
                logger.exception("Bad frame")
                handle_poison(msg, self.consumer, self.metrics,
                              self.config, logger,
                              tracker=self._poison)
                continue
            if span is not None:
                self._tracer.end_span(span)
            self._inflight.append((msg, valid, span))
            if self.checkpointing:
                # Barrier on processed-batch cadence, and also on raw
                # in-flight depth: empty frames never bump
                # metrics.batches, and the deque (which holds message
                # bodies) must stay bounded regardless of cadence.
                depth_forced = (len(self._inflight)
                                >= max(_INFLIGHT_DEPTH, self._snap_every))
                if (self.metrics.batches - self._batches_at_snap
                        >= self._snap_every or depth_forced):
                    if self.sharded:
                        # Mesh path stays synchronous: the state gather
                        # contains collectives, which must never run
                        # from a background thread racing the hot
                        # loop's own collectives.
                        self._checkpoint_and_ack()
                    else:
                        self._checkpoint_async(force=depth_forced)
            else:
                self._drain_inflight(
                    block=1 if len(self._inflight) >= _INFLIGHT_DEPTH
                    else 0)
            if max_events is not None and self.metrics.events >= max_events:
                break

    # -- queries ------------------------------------------------------------
    def lecture_days(self):
        """Sorted lecture days with an HLL bank (the countable keys;
        temporal bucket keys live in the same map but are served by
        the window verbs, not the day surface)."""
        from attendance_tpu.temporal.buckets import is_bucket_key
        return sorted(d for d in self._bank_of if not is_bucket_key(d))

    def validity_counts(self) -> Optional[tuple]:
        """(valid, invalid) totals accumulated on device since
        construction (single-chip and sharded — the mesh keeps
        per-replica two-lane counters summed at read).

        Forces a device sync AND (platform caveat) a D2H read that can
        permanently degrade async dispatch on relay-tunneled devices —
        call it after the LAST run of the process, never mid-stream.
        """
        if self.sharded:
            return self.engine.validity_counts()
        from attendance_tpu.models.fused import decode_counts
        return decode_counts(self.state.counts)

    def estimated_fpr(self) -> float:
        """Occupancy-based FPR estimate of the roster filter: fill^k
        (slight underestimate for the blocked layout, whose per-block
        fill variance adds a small penalty — the layout's sizing already
        compensates by deriving from error_rate/2)."""
        from attendance_tpu.models.bloom import bloom_packed_fill_fraction

        if self.sharded:
            # Device-side popcount + psum: one scalar D2H instead of
            # the whole filter (~14MB at a 10M roster) on a platform
            # where D2H volume is the expensive resource.
            fill = self.engine.fill_fraction()
        else:
            fill = float(bloom_packed_fill_fraction(self.state.bloom_bits))
        return fill ** self.params.k

    @staticmethod
    def _resolve_day(lecture) -> int:
        """One key space for the query surface (VERDICT r03 weak #7):
        accept the reference-style ``"LECTURE_YYYYMMDD"`` string
        (reference attendance_processor.py:149-165) alongside the
        fused path's native lecture-day int — both processors answer
        the same query shape identically."""
        if isinstance(lecture, str):
            from attendance_tpu.pipeline.events import _lecture_to_day
            return _lecture_to_day(lecture)
        return int(lecture)

    def get_attendance_stats(self, lecture_day) -> Dict:
        """PFCOUNT + partition scan for one lecture day — the fused-path
        analogue of the reference's stats query (reference
        attendance_processor.py:149-165): HLL unique attendees plus the
        stored records of that partition. ``lecture_day`` is an int day
        or a reference-style ``"LECTURE_YYYYMMDD"`` id."""
        day = self._resolve_day(lecture_day)
        records = self.store.scan_lecture(day)
        return {
            "unique_attendees": self.count(day),
            "attendance_records": records,
            "num_records": len(records["student_id"]),
        }

    def count(self, lecture_day) -> int:
        bank = self._bank_of.get(self._resolve_day(lecture_day))
        if bank is None:
            return 0
        if self.sharded:
            return self.engine.count(bank)
        hist = np.asarray(best_histogram(
            self.state.hll_regs[bank:bank + 1],
            self.config.hll_precision))[0]
        return int(round(estimate_from_histogram(
            hist, self.config.hll_precision)))

    def count_all(self) -> Dict[int, int]:
        """PFCOUNT of every registered lecture day in ONE device pass
        (one histogram over all banks instead of a dispatch per day) —
        the batch counterpart of :meth:`count`, matching the sharded
        engine's count_all."""
        from attendance_tpu.temporal.buckets import is_bucket_key
        days = {d: b for d, b in self._bank_of.items()
                if not is_bucket_key(d)}
        if not days:
            return {}
        if self.sharded:
            ests = self.engine.count_all()
            return {day: int(ests[bank]) for day, bank in days.items()}
        hists = np.asarray(best_histogram(self.state.hll_regs,
                                          self.config.hll_precision))
        return {day: int(round(estimate_from_histogram(
            hists[bank], self.config.hll_precision)))
            for day, bank in days.items()}

    def cleanup(self) -> None:
        # Wait out any in-flight background snapshot before closing the
        # transport it would ack through (the write itself is already
        # durable either way; this just keeps the acks clean), then
        # shut the writer thread down.
        if self.query_server is not None:
            self.query_server.stop()
            if (self._obs is not None
                    and getattr(self._obs, "_server", None) is not None):
                from attendance_tpu.serve import http as serve_http
                serve_http.detach(self._obs._server)
        self._flush_snapshots()
        self._stop_snap_writer()
        if self._fed is not None:
            # After the writer drained: the last fence's gossip frame
            # is published before the producer closes.
            self._fed.close()
            self._fed = None
        if hasattr(self.consumer, "lanes"):
            # Striped ingress: stop the lane workers (and their owned
            # sessions) before the client sweep below.
            self.consumer.close()
        self.client.close()
        self.store.close()
