"""Event schema + wire codecs (JSON parity path and fast binary path).

Schema ground truth is the reference generator's emitted dicts
(reference data_generator.py:112-118,126-132,142-148):
``{student_id:int, timestamp:iso-str, lecture_id:"LECTURE_YYYYMMDD",
is_valid:bool, event_type:"entry"|"exit"}`` — NOT the README's divergent
schema (SURVEY.md §0.3 item 1).

Two codecs:
  * JSON — byte-compatible with the reference's ``json.dumps(...).encode()``
    producer frames; the parity ingress.
  * Binary — fixed 20-byte little-endian records decoded with one
    ``np.frombuffer`` per batch. At the north-star rate (50M ev/s)
    per-event ``json.loads`` on the host is the bottleneck (SURVEY.md §7
    hard part d); the binary path turns a batch of frames into the four
    column arrays the device kernels consume with zero per-event Python.
"""

from __future__ import annotations

import json
import struct
from dataclasses import dataclass
from datetime import datetime, timedelta, timezone
from typing import Dict, List, Sequence, Tuple

import numpy as np

EVENT_ENTRY = 0
EVENT_EXIT = 1
_EVENT_NAMES = ("entry", "exit")

# Binary record layout (20 bytes): u32 student_id | u32 lecture_yyyymmdd |
# i64 unix_micros | u8 flags | 3 pad. A numpy structured dtype so a whole
# frame decodes with a single np.frombuffer.
BINARY_DTYPE = np.dtype([
    ("student_id", "<u4"),
    ("lecture_day", "<u4"),   # yyyymmdd as an integer
    ("micros", "<i8"),        # unix epoch microseconds
    ("flags", "<u1"),         # bit0 = is_valid, bit1 = event_type(exit)
    ("pad", "V3"),
])
assert BINARY_DTYPE.itemsize == 20

BINARY_MAGIC = b"ATB1"  # frame prefix distinguishing binary from JSON ('{')


def magic_match(data, magic: bytes) -> bool:
    """``data.startswith(magic)`` for ANY buffer type: the shm ring
    transport hands out zero-copy memoryviews over the mapped slots,
    which have no ``startswith`` — and converting a whole multi-MB
    frame to bytes just to sniff four magic bytes would defeat the
    zero-copy contract.  Slicing a memoryview is O(magic)."""
    head = data[:len(magic)]
    return (head if isinstance(head, bytes) else bytes(head)) == magic


@dataclass
class AttendanceEvent:
    student_id: int
    timestamp: str  # ISO-8601, as the reference emits
    lecture_id: str
    is_valid: bool
    event_type: str

    def to_dict(self) -> Dict:
        return {
            "student_id": self.student_id,
            "timestamp": self.timestamp,
            "lecture_id": self.lecture_id,
            "is_valid": self.is_valid,
            "event_type": self.event_type,
        }


def encode_event(event: AttendanceEvent) -> bytes:
    """The reference's wire format: json.dumps(dict).encode('utf-8')."""
    return json.dumps(event.to_dict()).encode("utf-8")


def decode_event(data: bytes) -> AttendanceEvent:
    d = json.loads(data.decode("utf-8"))
    return AttendanceEvent(
        student_id=int(d["student_id"]),
        timestamp=str(d["timestamp"]),
        lecture_id=str(d["lecture_id"]),
        is_valid=bool(d.get("is_valid", True)),
        event_type=str(d.get("event_type", "entry")),
    )


def decode_event_batch(frames: Sequence[bytes]) -> List[AttendanceEvent]:
    return [decode_event(f) for f in frames]


# ---------------------------------------------------------------------------
# Binary fast path
# ---------------------------------------------------------------------------

_EPOCH = datetime(1970, 1, 1, tzinfo=timezone.utc)


def _iso_to_micros(ts: str) -> int:
    # Naive timestamps are pinned to UTC so micros is a pure function of
    # the wall-clock string: `(micros // 3_600e6) % 24` recovers the hour
    # written in the event on any host timezone, keeping the columnar
    # analytics path in agreement with the row path (which parses the
    # string directly). Integer timedelta division, NOT
    # int(dt.timestamp() * 1e6): the float product truncates ~1% of
    # fractional timestamps one microsecond low, which would diverge
    # from the native scanner's exact arithmetic (hostpipe.c
    # parse_iso_micros) and break replay/dedup equality across paths.
    try:
        dt = datetime.fromisoformat(ts)
    except ValueError:
        # Python < 3.11 fromisoformat accepts only 3- or 6-digit
        # fractions and no 'Z' suffix, while the event wire allows any
        # fraction width (hostpipe.c parse_iso_micros). Normalize:
        # Z -> +00:00, fraction padded/truncated to exactly 6 digits
        # (pure decimal shift — same integer micros as the native
        # scanner's exact arithmetic).
        norm = ts[:-1] + "+00:00" if ts.endswith("Z") else ts
        i = norm.find(".")
        if i != -1:
            j = i + 1
            while j < len(norm) and norm[j].isdigit():
                j += 1
            frac = norm[i + 1:j][:6].ljust(6, "0")
            norm = norm[:i + 1] + frac + norm[j:]
        dt = datetime.fromisoformat(norm)
    if dt.tzinfo is None:
        dt = dt.replace(tzinfo=timezone.utc)
    return (dt - _EPOCH) // timedelta(microseconds=1)


_HASH_DAY_BASE = 100_000_000           # above any yyyymmdd calendar value
_HASH_DAY_LIMIT = _HASH_DAY_BASE + (1 << 26)


def _lecture_to_day(lecture_id: str) -> int:
    # "LECTURE_YYYYMMDD" -> yyyymmdd; non-conforming ids hash to a stable
    # bucket above any calendar value so they stay distinct from real
    # days. murmur3 (not builtin hash) so the mapping survives process
    # restarts — PYTHONHASHSEED salts str hashes per interpreter.
    tail = lecture_id.rsplit("_", 1)[-1]
    if tail.isdigit():
        if len(tail) == 8:
            return int(tail)
        # Round-trip of an already-hashed code: stores re-emit hashed
        # days as "LECTURE_<9-digit-code>" (columnar_store
        # .distinct_lecture_ids); parsing that back must return the
        # code itself, not hash the synthetic string to a new bucket.
        if len(tail) == 9 and _HASH_DAY_BASE <= int(tail) < _HASH_DAY_LIMIT:
            return int(tail)
    from attendance_tpu.ops.murmur3 import murmur3_bytes
    return _HASH_DAY_BASE + (murmur3_bytes(lecture_id.encode(), 0)
                             & 0x3FFFFFF)


def encode_event_binary(event: AttendanceEvent) -> bytes:
    rec = np.zeros(1, dtype=BINARY_DTYPE)
    rec["student_id"] = event.student_id & 0xFFFFFFFF
    rec["lecture_day"] = _lecture_to_day(event.lecture_id)
    rec["micros"] = _iso_to_micros(event.timestamp)
    flags = (1 if event.is_valid else 0)
    if event.event_type == "exit":
        flags |= 2
    rec["flags"] = flags
    return BINARY_MAGIC + rec.tobytes()


def encode_binary_batch(events: Sequence[AttendanceEvent]) -> bytes:
    """One frame holding N records (bulk transport for the bench path)."""
    rec = np.zeros(len(events), dtype=BINARY_DTYPE)
    for i, e in enumerate(events):
        rec["student_id"][i] = e.student_id & 0xFFFFFFFF
        rec["lecture_day"][i] = _lecture_to_day(e.lecture_id)
        rec["micros"][i] = _iso_to_micros(e.timestamp)
        rec["flags"][i] = ((1 if e.is_valid else 0)
                           | (2 if e.event_type == "exit" else 0))
    return BINARY_MAGIC + rec.tobytes()


def decode_binary_batch(data: bytes,
                        include_truth: bool = True) -> Dict[str, np.ndarray]:
    """Zero-copy columnar decode of one binary frame -> column arrays.

    Accepts both the interleaved record format (ATB1) and the planar
    format (ATB2); prefer planar on the hot path — its column views are
    contiguous, so the device transfer needs no host gather/copy first.

    include_truth=False skips materializing the generator's embedded
    ``is_valid`` ground-truth column (the processor recomputes validity
    and discards it, reference attendance_processor.py:109-113 — no
    point allocating it per frame on the hot path).
    """
    if magic_match(data, PLANAR_MAGIC):
        return decode_planar_batch(data, include_truth)
    if not magic_match(data, BINARY_MAGIC):
        raise ValueError("not a binary event frame")
    rec = np.frombuffer(data, dtype=BINARY_DTYPE, offset=len(BINARY_MAGIC))
    cols = {
        "student_id": rec["student_id"],
        "lecture_day": rec["lecture_day"],
        "micros": rec["micros"],
        "event_type": ((rec["flags"] >> 1) & 1).astype(np.int8),
    }
    if include_truth:
        cols["is_valid"] = (rec["flags"] & 1).astype(bool)
    return cols


# ---------------------------------------------------------------------------
# Planar binary format: contiguous column blocks, zero-copy views
# ---------------------------------------------------------------------------

PLANAR_MAGIC = b"ATB2"
# layout: magic | u32 n | student_id u32[n] | lecture_day u32[n]
#         | micros i64[n] | flags u8[n]


def encode_planar_batch(cols: Dict[str, np.ndarray]) -> bytes:
    """Pack column arrays into one planar frame (the hot-path producer)."""
    n = len(cols["student_id"])
    flags = (np.asarray(cols["is_valid"]).astype(np.uint8)
             | (np.asarray(cols["event_type"]).astype(np.uint8) << 1))
    parts = [PLANAR_MAGIC, np.uint32(n).tobytes(),
             np.ascontiguousarray(cols["student_id"],
                                  dtype=np.uint32).tobytes(),
             np.ascontiguousarray(cols["lecture_day"],
                                  dtype=np.uint32).tobytes(),
             np.ascontiguousarray(cols["micros"],
                                  dtype=np.int64).tobytes(),
             flags.tobytes()]
    return b"".join(parts)


def decode_planar_batch(data: bytes,
                        include_truth: bool = True) -> Dict[str, np.ndarray]:
    """Zero-copy decode: every column is a contiguous buffer view."""
    if not magic_match(data, PLANAR_MAGIC):
        raise ValueError("not a planar event frame")
    off = len(PLANAR_MAGIC)
    (n,) = np.frombuffer(data, np.uint32, count=1, offset=off)
    n = int(n)
    off += 4
    student = np.frombuffer(data, np.uint32, count=n, offset=off)
    off += 4 * n
    day = np.frombuffer(data, np.uint32, count=n, offset=off)
    off += 4 * n
    micros = np.frombuffer(data, np.int64, count=n, offset=off)
    off += 8 * n
    flags = np.frombuffer(data, np.uint8, count=n, offset=off)
    cols = {
        "student_id": student,
        "lecture_day": day,
        "micros": micros,
        "event_type": ((flags >> 1) & 1).astype(np.int8),
    }
    if include_truth:
        cols["is_valid"] = (flags & 1).astype(bool)
    return cols


def decode_json_batch_columns(payloads: Sequence[bytes]
                              ) -> Dict[str, np.ndarray]:
    """Reference-wire JSON payloads -> binary columns, batched.

    Fast path: the native host runtime's schema-specific scanner
    (hostpipe.c atp_parse_json_events, ~8x json.loads end to end).
    Payloads outside the fast shape (escape sequences, timezone
    suffixes, non-calendar lecture ids needing murmur3) are
    Python-parsed INDIVIDUALLY and the native scan resumes after each —
    a mixed stream keeps the fast path for its conforming majority
    instead of degrading whole batches. Results are identical either
    way (tested differentially, including the exact-microsecond
    timestamp arithmetic both sides share). Raises on malformed JSON
    like decode_event does; callers keep per-message poison handling."""
    from attendance_tpu.native import load as load_native

    nat = load_native()
    if nat is None or not payloads:
        return columns_from_events(decode_event_batch(payloads))
    if getattr(nat, "has_list_scan", False) and isinstance(payloads, list):
        # CPython-API scan: reads each bytes payload IN PLACE — no
        # join, no offset/length tables (that prepare pass costs more
        # per event than the scan itself). Non-bytes or non-fast-shape
        # entries surface as misses and take the Python codec below.
        batch = nat.empty_json_outputs(len(payloads))
        idx = 0
        while True:
            miss = nat.parse_json_list(payloads, batch, idx)
            if miss < 0:
                return batch.columns()
            batch.set_row(miss, columns_from_events(
                [decode_event(bytes(payloads[miss]))]))
            idx = miss + 1
    # Buffer-based scan: one join + offset/length table, then the same
    # resume protocol. No per-payload normalization pass — b"".join and
    # len() accept any buffer type directly; only the rare Python-codec
    # miss path needs real bytes.
    batch = nat.prepare_json_batch(payloads)  # one O(bytes) setup
    idx = 0
    while True:
        miss = nat.parse_json_from(batch, idx)
        if miss < 0:
            return batch.columns()
        # Python codec for the one non-fast-shape payload (written
        # straight into its output row), then resume the native scan
        # after it — O(1) setup per resume, not a tail re-join.
        batch.set_row(miss, columns_from_events(
            [decode_event(bytes(payloads[miss]))]))
        idx = miss + 1


def columns_from_events(events: Sequence[AttendanceEvent]
                        ) -> Dict[str, np.ndarray]:
    """Columnar view of decoded JSON events (the shape the kernels eat)."""
    n = len(events)
    student = np.empty(n, dtype=np.uint32)
    day = np.empty(n, dtype=np.uint32)
    micros = np.empty(n, dtype=np.int64)
    flags_valid = np.empty(n, dtype=bool)
    etype = np.empty(n, dtype=np.int8)
    for i, e in enumerate(events):
        student[i] = e.student_id & 0xFFFFFFFF
        day[i] = _lecture_to_day(e.lecture_id)
        micros[i] = _iso_to_micros(e.timestamp)
        flags_valid[i] = e.is_valid
        etype[i] = EVENT_EXIT if e.event_type == "exit" else EVENT_ENTRY
    return {"student_id": student, "lecture_day": day, "micros": micros,
            "is_valid": flags_valid, "event_type": etype}
