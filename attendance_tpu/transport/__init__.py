"""Event-transport layer with Pulsar call-shape compatibility.

The reference's transport is an external Pulsar broker driven through
pulsar-client: ``Client(host).create_producer(topic).send(bytes)`` on the
producer side (reference data_generator.py:40-41,122) and
``subscribe(topic, sub, consumer_type=Shared)`` / ``receive()`` /
``acknowledge()`` / ``negative_acknowledge()`` on the consumer side
(reference attendance_processor.py:29-34,101,132,136). This package keeps
those call shapes API-stable across two backends selected by
``--transport-backend``:

  * "memory" — hermetic in-process broker with the same delivery
               semantics: shared-subscription competing consumers,
               per-message ack, nack->redelivery, at-least-once.
  * "socket" — the memory broker behind a TCP front
               (transport.socket_broker): the same semantics across
               PROCESSES, including crash takeover on connection drop —
               the framework-native stand-in for the Pulsar service's
               multi-process scale-out role.
  * "pulsar" — the real broker via pulsar-client (import-gated).
"""

from typing import Optional

from attendance_tpu.transport.memory_broker import (  # noqa: F401
    MemoryBroker, MemoryClient, ReceiveTimeout)
from attendance_tpu.transport.resilience import (  # noqa: F401
    BrokerUnavailable, RetryPolicy)


def redelivery_count(msg) -> int:
    """Delivery-attempt count of a received message, backend-agnostic.

    The memory broker exposes ``redelivery_count`` as an attribute; the
    real pulsar-client exposes it as a method on ``pulsar.Message``.
    """
    rc = msg.redelivery_count
    return rc() if callable(rc) else rc


class PoisonTracker:
    """Client-side poison-attempt counts per message id.

    The broker's ``redelivery_count`` is bumped by EVERY requeue —
    nacks, but also crash takeovers and live-reconnect session resumes
    — so under connection churn a perfectly healthy frame arrives with
    a high count, and one transient decode failure (e.g. in-flight
    corruption) would then tip it straight into the dead-letter path:
    a REAL frame lost to someone else's reconnects (observed under
    chaos soak). Counting poison attempts here instead bounds retries
    by how often THIS frame actually failed, no matter how often the
    transport requeued it in between. Bounded LRU: only failing
    messages are ever tracked."""

    def __init__(self, cap: int = 4096):
        from collections import OrderedDict

        self._counts = OrderedDict()
        self._cap = cap

    def bump(self, message_id) -> int:
        """Record one poison attempt; returns the total so far."""
        count = self._counts.pop(message_id, 0) + 1
        self._counts[message_id] = count
        if len(self._counts) > self._cap:
            self._counts.popitem(last=False)
        return count

    def forget(self, message_id) -> None:
        self._counts.pop(message_id, None)


def handle_poison(msg, consumer, metrics, config, logger, *,
                  count_nack: bool = True,
                  reason: str = "poison-frame",
                  tracker: Optional[PoisonTracker] = None) -> None:
    """Bounded-retry poison-message policy shared by both processors.

    Nack for broker redelivery up to ``config.max_redeliveries`` attempts,
    then dead-letter (ack + count) so one undecodable frame cannot
    livelock the subscription. The reference nacks forever (reference
    attendance_processor.py:134-136, no DLQ despite its README).
    ``count_nack=False`` skips the nacked_batches counter for callers
    whose unit of nacking is a message, not a batch.

    With ``config.quarantine_dir`` set, the frame's bytes are written
    to the on-disk quarantine (transport/quarantine) BEFORE the ack —
    dead-lettering then preserves the only copy instead of dropping it,
    and ``doctor`` can list/replay the entry. A quarantine write
    failure falls back to the old drop-on-ack behavior (the
    subscription must not livelock because the quarantine disk died).

    ``tracker`` (a :class:`PoisonTracker`, one per consumer) bounds
    retries by the frame's OWN failure count instead of the broker's
    redelivery count, which reconnect/takeover requeues inflate for
    healthy frames too. Without one, the old broker-count behavior
    applies.
    """
    if tracker is not None:
        # Completed nacks so far for THIS frame's own failures — the
        # same quantity redelivery_count measures on a quiet network.
        attempts = tracker.bump(msg.message_id) - 1
        # Backstop: the tracker's LRU forgets under a mass-poison
        # burst wider than its cap (every frame would then read as
        # attempt 0 forever — the nack-forever livelock reborn). The
        # broker's redelivery count grows monotonically no matter what
        # this client remembers, so past a generous multiple of the
        # bound the frame dead-letters regardless; the margin keeps
        # ordinary reconnect-requeue inflation from tripping it.
        backstop = max(4 * config.max_redeliveries, 8)
        attempts = max(attempts,
                       redelivery_count(msg) - backstop
                       + config.max_redeliveries)
    else:
        attempts = redelivery_count(msg)
    if attempts >= config.max_redeliveries:
        if tracker is not None:
            tracker.forget(msg.message_id)
        qdir = getattr(config, "quarantine_dir", "")
        if qdir:
            try:
                from attendance_tpu.transport.quarantine import (
                    get_quarantine)
                props = (msg.properties()
                         if hasattr(msg, "properties") else None)
                get_quarantine(qdir).put(
                    msg.data(), topic=config.pulsar_topic,
                    reason=reason, redeliveries=attempts,
                    properties=props)
            except Exception:
                logger.exception(
                    "Quarantine write failed; dead-lettering anyway")
        logger.error("Dead-lettering poison frame after %d redeliveries",
                     attempts)
        metrics.dead_lettered += 1
        consumer.acknowledge(msg)
    else:
        if count_nack:
            metrics.nacked_batches += 1
        consumer.negative_acknowledge(msg)


def _fill_until(batch_size: int, timeout_s: float, step) -> None:
    """THE partial-batch timeout rule, in one place: call
    ``step(remaining_n, timeout_ms) -> received count`` until
    ``batch_size`` messages arrived or ``timeout_s`` expired with at
    least one (partial batch); a ReceiveTimeout from step ends the
    batch."""
    import time

    total = 0
    deadline = time.monotonic() + timeout_s
    while total < batch_size:
        remaining = deadline - time.monotonic()
        if remaining <= 0 and total:
            break
        timeout_ms = max(1, int(max(remaining, 0) * 1000))
        try:
            total += step(batch_size - total, timeout_ms)
        except ReceiveTimeout:
            break


def collect_batch(consumer, batch_size: int, timeout_s: float,
                  raw: bool = False) -> list:
    """Fill a micro-batch from a consumer: up to ``batch_size`` messages,
    or whatever arrived when ``timeout_s`` expires (partial batch).
    Shared by every micro-batching consumer (processor, bridge) so the
    partial-batch timeout rule has one definition (_fill_until).

    Uses the consumer's batch receive when it has one (the memory
    broker's receive_many drains pending messages under a single lock —
    per-message receive() tops out ~0.25M msg/s on lock round-trips
    alone); per-message receive is the fallback for clients without it
    (the gated real-Pulsar wrapper). ``raw=True`` selects the memory
    broker's zero-wrapper lane — ``(message_id, data, redeliveries,
    properties)`` tuples instead of Message objects; the caller must
    have feature-detected receive_many_raw."""
    batch_recv = (consumer.receive_many_raw if raw
                  else getattr(consumer, "receive_many", None))
    msgs = []

    def step(n, timeout_ms):
        if batch_recv is not None:
            got = batch_recv(n, timeout_millis=timeout_ms)
            msgs.extend(got)
            return len(got)
        msgs.append(consumer.receive(timeout_millis=timeout_ms))
        return 1

    _fill_until(batch_size, timeout_s, step)
    return msgs


def collect_chunks(consumer, batch_size: int, timeout_s: float) -> list:
    """Fill a micro-batch on the CHUNK lane: a list of
    (chunk_id, raw tuples) handles totalling up to ``batch_size``
    messages, or whatever arrived when ``timeout_s`` expires. Same
    partial-batch timeout rule as collect_batch (_fill_until); the
    caller must have feature-detected receive_chunk."""
    chunks = []

    def step(n, timeout_ms):
        cid, toks = consumer.receive_chunk(n, timeout_millis=timeout_ms)
        chunks.append((cid, toks))
        return len(toks)

    _fill_until(batch_size, timeout_s, step)
    return chunks


def acknowledge_all(consumer, msgs) -> None:
    """Ack a batch in one broker round-trip when the consumer supports
    it; per-message otherwise."""
    batch_ack = getattr(consumer, "acknowledge_many", None)
    if batch_ack is not None:
        batch_ack(msgs)
        return
    for m in msgs:
        consumer.acknowledge(m)


def make_client(config):
    """Build the transport client selected by config.transport_backend.

    The chaos chokepoint: when ``config.chaos`` is set, the socket
    backend gets the injector at its RPC seams (drop/conn_reset against
    real TCP connections) and EVERY backend is wrapped in the
    backend-agnostic chaos proxies (dup/delay/corrupt) — so the same
    spec drives the memory broker's hermetic soak and the socket
    broker's cross-process one."""
    from attendance_tpu import chaos

    inj = chaos.ensure(config)
    if getattr(config, "ingress_wire", "auto") == "shm":
        # Shared-memory ring ingress (transport/shm_ring): the event
        # topic's transport is the mmap'd ring, not a broker. The shm
        # fault sites (torn_slot, writer_stall at shm.slot) live
        # inside the producer; the byte-level proxies below are NOT
        # applied — in-flight corruption is a socket-wire failure
        # class, and the ring's seqlock already owns torn delivery.
        from attendance_tpu.transport.shm_ring import ShmClient
        return ShmClient.from_config(config)
    if config.transport_backend == "memory":
        client = MemoryClient(MemoryBroker.shared())
    elif config.transport_backend == "socket":
        from attendance_tpu.transport.resilience import RetryPolicy
        from attendance_tpu.transport.socket_broker import SocketClient
        client = SocketClient(config.socket_broker, chaos=inj,
                              policy=RetryPolicy.from_config(config))
    elif config.transport_backend == "pulsar":
        from attendance_tpu.transport.pulsar_client import PulsarClient
        client = PulsarClient(config.pulsar_host)
        if inj is not None:
            # The chaos proxies rebuild corrupted messages as
            # memory-broker Messages (attribute call-shape) — wrapping
            # the real pulsar client would hand its consumers
            # wrong-typed messages on the poison path. The fault plane
            # targets the framework-native backends.
            import logging
            logging.getLogger(__name__).warning(
                "--chaos is not supported on the pulsar backend; "
                "fault plane disabled for this client")
        return client
    else:
        raise ValueError(
            f"unknown transport backend {config.transport_backend!r}")
    return client if inj is None else chaos.ChaosClient(client, inj)
