"""Event-transport layer with Pulsar call-shape compatibility.

The reference's transport is an external Pulsar broker driven through
pulsar-client: ``Client(host).create_producer(topic).send(bytes)`` on the
producer side (reference data_generator.py:40-41,122) and
``subscribe(topic, sub, consumer_type=Shared)`` / ``receive()`` /
``acknowledge()`` / ``negative_acknowledge()`` on the consumer side
(reference attendance_processor.py:29-34,101,132,136). This package keeps
those call shapes API-stable across two backends selected by
``--transport-backend``:

  * "memory" — hermetic in-process broker with the same delivery
               semantics: shared-subscription competing consumers,
               per-message ack, nack->redelivery, at-least-once.
  * "pulsar" — the real broker via pulsar-client (import-gated).
"""

from attendance_tpu.transport.memory_broker import (  # noqa: F401
    MemoryBroker, MemoryClient, ReceiveTimeout)


def make_client(config):
    """Build the transport client selected by config.transport_backend."""
    if config.transport_backend == "memory":
        return MemoryClient(MemoryBroker.shared())
    if config.transport_backend == "pulsar":
        from attendance_tpu.transport.pulsar_client import PulsarClient
        return PulsarClient(config.pulsar_host)
    raise ValueError(
        f"unknown transport backend {config.transport_backend!r}")
