"""Event-transport layer with Pulsar call-shape compatibility.

The reference's transport is an external Pulsar broker driven through
pulsar-client: ``Client(host).create_producer(topic).send(bytes)`` on the
producer side (reference data_generator.py:40-41,122) and
``subscribe(topic, sub, consumer_type=Shared)`` / ``receive()`` /
``acknowledge()`` / ``negative_acknowledge()`` on the consumer side
(reference attendance_processor.py:29-34,101,132,136). This package keeps
those call shapes API-stable across two backends selected by
``--transport-backend``:

  * "memory" — hermetic in-process broker with the same delivery
               semantics: shared-subscription competing consumers,
               per-message ack, nack->redelivery, at-least-once.
  * "socket" — the memory broker behind a TCP front
               (transport.socket_broker): the same semantics across
               PROCESSES, including crash takeover on connection drop —
               the framework-native stand-in for the Pulsar service's
               multi-process scale-out role.
  * "pulsar" — the real broker via pulsar-client (import-gated).
"""

from attendance_tpu.transport.memory_broker import (  # noqa: F401
    MemoryBroker, MemoryClient, ReceiveTimeout)


def redelivery_count(msg) -> int:
    """Delivery-attempt count of a received message, backend-agnostic.

    The memory broker exposes ``redelivery_count`` as an attribute; the
    real pulsar-client exposes it as a method on ``pulsar.Message``.
    """
    rc = msg.redelivery_count
    return rc() if callable(rc) else rc


def handle_poison(msg, consumer, metrics, config, logger, *,
                  count_nack: bool = True) -> None:
    """Bounded-retry poison-message policy shared by both processors.

    Nack for broker redelivery up to ``config.max_redeliveries`` attempts,
    then dead-letter (ack + count) so one undecodable frame cannot
    livelock the subscription. The reference nacks forever (reference
    attendance_processor.py:134-136, no DLQ despite its README).
    ``count_nack=False`` skips the nacked_batches counter for callers
    whose unit of nacking is a message, not a batch.
    """
    attempts = redelivery_count(msg)
    if attempts >= config.max_redeliveries:
        logger.error("Dead-lettering poison frame after %d redeliveries",
                     attempts)
        metrics.dead_lettered += 1
        consumer.acknowledge(msg)
    else:
        if count_nack:
            metrics.nacked_batches += 1
        consumer.negative_acknowledge(msg)


def _fill_until(batch_size: int, timeout_s: float, step) -> None:
    """THE partial-batch timeout rule, in one place: call
    ``step(remaining_n, timeout_ms) -> received count`` until
    ``batch_size`` messages arrived or ``timeout_s`` expired with at
    least one (partial batch); a ReceiveTimeout from step ends the
    batch."""
    import time

    total = 0
    deadline = time.monotonic() + timeout_s
    while total < batch_size:
        remaining = deadline - time.monotonic()
        if remaining <= 0 and total:
            break
        timeout_ms = max(1, int(max(remaining, 0) * 1000))
        try:
            total += step(batch_size - total, timeout_ms)
        except ReceiveTimeout:
            break


def collect_batch(consumer, batch_size: int, timeout_s: float,
                  raw: bool = False) -> list:
    """Fill a micro-batch from a consumer: up to ``batch_size`` messages,
    or whatever arrived when ``timeout_s`` expires (partial batch).
    Shared by every micro-batching consumer (processor, bridge) so the
    partial-batch timeout rule has one definition (_fill_until).

    Uses the consumer's batch receive when it has one (the memory
    broker's receive_many drains pending messages under a single lock —
    per-message receive() tops out ~0.25M msg/s on lock round-trips
    alone); per-message receive is the fallback for clients without it
    (the gated real-Pulsar wrapper). ``raw=True`` selects the memory
    broker's zero-wrapper lane — ``(message_id, data, redeliveries,
    properties)`` tuples instead of Message objects; the caller must
    have feature-detected receive_many_raw."""
    batch_recv = (consumer.receive_many_raw if raw
                  else getattr(consumer, "receive_many", None))
    msgs = []

    def step(n, timeout_ms):
        if batch_recv is not None:
            got = batch_recv(n, timeout_millis=timeout_ms)
            msgs.extend(got)
            return len(got)
        msgs.append(consumer.receive(timeout_millis=timeout_ms))
        return 1

    _fill_until(batch_size, timeout_s, step)
    return msgs


def collect_chunks(consumer, batch_size: int, timeout_s: float) -> list:
    """Fill a micro-batch on the CHUNK lane: a list of
    (chunk_id, raw tuples) handles totalling up to ``batch_size``
    messages, or whatever arrived when ``timeout_s`` expires. Same
    partial-batch timeout rule as collect_batch (_fill_until); the
    caller must have feature-detected receive_chunk."""
    chunks = []

    def step(n, timeout_ms):
        cid, toks = consumer.receive_chunk(n, timeout_millis=timeout_ms)
        chunks.append((cid, toks))
        return len(toks)

    _fill_until(batch_size, timeout_s, step)
    return chunks


def acknowledge_all(consumer, msgs) -> None:
    """Ack a batch in one broker round-trip when the consumer supports
    it; per-message otherwise."""
    batch_ack = getattr(consumer, "acknowledge_many", None)
    if batch_ack is not None:
        batch_ack(msgs)
        return
    for m in msgs:
        consumer.acknowledge(m)


def make_client(config):
    """Build the transport client selected by config.transport_backend."""
    if config.transport_backend == "memory":
        return MemoryClient(MemoryBroker.shared())
    if config.transport_backend == "socket":
        from attendance_tpu.transport.socket_broker import SocketClient
        return SocketClient(config.socket_broker)
    if config.transport_backend == "pulsar":
        from attendance_tpu.transport.pulsar_client import PulsarClient
        return PulsarClient(config.pulsar_host)
    raise ValueError(
        f"unknown transport backend {config.transport_backend!r}")
