"""Real Pulsar transport (import-gated).

Thin adapter keeping the same call shapes as the memory broker; only
imported when ``--transport-backend=pulsar`` is selected, so the framework
runs hermetically where pulsar-client is not installed. Mirrors the
reference's usage: Shared subscription, ack/nack per message (reference
attendance_processor.py:29-34,101,132,136).
"""

from __future__ import annotations

try:
    import pulsar as _pulsar
    HAVE_PULSAR = True
except ImportError:  # pragma: no cover - environment without pulsar-client
    _pulsar = None
    HAVE_PULSAR = False


class PulsarClient:
    def __init__(self, service_url: str):
        if not HAVE_PULSAR:
            raise RuntimeError(
                "transport_backend='pulsar' requires the pulsar-client "
                "package")
        self._client = _pulsar.Client(service_url)

    def create_producer(self, topic: str):
        return self._client.create_producer(topic)

    def subscribe(self, topic: str, subscription_name: str,
                  consumer_type=None):
        if consumer_type is None:
            consumer_type = _pulsar.ConsumerType.Shared
        return self._client.subscribe(
            topic, subscription_name, consumer_type=consumer_type)

    def close(self) -> None:
        self._client.close()
