"""Shared-memory ring transport: zero-copy ingress for co-located
producers (ROADMAP item 3a — "close the last 1000x").

Every committed wire so far ships events through a TCP socket and at
least one Python repack; at 2^17-event frames the broker RPC + copy
chain caps ingress orders of magnitude under the device rate.  This
module is the co-located alternative: an mmap'd ring of fixed-size
slots, one planar binary frame (``events.PLANAR_MAGIC``) per slot,
**publish is a header stamp, consume is a bounds-checked view**:

  * the producer writes the frame bytes directly into the next free
    slot and stamps the slot's *sequence word* — seqlock-style: the
    word is bumped ODD before the payload write and EVEN (encoding the
    slot's generation) after it, so a reader polling the slot either
    sees the stable word for the sequence it expects or retries;
  * the consumer hands the dispatcher a zero-copy ``memoryview`` of
    the slot — the planar frame's columns decode as buffer views, no
    repack, no copy (the dispatcher maps slots);
  * ack/nack map onto a **consumer cursor + redelivery region**: the
    header persists ``ack_cursor`` (every sequence below it is
    processed AND durable per the group-commit contract) and a
    per-slot delivery count; a crashed consumer re-attaches and
    resumes from ``ack_cursor``, redelivering exactly the unacked
    tail — the PR 4 group-commit and PR 5 resume contracts hold with
    the ring as the wire;
  * a full ring (``nslots`` published-but-unacked frames) blocks the
    producer — backpressure, never overwrite: a slot is recycled only
    after the consumer acked past it, which is also what keeps handed-
    out views stable until their frame is acknowledged.

Crash contracts:

  * producer SIGKILL mid-write: the victim slot's sequence word never
    reaches its stable value, so the consumer never delivers it — the
    frame was never published (at-least-once producers re-send on
    restart, exactly like a socket send that died in flight);
  * consumer SIGKILL mid-run: ``ack_cursor`` is durable in the
    mapping; a fresh consumer resumes there and the unacked tail
    redelivers (bounded by the ring depth, which is what bounded the
    broker's in-flight window before);
  * torn reads: the seqlock retries them — the payload is returned
    only when the sequence word read stable both before and after the
    bounds check.  Retries are counted
    (``attendance_shm_torn_reads_total``).

Concurrency model: ONE producer process and ONE consumer process per
ring file (striped ingress uses one ring per lane).  Ordering relies
on x86-TSO store ordering (CPython cannot emit fences); the seqword
is written strictly after the payload bytes on publish, and read on
both sides of the payload on consume.

Chaos fault sites (site ``shm.slot``): ``torn_slot`` leaves the slot
mid-write (sequence word odd) for a beat before completing — a
concurrent reader observes the torn state and must retry, never
deliver; ``writer_stall`` parks the producer mid-write for the
configured duration (a stalled co-located producer must stall the
ring, not corrupt it).
"""

from __future__ import annotations

import heapq
import logging
import mmap
import os
import struct
import threading
import time
from pathlib import Path
from typing import List, Optional, Tuple

from attendance_tpu.transport.memory_broker import Message, ReceiveTimeout

logger = logging.getLogger(__name__)

RING_MAGIC = b"ATSHRNG1"
RING_VERSION = 1

_HDR = struct.Struct("<8sIIII")      # magic, version, nslots, slot_bytes, rsv
_U64 = struct.Struct("<Q")
_U32 = struct.Struct("<I")
_OFF_HEAD = 24                       # u64: next sequence to publish
_OFF_ACK = 32                        # u64: all sequences below are acked
_OFF_RED = 64                        # u32[nslots] delivery counts
_SLOT_HDR = 12                       # u64 seqword + u32 payload length

DEFAULT_SLOTS = 64
DEFAULT_SLOT_BYTES = 1 << 21


class ShmRingFull(RuntimeError):
    """Publish timed out against a full ring (consumer not draining) —
    the backpressure signal, surfaced instead of overwriting."""


def ring_path(directory, topic: str, lane: int) -> Path:
    """One ring file per (topic, lane): producer striping and lane
    subscription must agree on the mapping, so it lives here."""
    safe = "".join(c if (c.isalnum() or c in "-_.") else "_"
                   for c in topic)
    return Path(directory) / f"{safe}.lane{lane}.ring"


def _header_bytes(nslots: int) -> int:
    raw = _OFF_RED + 4 * nslots
    return (raw + 4095) // 4096 * 4096


class _Ring:
    """The shared mapping: geometry + field accessors both ends use."""

    def __init__(self, path, nslots: int, slot_bytes: int):
        if slot_bytes % 8 or slot_bytes <= _SLOT_HDR:
            raise ValueError(
                f"slot_bytes must be a multiple of 8 > {_SLOT_HDR} "
                f"(got {slot_bytes})")
        self.path = Path(path)
        self.path.parent.mkdir(parents=True, exist_ok=True)
        size = _header_bytes(nslots) + nslots * slot_bytes
        fd = os.open(self.path, os.O_RDWR | os.O_CREAT, 0o644)
        try:
            import fcntl
            fcntl.flock(fd, fcntl.LOCK_EX)
            try:
                if os.fstat(fd).st_size == 0:
                    os.ftruncate(fd, size)
                    os.pwrite(fd, _HDR.pack(RING_MAGIC, RING_VERSION,
                                            nslots, slot_bytes, 0), 0)
                else:
                    hdr = os.pread(fd, _HDR.size, 0)
                    magic, ver, have_n, have_sb, _ = _HDR.unpack(hdr)
                    if magic != RING_MAGIC:
                        raise ValueError(
                            f"{self.path} is not an shm ring "
                            f"(magic {magic!r})")
                    if ver != RING_VERSION:
                        raise ValueError(
                            f"{self.path}: ring version {ver}, "
                            f"this build speaks {RING_VERSION}")
                    if (have_n, have_sb) != (nslots, slot_bytes):
                        raise ValueError(
                            f"{self.path}: ring geometry is "
                            f"{have_n}x{have_sb}B, configured "
                            f"{nslots}x{slot_bytes}B — both ends must "
                            "agree (--shm-slots/--shm-slot-bytes)")
            finally:
                fcntl.flock(fd, fcntl.LOCK_UN)
            self._mm = mmap.mmap(fd, size)
        finally:
            os.close(fd)
        self._view = memoryview(self._mm)
        self.nslots = nslots
        self.slot_bytes = slot_bytes
        self.payload_cap = slot_bytes - _SLOT_HDR
        self._slot0 = _header_bytes(nslots)

    # -- header fields ------------------------------------------------------
    def head(self) -> int:
        return _U64.unpack_from(self._mm, _OFF_HEAD)[0]

    def set_head(self, v: int) -> None:
        _U64.pack_into(self._mm, _OFF_HEAD, v)

    def ack_cursor(self) -> int:
        return _U64.unpack_from(self._mm, _OFF_ACK)[0]

    def set_ack_cursor(self, v: int) -> None:
        _U64.pack_into(self._mm, _OFF_ACK, v)

    def delivery_count(self, seq: int) -> int:
        return _U32.unpack_from(self._mm,
                                _OFF_RED + 4 * (seq % self.nslots))[0]

    def set_delivery_count(self, seq: int, v: int) -> None:
        _U32.pack_into(self._mm, _OFF_RED + 4 * (seq % self.nslots), v)

    # -- slots --------------------------------------------------------------
    def slot_off(self, seq: int) -> int:
        return self._slot0 + (seq % self.nslots) * self.slot_bytes

    def seqword(self, seq: int) -> int:
        return _U64.unpack_from(self._mm, self.slot_off(seq))[0]

    def set_seqword(self, seq: int, v: int) -> None:
        _U64.pack_into(self._mm, self.slot_off(seq), v)

    @staticmethod
    def stable_word(seq: int) -> int:
        return (seq + 1) << 1

    def payload_view(self, seq: int):
        """Bounds-checked zero-copy view of the slot's payload, or
        None when the slot is torn/not yet published for ``seq`` (the
        seqlock read: stable word before AND after the bounds check)."""
        off = self.slot_off(seq)
        want = self.stable_word(seq)
        if _U64.unpack_from(self._mm, off)[0] != want:
            return None
        (ln,) = _U32.unpack_from(self._mm, off + 8)
        if ln > self.payload_cap:
            return None  # torn length: retry until the stamp settles
        view = self._view[off + _SLOT_HDR: off + _SLOT_HDR + ln]
        if _U64.unpack_from(self._mm, off)[0] != want:
            return None
        return view

    def close(self) -> None:
        try:
            self._view.release()
            self._mm.close()
        except (BufferError, ValueError):
            # Zero-copy views handed to a consumer may still be alive
            # at teardown (e.g. parked in an unprocessed lane block);
            # the mapping stays open until the process exits rather
            # than invalidating their memory out from under them.
            pass


class ShmRingProducer:
    """Single-writer publish side of one ring."""

    def __init__(self, path, *, nslots: int = DEFAULT_SLOTS,
                 slot_bytes: int = DEFAULT_SLOT_BYTES, chaos=None):
        self._ring = _Ring(path, nslots, slot_bytes)
        self._chaos = chaos
        self._head = self._ring.head()  # resume where the file says
        # A producer killed between the stable seqword stamp (the
        # publish point) and the head bump (bookkeeping) left a
        # PUBLISHED slot the header does not count — resuming at the
        # recorded head would overwrite a frame the consumer may have
        # already delivered (and still hold a zero-copy view of).
        # Reconstruct head by scanning forward over stable seqwords;
        # bounded by the ring depth.
        while (self._head - self._ring.ack_cursor()
               < self._ring.nslots
               and self._ring.seqword(self._head)
               == _Ring.stable_word(self._head)):
            self._head += 1
        if self._head != self._ring.head():
            self._ring.set_head(self._head)
        self._lock = threading.Lock()

    def send(self, data, properties=None, *,
             timeout_s: float = 30.0) -> int:
        """Publish one frame; returns its sequence.  Blocks while the
        ring is full (unacked depth == nslots) — backpressure toward
        the producer, never an overwrite.  ``properties`` are accepted
        for producer call-shape compatibility and dropped: the shm
        wire carries no property channel (traces root at dispatch)."""
        del properties
        ring = self._ring
        n = len(data)
        if n > ring.payload_cap:
            raise ValueError(
                f"frame of {n} bytes exceeds the ring's "
                f"{ring.payload_cap}-byte slots — raise "
                "--shm-slot-bytes or shrink --batch-size")
        with self._lock:
            seq = self._head
            deadline = time.monotonic() + timeout_s
            while seq - ring.ack_cursor() >= ring.nslots:
                if time.monotonic() > deadline:
                    raise ShmRingFull(
                        f"ring {ring.path.name} full for {timeout_s}s "
                        f"(head={seq}, ack={ring.ack_cursor()})")
                time.sleep(0.0002)
            off = ring.slot_off(seq)
            busy = _Ring.stable_word(seq) | 1
            ring.set_seqword(seq, busy)
            inj = self._chaos
            if inj is not None and inj.roll("shm.slot", "torn_slot"):
                # Leave the slot visibly torn mid-payload for a beat:
                # a concurrent reader must observe the odd word (or a
                # changed word) and retry, never deliver half a frame.
                half = n // 2
                ring._mm[off + _SLOT_HDR: off + _SLOT_HDR + half] = \
                    bytes(data[:half])
                time.sleep(0.001)
            if inj is not None:
                stall = inj.stall_s("shm.slot")
                if stall:
                    time.sleep(stall)
            ring._mm[off + _SLOT_HDR: off + _SLOT_HDR + n] = \
                data if isinstance(data, (bytes, bytearray)) \
                else bytes(data)
            _U32.pack_into(ring._mm, off + 8, n)
            ring.set_delivery_count(seq, 0)
            # The publish point: payload first, stable word second
            # (x86-TSO keeps the order); head is bookkeeping only.
            ring.set_seqword(seq, _Ring.stable_word(seq))
            self._head = seq + 1
            ring.set_head(self._head)
        return seq

    def send_many(self, datas, properties=None) -> int:
        last = -1
        for d in datas:
            last = self.send(d)
        return last

    def flush(self) -> None:
        pass  # publishes are synchronous stamps

    def close(self) -> None:
        self._ring.close()


class ShmRingConsumer:
    """Single-reader consume side of one ring: the broker-consumer
    call shape (receive / receive_chunk / acknowledge / nack /
    backlog) over the cursor + redelivery region."""

    def __init__(self, path, *, nslots: int = DEFAULT_SLOTS,
                 slot_bytes: int = DEFAULT_SLOT_BYTES, lane: int = 0):
        self._ring = _Ring(path, nslots, slot_bytes)
        # Resume from the durable cursor: everything below it was
        # acked (group-commit durable); the unacked tail redelivers.
        self._ack_cursor = self._ring.ack_cursor()
        self._cursor = self._ack_cursor
        self._acked: set = set()
        self._redeliver: List[int] = []  # heap of nacked sequences
        self._chunks = {}
        self._next_chunk = 1
        self._lock = threading.Lock()
        self.torn_reads = 0
        self._c_torn = None
        from attendance_tpu import obs
        t = obs.get()
        if t is not None:
            lane_l = str(lane)
            self._c_torn = t.registry.counter(
                "attendance_shm_torn_reads_total",
                help="Seqlock-retried torn slot reads", lane=lane_l)
            ring = self._ring

            def _depth(r=ring) -> float:
                try:
                    return float(r.head() - r.ack_cursor())
                except ValueError:
                    # The final atexit exposition block can scrape
                    # after cleanup unmapped the ring; NaN (rendered
                    # per prom text rules), never a lying 0 or a
                    # warning-logged skip.
                    return float("nan")

            t.registry.gauge(
                "attendance_shm_ring_depth",
                help="Published-but-unacked frames in the shm ring",
                lane=lane_l).set_function(_depth)

    # -- receive ------------------------------------------------------------
    def _next_raw(self) -> Optional[Tuple[int, object, int, None]]:
        """One delivery attempt: redelivery heap first, then the
        cursor — None when nothing is deliverable right now."""
        with self._lock:
            if self._redeliver:
                seq = heapq.heappop(self._redeliver)
            else:
                seq = self._cursor
                view = self._ring.payload_view(seq)
                if view is None:
                    if self._ring.seqword(seq) == (
                            _Ring.stable_word(seq) | 1):
                        # The slot's sequence word is the BUSY (odd)
                        # marker for exactly this generation: we
                        # caught the writer mid-payload — a torn
                        # read, observed and retried, never delivered.
                        self.torn_reads += 1
                        if self._c_torn is not None:
                            self._c_torn.inc()
                    return None
                self._cursor = seq + 1
                red = self._ring.delivery_count(seq)
                self._ring.set_delivery_count(seq, red + 1)
                return (seq, view, red, None)
        # Redelivery: the slot is still stable (unacked slots are
        # never recycled), so a vanished view here is a hard fault.
        view = self._ring.payload_view(seq)
        if view is None:
            raise RuntimeError(
                f"shm ring {self._ring.path.name}: unacked slot "
                f"{seq} no longer readable (protocol violation)")
        with self._lock:
            red = self._ring.delivery_count(seq)
            self._ring.set_delivery_count(seq, red + 1)
        return (seq, view, red, None)

    def _collect_raw(self, max_n: int,
                     timeout_millis: Optional[int]) -> list:
        deadline = time.monotonic() + (
            (timeout_millis if timeout_millis is not None else 50)
            / 1000.0)
        out = []
        while len(out) < max_n:
            tok = self._next_raw()
            if tok is not None:
                out.append(tok)
                continue
            if out or time.monotonic() >= deadline:
                break
            time.sleep(0.0002)
        if not out:
            raise ReceiveTimeout(
                f"no shm frame within {timeout_millis}ms")
        return out

    def receive(self, timeout_millis: Optional[int] = None) -> Message:
        seq, view, red, props = self._collect_raw(1, timeout_millis)[0]
        return Message(view, seq, red, props)

    def receive_many_raw(self, max_n: int,
                         timeout_millis: Optional[int] = None) -> list:
        return self._collect_raw(max_n, timeout_millis)

    def receive_many(self, max_n: int,
                     timeout_millis: Optional[int] = None) -> list:
        return [Message(d, s, r, p) for s, d, r, p
                in self._collect_raw(max_n, timeout_millis)]

    # -- chunk lane (what the striped ingress workers speak) ----------------
    def receive_chunk(self, max_n: int,
                      timeout_millis: Optional[int] = None):
        toks = self._collect_raw(max_n, timeout_millis)
        with self._lock:
            cid = self._next_chunk
            self._next_chunk += 1
            self._chunks[cid] = [t[0] for t in toks]
        return cid, toks

    def acknowledge_chunk(self, chunk_id: int) -> None:
        self.acknowledge_ids(self._chunks.pop(chunk_id, ()))

    def nack_chunk(self, chunk_id: int) -> None:
        seqs = self._chunks.pop(chunk_id, ())
        with self._lock:
            for s in seqs:
                heapq.heappush(self._redeliver, s)

    def explode_chunk(self, chunk_id: int) -> None:
        # Per-message settlement needs no chunk bookkeeping here: acks
        # and nacks are per-sequence already.
        self._chunks.pop(chunk_id, None)

    # -- settlement: the consumer cursor ------------------------------------
    def acknowledge_ids(self, seqs) -> None:
        ring = self._ring
        with self._lock:
            for s in seqs:
                if s >= self._ack_cursor:
                    self._acked.add(s)
            # Advance over the contiguous acked prefix only: a nacked
            # (still in-flight) frame holds the cursor back, so a
            # crash before ITS ack still redelivers it on resume.
            moved = False
            while self._ack_cursor in self._acked:
                self._acked.discard(self._ack_cursor)
                self._ack_cursor += 1
                moved = True
            if moved:
                ring.set_ack_cursor(self._ack_cursor)

    def acknowledge(self, msg) -> None:
        self.acknowledge_ids((msg.message_id,))

    def acknowledge_many(self, msgs) -> None:
        self.acknowledge_ids([m.message_id for m in msgs])

    def negative_acknowledge(self, msg) -> None:
        with self._lock:
            heapq.heappush(self._redeliver, msg.message_id)

    def backlog(self) -> int:
        with self._lock:
            return (self._ring.head() - self._cursor
                    + len(self._redeliver))

    def close(self) -> None:
        # Unacked sequences simply stay unacked in the mapping — the
        # next attach redelivers them (the crash-takeover contract,
        # with the file as the broker).
        self._ring.close()


class _StripedShmProducer:
    """Producer striping whole frames round-robin across the topic's
    lane rings (the lane count both ends read from the same config)."""

    def __init__(self, rings: List[ShmRingProducer]):
        self._rings = rings
        self._i = 0

    def send(self, data, properties=None) -> int:
        ring = self._rings[self._i]
        self._i = (self._i + 1) % len(self._rings)
        return ring.send(data, properties)

    def send_many(self, datas, properties=None) -> int:
        last = -1
        for d in datas:
            last = self.send(d)
        return last

    def flush(self) -> None:
        pass

    def close(self) -> None:
        for r in self._rings:
            r.close()


class ShmClient:
    """Client call shape over a directory of ring files: one ring per
    (topic, lane).  ``subscribe_lane`` is what the striped ingress
    plane calls; ``subscribe`` serves the classic single-consumer run
    loop (lane 0 of a single-lane topic)."""

    def __init__(self, directory, *, lanes: int = 1,
                 nslots: int = DEFAULT_SLOTS,
                 slot_bytes: int = DEFAULT_SLOT_BYTES, chaos=None):
        if not directory:
            raise ValueError(
                "--ingress-wire=shm needs --shm-dir (the directory "
                "holding the ring files; /dev/shm/... for a true "
                "memory-backed ring)")
        self.directory = Path(directory)
        self.lanes = max(1, lanes)
        self.nslots = nslots
        self.slot_bytes = slot_bytes
        self._chaos = chaos
        self._owned: list = []

    @classmethod
    def from_config(cls, config) -> "ShmClient":
        from attendance_tpu import chaos
        return cls(getattr(config, "shm_dir", ""),
                   lanes=max(1, getattr(config, "ingress_lanes", 0)),
                   nslots=getattr(config, "shm_slots", DEFAULT_SLOTS),
                   slot_bytes=getattr(config, "shm_slot_bytes",
                                      DEFAULT_SLOT_BYTES),
                   chaos=chaos.ensure(config))

    def _track(self, obj):
        self._owned.append(obj)
        return obj

    def create_producer(self, topic: str):
        rings = [ShmRingProducer(
            ring_path(self.directory, topic, i), nslots=self.nslots,
            slot_bytes=self.slot_bytes, chaos=self._chaos)
            for i in range(self.lanes)]
        if len(rings) == 1:
            return self._track(rings[0])
        return self._track(_StripedShmProducer(rings))

    def subscribe(self, topic: str, subscription_name: str,
                  **_kw) -> ShmRingConsumer:
        return self.subscribe_lane(topic, subscription_name, 0)

    def subscribe_lane(self, topic: str, subscription_name: str,
                       lane: int) -> ShmRingConsumer:
        del subscription_name  # one consumer per ring; no sub registry
        return self._track(ShmRingConsumer(
            ring_path(self.directory, topic, lane),
            nslots=self.nslots, slot_bytes=self.slot_bytes, lane=lane))

    def close(self) -> None:
        for obj in self._owned:
            try:
                obj.close()
            except Exception:
                pass
        self._owned.clear()


__all__ = [
    "ShmRingProducer", "ShmRingConsumer", "ShmClient", "ShmRingFull",
    "ring_path", "DEFAULT_SLOTS", "DEFAULT_SLOT_BYTES",
]
