"""Deadline + exponential-backoff-with-jitter retry for broker RPCs.

Before this module, every socket hiccup surfaced a raw
``ConnectionError``/``OSError`` to whoever happened to be calling
(transport/socket_broker.py): the generic processor's whole-batch nack
could absorb some of them, the fused pipeline's poison path DEAD-LETTERED
real frames for them, and a producer simply crashed. Now every socket
RPC routes through :func:`resilient_call`: transient transport failures
are invisible (reconnect + bounded retry with jittered backoff), and a
genuinely dead broker fails with ONE clear :class:`BrokerUnavailable`
after the configured budget — which subclasses ``ConnectionError`` so
existing callers that handled the raw error still do.

The backoff jitter draws from ``random.random()`` (NOT the chaos plane's
seeded streams): retry timing is remediation, not an injected fault, and
sharing the fault streams would make the fault schedule depend on how
many retries happened — breaking seed replay.
"""

from __future__ import annotations

import logging
import random
import time
from typing import Callable, Optional, Tuple

logger = logging.getLogger(__name__)


class BrokerUnavailable(ConnectionError):
    """The broker stayed unreachable for the whole retry budget."""


class ChaosDrop(ConnectionError):
    """Injected request loss (``drop``): transient by construction —
    the request was never sent, so a plain retry is always safe."""


# What a retry may safely swallow: transport-level failures (the request
# may or may not have executed — every broker op is safe to repeat:
# publishes duplicate into idempotent sinks, receives requeue via
# connection-drop takeover, acks/nacks of unknown ids are no-ops).
TRANSIENT_ERRORS = (ConnectionError, OSError, TimeoutError)


class RetryPolicy:
    """Deadline + backoff shape for one logical RPC."""

    __slots__ = ("budget_s", "base_s", "cap_s", "multiplier")

    def __init__(self, budget_s: float = 15.0, base_s: float = 0.05,
                 cap_s: float = 2.0, multiplier: float = 2.0):
        self.budget_s = budget_s
        self.base_s = base_s
        self.cap_s = cap_s
        self.multiplier = multiplier

    @classmethod
    def from_config(cls, config) -> "RetryPolicy":
        return cls(budget_s=getattr(config, "retry_budget_s", 15.0))

    def backoff_s(self, attempt: int) -> float:
        """Full-jitter exponential backoff for the Nth retry (1-based):
        uniform in (0, min(cap, base * multiplier**(n-1))] — the AWS
        full-jitter shape, which decorrelates competing retriers."""
        ceiling = min(self.cap_s,
                      self.base_s * self.multiplier ** (attempt - 1))
        return random.random() * ceiling or 1e-4


def _note_retry(site: str, attempt: int, exc: BaseException,
                t0: float) -> None:
    """Cold-path bookkeeping for one retry: counter + span. Resolved
    lazily — retries are rare by definition, so the lookup cost is
    irrelevant and the hot path carries no telemetry handle."""
    from attendance_tpu import obs

    t = obs.get()
    if t is None:
        return
    t.registry.counter(
        "attendance_retry_attempts_total",
        help="Broker RPC retries after a transient failure",
        site=site).inc()
    tracer = t.tracer
    if tracer is not None:
        cur = tracer.current()
        tracer.add_span(
            "rpc_retry", t0, time.perf_counter(),
            trace_id=cur.trace_id if cur is not None else tracer.new_id(),
            parent_id=cur.span_id if cur is not None else None,
            role="transport",
            args={"site": site, "attempt": attempt,
                  "error": type(exc).__name__})


def note_reconnect(site: str = "socket") -> None:
    """Count one transport reconnect (cold path)."""
    from attendance_tpu import obs

    t = obs.get()
    if t is not None:
        t.registry.counter(
            "attendance_reconnects_total",
            help="Broker transport reconnects (incl. session resume)",
        ).inc()


def resilient_call(rpc, op_body: Callable[[], Tuple[int, bytes]], *,
                   site: str, policy: RetryPolicy,
                   ensure_session: Optional[Callable[[], None]] = None,
                   aborted: Optional[Callable[[], bool]] = None
                   ) -> Tuple[int, bytes]:
    """One logical RPC with transparent reconnect + bounded retry.

    ``op_body()`` builds ``(opcode, body)`` fresh per attempt (a
    consumer's body embeds its CURRENT handle, which a session resume
    replaces); ``ensure_session`` runs before each attempt and may
    itself RPC (re-subscribe after a reconnect — its transient failures
    are retried like the call's own). ``aborted`` short-circuits the
    loop when the caller was closed underneath a parked retry (clean
    shutdown must not burn the whole budget reconnecting to a broker
    that was torn down on purpose).
    """
    deadline = time.monotonic() + policy.budget_s
    attempt = 0
    while True:
        try:
            if rpc.broken:
                rpc.reconnect()
            if ensure_session is not None:
                ensure_session()
            return rpc.call(*op_body())
        except TRANSIENT_ERRORS as exc:
            attempt += 1
            t0 = time.perf_counter()
            if aborted is not None and aborted():
                raise
            now = time.monotonic()
            if now >= deadline:
                raise BrokerUnavailable(
                    f"broker RPC at {site!r} failed after {attempt} "
                    f"attempts over {policy.budget_s:.1f}s: {exc!r}"
                ) from exc
            if attempt == 1:
                logger.debug("transient broker failure at %s: %r "
                             "(retrying)", site, exc)
            time.sleep(min(policy.backoff_s(attempt), deadline - now))
            _note_retry(site, attempt, exc, t0)
