"""On-disk quarantine for dead-lettered frames (the durable DLQ).

The reference nacks a poison frame forever (reference
attendance_processor.py:134-136); this framework's ``handle_poison``
bounds the retries and ACKS the frame after ``max_redeliveries`` — which
keeps the subscription live but, until now, DROPPED the bytes: the only
copy of an undecodable frame died with the ack. With
``--quarantine-dir`` set, the dead-letter path writes the frame to disk
first, so a poison frame is an ARTIFACT (triage: what exactly arrived?)
and a REPLAYABLE message (a frame dead-lettered by a since-fixed decoder
bug, or by transient in-flight corruption, re-enters the pipeline via
``doctor --replay-quarantine``).

Layout (one quarantine directory per consumer role)::

    <dir>/q-000001.frame   raw payload bytes, fsync'd first
    <dir>/q-000001.json    metadata sidecar — its presence COMMITS the
                           entry (a crash between the two writes leaves
                           an ignored orphan .frame)

Metadata: ``ts`` (epoch seconds), ``topic``, ``reason``,
``redeliveries``, ``bytes``, ``sha256`` (payload digest — lets a replay
audit prove the bytes republished are the bytes quarantined), and
``properties`` (the broker message properties, trace context included,
so a quarantined frame still points into its span tree).
"""

from __future__ import annotations

import json
import logging
import os
import threading
import time
from pathlib import Path
from typing import Dict, List, Optional

from attendance_tpu.utils.integrity import bytes_digest

logger = logging.getLogger(__name__)

_FRAME_SUFFIX = ".frame"
_META_SUFFIX = ".json"

_instances: dict = {}
_instances_lock = threading.Lock()


def get_quarantine(directory) -> "Quarantine":
    """Process-cached Quarantine per directory: the dead-letter path
    runs per poison frame, and a fresh instance would re-glob the
    whole directory to rediscover the sequence each time (O(entries)
    per dead-letter). Cross-process writers stay safe either way via
    the O_EXCL frame create."""
    key = str(Path(directory))
    with _instances_lock:
        q = _instances.get(key)
        if q is None:
            q = _instances[key] = Quarantine(directory)
        return q


def _fsync_write(path: Path, data: bytes, exclusive: bool = False) -> None:
    with open(path, "xb" if exclusive else "wb") as f:
        f.write(data)
        f.flush()
        os.fsync(f.fileno())


class Quarantine:
    """Writer half: appends dead-lettered frames to a directory."""

    def __init__(self, directory):
        self.dir = Path(directory)
        self.dir.mkdir(parents=True, exist_ok=True)
        self._lock = threading.Lock()
        self._seq = max(
            (int(p.stem.split("-")[1]) for p in
             self.dir.glob(f"q-*{_FRAME_SUFFIX}")), default=0)

    def put(self, data: bytes, *, topic: str = "", reason: str = "",
            redeliveries: int = 0,
            properties: Optional[dict] = None) -> Path:
        """Durably quarantine one frame; returns the frame path. The
        metadata sidecar lands (fsync'd) AFTER the frame bytes — its
        presence is the commit point listings honor. The frame file is
        created EXCLUSIVELY (O_EXCL) with seq-bump retry, so competing
        writers sharing one directory — other processes, or per-call
        Quarantine instances that derived the same next seq from the
        same glob — can never overwrite each other's only copy."""
        while True:
            with self._lock:
                self._seq += 1
                stem = f"q-{self._seq:06d}"
            frame = self.dir / (stem + _FRAME_SUFFIX)
            try:
                _fsync_write(frame, bytes(data), exclusive=True)
                break
            except FileExistsError:
                continue  # another writer owns this seq: take the next
        meta = {
            "ts": round(time.time(), 3),
            "topic": topic,
            "reason": reason,
            "redeliveries": int(redeliveries),
            "bytes": len(data),
            # The shared digest spelling (utils/integrity): scrub and
            # the replay audit verify the frame against this sidecar.
            "sha256": bytes_digest(data),
        }
        if properties:
            meta["properties"] = dict(properties)
        _fsync_write(self.dir / (stem + _META_SUFFIX),
                     json.dumps(meta, sort_keys=True).encode())
        from attendance_tpu import obs
        t = obs.get()
        if t is not None:
            t.registry.counter(
                "attendance_quarantined_frames_total",
                help="Frames dead-lettered into the on-disk quarantine",
                reason=reason or "unknown").inc()
        logger.error("Quarantined %d-byte frame after %d redeliveries "
                     "-> %s (%s)", len(data), redeliveries, frame,
                     reason or "unspecified")
        return frame


def list_entries(directory) -> List[Dict]:
    """Committed quarantine entries (metadata + frame path), in
    quarantine order. Orphan ``.frame`` files without a sidecar (a
    crash mid-put) are skipped — their frame was never acked, so it
    redelivers through the broker anyway."""
    d = Path(directory)
    if not d.is_dir():
        return []
    out = []
    for meta_path in sorted(d.glob(f"q-*{_META_SUFFIX}")):
        frame = meta_path.with_suffix(_FRAME_SUFFIX)
        if not frame.exists():
            continue
        try:
            meta = json.loads(meta_path.read_text())
        except (json.JSONDecodeError, OSError):
            logger.warning("unreadable quarantine sidecar %s", meta_path)
            continue
        meta["frame"] = str(frame)
        meta["name"] = meta_path.stem
        out.append(meta)
    return out


def replay(directory, producer, *, remove: bool = False) -> int:
    """Republish every committed entry's frame bytes through
    ``producer`` (original message properties reattached, so the trace
    context survives the round-trip); returns the count. With
    ``remove`` the entry's files are deleted AFTER its publish returns
    — a replay interrupted midway leaves the tail quarantined."""
    n = 0
    for entry in list_entries(directory):
        frame = Path(entry["frame"])
        data = frame.read_bytes()
        producer.send(data, entry.get("properties") or None)
        n += 1
        if remove:
            for path in (frame, frame.with_suffix(_META_SUFFIX)):
                try:
                    path.unlink()
                except OSError:
                    pass
    return n
