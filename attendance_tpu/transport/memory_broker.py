"""Hermetic in-process broker with Pulsar delivery semantics.

Implements exactly the slice of Pulsar behavior the reference relies on
(SURVEY.md §5 "failure detection"): durable topic buffering, *shared*
subscriptions where competing consumers each receive disjoint messages
(reference attendance_processor.py:30-34), per-message acknowledge, and
negative_acknowledge -> redelivery to any consumer of the subscription
(reference attendance_processor.py:132,134-136). Unacked messages from a
closed consumer return to the subscription queue (crash takeover).

Thread-safe: producers and consumers may live on different threads (the
pipelined processor overlaps host ingest with device dispatch).
"""

from __future__ import annotations

import itertools
import threading
import time
from collections import deque
from typing import Deque, Dict, Optional, Tuple

# Messages retained for late-joining subscriptions (the generator often
# finishes before the processor subscribes). Bounded so a long-running
# broker doesn't hold every payload ever published: a late subscriber sees
# the most recent RETAINED_LIMIT messages, like a topic with bounded
# retention.
RETAINED_LIMIT = 1 << 16


class ReceiveTimeout(Exception):
    """receive(timeout_millis) expired with no message (maps to
    pulsar.Timeout in the real client)."""


class Message:
    """A delivered message: payload bytes + broker bookkeeping ids."""

    __slots__ = ("_data", "message_id", "redelivery_count")

    def __init__(self, data: bytes, message_id: int, redelivery_count: int):
        self._data = data
        self.message_id = message_id
        self.redelivery_count = redelivery_count

    def data(self) -> bytes:
        return self._data


class _Subscription:
    """One named subscription on a topic: a shared pending queue plus an
    in-flight (delivered, unacked) map — Pulsar Shared subscription.
    In-flight entries record the owning consumer so a consumer close only
    requeues ITS unacked messages, not those delivered to still-live
    competing consumers (Pulsar crash-takeover semantics)."""

    def __init__(self, name: str):
        self.name = name
        self.pending: Deque[Tuple[int, bytes, int]] = deque()
        # message_id -> (payload, redeliveries, owner consumer id)
        self.inflight: Dict[int, Tuple[bytes, int, int]] = {}
        self.cond = threading.Condition()

    def enqueue(self, message_id: int, data: bytes, redeliveries: int = 0):
        with self.cond:
            self.pending.append((message_id, data, redeliveries))
            self.cond.notify()

    def receive(self, timeout_s: Optional[float],
                owner: int) -> Message:
        return self.receive_many(1, timeout_s, owner)[0]

    def receive_many_raw(self, max_n: int, timeout_s: Optional[float],
                         owner: int) -> list:
        """Drain up to max_n pending messages under ONE lock
        acquisition, returning raw ``(message_id, data, redeliveries)``
        tuples — the zero-wrapper lane for batching consumers whose
        per-event budget is microseconds (the JSON bridge). Blocks
        until at least one message is available or the timeout
        expires."""
        deadline = (None if timeout_s is None
                    else time.monotonic() + timeout_s)
        with self.cond:
            # Loop: a competing consumer may steal the message between
            # notify and wake-up, and waits can wake spuriously.
            while not self.pending:
                if deadline is None:
                    self.cond.wait()
                    continue
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    raise ReceiveTimeout(
                        f"no message within {timeout_s}s on {self.name!r}")
                self.cond.wait(remaining)
            # Bulk-pop then comprehensions: at JSON-wire rates this
            # loop IS the receive cost (hundreds of thousands of
            # per-message iterations/s), and comprehension + dict.update
            # run ~2x the interpreted append-per-message form.
            k = min(max_n, len(self.pending))
            popped = [self.pending.popleft() for _ in range(k)]
            self.inflight.update(
                (mid, (data, red, owner)) for mid, data, red in popped)
            return popped

    def receive_many(self, max_n: int, timeout_s: Optional[float],
                     owner: int) -> list:
        """Like receive_many_raw, wrapped in Message objects (the
        Pulsar batch_receive shape); receive() is the max_n=1 case."""
        return [Message(data, mid, red) for mid, data, red
                in self.receive_many_raw(max_n, timeout_s, owner)]

    def acknowledge(self, message_id: int) -> None:
        with self.cond:
            self.inflight.pop(message_id, None)

    def acknowledge_many(self, message_ids) -> None:
        with self.cond:
            for mid in message_ids:
                self.inflight.pop(mid, None)

    def negative_acknowledge(self, message_id: int) -> None:
        with self.cond:
            entry = self.inflight.pop(message_id, None)
            if entry is not None:
                data, redeliveries, _ = entry
                self.pending.append((message_id, data, redeliveries + 1))
                self.cond.notify()

    def requeue_inflight(self, owner: int) -> None:
        """Crash takeover: return the closing consumer's own unacked
        messages to the queue (other consumers' deliveries stay theirs)."""
        with self.cond:
            mine = [(mid, d, r) for mid, (d, r, o) in self.inflight.items()
                    if o == owner]
            for mid, data, redeliveries in mine:
                del self.inflight[mid]
                self.pending.append((mid, data, redeliveries + 1))
            if mine:
                self.cond.notify_all()

    def backlog(self) -> int:
        with self.cond:
            return len(self.pending) + len(self.inflight)


class _Topic:
    def __init__(self, name: str):
        self.name = name
        self.lock = threading.Lock()
        self.subscriptions: Dict[str, _Subscription] = {}
        self.retained: Deque[Tuple[int, bytes]] = deque(maxlen=RETAINED_LIMIT)
        self._ids = itertools.count()

    def subscription(self, name: str) -> _Subscription:
        with self.lock:
            sub = self.subscriptions.get(name)
            if sub is None:
                sub = self.subscriptions[name] = _Subscription(name)
                # A new subscription starts at the earliest retained
                # message (the generator may run before the processor).
                for mid, data in self.retained:
                    sub.enqueue(mid, data)
            return sub

    def publish(self, data: bytes) -> int:
        with self.lock:
            mid = next(self._ids)
            self.retained.append((mid, data))
            subs = list(self.subscriptions.values())
        for sub in subs:
            sub.enqueue(mid, data)
        return mid


class MemoryBroker:
    """Process-wide topic registry (one per process, like one broker)."""

    _shared: Optional["MemoryBroker"] = None
    _shared_lock = threading.Lock()

    def __init__(self):
        self._topics: Dict[str, _Topic] = {}
        self._lock = threading.Lock()

    @classmethod
    def shared(cls) -> "MemoryBroker":
        with cls._shared_lock:
            if cls._shared is None:
                cls._shared = cls()
            return cls._shared

    @classmethod
    def reset_shared(cls) -> None:
        with cls._shared_lock:
            cls._shared = None

    def topic(self, name: str) -> _Topic:
        with self._lock:
            t = self._topics.get(name)
            if t is None:
                t = self._topics[name] = _Topic(name)
            return t


class MemoryProducer:
    def __init__(self, topic: _Topic):
        self._topic = topic
        self._closed = False

    def send(self, data: bytes) -> int:
        if self._closed:
            raise RuntimeError("producer closed")
        return self._topic.publish(bytes(data))

    def flush(self) -> None:
        pass

    def close(self) -> None:
        self._closed = True


_consumer_ids = itertools.count()


class MemoryConsumer:
    def __init__(self, sub: _Subscription):
        self._sub = sub
        self._closed = False
        self._id = next(_consumer_ids)

    def receive(self, timeout_millis: Optional[int] = None) -> Message:
        if self._closed:
            raise RuntimeError("consumer closed")
        timeout_s = None if timeout_millis is None else timeout_millis / 1e3
        return self._sub.receive(timeout_s, self._id)

    def receive_many(self, max_n: int,
                     timeout_millis: Optional[int] = None) -> list:
        """Batch receive: up to max_n already-pending messages in one
        call (the batching consumers' fast lane; one lock round-trip
        instead of one per message)."""
        if self._closed:
            raise RuntimeError("consumer closed")
        timeout_s = None if timeout_millis is None else timeout_millis / 1e3
        return self._sub.receive_many(max_n, timeout_s, self._id)

    def receive_many_raw(self, max_n: int,
                         timeout_millis: Optional[int] = None) -> list:
        """Batch receive as raw (message_id, data, redeliveries)
        tuples — no Message wrappers. Ack with acknowledge_ids;
        reconstruct a Message(data, message_id, redeliveries) only on
        the poison path. Memory-broker extension (the real pulsar
        client has no such lane; callers feature-detect)."""
        if self._closed:
            raise RuntimeError("consumer closed")
        timeout_s = None if timeout_millis is None else timeout_millis / 1e3
        return self._sub.receive_many_raw(max_n, timeout_s, self._id)

    def acknowledge_ids(self, message_ids) -> None:
        self._sub.acknowledge_many(message_ids)

    def acknowledge(self, msg: Message) -> None:
        self._sub.acknowledge(msg.message_id)

    def acknowledge_many(self, msgs) -> None:
        self._sub.acknowledge_many([m.message_id for m in msgs])

    def negative_acknowledge(self, msg: Message) -> None:
        self._sub.negative_acknowledge(msg.message_id)

    def backlog(self) -> int:
        return self._sub.backlog()

    def close(self) -> None:
        if not self._closed:
            self._closed = True
            self._sub.requeue_inflight(self._id)


class MemoryClient:
    """pulsar.Client call-shape over the in-process broker."""

    def __init__(self, broker: MemoryBroker):
        self._broker = broker

    def create_producer(self, topic: str) -> MemoryProducer:
        return MemoryProducer(self._broker.topic(topic))

    def subscribe(self, topic: str, subscription_name: str,
                  consumer_type=None) -> MemoryConsumer:
        del consumer_type  # shared semantics are the only mode implemented
        return MemoryConsumer(
            self._broker.topic(topic).subscription(subscription_name))

    def close(self) -> None:
        pass
