"""Hermetic in-process broker with Pulsar delivery semantics.

Implements exactly the slice of Pulsar behavior the reference relies on
(SURVEY.md §5 "failure detection"): durable topic buffering, *shared*
subscriptions where competing consumers each receive disjoint messages
(reference attendance_processor.py:30-34), per-message acknowledge, and
negative_acknowledge -> redelivery to any consumer of the subscription
(reference attendance_processor.py:132,134-136). Unacked messages from a
closed consumer return to the subscription queue (crash takeover).

Thread-safe: producers and consumers may live on different threads (the
pipelined processor overlaps host ingest with device dispatch).
"""

from __future__ import annotations

import itertools
import threading
import time
from collections import deque
from typing import Deque, Dict, Optional, Tuple

# Messages retained for late-joining subscriptions (the generator often
# finishes before the processor subscribes). Bounded so a long-running
# broker doesn't hold every payload ever published: a late subscriber sees
# the most recent RETAINED_LIMIT messages, like a topic with bounded
# retention.
RETAINED_LIMIT = 1 << 16


class ReceiveTimeout(Exception):
    """receive(timeout_millis) expired with no message (maps to
    pulsar.Timeout in the real client)."""


class Message:
    """A delivered message: payload bytes + broker bookkeeping ids +
    optional string properties (the Pulsar message-properties slice the
    trace context travels in — properties survive redelivery and crash
    takeover exactly like the payload)."""

    __slots__ = ("_data", "message_id", "redelivery_count", "_props")

    def __init__(self, data: bytes, message_id: int,
                 redelivery_count: int, properties: Optional[dict] = None):
        self._data = data
        self.message_id = message_id
        self.redelivery_count = redelivery_count
        self._props = properties

    def data(self) -> bytes:
        return self._data

    def properties(self) -> dict:
        """Producer-attached string properties (pulsar.Message shape)."""
        return self._props or {}


class _Subscription:
    """One named subscription on a topic: a shared pending queue plus an
    in-flight (delivered, unacked) map — Pulsar Shared subscription.
    In-flight entries record the owning consumer so a consumer close only
    requeues ITS unacked messages, not those delivered to still-live
    competing consumers (Pulsar crash-takeover semantics)."""

    def __init__(self, name: str, topic: str = ""):
        self.name = name
        # Live telemetry hooks (obs/): resolved ONCE here — when the
        # process has no telemetry every hot-path hook below is a
        # single `is not None` branch. The queue-depth gauge is a
        # CALLBACK read at scrape time, so depth tracking costs the
        # enqueue/pop paths nothing at all.
        from attendance_tpu import obs
        t = obs.get()
        if t is not None:
            import weakref
            labels = dict(topic=topic, subscription=name)
            ref = weakref.ref(self)  # a dead sub must not be pinned
            t.registry.gauge(
                "attendance_queue_depth",
                help="Pending messages on a broker subscription",
                **labels).set_function(
                    lambda ref=ref: s._count
                    if (s := ref()) is not None else 0)
            self._obs_redelivered = t.registry.counter(
                "attendance_broker_redeliveries_total",
                help="Messages requeued by nack or consumer crash",
                **labels)
            self._obs_recv_msgs = t.registry.counter(
                "attendance_broker_received_messages_total",
                help="Messages delivered to consumers", **labels)
            self._obs_recv_bytes = t.registry.counter(
                "attendance_broker_received_bytes_total",
                help="Payload bytes delivered to consumers", **labels)
        else:
            self._obs_redelivered = None
            self._obs_recv_msgs = None
            self._obs_recv_bytes = None
        # Pending messages, block-structured: sealed blocks are
        # [entries_list, consumed_offset] pairs; _tail is the open
        # block single-message enqueues append to (sealed lazily).
        # Bulk enqueues hand their WHOLE entries list over as one
        # block, and bulk receives slice blocks back out — so the
        # per-message cost of the bulk lanes is one list-slot copy,
        # not a deque popleft + tuple churn each (the dominant broker
        # cost at JSON-wire rates). Block entry lists may be SHARED
        # (publish_many passes one list to every subscription); they
        # are immutable by convention — only the offset advances.
        self._blocks: Deque[list] = deque()
        self._tail: list = []
        self._count = 0
        # message_id -> (payload, redeliveries, owner consumer id,
        # properties)
        self.inflight: Dict[int, Tuple[bytes, int, int, Optional[dict]]] = {}
        # chunk_id -> (list of (mid, payload, red, props), owner) — the
        # chunk
        # lane's whole-batch in-flight entries (see receive_chunk).
        self.chunk_inflight: Dict[int, Tuple[list, int]] = {}
        self._chunk_ids = itertools.count()
        self.cond = threading.Condition()
        # Consumers currently blocked in a wait. Producers skip the
        # (expensive, ~1us) notify when nobody is waiting — at JSON-wire
        # rates the per-message publish cost is dominated by it.
        self._waiting = 0

    def _notify_if_waiting(self, n: int = 1) -> None:
        """Wake up to ``n`` blocked consumers — one per enqueued
        message, not one per enqueue call: a bulk block must wake every
        competing consumer it can feed, or all but one sleep through a
        full queue (lost wakeup)."""
        if self._waiting:
            self.cond.notify(min(self._waiting, n))

    # -- pending-queue internals (cond held) --------------------------------
    def _append_one(self, entry: Tuple[int, bytes, int,
                                       Optional[dict]]) -> None:
        self._tail.append(entry)
        self._count += 1

    def _append_block(self, entries: list) -> None:
        if not entries:
            return
        if self._tail:
            self._blocks.append([self._tail, 0])
            self._tail = []
        self._blocks.append([entries, 0])
        self._count += len(entries)

    def _pop_entries(self, max_n: int) -> list:
        """Up to max_n pending tuples in FIFO order (cond held,
        _count > 0). Whole-block handovers return the block's list
        itself (owned by this subscription — see enqueue_many) with
        zero per-message work; receivers treat returned token lists as
        read-only until settled (chunk entries alias them)."""
        k = min(max_n, self._count)
        self._count -= k
        parts = []
        taken = 0
        while taken < k:
            if not self._blocks:
                self._blocks.append([self._tail, 0])
                self._tail = []
            blk = self._blocks[0]
            lst, off = blk
            avail = len(lst) - off
            take = min(k - taken, avail)
            if take == avail:
                self._blocks.popleft()
                parts.append(lst if off == 0 else lst[off:])
            else:
                parts.append(lst[off:off + take])
                blk[1] = off + take
            taken += take
        if len(parts) == 1:
            return parts[0]
        return [t for p in parts for t in p]

    def enqueue(self, message_id: int, data: bytes, redeliveries: int = 0,
                properties: Optional[dict] = None):
        with self.cond:
            self._append_one((message_id, data, redeliveries, properties))
            self._notify_if_waiting()

    def enqueue_many(self, entries) -> None:
        """Bulk enqueue of (mid, data, redeliveries, properties)
        tuples: one lock
        acquisition, one block handover, one notify per waiting
        consumer it can feed. The subscription takes OWNERSHIP of a
        list argument (whole-block pops hand it back out); callers
        sharing one list across subscriptions must pass copies
        (publish_many does)."""
        entries = (entries if isinstance(entries, list)
                   else list(entries))
        with self.cond:
            self._append_block(entries)
            self._notify_if_waiting(len(entries))

    def receive(self, timeout_s: Optional[float],
                owner: int) -> Message:
        return self.receive_many(1, timeout_s, owner)[0]

    def receive_many_raw(self, max_n: int, timeout_s: Optional[float],
                         owner: int) -> list:
        """Drain up to max_n pending messages under ONE lock
        acquisition, returning raw ``(message_id, data, redeliveries,
        properties)`` tuples — the zero-wrapper lane for batching consumers whose
        per-event budget is microseconds (the JSON bridge). Blocks
        until at least one message is available or the timeout
        expires."""
        def register(popped):
            self.inflight.update(
                (mid, (data, red, owner, props))
                for mid, data, red, props in popped)

        return self._pop_pending(max_n, timeout_s, register)

    def _pop_pending(self, max_n: int, timeout_s: Optional[float],
                     register=None) -> list:
        """Block until pending is non-empty (or timeout), then bulk-pop
        up to max_n tuples under one lock acquisition (block handover:
        see _pop_entries). ``register`` runs on the popped list UNDER
        THE SAME LOCK — pop and in-flight registration must be atomic,
        or a concurrent close()'s requeue_inflight could run in the
        window where messages exist in neither pending nor inflight
        and lose them."""
        deadline = (None if timeout_s is None
                    else time.monotonic() + timeout_s)
        with self.cond:
            # Loop: a competing consumer may steal the message between
            # notify and wake-up, and waits can wake spuriously.
            while not self._count:
                remaining = None
                if deadline is not None:
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        raise ReceiveTimeout(
                            f"no message within {timeout_s}s "
                            f"on {self.name!r}")
                self._waiting += 1
                try:
                    self.cond.wait(remaining)
                finally:
                    self._waiting -= 1
            popped = self._pop_entries(max_n)
            if register is not None:
                register(popped)
            if self._obs_recv_msgs is not None:
                self._obs_recv_msgs.inc(len(popped))
                self._obs_recv_bytes.inc(
                    sum(len(t[1]) for t in popped))
            return popped

    def receive_chunk(self, max_n: int, timeout_s: Optional[float],
                      owner: int) -> Tuple[int, list]:
        """The chunk lane: like receive_many_raw, but the whole batch
        is tracked as ONE in-flight entry keyed by a chunk id — the
        per-message inflight dict traffic (the dominant broker cost at
        JSON-wire rates) drops to one dict op per BATCH. The caller
        settles the chunk wholesale (acknowledge_chunk / nack_chunk) or
        explodes it into per-message entries when it needs per-message
        ack/nack (the poison path)."""
        cid_box = []

        def register(popped):
            cid = next(self._chunk_ids)
            self.chunk_inflight[cid] = (popped, owner)
            cid_box.append(cid)

        popped = self._pop_pending(max_n, timeout_s, register)
        return cid_box[0], popped

    def acknowledge_chunk(self, chunk_id: int) -> None:
        with self.cond:
            self.chunk_inflight.pop(chunk_id, None)

    def nack_chunk(self, chunk_id: int) -> None:
        """Wholesale negative-ack: requeue every message of the chunk
        with a bumped redelivery count."""
        with self.cond:
            entry = self.chunk_inflight.pop(chunk_id, None)
            if entry is not None:
                requeued = [(mid, data, red + 1, props)
                            for mid, data, red, props in entry[0]]
                self._append_block(requeued)
                self._notify_if_waiting(len(requeued))
                if self._obs_redelivered is not None:
                    self._obs_redelivered.inc(len(requeued))

    def explode_chunk(self, chunk_id: int) -> None:
        """Convert a chunk's messages into ordinary per-message
        in-flight entries so the per-message ack/nack surface applies
        (rare: the bridge's poison path)."""
        with self.cond:
            entry = self.chunk_inflight.pop(chunk_id, None)
            if entry is not None:
                popped, owner = entry
                self.inflight.update(
                    (mid, (data, red, owner, props))
                    for mid, data, red, props in popped)

    def receive_many(self, max_n: int, timeout_s: Optional[float],
                     owner: int) -> list:
        """Like receive_many_raw, wrapped in Message objects (the
        Pulsar batch_receive shape); receive() is the max_n=1 case."""
        return [Message(data, mid, red, props)
                for mid, data, red, props
                in self.receive_many_raw(max_n, timeout_s, owner)]

    def acknowledge(self, message_id: int) -> None:
        with self.cond:
            self.inflight.pop(message_id, None)

    def acknowledge_many(self, message_ids) -> None:
        with self.cond:
            for mid in message_ids:
                self.inflight.pop(mid, None)

    def negative_acknowledge(self, message_id: int) -> None:
        with self.cond:
            entry = self.inflight.pop(message_id, None)
            if entry is not None:
                data, redeliveries, _, props = entry
                self._append_one((message_id, data, redeliveries + 1,
                                  props))
                self._notify_if_waiting()
                if self._obs_redelivered is not None:
                    self._obs_redelivered.inc()

    def requeue_inflight(self, owner: int) -> None:
        """Crash takeover: return the closing consumer's own unacked
        messages (per-message AND chunk entries) to the queue; other
        consumers' deliveries stay theirs.

        Requeued entries go to the HEAD of the pending queue, in
        publish (message-id) order: a successor consumer then replays
        the dead consumer's window BEFORE the undelivered backlog —
        the same resume-from-durable-cursor order the shm ring gives.
        Tail requeue (the old behavior) replayed the crash window
        AFTER the whole backlog, an arbitrarily large delivery
        reordering that an event-time consumer (the temporal plane's
        watermark) cannot bound a lateness budget for — the temporal
        soak caught redelivered events landing behind rotated buckets
        and side-channeling instead of counting."""
        with self.cond:
            mine = [(mid, d, r + 1, p)
                    for mid, (d, r, o, p) in self.inflight.items()
                    if o == owner]
            for mid, _, _, _ in mine:
                del self.inflight[mid]
            my_chunks = [cid for cid, (_, o) in self.chunk_inflight.items()
                         if o == owner]
            for cid in my_chunks:
                popped, _ = self.chunk_inflight.pop(cid)
                mine.extend((mid, data, red + 1, props)
                            for mid, data, red, props in popped)
            if mine:
                # Message ids are allocated monotonically at publish,
                # so sorting restores the exact original order across
                # the per-message and chunk in-flight maps.
                mine.sort(key=lambda t: t[0])
                self._blocks.appendleft([mine, 0])
                self._count += len(mine)
                self.cond.notify_all()
                if self._obs_redelivered is not None:
                    self._obs_redelivered.inc(len(mine))

    def backlog(self) -> int:
        with self.cond:
            return (self._count + len(self.inflight)
                    + sum(len(popped) for popped, _
                          in self.chunk_inflight.values()))


class _Topic:
    def __init__(self, name: str):
        self.name = name
        self.lock = threading.Lock()
        self.subscriptions: Dict[str, _Subscription] = {}
        # (mid, data, properties) — retention keeps properties so late
        # subscribers still see the trace context.
        self.retained: Deque[Tuple[int, bytes, Optional[dict]]] = deque(
            maxlen=RETAINED_LIMIT)
        self._ids = itertools.count()

    def subscription(self, name: str) -> _Subscription:
        with self.lock:
            sub = self.subscriptions.get(name)
            if sub is None:
                sub = self.subscriptions[name] = _Subscription(
                    name, topic=self.name)
                # A new subscription starts at the earliest retained
                # message (the generator may run before the processor).
                sub.enqueue_many([(mid, data, 0, props)
                                  for mid, data, props in self.retained])
            return sub

    def publish(self, data: bytes,
                properties: Optional[dict] = None) -> int:
        with self.lock:
            mid = next(self._ids)
            self.retained.append((mid, data, properties))
            subs = list(self.subscriptions.values())
        for sub in subs:
            sub.enqueue(mid, data, properties=properties)
        return mid

    def publish_many(self, datas, properties=None) -> int:
        """Bulk publish: one id/retention pass and one enqueue_many per
        subscription for the whole batch (per-message publish pays a
        lock round-trip per message — at JSON-wire rates that alone is
        ~1.4us/message). ``properties`` is an optional per-message list
        aligned with ``datas``. Returns the FIRST assigned message id;
        ids are consecutive."""
        if properties is None:
            properties = [None] * len(datas)
        with self.lock:
            entries = [(next(self._ids), bytes(d), p)
                       for d, p in zip(datas, properties)]
            self.retained.extend(entries)
            subs = list(self.subscriptions.values())
        tuples = [(mid, d, 0, p) for mid, d, p in entries]
        # Each subscription takes ownership of its block (whole-block
        # pops hand the list back out): one shared list across subs
        # would alias a consumer's returned batch with another sub's
        # live pending queue.
        for i, sub in enumerate(subs):
            sub.enqueue_many(tuples if i == 0 else list(tuples))
        return entries[0][0] if entries else -1


class MemoryBroker:
    """Process-wide topic registry (one per process, like one broker)."""

    _shared: Optional["MemoryBroker"] = None
    _shared_lock = threading.Lock()

    def __init__(self):
        self._topics: Dict[str, _Topic] = {}
        self._lock = threading.Lock()

    @classmethod
    def shared(cls) -> "MemoryBroker":
        with cls._shared_lock:
            if cls._shared is None:
                cls._shared = cls()
            return cls._shared

    @classmethod
    def reset_shared(cls) -> None:
        with cls._shared_lock:
            cls._shared = None

    def topic(self, name: str) -> _Topic:
        with self._lock:
            t = self._topics.get(name)
            if t is None:
                t = self._topics[name] = _Topic(name)
            return t


class MemoryProducer:
    def __init__(self, topic: _Topic):
        self._topic = topic
        self._closed = False
        self._seq = itertools.count()
        from attendance_tpu import obs
        t = obs.get()
        # Captured ONCE (the obs/ discipline): with telemetry off —
        # or metrics-only — every send below pays one branch.
        self._tracer = t.tracer if t is not None else None
        if t is not None:
            self._obs_msgs = t.registry.counter(
                "attendance_broker_sent_messages_total",
                help="Messages published", topic=topic.name)
            self._obs_bytes = t.registry.counter(
                "attendance_broker_sent_bytes_total",
                help="Payload bytes published", topic=topic.name)
        else:
            self._obs_msgs = None
            self._obs_bytes = None

    def send(self, data: bytes, properties: Optional[dict] = None) -> int:
        if self._closed:
            raise RuntimeError("producer closed")
        if self._obs_msgs is not None:
            self._obs_msgs.inc()
            self._obs_bytes.inc(len(data))
        if self._tracer is not None:
            # Root (or continue) the message's trace and carry the
            # context in the message properties — the Dapper hop.
            span, properties = self._tracer.begin_publish(
                self._topic.name, next(self._seq), properties)
            try:
                return self._topic.publish(bytes(data), properties)
            finally:
                self._tracer.end_span(span)
        return self._topic.publish(bytes(data), properties)

    def send_many(self, datas, properties=None) -> int:
        """Bulk send (memory-broker extension; callers feature-detect):
        one broker pass for the whole batch. ``properties`` is an
        optional per-message list. Returns the first id."""
        if self._closed:
            raise RuntimeError("producer closed")
        if self._obs_msgs is not None:
            datas = [bytes(d) for d in datas]
            self._obs_msgs.inc(len(datas))
            self._obs_bytes.inc(sum(len(d) for d in datas))
        if self._tracer is not None and properties is None:
            span, properties = self._tracer.begin_publish_many(
                self._topic.name, next(self._seq), len(datas))
            try:
                return self._topic.publish_many(datas, properties)
            finally:
                self._tracer.end_span(span)
        return self._topic.publish_many(datas, properties)

    def flush(self) -> None:
        pass

    def close(self) -> None:
        self._closed = True


_consumer_ids = itertools.count()


class MemoryConsumer:
    def __init__(self, sub: _Subscription):
        self._sub = sub
        self._closed = False
        self._id = next(_consumer_ids)

    def receive(self, timeout_millis: Optional[int] = None) -> Message:
        if self._closed:
            raise RuntimeError("consumer closed")
        timeout_s = None if timeout_millis is None else timeout_millis / 1e3
        return self._sub.receive(timeout_s, self._id)

    def receive_many(self, max_n: int,
                     timeout_millis: Optional[int] = None) -> list:
        """Batch receive: up to max_n already-pending messages in one
        call (the batching consumers' fast lane; one lock round-trip
        instead of one per message)."""
        if self._closed:
            raise RuntimeError("consumer closed")
        timeout_s = None if timeout_millis is None else timeout_millis / 1e3
        return self._sub.receive_many(max_n, timeout_s, self._id)

    def receive_many_raw(self, max_n: int,
                         timeout_millis: Optional[int] = None) -> list:
        """Batch receive as raw (message_id, data, redeliveries,
        properties) tuples — no Message wrappers. Ack with
        acknowledge_ids; reconstruct a Message(data, message_id,
        redeliveries) only on the poison path. Memory-broker extension (the real pulsar
        client has no such lane; callers feature-detect)."""
        if self._closed:
            raise RuntimeError("consumer closed")
        timeout_s = None if timeout_millis is None else timeout_millis / 1e3
        return self._sub.receive_many_raw(max_n, timeout_s, self._id)

    def receive_chunk(self, max_n: int,
                      timeout_millis: Optional[int] = None
                      ) -> Tuple[int, list]:
        """Chunk-lane batch receive: (chunk_id, raw tuples). The whole
        chunk is ONE in-flight entry; settle it with acknowledge_chunk
        / nack_chunk, or explode_chunk into per-message entries for the
        per-message ack/nack surface (poison handling). Memory-broker
        extension; callers feature-detect."""
        if self._closed:
            raise RuntimeError("consumer closed")
        timeout_s = None if timeout_millis is None else timeout_millis / 1e3
        return self._sub.receive_chunk(max_n, timeout_s, self._id)

    def acknowledge_chunk(self, chunk_id: int) -> None:
        self._sub.acknowledge_chunk(chunk_id)

    def nack_chunk(self, chunk_id: int) -> None:
        self._sub.nack_chunk(chunk_id)

    def explode_chunk(self, chunk_id: int) -> None:
        self._sub.explode_chunk(chunk_id)

    def acknowledge_ids(self, message_ids) -> None:
        self._sub.acknowledge_many(message_ids)

    def acknowledge(self, msg: Message) -> None:
        self._sub.acknowledge(msg.message_id)

    def acknowledge_many(self, msgs) -> None:
        self._sub.acknowledge_many([m.message_id for m in msgs])

    def negative_acknowledge(self, msg: Message) -> None:
        self._sub.negative_acknowledge(msg.message_id)

    def backlog(self) -> int:
        return self._sub.backlog()

    def close(self) -> None:
        if not self._closed:
            self._closed = True
            self._sub.requeue_inflight(self._id)


class MemoryClient:
    """pulsar.Client call-shape over the in-process broker."""

    def __init__(self, broker: MemoryBroker):
        self._broker = broker

    def create_producer(self, topic: str) -> MemoryProducer:
        return MemoryProducer(self._broker.topic(topic))

    def subscribe(self, topic: str, subscription_name: str,
                  consumer_type=None) -> MemoryConsumer:
        del consumer_type  # shared semantics are the only mode implemented
        return MemoryConsumer(
            self._broker.topic(topic).subscription(subscription_name))

    def close(self) -> None:
        pass
