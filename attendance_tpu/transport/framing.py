"""THE length-prefixed wire framing, shared by every socket surface.

Before this module existed the repo had grown three hand-rolled copies
of the same little-endian framing: the broker protocol
(``transport/socket_broker.py``), the query plane's batch RPC
(``serve/rpc.py``, which at least imported the broker's private
helpers), and the chunk-lane message-batch encoding duplicated between
the broker server's ``_handle`` and the client's ``_receive_op``. This
module is the single definition; the federation gossip wire
(``attendance_tpu/federation``) is the fourth user, not a fourth copy.

Frame shape (little-endian): ``u8 code, u32 body_len, body`` — ``code``
is an opcode on requests and a status on replies. Properties (the
trace-context / metadata carrier) are a u32-length-prefixed compact
JSON dict (length 0 = none). A message batch (the chunk-lane reply
carrying broker deliveries) is ``u64 chunk_id, u32 count`` followed per
message by ``u64 message_id, u32 redeliveries, u32 data_len``, the
props block, then the payload bytes.
"""

from __future__ import annotations

import json
import socket
import struct
from typing import List, Optional, Tuple

HDR = struct.Struct("<BI")

_U32 = struct.Struct("<I")
_BATCH_HDR = struct.Struct("<QI")
_MSG_HDR = struct.Struct("<QII")


def recv_exact(sock: socket.socket, n: int) -> bytes:
    """Read exactly ``n`` bytes or raise ConnectionError on EOF."""
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("connection closed")
        buf.extend(chunk)
    return bytes(buf)


def send_frame(sock: socket.socket, code: int, body: bytes) -> None:
    sock.sendall(HDR.pack(code, len(body)) + body)


def recv_frame(sock: socket.socket) -> Tuple[int, bytes]:
    code, blen = HDR.unpack(recv_exact(sock, HDR.size))
    return code, recv_exact(sock, blen) if blen else b""


def enc_props(props) -> bytes:
    """u32-length-prefixed compact JSON dict; empty/None = zero length."""
    if not props:
        return _U32.pack(0)
    body = json.dumps(props, separators=(",", ":")).encode()
    return _U32.pack(len(body)) + body


def dec_props(body: bytes, off: int):
    """-> (props_or_None, next_offset)."""
    (plen,) = _U32.unpack_from(body, off)
    off += 4
    if not plen:
        return None, off
    return json.loads(body[off:off + plen]), off + plen


def enc_message_batch(chunk_id: int, msgs) -> bytes:
    """Encode one delivery batch: ``msgs`` is a sequence of
    ``(message_id, data, redeliveries, props)`` tuples (the broker's
    raw delivery shape)."""
    parts = [_BATCH_HDR.pack(chunk_id, len(msgs))]
    for mid, data, red, props in msgs:
        parts.append(_MSG_HDR.pack(mid, red, len(data)))
        parts.append(enc_props(props))
        parts.append(data)
    return b"".join(parts)


def dec_message_batch(body: bytes) -> Tuple[int, List[tuple]]:
    """Decode one delivery batch -> (chunk_id, [(mid, data, red,
    props)]). Payloads are REAL bytes copies on purpose: the native
    frame decoder and the CPython-API JSON scanner both require bytes
    objects (memoryview slices dead-letter every frame — measured),
    and the copy is not the lane's bottleneck."""
    cid, count = _BATCH_HDR.unpack_from(body)
    out: List[tuple] = []
    off = _BATCH_HDR.size
    for _ in range(count):
        mid, red, dlen = _MSG_HDR.unpack_from(body, off)
        off += _MSG_HDR.size
        props, off = dec_props(body, off)
        out.append((mid, body[off:off + dlen], red, props))
        off += dlen
    return cid, out


def enc_str(s: str) -> bytes:
    """u16-length-prefixed UTF-8 string (topic/subscription fields)."""
    b = s.encode()
    return struct.pack("<H", len(b)) + b


def dec_str(body: bytes, off: int) -> Tuple[str, int]:
    (n,) = struct.unpack_from("<H", body, off)
    off += 2
    return body[off:off + n].decode(), off + n


CK_MAGIC = b"CKF1"
_CK_DIGEST_LEN = 32


class FrameChecksumError(ValueError):
    """A checksummed frame's payload no longer hashes to its header
    digest — in-flight rot; reject loudly, never fold wrong bytes."""


def enc_checksummed(body: bytes) -> bytes:
    """The checksum-bearing frame variant (integrity plane): magic +
    raw sha256(body) + body — ONE implementation, shared with the
    spill-record header (utils/integrity.wrap_record; only the magic
    differs). Used by the federation gossip wire and the fleet push
    wire so wire rot is rejected at the fold instead of poisoning the
    merged view. Decoders tolerate UN-wrapped legacy frames (see
    :func:`dec_checksummed`) — same tolerance pattern as the gossip
    traceparent field."""
    from attendance_tpu.utils.integrity import wrap_record

    return wrap_record(body, magic=CK_MAGIC)


def dec_checksummed(data: bytes):
    """-> (body, verified). A frame without the magic is a legacy
    frame and passes through unverified (``verified=False`` — warn
    once per peer, don't fail the fold); a wrapped frame whose digest
    no longer matches raises :class:`FrameChecksumError`."""
    from attendance_tpu.utils.integrity import (
        IntegrityError, unwrap_record)

    try:
        return unwrap_record(data, magic=CK_MAGIC)
    except IntegrityError as exc:
        raise FrameChecksumError(
            f"checksummed frame failed verification ({exc} — "
            "in-flight corruption)") from None


def enc_array(arr) -> bytes:
    """One numpy array with a self-describing u32-prefixed header —
    the federation merge frames' array block. dtype is the portable
    little-endian ``np.dtype.str`` spelling."""
    import numpy as np

    arr = np.ascontiguousarray(arr)
    hdr = enc_props({"dtype": arr.dtype.str, "shape": list(arr.shape)})
    raw = arr.tobytes()
    return hdr + _U32.pack(len(raw)) + raw


def dec_array(body: bytes, off: int):
    """-> (array, next_offset); the array is a copy (frames outlive
    the receive buffer)."""
    import numpy as np

    hdr, off = dec_props(body, off)
    (nbytes,) = _U32.unpack_from(body, off)
    off += 4
    arr = np.frombuffer(body, dtype=np.dtype(hdr["dtype"]),
                        count=int(np.prod(hdr["shape"], dtype=np.int64))
                        if hdr["shape"] else 1,
                        offset=off)
    return arr.reshape(hdr["shape"]).copy(), off + nbytes
