"""Cross-process broker transport: the memory broker behind a TCP front.

The reference gets multi-process scale-out from an external Pulsar
service: N processor processes join one Shared subscription and receive
disjoint messages (reference attendance_processor.py:30-34). This module
is the framework-native equivalent for environments without a broker
service: a :class:`BrokerServer` hosts a :class:`MemoryBroker` (same
delivery semantics: shared subscriptions, ack/nack, redelivery, crash
takeover) behind a length-prefixed TCP protocol, and :class:`SocketClient`
speaks the same producer/consumer call shape as MemoryClient — so every
existing consumer (processor, bridge, fused pipeline) scales across
PROCESSES by pointing at a broker address instead of an in-process object.

Crash takeover works across processes: when a client connection drops
(crash, kill), the server closes that connection's consumers, requeueing
their unacked messages for the surviving competitors — the Pulsar
behavior the reference relies on for fault tolerance (SURVEY.md §5).

Protocol (little-endian): request = u8 opcode, u32 body_len, body;
reply = u8 status (0 ok / 1 timeout / 2 error), u32 body_len, body.
One in-flight request per connection (synchronous RPC); batch receives
amortize the round-trip exactly like the in-process batch lanes.
Message properties (the trace-context carrier) ride as a u32-length-
prefixed JSON dict next to each payload in both directions (length 0 =
no properties), so trace context survives the TCP hop, redelivery, and
crash takeover exactly like in-process.
"""

from __future__ import annotations

import itertools
import logging
import socket
import struct
import threading
import time
from collections import deque
from typing import Dict, Optional, Tuple

from attendance_tpu.transport.framing import (
    HDR as _HDR, dec_message_batch, dec_props as _dec_props,
    enc_message_batch, enc_props as _enc_props, enc_str,
    recv_exact as _recv_exact, recv_frame as _recv_frame,
    send_frame as _send_frame)
from attendance_tpu.transport.memory_broker import (
    MemoryBroker, Message, ReceiveTimeout)
from attendance_tpu.transport.resilience import (  # noqa: F401 (re-export)
    BrokerUnavailable, ChaosDrop, RetryPolicy, note_reconnect,
    resilient_call)

logger = logging.getLogger(__name__)

_OP_PRODUCE = 1
_OP_SUBSCRIBE = 2
_OP_RECEIVE = 3
_OP_ACK_IDS = 4
_OP_NACK = 5
_OP_BACKLOG = 6
_OP_CLOSE_CONSUMER = 7
_OP_PRODUCE_MANY = 8
_OP_RECEIVE_CHUNK = 9
_OP_ACK_CHUNK = 10
_OP_NACK_CHUNK = 11
_OP_EXPLODE_CHUNK = 12

_ST_OK = 0
_ST_TIMEOUT = 1
_ST_ERROR = 2

# Default port of the standalone broker (python -m ...socket_broker) and
# of Config.socket_broker — one constant so the out-of-box recipe works.
DEFAULT_PORT = 6655

# Server-side cap on one blocking wait; a client "no timeout" receive
# loops these so a dead server can't hang a client thread forever
# (socket timeout below is the backstop). Framing itself (header
# struct, frame send/recv, props and message-batch encodings) lives in
# transport.framing — shared with serve/rpc and the federation gossip
# wire; the leading-underscore aliases above keep this module's
# historical spellings importable.
_MAX_WAIT_MS = 10_000


class BrokerServer:
    """TCP front over a MemoryBroker; one thread per client connection.

    The per-connection thread model matches the workload: a handful of
    producer/consumer processes each holding one connection, with batch
    receives doing the heavy lifting per round-trip.
    """

    def __init__(self, broker: Optional[MemoryBroker] = None,
                 host: str = "127.0.0.1", port: int = 0):
        self.broker = broker or MemoryBroker()
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((host, port))
        self._sock.listen(16)
        self.host, self.port = self._sock.getsockname()
        self._stopping = False
        self._accept_thread: Optional[threading.Thread] = None
        self._lock = threading.Lock()
        # (topic, subscription) -> live socket-consumer count, for
        # coordination (a test/parent can wait until N competitors
        # joined before publishing).
        self._consumer_counts: Dict[Tuple[str, str], int] = {}

    @property
    def address(self) -> str:
        return f"{self.host}:{self.port}"

    def start(self) -> "BrokerServer":
        self._accept_thread = threading.Thread(
            target=self._accept_loop, name="broker-accept", daemon=True)
        self._accept_thread.start()
        return self

    def stop(self) -> None:
        self._stopping = True
        try:
            self._sock.close()
        except OSError:
            pass

    def consumer_count(self, topic: str, subscription: str) -> int:
        with self._lock:
            return self._consumer_counts.get((topic, subscription), 0)

    # -- connection handling -------------------------------------------------
    def _accept_loop(self) -> None:
        while not self._stopping:
            try:
                conn, addr = self._sock.accept()
            except OSError:
                return  # listener closed
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            threading.Thread(target=self._serve_connection,
                             args=(conn, addr),
                             name=f"broker-conn-{addr[1]}",
                             daemon=True).start()

    def _serve_connection(self, conn: socket.socket, addr) -> None:
        # handle -> (MemoryConsumer, topic, subscription) owned by THIS
        # connection; a dropped connection requeues exactly these.
        consumers: Dict[int, tuple] = {}
        # Handles cross the wire as u32; exhausting the range surfaces
        # as a protocol error from alloc() BEFORE any registration, not
        # a struct.error after it.
        handle_counter = iter(range(1 << 32))
        try:
            while True:
                try:
                    op, body = _recv_frame(conn)
                except ConnectionError:
                    break
                try:
                    status, reply = self._handle(
                        op, body, consumers,
                        alloc=lambda: next(handle_counter))
                except Exception as exc:  # protocol keeps flowing
                    status, reply = _ST_ERROR, repr(exc).encode()
                try:
                    _send_frame(conn, status, reply)
                except (ConnectionError, OSError):
                    # Peer dropped mid-reply (fast client teardown
                    # severs connections abruptly): normal shutdown.
                    break
        finally:
            conn.close()
            # Cross-process crash takeover: close every consumer this
            # connection owned (requeues its unacked messages).
            for consumer, topic, sub in consumers.values():
                consumer.close()
                with self._lock:
                    self._consumer_counts[(topic, sub)] -= 1

    def _handle(self, op: int, body: bytes, consumers: Dict[int, tuple],
                alloc) -> Tuple[int, bytes]:
        if op == _OP_PRODUCE:
            (tlen,) = struct.unpack_from("<H", body)
            topic = body[2:2 + tlen].decode()
            props, off = _dec_props(body, 2 + tlen)
            payload = body[off:]
            mid = self.broker.topic(topic).publish(payload, props)
            return _ST_OK, struct.pack("<Q", mid)
        if op == _OP_SUBSCRIBE:
            (tlen,) = struct.unpack_from("<H", body)
            topic = body[2:2 + tlen].decode()
            (slen,) = struct.unpack_from("<H", body, 2 + tlen)
            sub = body[4 + tlen:4 + tlen + slen].decode()
            from attendance_tpu.transport.memory_broker import (
                MemoryConsumer)
            consumer = MemoryConsumer(
                self.broker.topic(topic).subscription(sub))
            # Allocate the handle only once the consumer exists, and
            # consume it in the same expression that registers the
            # entry: a fresh handle per alloc() means a partially
            # completed subscribe can never hand its handle to the next
            # one and orphan a registered consumer's inflight messages.
            handle = alloc()
            consumers[handle] = (consumer, topic, sub)
            with self._lock:
                key = (topic, sub)
                self._consumer_counts[key] = (
                    self._consumer_counts.get(key, 0) + 1)
            return _ST_OK, struct.pack("<I", handle)
        if op == _OP_PRODUCE_MANY:
            (tlen,) = struct.unpack_from("<H", body)
            topic = body[2:2 + tlen].decode()
            off = 2 + tlen
            (count,) = struct.unpack_from("<I", body, off)
            off += 4
            datas, props = [], []
            for _ in range(count):
                p, off = _dec_props(body, off)
                props.append(p)
                (dlen,) = struct.unpack_from("<I", body, off)
                off += 4
                datas.append(body[off:off + dlen])
                off += dlen
            first = self.broker.topic(topic).publish_many(datas, props)
            return _ST_OK, struct.pack("<q", first)
        if op in (_OP_RECEIVE, _OP_RECEIVE_CHUNK):
            handle, max_n, timeout_ms = struct.unpack("<IIi", body)
            consumer = consumers[handle][0]
            timeout_ms = min(timeout_ms, _MAX_WAIT_MS)
            try:
                if op == _OP_RECEIVE_CHUNK:
                    cid, msgs = consumer.receive_chunk(
                        max_n, timeout_millis=timeout_ms)
                else:
                    cid = 0
                    msgs = consumer.receive_many_raw(
                        max_n, timeout_millis=timeout_ms)
            except ReceiveTimeout:
                return _ST_TIMEOUT, b""
            return _ST_OK, enc_message_batch(cid, msgs)
        if op == _OP_ACK_CHUNK:
            handle, cid = struct.unpack("<IQ", body)
            consumers[handle][0].acknowledge_chunk(cid)
            return _ST_OK, b""
        if op == _OP_NACK_CHUNK:
            handle, cid = struct.unpack("<IQ", body)
            consumers[handle][0].nack_chunk(cid)
            return _ST_OK, b""
        if op == _OP_EXPLODE_CHUNK:
            handle, cid = struct.unpack("<IQ", body)
            consumers[handle][0].explode_chunk(cid)
            return _ST_OK, b""
        if op == _OP_ACK_IDS:
            handle, n = struct.unpack_from("<II", body)
            mids = struct.unpack_from(f"<{n}Q", body, 8)
            consumers[handle][0].acknowledge_ids(mids)
            return _ST_OK, b""
        if op == _OP_NACK:
            handle, mid = struct.unpack("<IQ", body)
            consumers[handle][0].negative_acknowledge(
                Message(b"", mid, 0))
            return _ST_OK, b""
        if op == _OP_BACKLOG:
            (handle,) = struct.unpack("<I", body)
            return _ST_OK, struct.pack(
                "<Q", consumers[handle][0].backlog())
        if op == _OP_CLOSE_CONSUMER:
            (handle,) = struct.unpack("<I", body)
            entry = consumers.pop(handle, None)
            if entry is not None:
                consumer, topic, sub = entry
                consumer.close()
                with self._lock:
                    self._consumer_counts[(topic, sub)] -= 1
            return _ST_OK, b""
        return _ST_ERROR, f"unknown opcode {op}".encode()


class _Rpc:
    """One synchronous request/reply channel to the server. A client's
    producers share the client channel under the lock (their calls are
    short round-trips); each consumer gets a DEDICATED channel, because
    a blocking receive holds its channel for up to a full server wait
    round (~10s) and must not stall producers or sibling consumers used
    from other threads of the same client.

    The channel is RECONNECTABLE: a transport failure marks it broken
    (and severs the socket — the server's connection-drop takeover then
    requeues any in-flight deliveries), and :meth:`reconnect` opens a
    fresh connection and bumps ``generation`` so session-holding
    callers (consumers) know their server-side handle died with the old
    connection and must re-subscribe. The retry loop around both lives
    in transport/resilience.resilient_call.

    With a chaos injector attached, each call rolls the transport
    faults at this channel's site: ``drop`` loses the request before it
    is sent (pure retry); ``conn_reset`` severs the REAL socket before
    or after the send (coin flip — request-lost vs reply-lost, the two
    wire directions), so the remediation exercised is the same
    reconnect path a genuine peer reset takes."""

    def __init__(self, address: str, *, chaos=None,
                 site: str = "socket"):
        self._address = address
        self._chaos = chaos
        self._site = site
        self._lock = threading.Lock()
        self._sock: Optional[socket.socket] = None
        self.generation = 0
        self.reconnects = 0
        self._connect_locked()

    def _connect_locked(self) -> None:
        host, port = self._address.rsplit(":", 1)
        sock = socket.create_connection((host, int(port)))
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        # Backstop: the server bounds each blocking wait at
        # _MAX_WAIT_MS, so a healthy server always replies well within
        # this; only a dead/hung server trips it.
        sock.settimeout(_MAX_WAIT_MS / 1000 + 30)
        self._sock = sock

    @property
    def broken(self) -> bool:
        return self._sock is None

    def _sever_locked(self) -> None:
        sock, self._sock = self._sock, None
        if sock is not None:
            try:
                sock.close()
            except OSError:
                pass

    def reconnect(self) -> None:
        """Open a fresh connection (idempotent: a sibling thread that
        already reconnected wins). Bumping ``generation`` is what tells
        consumers their server-side session is gone."""
        with self._lock:
            if self._sock is not None:
                return
            self._connect_locked()
            self.generation += 1
            self.reconnects += 1
        note_reconnect(self._site)

    def call(self, op: int, body: bytes) -> Tuple[int, bytes]:
        """ONE attempt; transport failures sever the channel and
        propagate (resilient_call owns the retry/reconnect loop)."""
        with self._lock:
            # Local capture: close() nulls self._sock WITHOUT the lock
            # (it must wake a parked recv, never queue behind it), so
            # every use below goes through this snapshot — a racing
            # close turns into an OSError from the closed fd, which is
            # the designed sever-and-retry path, not an AttributeError.
            sock = self._sock
            if sock is None:
                raise ConnectionError("broker connection is down")
            c = self._chaos
            sever_after = False
            if c is not None:
                d = c.delay_s(self._site)
                if d:
                    time.sleep(d)
                if c.roll(self._site, "drop"):
                    raise ChaosDrop(
                        f"chaos drop at {self._site} (request lost)")
                if c.roll(self._site, "conn_reset"):
                    if c.coin(self._site, "conn_reset"):
                        self._sever_locked()
                        raise ConnectionError(
                            f"chaos conn_reset at {self._site} "
                            "(request direction)")
                    sever_after = True  # reply direction: send executes
            try:
                _send_frame(sock, op, body)
                if sever_after:
                    self._sever_locked()
                    raise ConnectionError(
                        f"chaos conn_reset at {self._site} "
                        "(reply direction)")
                return _recv_frame(sock)
            except (ConnectionError, OSError):
                self._sever_locked()
                raise

    def try_call(self, op: int, body: bytes
                 ) -> Optional[Tuple[int, bytes]]:
        """call(), but None instead of waiting when another thread
        holds the channel (e.g. parked in a blocking receive) or the
        channel is broken (teardown must not reconnect)."""
        if not self._lock.acquire(blocking=False):
            return None
        try:
            sock = self._sock  # close() may null it concurrently
            if sock is None:
                return None
            try:
                _send_frame(sock, op, body)
                return _recv_frame(sock)
            except (ConnectionError, OSError):
                self._sever_locked()
                raise
        finally:
            self._lock.release()

    def close(self) -> None:
        # shutdown() first so a thread parked in recv() on this channel
        # wakes immediately instead of waiting out the server round.
        sock = self._sock
        self._sock = None
        if sock is None:
            return
        try:
            sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            sock.close()
        except OSError:
            pass


def _check(status: int, reply: bytes) -> bytes:
    if status == _ST_ERROR:
        raise RuntimeError(f"broker error: {reply.decode(errors='replace')}")
    return reply


class SocketProducer:
    def __init__(self, rpc: _Rpc, topic: str,
                 policy: Optional[RetryPolicy] = None):
        self._rpc = rpc
        self._topic = topic
        self._policy = policy or RetryPolicy()
        self._prefix = enc_str(topic)
        self._closed = False
        self._seq = itertools.count()
        # Client-side telemetry (obs/): wire traffic as seen by THIS
        # process (the server's own broker carries the queue gauges).
        from attendance_tpu import obs
        tel = obs.get()
        self._tracer = tel.tracer if tel is not None else None
        if tel is not None:
            self._obs_msgs = tel.registry.counter(
                "attendance_socket_sent_messages_total",
                help="Messages sent to the socket broker", topic=topic)
            self._obs_bytes = tel.registry.counter(
                "attendance_socket_sent_bytes_total",
                help="Payload bytes sent to the socket broker",
                topic=topic)
        else:
            self._obs_msgs = None
            self._obs_bytes = None

    def send(self, data: bytes, properties=None) -> int:
        if self._closed:
            raise RuntimeError("producer closed")
        if self._obs_msgs is not None:
            self._obs_msgs.inc()
            self._obs_bytes.inc(len(data))
        span = None
        if self._tracer is not None:
            span, properties = self._tracer.begin_publish(
                self._topic, next(self._seq), properties)
        body = self._prefix + _enc_props(properties) + bytes(data)
        try:
            # A retried publish whose first attempt DID execute (reply
            # lost) duplicates the message — safe: every downstream
            # sink is idempotent / read-time-deduped (SURVEY.md §5).
            status, reply = resilient_call(
                self._rpc, lambda: (_OP_PRODUCE, body),
                site="socket.produce", policy=self._policy,
                aborted=lambda: self._closed)
        finally:
            if span is not None:
                self._tracer.end_span(span)
        (mid,) = struct.unpack("<Q", _check(status, reply))
        return mid

    def send_many(self, datas, properties=None) -> int:
        """Bulk send: ONE round-trip and one broker pass for the whole
        batch (mirrors the memory producer's send_many; callers
        feature-detect). ``properties`` is an optional per-message
        list. Returns the first assigned id."""
        if self._closed:
            raise RuntimeError("producer closed")
        datas = [bytes(d) for d in datas]
        if self._obs_msgs is not None:
            self._obs_msgs.inc(len(datas))
            self._obs_bytes.inc(sum(len(d) for d in datas))
        span = None
        if self._tracer is not None and properties is None:
            span, properties = self._tracer.begin_publish_many(
                self._topic, next(self._seq), len(datas))
        if properties is None:
            properties = [None] * len(datas)
        parts = [self._prefix, struct.pack("<I", len(datas))]
        for d, p in zip(datas, properties):
            parts.append(_enc_props(p))
            parts.append(struct.pack("<I", len(d)))
            parts.append(d)
        body = b"".join(parts)
        try:
            status, reply = resilient_call(
                self._rpc, lambda: (_OP_PRODUCE_MANY, body),
                site="socket.produce", policy=self._policy,
                aborted=lambda: self._closed)
        finally:
            if span is not None:
                self._tracer.end_span(span)
        (first,) = struct.unpack("<q", _check(status, reply))
        return first

    def flush(self) -> None:
        pass

    def close(self) -> None:
        self._closed = True


class SocketConsumer:
    """Consumer call-shape of MemoryConsumer over the socket protocol,
    including the zero-wrapper raw lane (the bridge feature-detects
    receive_many_raw) and batch acks.

    Single-message ``receive()`` — the fused pipeline's frame loop —
    is PREFETCHED: one server round-trip pulls up to ``prefetch``
    pending messages and the surplus is buffered client-side, so a
    backlog of binary frames costs one RPC per ``prefetch`` frames
    instead of one per frame (the per-frame round trip was the
    socket-lane JSON probe's convergence ceiling — BENCH_r05
    ``socket_json_converged: false``). Crash semantics are unchanged:
    buffered messages are still in-flight AT THE SERVER, so a dropped
    connection requeues them for the surviving competitors exactly
    like un-received ones.

    Session resume: every RPC rides transport/resilience.resilient_call
    — a severed connection (peer reset, broker restart, injected
    ``conn_reset``) reconnects transparently and, because the
    server-side consumer handle died with the old connection, the
    consumer RE-SUBSCRIBES for a fresh handle before retrying. The
    server's connection-drop takeover requeued everything the old
    session held in flight (prefetch buffer included — it is dropped
    on resume), so redelivery covers exactly what the reconnect could
    have lost: live reconnects reuse the crash-takeover machinery. A
    broker that stays down past the retry budget surfaces ONE
    ``BrokerUnavailable``."""

    PREFETCH = 16

    def __init__(self, rpc: _Rpc, handle: int, owns_rpc: bool = False,
                 owner: "Optional[SocketClient]" = None,
                 topic: str = "", subscription: str = "",
                 prefetch: int = PREFETCH,
                 policy: Optional[RetryPolicy] = None,
                 lane: Optional[int] = None):
        self._rpc = rpc
        self._handle = handle
        self._owns_rpc = owns_rpc
        self._owner = owner
        self._closed = False
        self._prefetch = max(1, prefetch)
        self._buffered: "deque" = deque()
        self._policy = policy or RetryPolicy()
        self._session_gen = rpc.generation
        self._sub_body = _subscribe_body(topic, subscription)
        self.lane = lane  # striped-ingress lane index (None = unlaned)
        self.resubscribes = 0
        from attendance_tpu import obs
        tel = obs.get()
        if tel is not None:
            labels = dict(topic=topic, subscription=subscription)
            if lane is not None:
                labels["lane"] = str(lane)
            self._obs_msgs = tel.registry.counter(
                "attendance_socket_received_messages_total",
                help="Messages received from the socket broker",
                **labels)
            self._obs_bytes = tel.registry.counter(
                "attendance_socket_received_bytes_total",
                help="Payload bytes received from the socket broker",
                **labels)
            self._obs_nacks = tel.registry.counter(
                "attendance_socket_nacks_total",
                help="Negative acknowledgements sent", **labels)
        else:
            self._obs_msgs = None
            self._obs_bytes = None
            self._obs_nacks = None

    def _ensure_session(self) -> None:
        """Re-subscribe after a transport reconnect: the server-side
        consumer handle (and its in-flight state, prefetch buffer
        included) died with the old connection — its unacked messages
        were requeued by the connection-drop takeover and will
        redeliver to the NEW session, so dropping the stale client
        buffer loses nothing and keeps delivery in order."""
        if self._rpc.generation == self._session_gen:
            return
        status, reply = self._rpc.call(_OP_SUBSCRIBE, self._sub_body)
        (self._handle,) = struct.unpack("<I", _check(status, reply))
        self._session_gen = self._rpc.generation
        self._buffered.clear()
        self.resubscribes += 1
        logger.info("socket consumer re-subscribed after reconnect "
                    "(session %d)", self._session_gen)

    def _call(self, op: int, body_fn) -> Tuple[int, bytes]:
        """One consumer RPC through the deadline+retry helper;
        ``body_fn`` rebuilds the body per attempt so it embeds the
        CURRENT handle after a session resume."""
        return resilient_call(
            self._rpc, lambda: (op, body_fn()),
            site="socket.consume", policy=self._policy,
            ensure_session=self._ensure_session,
            aborted=lambda: self._closed)

    def _receive_op(self, op: int, max_n: int,
                    timeout_millis: Optional[int]):
        if self._closed:
            raise RuntimeError("consumer closed")
        # The server bounds one blocking wait at _MAX_WAIT_MS, so both
        # long and absent timeouts are chunked client-side.
        deadline = (None if timeout_millis is None
                    else time.monotonic() + timeout_millis / 1e3)
        while True:
            if self._closed:
                # close()/client.close() from another thread between
                # wait rounds: surface the clean shutdown signal, not
                # the dead handle's server error.
                raise RuntimeError("consumer closed")
            if deadline is None:
                wait = _MAX_WAIT_MS
            else:
                rem_ms = int((deadline - time.monotonic()) * 1000)
                if rem_ms <= 0:
                    raise ReceiveTimeout(
                        f"no message within {timeout_millis}ms")
                wait = min(rem_ms, _MAX_WAIT_MS)
            status, reply = self._call(
                op, lambda: struct.pack("<IIi", self._handle, max_n,
                                        int(wait)))
            if status == _ST_TIMEOUT:
                continue  # deadline not reached yet: wait again
            body = _check(status, reply)
            cid, out = dec_message_batch(body)
            if self._obs_msgs is not None:
                self._obs_msgs.inc(len(out))
                self._obs_bytes.inc(sum(len(t[1]) for t in out))
            return cid, out

    def receive_many_raw(self, max_n: int,
                         timeout_millis: Optional[int] = None) -> list:
        # Serve (and fully drain, up to max_n) any prefetched messages
        # first: a consumer mixing receive() with the batch lanes must
        # never see buffered messages reordered behind later ones.
        if self._buffered:
            out = []
            while self._buffered and len(out) < max_n:
                out.append(self._buffered.popleft())
            return out
        return self._receive_op(_OP_RECEIVE, max_n, timeout_millis)[1]

    def receive_chunk(self, max_n: int,
                      timeout_millis: Optional[int] = None
                      ) -> Tuple[int, list]:
        """Chunk-lane batch receive over the wire: one server-side
        in-flight entry for the whole batch, settled with
        acknowledge_chunk / nack_chunk / explode_chunk — the bridge's
        feature-detected fast lane works identically cross-process.

        Incompatible with single-message ``receive()`` on the SAME
        consumer: prefetched messages cannot be folded into a chunk
        handle, so serving the chunk lane past a non-empty buffer
        would deliver out of order (or strand the buffered messages
        until connection drop). No component mixes the lanes; fail
        loudly if one starts to."""
        if self._buffered:
            raise RuntimeError(
                "receive_chunk after receive() left prefetched "
                "messages buffered — don't mix the chunk lane with "
                "single-message receive on one consumer")
        return self._receive_op(_OP_RECEIVE_CHUNK, max_n, timeout_millis)

    def acknowledge_chunk(self, chunk_id: int) -> None:
        # Settling a chunk from a PRE-reconnect session is a server-
        # side no-op: the takeover already requeued it, and those
        # messages redeliver (at-least-once, like every retry here).
        _check(*self._call(
            _OP_ACK_CHUNK,
            lambda: struct.pack("<IQ", self._handle, chunk_id)))

    def nack_chunk(self, chunk_id: int) -> None:
        _check(*self._call(
            _OP_NACK_CHUNK,
            lambda: struct.pack("<IQ", self._handle, chunk_id)))

    def explode_chunk(self, chunk_id: int) -> None:
        _check(*self._call(
            _OP_EXPLODE_CHUNK,
            lambda: struct.pack("<IQ", self._handle, chunk_id)))

    def receive_many(self, max_n: int,
                     timeout_millis: Optional[int] = None) -> list:
        return [Message(data, mid, red, props) for mid, data, red, props
                in self.receive_many_raw(max_n, timeout_millis)]

    def receive(self, timeout_millis: Optional[int] = None) -> Message:
        """One message, served from the prefetch buffer when possible
        (ONE round-trip per ``prefetch`` backlog messages — see the
        class docstring)."""
        if not self._buffered:
            self._buffered.extend(self._receive_op(
                _OP_RECEIVE, self._prefetch, timeout_millis)[1])
        mid, data, red, props = self._buffered.popleft()
        return Message(data, mid, red, props)

    def acknowledge_ids(self, message_ids) -> None:
        mids = list(message_ids)
        _check(*self._call(
            _OP_ACK_IDS,
            lambda: struct.pack(f"<II{len(mids)}Q", self._handle,
                                len(mids), *mids)))

    def acknowledge(self, msg: Message) -> None:
        self.acknowledge_ids([msg.message_id])

    def acknowledge_many(self, msgs) -> None:
        self.acknowledge_ids([m.message_id for m in msgs])

    def negative_acknowledge(self, msg: Message) -> None:
        # Only the id crosses the wire: the subscription re-derives the
        # redelivery count from its own in-flight state on requeue.
        if self._obs_nacks is not None:
            self._obs_nacks.inc()
        _check(*self._call(
            _OP_NACK,
            lambda: struct.pack("<IQ", self._handle, msg.message_id)))

    def backlog(self) -> int:
        status, reply = self._call(
            _OP_BACKLOG, lambda: struct.pack("<I", self._handle))
        (n,) = struct.unpack("<Q", _check(status, reply))
        return n

    def _abort(self) -> None:
        """Teardown without the graceful RPC: mark closed, sever the
        owned connection (the server's connection-drop takeover
        requeues unacked messages), deregister from the owner."""
        self._closed = True
        if self._owns_rpc:
            self._rpc.close()
        if self._owner is not None:
            self._owner._consumers.discard(self)

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        try:
            # Graceful close-RPC only when the channel is free RIGHT
            # NOW: a sibling thread parked in a blocking receive holds
            # it for up to a full server wait round, and severing the
            # connection below yields the same requeue semantics.
            res = self._rpc.try_call(
                _OP_CLOSE_CONSUMER, struct.pack("<I", self._handle))
            if res is not None:
                _check(*res)
        except (ConnectionError, OSError):
            # Broker already gone: its connection-drop takeover has
            # (or will have) requeued this consumer's unacked
            # messages; raising here would only mask the original
            # failure in teardown paths.
            pass
        finally:
            # The dedicated connection must close even when the broker
            # replied with a protocol error (_ST_ERROR -> RuntimeError).
            self._abort()


def _subscribe_body(topic: str, subscription: str) -> bytes:
    return enc_str(topic) + enc_str(subscription)


class SocketClient:
    """pulsar.Client call-shape against a BrokerServer address.

    Producers share the client's channel; every consumer gets its own
    TCP connection (see _Rpc), so threaded producer+consumer use works
    like the memory broker's. Consumer connections are closed by
    consumer.close() and swept by client.close().

    ``chaos`` attaches the fault injector to every channel this client
    opens; ``policy`` shapes the retry budget all its RPCs share
    (transport/resilience.RetryPolicy)."""

    def __init__(self, address: str, *, chaos=None,
                 policy: Optional[RetryPolicy] = None):
        self._address = address
        self._chaos = chaos
        self._policy = policy or RetryPolicy()
        self._rpc = _Rpc(address, chaos=chaos, site="socket.produce")
        self._consumers: set = set()

    def create_producer(self, topic: str) -> SocketProducer:
        return SocketProducer(self._rpc, topic, policy=self._policy)

    def subscribe(self, topic: str, subscription_name: str,
                  consumer_type=None, *,
                  lane: Optional[int] = None) -> SocketConsumer:
        del consumer_type  # shared semantics, like the memory broker
        site = ("socket.consume" if lane is None
                else f"socket.consume.lane{lane}")
        rpc = _Rpc(self._address, chaos=self._chaos, site=site)
        body = _subscribe_body(topic, subscription_name)
        try:
            status, reply = resilient_call(
                rpc, lambda: (_OP_SUBSCRIBE, body),
                site=site, policy=self._policy)
            (handle,) = struct.unpack("<I", _check(status, reply))
        except BaseException:
            rpc.close()
            raise
        consumer = SocketConsumer(rpc, handle, owns_rpc=True, owner=self,
                                  topic=topic,
                                  subscription=subscription_name,
                                  policy=self._policy, lane=lane)
        self._consumers.add(consumer)
        return consumer

    def subscribe_lane(self, topic: str, subscription_name: str,
                       lane: int) -> SocketConsumer:
        """Lane-affine subscribe for the striped ingress plane: the
        lane gets its OWN TCP connection and session (reconnect,
        resume, and crash takeover are per lane — one severed lane
        never stalls its siblings), its own chaos/retry site
        (``socket.consume.laneN``) so fault streams and retry spans
        attribute to the lane, and lane-labeled traffic counters."""
        return self.subscribe(topic, subscription_name, lane=lane)

    def close(self) -> None:
        # Fast teardown: sever every consumer's dedicated connection
        # instead of the graceful close-RPC — the RPC would serialize
        # behind any thread parked in a blocking receive, and the
        # server's connection-drop takeover requeues unacked messages
        # either way.
        for consumer in list(self._consumers):
            consumer._abort()
        self._consumers.clear()
        self._rpc.close()


def spawn_broker(*, cwd=None, fleet_push: str = ""):
    """Spawn a standalone broker subprocess on an ephemeral port and
    return ``(proc, addr)`` once its startup line names the address.
    The caller owns teardown (``proc.kill()``). ``fleet_push`` points
    the broker's telemetry at a fleet collector (role=broker)."""
    import subprocess
    import sys

    cmd = [sys.executable, "-m",
           "attendance_tpu.transport.socket_broker", "--port", "0"]
    if fleet_push:
        cmd += ["--fleet-push", fleet_push]
    proc = subprocess.Popen(
        cmd, stdout=subprocess.PIPE, text=True,
        cwd=None if cwd is None else str(cwd))
    line = (proc.stdout.readline() or "").strip()
    if not line:
        rc = proc.poll()
        raise RuntimeError(
            f"broker subprocess died at startup (rc={rc})")
    return proc, line.rsplit(" ", 1)[-1]


def main(argv=None) -> None:
    """Run a standalone broker process:
    ``python -m attendance_tpu.transport.socket_broker`` (listens on
    the Config.socket_broker default; ``--port 0`` for an ephemeral
    port, printed on startup)."""
    import argparse

    p = argparse.ArgumentParser(description="attendance_tpu socket broker")
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=DEFAULT_PORT)
    p.add_argument("--metrics-port", type=int, default=0,
                   help="serve GET /metrics for this broker's queues "
                   "(0 = off, -1 = ephemeral)")
    p.add_argument("--metrics-prom", default="",
                   help="append Prometheus exposition blocks here")
    p.add_argument("--fleet-push", default="",
                   help="push this broker's telemetry (queue depths, "
                   "traffic counters) to a fleet collector at "
                   "HOST:PORT")
    args = p.parse_args(argv)
    if args.metrics_port or args.metrics_prom or args.fleet_push:
        # Enable BEFORE the broker exists so its subscriptions register
        # queue-depth gauges as clients subscribe.
        from attendance_tpu import obs
        from attendance_tpu.config import Config
        obs.enable(Config(metrics_port=args.metrics_port,
                          metrics_prom=args.metrics_prom,
                          fleet_push=args.fleet_push,
                          fleet_role="broker"))
    server = BrokerServer(host=args.host, port=args.port).start()
    print(f"broker listening on {server.address}", flush=True)
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        server.stop()


if __name__ == "__main__":
    main()
