"""Metrics registry: named Counters, Gauges, and log-bucketed Histograms.

The live half of the observability story (SURVEY.md §5): where
``ProcessorMetrics`` is an end-of-run artifact, these metrics are
readable at any moment by the exposition layer (obs.exposition) without
stopping or perturbing the hot loop.

Design constraints, in order:

* Hot-path record cost is an increment plus a bit-scan. A histogram
  ``observe`` scales the value to integer units and buckets it by
  ``int.bit_length()`` (power-of-2 bucket boundaries) — no bisect, no
  float log. The only synchronization is one per-metric mutex held for
  the increment itself; metrics never share a lock, so two pipeline
  threads recording different stages never contend.
* Disabled cost is zero: nothing in this module runs unless telemetry
  was enabled — instrumented call sites hold ``None`` and pay one
  branch (the ``utils/profiling.py`` discipline).
* Collection is lock-consistent per metric, not globally atomic: a
  scrape sees each metric at some point during the scrape, exactly like
  a Prometheus client library.

Identity is (name, sorted label items): asking the registry for the
same name+labels returns the same metric object, so call sites may
re-request handles without double-counting.
"""

from __future__ import annotations

import logging
import math
import threading
from typing import Callable, Dict, List, Optional, Tuple

logger = logging.getLogger(__name__)

# Histogram geometry: bucket i counts observations whose scaled value u
# satisfies u.bit_length() == i, i.e. u < 2**i — upper bound 2**i units.
# 28 buckets at microsecond scale span 1us .. ~134s, which brackets every
# stage latency this framework can produce (a snapshot stall measured in
# seconds sits mid-range).
NUM_BUCKETS = 28


def quantile_from_buckets(buckets: List[int], count: int, q: float,
                          scale: float = 1e6) -> float:
    """Value at quantile ``q`` of a power-of-2 bucket snapshot, with
    linear interpolation inside the landing bucket (bucket i spans
    [2**(i-1), 2**i) scaled units; bucket 0 is [0, 1)).

    ``count`` is the TOTAL observation count including overflow
    (samples past the last finite bound, which the snapshot's bucket
    list does not carry) — a rank landing there answers +Inf, the same
    "don't claim it was below the bound" honesty as the exposition's
    +Inf bucket. NaN when the snapshot is empty."""
    if count <= 0:
        return float("nan")
    if not (0.0 <= q <= 1.0):
        raise ValueError(f"quantile out of range: {q}")
    # Rank of the target observation, 1-based; q=0 -> first sample.
    rank = max(1, int(math.ceil(q * count)))
    cum = 0
    for i, b in enumerate(buckets):
        if b <= 0:
            continue
        if cum + b >= rank:
            lo = 0.0 if i == 0 else float(1 << (i - 1))
            hi = float(1 << i)
            frac = (rank - cum) / b
            return (lo + (hi - lo) * frac) / scale
        cum += b
    return float("inf")  # rank falls in the overflow tail


class Counter:
    """Monotonic counter. ``inc`` of a negative amount raises — the
    monotonicity contract is what lets consumers compute rates."""

    __slots__ = ("name", "labels", "help", "_lock", "_value")

    def __init__(self, name: str, labels: Tuple[Tuple[str, str], ...],
                 help: str = ""):
        self.name = name
        self.labels = labels
        self.help = help
        self._lock = threading.Lock()
        self._value = 0

    def inc(self, amount: int = 1) -> None:
        if amount < 0:
            raise ValueError(
                f"counter {self.name} cannot decrease (inc {amount})")
        with self._lock:
            self._value += amount

    @property
    def value(self) -> int:
        with self._lock:
            return self._value


class Gauge:
    """Set/add gauge, or a callback gauge (``set_function``) whose value
    is read lazily at collection time — queue depths cost the hot path
    nothing this way; only the scrape pays the read."""

    __slots__ = ("name", "labels", "help", "_lock", "_value", "_fn")

    def __init__(self, name: str, labels: Tuple[Tuple[str, str], ...],
                 help: str = ""):
        self.name = name
        self.labels = labels
        self.help = help
        self._lock = threading.Lock()
        self._value = 0.0
        self._fn: Optional[Callable[[], float]] = None

    def set(self, value: float) -> None:
        with self._lock:
            self._value = value
            self._fn = None

    def add(self, delta: float) -> None:
        with self._lock:
            self._value += delta

    def set_function(self, fn: Callable[[], float]) -> None:
        with self._lock:
            self._fn = fn

    def read(self) -> float:
        """Current value; a callback gauge's exception PROPAGATES —
        the exposition layer skips the sample with a warning (a bad
        device read must not render as a silent 0.0, which consumers
        would read as "FPR is zero", the opposite of broken)."""
        with self._lock:
            fn = self._fn
            if fn is None:
                return self._value
        return float(fn())

    @property
    def value(self) -> float:
        try:
            return self.read()
        except Exception:
            # A dead callback (e.g. its subscription was torn down) must
            # not break every future scrape.
            return 0.0


class Histogram:
    """Log-bucketed (power-of-2) histogram.

    ``scale`` converts observed values to integer bucket units before
    the bit-scan; the default 1e6 gives microsecond-resolution buckets
    for values observed in seconds. Upper bound of bucket i is
    ``2**i / scale`` (in observed units); the last bucket is +Inf.
    """

    __slots__ = ("name", "labels", "help", "scale", "_lock", "_buckets",
                 "_overflow", "_sum", "_count", "_exemplar")

    def __init__(self, name: str, labels: Tuple[Tuple[str, str], ...],
                 help: str = "", scale: float = 1e6):
        self.name = name
        self.labels = labels
        self.help = help
        self.scale = scale
        self._lock = threading.Lock()
        self._buckets = [0] * NUM_BUCKETS
        # Samples past the last finite bound count ONLY toward +Inf
        # (and sum/count): folding them into the last finite bucket
        # would claim e.g. a 10-minute stall was <= 134s — exactly the
        # forensic lie cumulative-bucket semantics exist to prevent.
        self._overflow = 0
        self._sum = 0.0
        self._count = 0
        # Worst observation carrying a trace id since the last scrape:
        # the OpenMetrics exemplar that links a p99 breach straight to
        # the span tree of the batch that caused it.
        self._exemplar: Optional[Tuple[float, str]] = None

    def observe(self, value: float, trace_id: str = "") -> None:
        u = int(value * self.scale)
        idx = u.bit_length() if u > 0 else 0
        with self._lock:
            if idx >= NUM_BUCKETS:
                self._overflow += 1
            else:
                self._buckets[idx] += 1
            self._sum += value
            self._count += 1
            if trace_id and (self._exemplar is None
                             or value >= self._exemplar[0]):
                self._exemplar = (value, trace_id)

    def exemplar(self, reset: bool = True) -> Optional[Tuple[float, str]]:
        """(value, trace_id) of the worst traced observation in the
        current window, or None. ``reset`` starts a new window (the
        exposition layer resets per scrape, so each block carries that
        interval's worst batch — exemplars are best-effort samples,
        not cumulative state)."""
        with self._lock:
            ex = self._exemplar
            if reset:
                self._exemplar = None
            return ex

    def bucket_bound(self, idx: int) -> float:
        """Upper bound (observed units) of bucket ``idx``."""
        return (1 << idx) / self.scale

    def snapshot(self) -> Tuple[List[int], float, int]:
        with self._lock:
            return list(self._buckets), self._sum, self._count

    def quantile(self, q: float) -> float:
        """p-quantile estimate from the live buckets (the "dequeue_wait
        p99" the tracing docstring narrates — now computable): linear
        interpolation inside the landing power-of-2 bucket, +Inf when
        the rank falls past the last finite bound, NaN when empty. The
        SLO engine computes WINDOWED quantiles from snapshot deltas
        via :func:`quantile_from_buckets` directly."""
        buckets, _, count = self.snapshot()
        return quantile_from_buckets(buckets, count, q, self.scale)

    @property
    def count(self) -> int:
        with self._lock:
            return self._count

    @property
    def sum(self) -> float:
        with self._lock:
            return self._sum


def _label_key(labels: Dict[str, str]) -> Tuple[Tuple[str, str], ...]:
    return tuple(sorted((str(k), str(v)) for k, v in labels.items()))


# Ceiling on distinct label sets per metric name. Per-day gauges (the
# audit/read-error families label by day) grow one series per lecture
# day, which is unbounded on a long multi-day run — and every series
# costs scrape time and exposition bytes FOREVER (a registry never
# forgets). Past the cap, new label sets fold into one per-family
# overflow metric and the overflow is announced ONCE at ERROR.
DEFAULT_MAX_SERIES = 1024

SERIES_GAUGE = "attendance_metric_series_total"


class Registry:
    """Get-or-create registry of metrics keyed by (name, labels).

    ``max_series`` caps distinct label sets per metric NAME (the
    cardinality guard; <= 0 = unlimited): the first overflowing
    registration logs at ERROR, and overflowing call sites receive a
    shared per-family sink metric of the right type — still safe to
    record into, just not exported — so a hot loop never crashes on a
    cardinality leak and the exposition never silently balloons. The
    registry's own series count is exported as the
    ``attendance_metric_series_total`` self-gauge, so the approach to
    the cap is itself observable."""

    def __init__(self, max_series: int = DEFAULT_MAX_SERIES):
        self._lock = threading.Lock()
        self._metrics: Dict[Tuple, object] = {}
        # name -> (kind, help), pinned by the first registration so a
        # later get with a different kind fails loudly instead of
        # corrupting the exposition.
        self._families: Dict[str, Tuple[str, str]] = {}
        self.max_series = max_series
        self._series_of: Dict[str, int] = {}  # name -> label-set count
        self._overflow: Dict[str, object] = {}  # name -> sink metric
        self._overflow_total = 0
        self.gauge(SERIES_GAUGE,
                   help="Distinct metric series (name+labels) held by "
                   "this registry — the label-cardinality guard's "
                   "self-measurement").set_function(
                       lambda: float(len(self._metrics)))

    def _get(self, kind: str, cls, name: str, help: str,
             labels: Dict[str, str], **kwargs):
        key = (name, _label_key(labels))
        with self._lock:
            m = self._metrics.get(key)
            if m is not None:
                if self._families[name][0] != kind:
                    raise ValueError(
                        f"metric {name} already registered as "
                        f"{self._families[name][0]}, not {kind}")
                return m
            fam = self._families.get(name)
            if fam is not None and fam[0] != kind:
                raise ValueError(
                    f"metric {name} already registered as {fam[0]}, "
                    f"not {kind}")
            if (self.max_series > 0
                    and self._series_of.get(name, 0) >= self.max_series):
                return self._overflow_sink(kind, cls, name, help,
                                           **kwargs)
            if fam is None:
                self._families[name] = (kind, help)
            m = cls(name, key[1], help=help or (fam[1] if fam else ""),
                    **kwargs)
            self._metrics[key] = m
            self._series_of[name] = self._series_of.get(name, 0) + 1
            return m

    def _overflow_sink(self, kind: str, cls, name: str, help: str,
                       **kwargs):
        """One shared, UNEXPORTED sink metric per overflowing family
        (lock held by caller). Returning a real metric object keeps
        every call-site contract (inc/set/observe) intact; keeping it
        out of ``_metrics`` is what stops the exposition growing."""
        self._overflow_total += 1
        sink = self._overflow.get(name)
        if sink is None:
            sink = self._overflow[name] = cls(
                name, (("overflow", "true"),), help=help, **kwargs)
            logger.error(
                "metric %s overflowed the label-cardinality cap "
                "(max_series=%d): further label sets fold into one "
                "unexported sink — a label is probably carrying an "
                "unbounded value (day, key, id)", name, self.max_series)
        return sink

    def counter(self, name: str, help: str = "", **labels) -> Counter:
        return self._get("counter", Counter, name, help, labels)

    def gauge(self, name: str, help: str = "", **labels) -> Gauge:
        return self._get("gauge", Gauge, name, help, labels)

    def histogram(self, name: str, help: str = "", scale: float = 1e6,
                  **labels) -> Histogram:
        return self._get("histogram", Histogram, name, help, labels,
                         scale=scale)

    def collect(self):
        """(name, kind, help, [metrics]) families, sorted by name —
        deterministic order keeps the exposition golden-testable."""
        with self._lock:
            metrics = list(self._metrics.values())
            families = dict(self._families)
        by_name: Dict[str, list] = {}
        for m in metrics:
            by_name.setdefault(m.name, []).append(m)
        out = []
        for name in sorted(by_name):
            kind, help = families[name]
            members = sorted(by_name[name], key=lambda m: m.labels)
            out.append((name, kind, help, members))
        return out
