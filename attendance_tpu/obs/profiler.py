"""Continuous profiling & performance attribution (ISSUE 15).

The fleet plane (PR 9) answers *what* the system is doing — rates,
lag, burn — but nothing answers *where the time goes*:
BENCH_TEMPORAL_r14 records temporal-on at 1.21M ev/s vs 15.4M off and
the trajectory can only guess it's the host passes sharing the
dispatch thread. This module is the attribution layer:

* :class:`StageTracker` — a per-thread "current pipeline stage"
  registry the instrumented hot paths mark at the SAME transitions
  that already feed the stage histograms and span tracer
  (dequeue/decode/dispatch/device_wait/temporal/snapshot/serve/
  lane_decode). One dict write per transition; a plain dict keyed by
  thread ident is GIL-atomic, so the sampler reads it lock-free.
* :class:`SamplingProfiler` — a background thread sampling
  ``sys._current_frames()`` at ``--profile-hz`` (default 0 = off),
  folding each sample into per-thread collapsed stacks attributed to
  the thread's marked stage. Exports: ``profile.folded``
  (flamegraph.pl / speedscope collapsed-stack format),
  ``profile_trace.json`` (a Chrome-trace/Perfetto stage timeline —
  consecutive same-stage samples merge into one slice per thread),
  and ``attribution.json`` (the per-stage self-time document
  ``telemetry --attribution`` renders and the bench artifact embeds).
  Stage self-time fractions are also exported live as
  ``attendance_profile_stage_fraction{stage=}`` callback gauges, so
  they ride every existing surface for free: the prom file, fleet
  pushes, ``doctor``, and the ``fleet`` dashboard's top-stage column.
* :class:`RecompileTracker` — device-side compile visibility: every
  jitted dispatch site reports its (function, shape fingerprint); a
  fingerprint never seen before is one (re)compile
  (``attendance_recompiles_total{fn=}``), and one seen after
  :meth:`RecompileTracker.mark_warm` (the first completed run loop)
  additionally counts as a STEADY-STATE recompile
  (``attendance_recompiles_steady_total{fn=}``) — the number
  ``doctor --recompile-ceiling`` gates at 0, because a steady
  pipeline recompiling means unpadded shapes are leaking into XLA
  (the recompile storms that were previously invisible).

Discipline (same as the rest of obs/): everything here is off unless
``--profile-hz`` > 0; instrumented sites capture the tracker handles
once at construction and pay one ``is not None`` branch when off.
When ON, the hot threads pay only the stage-mark dict writes and the
per-dispatch fingerprint set lookup — the sampling itself runs
entirely on the profiler's own thread.
"""

from __future__ import annotations

import json
import logging
import os
import sys
import threading
import time
from pathlib import Path
from typing import Dict, List, Optional, Tuple

logger = logging.getLogger(__name__)

# Frames kept per sampled stack (deep jax traces truncate; the hot
# loops this exists for are far shallower).
MAX_STACK_DEPTH = 48
# Chrome-trace stage slices retained (drops counted, never realloc'd).
MAX_SLICES = 1 << 16
# Distinct collapsed stacks retained; past this, new stacks fold into
# a per-(thread, stage) "(truncated)" row so a pathological workload
# cannot OOM the process through its own profiler.
MAX_STACKS = 1 << 14

FOLDED_FILE = "profile.folded"
TRACE_FILE = "profile_trace.json"
ATTRIBUTION_FILE = "attribution.json"

UNTAGGED = "untagged"


def _role_of(thread_name: str) -> str:
    """Thread name -> bounded role label: strip the per-instance
    numeric suffixes pool threads carry (``fleet-conn-51734``,
    ``Thread-3``) so the attribution table's columns stay a small
    fixed set instead of one per connection."""
    return thread_name.rstrip("0123456789").rstrip("-_") or "thread"


class StageTracker:
    """Per-thread current-stage registry.

    ``set`` returns the previous stage so nested scopes can restore
    it; long-lived single-purpose threads (snapshot writer, serve
    handlers, lane workers) mark once and stay. Reads from the
    sampler thread are lock-free: dict item assignment is atomic
    under the GIL, and a momentarily stale read mislabels at most one
    sample."""

    __slots__ = ("_stages",)

    def __init__(self):
        self._stages: Dict[int, str] = {}

    def set(self, stage: str) -> Optional[str]:
        ident = threading.get_ident()
        prev = self._stages.get(ident)
        self._stages[ident] = stage
        return prev

    def restore(self, prev: Optional[str]) -> None:
        ident = threading.get_ident()
        if prev is None:
            self._stages.pop(ident, None)
        else:
            self._stages[ident] = prev

    def get(self, ident: int) -> Optional[str]:
        return self._stages.get(ident)

    def prune(self, live_idents) -> None:
        """Drop marks of threads no longer alive (the sampler calls
        this with ``sys._current_frames()``'s key set): CPython
        recycles thread idents, so a dead serve handler's sticky mark
        would otherwise mislabel whichever later thread inherits its
        ident — and thread-per-connection churn would grow the dict
        forever. Racing a brand-new thread's first ``set`` can at
        worst drop one mark for one sample; the next transition
        re-marks."""
        for ident in list(self._stages):
            if ident not in live_idents:
                self._stages.pop(ident, None)

    def clear(self) -> None:
        self._stages.pop(threading.get_ident(), None)


class RecompileTracker:
    """Shape-fingerprint ledger over the jitted entry points.

    Dispatch sites call :meth:`observe` with their function name and
    the tuple of shape-determining parameters (key width, padded
    lane count, bank count, ...). A fingerprint's first appearance is
    exactly one XLA trace+compile of a new program variant — the
    per-frame fast path is one dict lookup plus one set-membership
    test, no lock (dispatch sites all live on the dispatch thread;
    the rare mutation takes the lock for the counters)."""

    _WARN_PER_FN = 8  # steady-recompile WARNINGs logged per fn

    def __init__(self, registry=None):
        self._registry = registry
        self._seen: Dict[str, set] = {}
        self._lock = threading.Lock()
        self._warm = False
        self._log: List[dict] = []  # bounded fingerprint log
        self._warned: Dict[str, int] = {}
        self._counters: Dict[str, object] = {}
        self._steady_counters: Dict[str, object] = {}
        self.total = 0
        self.steady = 0

    def observe(self, fn: str, fingerprint: Tuple) -> bool:
        """Record one dispatch; returns True iff this (fn,
        fingerprint) is a NEW compile."""
        seen = self._seen.get(fn)
        if seen is not None and fingerprint in seen:
            return False
        with self._lock:
            seen = self._seen.setdefault(fn, set())
            if fingerprint in seen:
                return False
            seen.add(fingerprint)
            self.total += 1
            steady = self._warm
            if steady:
                self.steady += 1
            if len(self._log) < 256:
                self._log.append({
                    "fn": fn, "fingerprint": list(fingerprint),
                    "steady": steady, "ts": round(time.time(), 3)})
        if steady:
            # A steady-state recompile is the invisible storm this
            # tracker exists for — name the shape while it happens,
            # BOUNDED per fn: during an actual storm (new shape every
            # frame) an unthrottled warning would add synchronous log
            # I/O to every hot-loop dispatch; the counters and the
            # fingerprint log carry the full count regardless.
            warned = self._warned.get(fn, 0)
            if warned < self._WARN_PER_FN:
                self._warned[fn] = warned + 1
                logger.warning(
                    "steady-state recompile: %s %r (unpadded shape "
                    "leaking into XLA?)%s", fn, fingerprint,
                    " — further warnings for this fn suppressed; "
                    "see attendance_recompiles_steady_total"
                    if warned + 1 == self._WARN_PER_FN else "")
        reg = self._registry
        if reg is not None:
            c = self._counters.get(fn)
            if c is None:
                c = self._counters[fn] = reg.counter(
                    "attendance_recompiles_total",
                    help="Jitted program variants compiled, per entry "
                    "point (one per new shape fingerprint)", fn=fn)
                self._steady_counters[fn] = reg.counter(
                    "attendance_recompiles_steady_total",
                    help="Recompiles AFTER the first completed run "
                    "loop (steady state must hold 0: a nonzero count "
                    "means unpadded shapes leak into XLA)", fn=fn)
            c.inc()
            if steady:
                self._steady_counters[fn].inc()
        return True

    def mark_warm(self) -> None:
        """Every fingerprint from here on counts as steady-state.
        Called at the end of the first completed run loop — warmup
        compiles are the expected cost of a fresh process; anything
        after is a leak."""
        self._warm = True

    @property
    def warm(self) -> bool:
        return self._warm

    def snapshot(self) -> dict:
        with self._lock:
            return {"total": self.total, "steady": self.steady,
                    "fingerprints": list(self._log)}


class SamplingProfiler:
    """Low-overhead host sampling profiler (the wall-clock half of
    the attribution plane). One daemon thread; hot threads are only
    ever READ (``sys._current_frames`` + the stage dict)."""

    def __init__(self, hz: float, *, registry=None, out_dir: str = "",
                 _clock=time.perf_counter):
        if hz <= 0:
            raise ValueError("profile hz must be positive")
        self.hz = float(hz)
        self.out_dir = out_dir
        self.stages = StageTracker()
        self._registry = registry
        self._clock = _clock
        self._epoch = time.time() - time.perf_counter()
        self._lock = threading.Lock()
        self._samples = 0
        self._by_stage: Dict[str, int] = {}
        self._by_thread_stage: Dict[Tuple[str, str], int] = {}
        self._stacks: Dict[Tuple[str, str, str], int] = {}
        self._stacks_truncated = 0
        # Per-thread stage timeline -> Chrome-trace slices.
        self._open: Dict[int, tuple] = {}  # ident -> (name, stage, t0)
        self._slices: List[tuple] = []  # (tname, ident, stage, t0, t1)
        self._slices_dropped = 0
        self._t_start: Optional[float] = None
        self._t_stop: Optional[float] = None
        self._stage_gauges: Dict[str, object] = {}
        self._stop_ev = threading.Event()
        self._thread: Optional[threading.Thread] = None
        if registry is not None:
            registry.gauge(
                "attendance_profile_samples_total",
                help="Stack samples folded by the host sampling "
                "profiler").set_function(lambda: float(self.samples))

    # -- lifecycle -----------------------------------------------------------
    def start(self) -> "SamplingProfiler":
        if self._thread is not None:
            return self
        self._t_start = time.time()
        self._stop_ev.clear()
        self._thread = threading.Thread(
            target=self._loop, name="attendance-profiler", daemon=True)
        self._thread.start()
        logger.info("Sampling profiler on at %.0f Hz%s", self.hz,
                    f" (artifacts -> {self.out_dir})"
                    if self.out_dir else "")
        return self

    def stop(self) -> None:
        """Stop sampling and close open timeline slices. Hygiene
        contract (tested): after stop() returns, the sampler thread
        is joined — no leaked thread, no samples folded after.

        Artifact writing is the OWNER's job (Telemetry.flush_profile,
        which threads the recompile ledger in, or an explicit
        :meth:`write`): writing here too would double every shutdown's
        I/O and transiently publish an attribution.json missing the
        recompiles block."""
        t = self._thread
        if t is None:
            return
        self._stop_ev.set()
        t.join(timeout=5.0)
        self._thread = None
        self._t_stop = time.time()
        now = self._wall()
        with self._lock:
            for ident, (tname, stage, t0) in self._open.items():
                self._push_slice(tname, ident, stage, t0, now)
            self._open.clear()

    @property
    def running(self) -> bool:
        return self._thread is not None and self._thread.is_alive()

    @property
    def samples(self) -> int:
        with self._lock:
            return self._samples

    # -- sampling ------------------------------------------------------------
    def _wall(self) -> float:
        return self._epoch + self._clock()

    def _loop(self) -> None:
        interval = 1.0 / self.hz
        next_t = self._clock()
        while True:
            next_t += interval
            delay = next_t - self._clock()
            if delay > 0:
                if self._stop_ev.wait(delay):
                    return
            else:
                # Fell behind (GIL-starved host): resync instead of
                # bursting catch-up samples that would overweight the
                # moment the host freed up.
                next_t = self._clock()
                if self._stop_ev.is_set():
                    return
            self.sample_once()

    def sample_once(self) -> None:
        """Take one sample of every live thread (public for tests)."""
        me = threading.get_ident()
        frames = sys._current_frames()
        self.stages.prune(frames.keys())
        names = {t.ident: t.name for t in threading.enumerate()}
        now = self._wall()
        folded = []
        for ident, frame in frames.items():
            if ident == me:
                continue
            parts: List[str] = []
            f, depth = frame, 0
            while f is not None and depth < MAX_STACK_DEPTH:
                code = f.f_code
                parts.append(f"{os.path.basename(code.co_filename)}"
                             f":{code.co_name}")
                f = f.f_back
                depth += 1
            parts.reverse()  # root first (collapsed-stack convention)
            stage = self.stages.get(ident) or UNTAGGED
            tname = names.get(ident, f"tid{ident}")
            folded.append((ident, tname, _role_of(tname), stage,
                           ";".join(parts)))
        del frames  # drop the frame refs promptly
        new_stages = []
        with self._lock:
            for ident, tname, role, stage, stack in folded:
                self._samples += 1
                if stage not in self._by_stage:
                    new_stages.append(stage)
                self._by_stage[stage] = self._by_stage.get(stage, 0) + 1
                tkey = (role, stage)
                self._by_thread_stage[tkey] = \
                    self._by_thread_stage.get(tkey, 0) + 1
                skey = (role, stage, stack)
                if skey in self._stacks or len(self._stacks) < MAX_STACKS:
                    self._stacks[skey] = self._stacks.get(skey, 0) + 1
                else:
                    self._stacks_truncated += 1
                    tk = (role, stage, "(truncated)")
                    self._stacks[tk] = self._stacks.get(tk, 0) + 1
                open_ = self._open.get(ident)
                if open_ is None:
                    self._open[ident] = (tname, stage, now)
                elif open_[1] != stage:
                    self._push_slice(open_[0], ident, open_[1],
                                     open_[2], now)
                    self._open[ident] = (tname, stage, now)
        for stage in new_stages:
            self._register_stage_gauge(stage)

    def _push_slice(self, tname: str, ident: int, stage: str,
                    t0: float, t1: float) -> None:
        # Lock held by caller.
        if len(self._slices) >= MAX_SLICES:
            self._slices_dropped += 1
            return
        self._slices.append((tname, ident, stage, t0, t1))

    def _register_stage_gauge(self, stage: str) -> None:
        reg = self._registry
        if reg is None or stage in self._stage_gauges:
            return

        def read(stage=stage) -> float:
            with self._lock:
                total = self._samples
                n = self._by_stage.get(stage, 0)
            return n / total if total else 0.0

        g = reg.gauge(
            "attendance_profile_stage_fraction",
            help="Self-time fraction of all profiler samples "
            "attributed to this pipeline stage", stage=stage)
        g.set_function(read)
        self._stage_gauges[stage] = g

    # -- exports -------------------------------------------------------------
    def collapsed(self) -> str:
        """flamegraph.pl / speedscope collapsed-stack lines:
        ``thread-role;stage;frame;frame... count``."""
        with self._lock:
            items = sorted(self._stacks.items())
        return "\n".join(
            f"{role};{stage};{stack} {count}"
            for (role, stage, stack), count in items) + ("\n" if items
                                                         else "")

    def chrome_trace(self) -> dict:
        """Stage-timeline Chrome-trace document: one ``X`` slice per
        run of consecutive same-stage samples per thread — loadable
        in Perfetto next to the span tracer's export."""
        now = self._wall()
        with self._lock:
            slices = list(self._slices)
            for ident, (tname, stage, t0) in self._open.items():
                slices.append((tname, ident, stage, t0, now))
            dropped = self._slices_dropped
            total = self._samples
        tid_of: Dict[int, int] = {}
        events: List[dict] = []
        for tname, ident, stage, t0, t1 in slices:
            tid = tid_of.get(ident)
            if tid is None:
                tid = tid_of[ident] = len(tid_of) + 1
                events.append({"name": "thread_name", "ph": "M",
                               "pid": 1, "tid": tid,
                               "args": {"name": tname}})
            events.append({"name": stage, "ph": "X", "pid": 1,
                           "tid": tid, "ts": round(t0 * 1e6, 3),
                           "dur": round(max(t1 - t0, 0.0) * 1e6, 3),
                           "args": {"source": "sampling-profiler"}})
        meta = [{"name": "process_name", "ph": "M", "pid": 1, "tid": 0,
                 "args": {"name": f"profiled pid {os.getpid()}"}}]
        return {"traceEvents": meta + events, "displayTimeUnit": "ms",
                "otherData": {"sampling_hz": self.hz,
                              "samples": total,
                              "dropped_slices": dropped}}

    def attribution(self, recompiles: Optional[RecompileTracker] = None
                    ) -> dict:
        """The per-stage self-time document: wall %% by stage x thread
        role — what ``telemetry --attribution`` renders and the bench
        artifact's attribution block embeds."""
        with self._lock:
            total = self._samples
            by_stage = dict(self._by_stage)
            by_ts = dict(self._by_thread_stage)
        t_end = self._t_stop or time.time()
        doc = {
            "kind": "attribution",
            "pid": os.getpid(),
            "hz": self.hz,
            "samples_total": total,
            "duration_s": round(
                max(t_end - (self._t_start or t_end), 0.0), 3),
            "stages": {
                stage: {"samples": n,
                        "frac": round(n / total, 6) if total else 0.0}
                for stage, n in sorted(by_stage.items())},
            "threads": {},
        }
        for (role, stage), n in sorted(by_ts.items()):
            doc["threads"].setdefault(role, {})[stage] = n
        doc["top"] = [
            [stage, doc["stages"][stage]["frac"]]
            for stage in sorted(by_stage,
                                key=lambda s: -by_stage[s])[:3]]
        if recompiles is not None:
            doc["recompiles"] = recompiles.snapshot()
        return doc

    def write(self, out_dir,
              recompiles: Optional[RecompileTracker] = None) -> Path:
        """Write the three artifacts under ``out_dir`` (atomic
        renames; idempotent). Callers: Telemetry.flush_profile — at
        run-end, telemetry stop, and atexit — which threads the
        recompile ledger in. stop() deliberately does NOT write (a
        write here too would double shutdown I/O and transiently
        publish attribution.json without the ledger). Returns the
        attribution path."""
        root = Path(out_dir)
        root.mkdir(parents=True, exist_ok=True)
        for name, payload in (
                (FOLDED_FILE, self.collapsed()),
                (TRACE_FILE, json.dumps(self.chrome_trace())),
                (ATTRIBUTION_FILE,
                 json.dumps(self.attribution(recompiles), indent=1))):
            tmp = root / (name + ".tmp")
            tmp.write_text(payload)
            tmp.replace(root / name)
        return root / ATTRIBUTION_FILE


def format_attribution_table(doc: dict) -> str:
    """Render an attribution document as the per-stage self-time
    table (wall %% by stage x thread role), stages sorted by
    self-time. The golden-file contract of ``telemetry
    --attribution``."""
    from attendance_tpu.obs.exposition import _table

    total = int(doc.get("samples_total", 0))
    stages = doc.get("stages", {})
    threads = doc.get("threads", {})
    roles = sorted(threads)
    headers = ["stage", "self%", "samples"] + roles
    rows: List[List[str]] = []
    for stage in sorted(stages, key=lambda s: -stages[s]["samples"]):
        info = stages[stage]
        row = [stage, f"{info['frac']:.1%}", str(info["samples"])]
        for role in roles:
            n = threads.get(role, {}).get(stage, 0)
            row.append(f"{n / total:.1%}" if total and n else "-")
        rows.append(row)
    head = (f"attribution: {total} samples @ "
            f"{doc.get('hz', 0):g} Hz over "
            f"{doc.get('duration_s', 0):g}s (pid {doc.get('pid')})")
    lines = [head, _table(rows, headers)]
    rec = doc.get("recompiles")
    if rec:
        lines.append(
            f"recompiles: {rec.get('total', 0)} total, "
            f"{rec.get('steady', 0)} steady-state"
            + (" (CEILING BREACH CANDIDATE)" if rec.get("steady")
               else ""))
        for fp in rec.get("fingerprints", [])[:8]:
            lines.append(
                f"  {'steady ' if fp.get('steady') else ''}"
                f"{fp.get('fn')} {tuple(fp.get('fingerprint', ()))}")
    return "\n".join(lines)
