"""Prometheus text exposition + the two delivery surfaces.

``render`` turns a Registry into Prometheus text-exposition format
(version 0.0.4 — the format every scraper and promtool parses). Two
delivery modes, both off the hot path:

* :class:`FileReporter` — a background thread appending one rendered
  block per interval to a file (``--metrics-prom``), each prefixed with
  a ``# scrape <unix_ts>`` marker so consumers (and the CLI
  ``telemetry`` verb) can split blocks.
* :class:`MetricsServer` — a stdlib ThreadingHTTPServer answering
  ``GET /metrics`` with a fresh render (``--metrics-port``); no
  third-party dependency, matching the container constraint.

Also home of the ``telemetry`` CLI verb's table formatters: a prom file
or a flight-recorder dump pretty-printed as a live-style table.
"""

from __future__ import annotations

import json
import logging
import math
import re
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import List, Optional

from attendance_tpu.obs.registry import (
    Counter, Gauge, Histogram, NUM_BUCKETS, Registry)

logger = logging.getLogger(__name__)

CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"


def _fmt(v) -> str:
    """Prometheus sample value: integers bare, floats via repr (both
    are valid exposition floats; bare ints keep counters exact).
    Non-finite values render per the text-format spec (``NaN``,
    ``+Inf``, ``-Inf``) — repr would emit ``nan``/``inf``, which
    promtool rejects, and the int-folding below would raise on them."""
    if isinstance(v, bool):
        return str(int(v))
    if isinstance(v, int):
        return str(v)
    f = float(v)
    if math.isnan(f):
        return "NaN"
    if math.isinf(f):
        return "+Inf" if f > 0 else "-Inf"
    if f == int(f) and abs(f) < 1e15:
        return str(int(f))
    return repr(f)


def _labels(items, extra: str = "") -> str:
    parts = [f'{k}="{_escape(v)}"' for k, v in items]
    if extra:
        parts.append(extra)
    return "{" + ",".join(parts) + "}" if parts else ""


def _escape(v: str) -> str:
    return str(v).replace("\\", "\\\\").replace('"', '\\"').replace(
        "\n", "\\n")


def render(registry: Registry) -> str:
    """Registry -> Prometheus text exposition (one trailing newline)."""
    lines: List[str] = []
    for name, kind, help, members in registry.collect():
        if help:
            lines.append(f"# HELP {name} {_escape(help)}")
        lines.append(f"# TYPE {name} {kind}")
        for m in members:
            if isinstance(m, Counter):
                lines.append(f"{name}{_labels(m.labels)} {_fmt(m.value)}")
            elif isinstance(m, Gauge):
                # Callback gauges (queue depths, sketch health) read
                # live state at scrape time; one raising callback (a
                # bad device read) must SKIP its sample with a warning
                # — not 500 the /metrics endpoint, not abort the prom
                # file append, and not render a lying 0.0.
                try:
                    v = m.read()
                except Exception as exc:
                    logger.warning(
                        "gauge %s%s raised at scrape time; sample "
                        "skipped: %r", name, _labels(m.labels), exc)
                    continue
                lines.append(f"{name}{_labels(m.labels)} {_fmt(v)}")
            elif isinstance(m, Histogram):
                buckets, total, count = m.snapshot()
                # OpenMetrics-style exemplar on the landing bucket: the
                # worst traced observation of this scrape window, so a
                # p99 breach links straight to its span tree. Reading
                # it resets the window (best-effort sample semantics).
                ex = m.exemplar()
                ex_idx = -1
                if ex is not None:
                    u = int(ex[0] * m.scale)
                    ex_idx = min(u.bit_length() if u > 0 else 0,
                                 NUM_BUCKETS)
                ex_suffix = ("" if ex is None else
                             f' # {{trace_id="{_escape(ex[1])}"}}'
                             f" {_fmt(ex[0])}")
                cum = 0
                for i in range(NUM_BUCKETS):
                    cum += buckets[i]
                    le = 'le="%s"' % _fmt(m.bucket_bound(i))
                    line = f"{name}_bucket{_labels(m.labels, le)} {cum}"
                    if i == ex_idx:
                        line += ex_suffix
                    lines.append(line)
                inf = 'le="+Inf"'
                line = f"{name}_bucket{_labels(m.labels, inf)} {count}"
                if ex_idx == NUM_BUCKETS:
                    line += ex_suffix
                lines.append(line)
                lines.append(f"{name}_sum{_labels(m.labels)} {_fmt(total)}")
                lines.append(f"{name}_count{_labels(m.labels)} {count}")
    return "\n".join(lines) + "\n"


class FileReporter:
    """Append a rendered block to ``path`` every ``interval_s``."""

    def __init__(self, registry: Registry, path: str,
                 interval_s: float = 1.0):
        self.registry = registry
        self.path = path
        self.interval_s = interval_s
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._loop, name="metrics-reporter", daemon=True)

    def start(self) -> "FileReporter":
        self._thread.start()
        return self

    def _write_block(self) -> None:
        block = f"# scrape {time.time():.3f}\n" + render(self.registry)
        with open(self.path, "a") as f:
            f.write(block)

    def _loop(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self._write_block()
            except Exception:
                logger.exception("metrics reporter write failed")

    def stop(self) -> None:
        self._stop.set()
        self._thread.join(timeout=5.0)
        try:
            self._write_block()  # final block: short runs still report
        except Exception:
            logger.exception("metrics reporter final write failed")


class MetricsServer:
    """``GET /metrics`` over stdlib http.server; port 0 = ephemeral
    (the bound port is exposed as ``.port``).

    Other subsystems may mount extra paths on the same endpoint via
    :meth:`add_route` (the query plane's ``/query/*`` verbs,
    serve/http.py): a route handler takes ``(method, path, query_str,
    body_bytes)`` and returns ``(status, content_type, body_bytes)``.
    Routes are matched by exact path after stripping the query string;
    a raising handler answers 500 with the repr — never a hung
    connection."""

    def __init__(self, registry: Registry, port: int,
                 host: str = "127.0.0.1"):
        outer = self
        self.routes: dict = {}

        class Handler(BaseHTTPRequestHandler):
            def _dispatch(self, method: str):
                path, _, query = self.path.partition("?")
                route = outer.routes.get(path)
                if route is not None:
                    length = int(self.headers.get("Content-Length")
                                 or 0)
                    body = self.rfile.read(length) if length else b""
                    try:
                        status, ctype, reply = route(method, path,
                                                     query, body)
                    except Exception as exc:
                        status, ctype = 500, "text/plain; charset=utf-8"
                        reply = repr(exc).encode()
                    self.send_response(status)
                    self.send_header("Content-Type", ctype)
                    self.send_header("Content-Length", str(len(reply)))
                    self.end_headers()
                    self.wfile.write(reply)
                    return
                if method == "GET" and path in ("/metrics", "/"):
                    body = render(outer.registry).encode()
                    self.send_response(200)
                    self.send_header("Content-Type", CONTENT_TYPE)
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                    return
                self.send_error(404)

            def do_GET(self):  # noqa: N802 (stdlib naming)
                self._dispatch("GET")

            def do_POST(self):  # noqa: N802 (stdlib naming)
                self._dispatch("POST")

            def log_message(self, *args):  # scrapes are not log lines
                pass

        self.registry = registry
        self._httpd = ThreadingHTTPServer((host, port), Handler)
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="metrics-http",
            daemon=True)

    def add_route(self, path: str, handler) -> None:
        """Mount ``handler(method, path, query, body) -> (status,
        content_type, body_bytes)`` at an exact path."""
        self.routes[path] = handler

    def remove_route(self, path: str) -> None:
        """Unmount a path (idempotent). Subsystems that mounted routes
        must remove them on teardown: the server is process-global, so
        a leaked closure would keep answering from (and pinning) a
        dead owner's state."""
        self.routes.pop(path, None)

    def start(self) -> "MetricsServer":
        self._thread.start()
        logger.info("Serving Prometheus metrics on :%d/metrics",
                    self.port)
        return self

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        self._thread.join(timeout=5.0)


# -- CLI table formatting ----------------------------------------------------

def parse_prom(text: str):
    """Samples of the LAST scrape block: [(name, labels_str, value)].
    Accepts both reporter files (multiple ``# scrape`` blocks) and a
    single raw exposition."""
    blocks = text.split("# scrape ")
    last = blocks[-1]
    if len(blocks) > 1:  # drop the timestamp line of the marker
        last = last.split("\n", 1)[1] if "\n" in last else ""
    samples = []
    for line in last.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        if " # " in line:
            # Drop an OpenMetrics exemplar suffix (` # {trace_id=...}
            # <value>`) so pre-exemplar offline consumers keep parsing
            # the sample itself; parse_exemplars reads the suffix.
            line = line.split(" # ", 1)[0].rstrip()
        try:
            metric, value = line.rsplit(" ", 1)
        except ValueError:
            continue
        if "{" in metric:
            name, rest = metric.split("{", 1)
            labels = rest.rstrip("}")
        else:
            name, labels = metric, ""
        samples.append((name, labels, value))
    return samples


_EXEMPLAR_RE = re.compile(r'\{trace_id="([^"]*)"\}\s+(\S+)')


def parse_exemplars(text: str):
    """OpenMetrics exemplars of the LAST scrape block:
    {(base_name, labels_without_le): (value, trace_id)} — the worst
    traced observation per histogram series, the jump from a latency
    breach into the trace slice."""
    blocks = text.split("# scrape ")
    last = blocks[-1]
    if len(blocks) > 1:
        last = last.split("\n", 1)[1] if "\n" in last else ""
    out = {}
    for line in last.splitlines():
        line = line.strip()
        if not line or line.startswith("#") or " # " not in line:
            continue
        metric_part, ex_part = line.split(" # ", 1)
        m = _EXEMPLAR_RE.match(ex_part.strip())
        if m is None:
            continue
        metric = metric_part.rsplit(" ", 1)[0]
        if "{" in metric:
            name, rest = metric.split("{", 1)
            labels = rest.rstrip("}")
        else:
            name, labels = metric, ""
        if name.endswith("_bucket"):
            name = name[:-len("_bucket")]
            labels = ",".join(p for p in labels.split(",")
                              if p and not p.startswith("le="))
        try:
            out[(name, labels)] = (float(m.group(2)), m.group(1))
        except ValueError:
            continue
    return out


def _table(rows: List[List[str]], headers: List[str]) -> str:
    widths = [max(len(h), *(len(r[i]) for r in rows)) if rows else len(h)
              for i, h in enumerate(headers)]
    fmt = "  ".join(f"{{:<{w}}}" for w in widths)
    out = [fmt.format(*headers), fmt.format(*("-" * w for w in widths))]
    out.extend(fmt.format(*r) for r in rows)
    return "\n".join(out)


def label_value(labels: str, key: str) -> Optional[str]:
    """Value of one label in a parse_prom labels string, unquoted;
    None when absent — the ONE label-value parse every offline
    consumer (doctor rows, fleet headlines, the le= bound below)
    shares."""
    for part in labels.split(","):
        if part.startswith(f"{key}="):
            return part[len(key) + 1:].strip('"')
    return None


def _parse_le(labels: str) -> Optional[float]:
    raw = label_value(labels, "le")
    if raw is None:
        return None
    return float("inf") if raw == "+Inf" else float(raw)


def quantiles_from_cumulative(pairs, qs) -> List[float]:
    """Quantile estimates from cumulative (le_bound, cum_count) bucket
    samples, linearly interpolated inside the landing bucket (the
    text-exposition counterpart of registry.quantile_from_buckets —
    this one works from a scraped prom file, where only the cumulative
    form survives). NaN per quantile when the histogram is empty; +Inf
    when the rank lands in the +Inf bucket."""
    pairs = sorted(pairs, key=lambda p: p[0])
    count = pairs[-1][1] if pairs else 0.0
    out = []
    for q in qs:
        if count <= 0:
            out.append(float("nan"))
            continue
        rank = max(1.0, math.ceil(q * count))
        lo_bound, lo_cum = 0.0, 0.0
        value = float("inf")
        for bound, cum in pairs:
            if cum >= rank:
                if math.isinf(bound):
                    value = bound
                elif cum > lo_cum:
                    frac = (rank - lo_cum) / (cum - lo_cum)
                    value = lo_bound + (bound - lo_bound) * frac
                else:
                    value = bound
                break
            lo_bound, lo_cum = bound, cum
        out.append(value)
    return out


def fold_headline_samples(samples, acc: Optional[dict] = None) -> dict:
    """Fold one exposition's parsed samples into the shared headline
    accumulator — the ONE definition of the cross-role headline
    numbers both fleet surfaces read (`fleet` status/dashboard and
    ``doctor --fleet``'s fleet-wide rows): events sum, SLO-firing
    count, per-sample read-staleness values, the series self-gauge,
    and merge-lag cumulative buckets summed by ``le`` (so folding
    several roles' samples yields the merged histogram). Pass the
    returned ``acc`` back in to accumulate across instances."""
    if acc is None:
        acc = {"events": 0.0, "have_events": False, "firing": 0,
               "staleness": [], "series": None, "lag_by_le": {},
               "prof_stages": {}, "incidents": None}
    for name, labels, value in samples:
        try:
            v = float(value)
        except ValueError:
            continue
        if math.isnan(v):
            continue
        if name == "attendance_events_total":
            acc["events"] += v
            acc["have_events"] = True
        elif name == "attendance_slo_firing" and v >= 1.0:
            acc["firing"] += 1
        elif name == "attendance_read_staleness_seconds":
            acc["staleness"].append(v)
        elif name == "attendance_metric_series_total":
            acc["series"] = int(v)
        elif name == "attendance_incidents_open":
            # Summed across folded instances; None stays "metric
            # absent" (pre-17 exposition) vs 0 "engine on, no incident".
            acc["incidents"] = int(v) + (acc["incidents"] or 0)
        elif name == "attendance_profile_stage_fraction":
            # Sampling-profiler self-time per stage (ISSUE 15) — the
            # fleet surfaces render each role's top stage from it.
            stage = label_value(labels, "stage")
            if stage is not None:
                acc["prof_stages"][stage] = max(
                    acc["prof_stages"].get(stage, 0.0), v)
        elif name == "attendance_fed_merge_lag_seconds_bucket":
            le = _parse_le(labels)
            if le is not None:
                acc["lag_by_le"][le] = (acc["lag_by_le"].get(le, 0.0)
                                        + v)
    return acc


def rank_profile_stages(fracs: dict, top: int = 3) -> list:
    """Busiest-first (stage, fraction) pairs with marked stages
    ranking above the untagged remainder (untagged shows only when it
    is all there is) — the ONE ordering shared by the fleet
    dashboard's ``top_stage`` cell and doctor's "profiled top stages"
    row, so the two surfaces can never name different top stages for
    the same exposition."""
    tagged = {s: v for s, v in fracs.items() if s != "untagged"} \
        or fracs
    return sorted(tagged.items(), key=lambda kv: -kv[1])[:top]


def format_prom_table(text: str) -> str:
    """Live-style table of the last scrape block of a prom file.
    Histograms are folded to count/sum/mean plus p50/p95/p99 derived
    from the cumulative buckets (registry.Histogram.quantile's offline
    twin) — the raw buckets stay in the file for machine consumers."""
    samples = parse_prom(text)
    exemplars = parse_exemplars(text)
    hist: dict = {}
    rows = []
    for name, labels, value in samples:
        for suffix in ("_bucket", "_sum", "_count"):
            if name.endswith(suffix):
                base = name[:-len(suffix)]
                key_labels = ",".join(
                    p for p in labels.split(",") if not
                    p.startswith("le=")) if suffix == "_bucket" else labels
                h = hist.setdefault((base, key_labels), {})
                if suffix == "_bucket":
                    le = _parse_le(labels)
                    if le is not None:
                        h.setdefault("_buckets", []).append(
                            (le, float(value)))
                else:
                    h[suffix] = value
                break
        else:
            rows.append([name, labels, value])
    for (base, labels), h in sorted(hist.items()):
        count = float(h.get("_count", 0) or 0)
        total = float(h.get("_sum", 0) or 0)
        mean = f"{total / count:.6g}" if count else "n/a"
        cell = f"count={int(count)} sum={total:.6g} mean={mean}"
        if count and h.get("_buckets"):
            p50, p95, p99 = quantiles_from_cumulative(
                h["_buckets"], (0.50, 0.95, 0.99))
            cell += (f" p50={p50:.6g} p95={p95:.6g} p99={p99:.6g}")
        ex = exemplars.get((base, labels))
        if ex is not None:
            cell += f" exemplar={ex[1]}@{ex[0]:.6g}"
        rows.append([base, labels, cell])
    rows.sort()
    return _table(rows, ["metric", "labels", "value"])


def format_flight_table(doc: dict, last: int = 32) -> str:
    """Flight-recorder dump -> table of the most recent records."""
    records = doc.get("records", [])[-last:]
    cols: List[str] = []
    for r in records:
        for k in r:
            if k not in cols:
                cols.append(k)
    rows = [[str(r.get(c, "")) for c in cols] for r in records]
    head = (f"flight recorder dump: reason={doc.get('reason')} "
            f"pid={doc.get('pid')} total_records="
            f"{doc.get('total_records')} ring={doc.get('ring_size')} "
            f"(showing last {len(records)})")
    return head + "\n" + _table(rows, cols or ["(empty)"])


def format_trace_tree(doc: dict, last: int = 32) -> str:
    """Chrome-trace export (--trace-out) -> per-trace span trees with
    durations: one block per trace_id (most recent ``last`` traces),
    spans indented under their parent in start order, each line
    ``name  dur  [role]  {extra args}``."""
    # Normalize up front: the trace-event format permits args-less
    # events (foreign/profiler traces routed here by format_file's
    # sniffing) and this formatter must print a tree, not KeyError.
    events = [{**e, "args": e.get("args") or {}}
              for e in doc.get("traceEvents", [])
              if e.get("ph") == "X"]
    roles = {e["pid"]: (e.get("args") or {}).get("name", "")
             for e in doc.get("traceEvents", [])
             if e.get("ph") == "M" and e.get("name") == "process_name"}
    by_trace: dict = {}
    for e in events:
        tid = e["args"].get("trace_id", "?")
        by_trace.setdefault(tid, []).append(e)
    # Most recent traces last, ordered by their earliest span.
    ordered = sorted(by_trace.items(),
                     key=lambda kv: min(e.get("ts", 0) for e in kv[1]))
    shown = ordered[-last:]
    out = [f"trace export: {len(events)} spans in {len(by_trace)} "
           f"traces (showing last {len(shown)}); "
           f"dropped={doc.get('otherData', {}).get('dropped_spans', 0)}"]

    def _fmt_dur(us: float) -> str:
        return (f"{us / 1e3:.3f}ms" if us < 1e6 else f"{us / 1e6:.3f}s")

    for trace_id, spans in shown:
        spans.sort(key=lambda e: (e.get("ts", 0), -e.get("dur", 0)))
        children: dict = {}
        ids = {e["args"].get("span_id") for e in spans}
        roots = []
        for e in spans:
            parent = e["args"].get("parent_span_id")
            if parent in ids and parent != e["args"].get("span_id"):
                children.setdefault(parent, []).append(e)
            else:
                roots.append(e)
        out.append(f"trace {trace_id}:")
        stack = [(e, 1) for e in reversed(roots)]
        while stack:
            e, depth = stack.pop()
            extra = {k: v for k, v in e["args"].items()
                     if k not in ("trace_id", "span_id",
                                  "parent_span_id")}
            role = roles.get(e["pid"], "")
            out.append("  " * depth + f"{e['name']}  "
                       f"{_fmt_dur(e.get('dur', 0))}"
                       + (f"  [{role}]" if role else "")
                       + (f"  {extra}" if extra else ""))
            for c in reversed(children.get(e["args"].get("span_id"),
                                           [])):
                stack.append((c, depth + 1))
    return "\n".join(out)


def format_file(path: str, last: int = 32) -> str:
    """Sniff ``path`` (trace export / flight-dump JSON / prom text)
    and format it."""
    with open(path) as f:
        text = f.read()
    stripped = text.lstrip()
    if stripped.startswith("{"):
        doc = json.loads(text)
        if doc.get("kind") == "attribution":
            from attendance_tpu.obs.profiler import (
                format_attribution_table)
            return format_attribution_table(doc)
        if "traceEvents" in doc:
            return format_trace_tree(doc, last=last)
        return format_flight_table(doc, last=last)
    return format_prom_table(text)
