"""SLO burn-rate engine + the offline ``doctor`` verdict.

Turns the ROADMAP's acceptance targets into runtime-evaluated SLOs
(Beyer et al., *Site Reliability Engineering*, multi-window
multi-burn-rate alerting): each declarative objective is sampled every
tick, classified breach/ok, and aggregated over a FAST and a SLOW
window. Burn rate = breaching fraction / error budget; an alert FIRES
only when both windows burn past the firing threshold — the slow
window rejects single-window spikes, the fast window keeps detection
fresh — and CLEARS with hysteresis (fast burn must fall below half the
firing threshold), so a breach oscillating around the ceiling cannot
flap the alert.

Outputs:

* ``attendance_slo_burn_rate{slo=...,window=fast|slow}`` gauges and
  ``attendance_slo_firing{slo=...}`` 0/1 on the normal scrape surface;
* a structured JSONL alert log (``--alert-log``): one line per
  transition (firing/resolved) with value, threshold, both burns, and
  the most recent batch's trace id for cross-reference;
* a flight-recorder record per transition (``alert``/``state``
  fields), so a ring dump shows WHERE in the batch stream the SLO
  broke.

The ``doctor`` half replays run artifacts OFFLINE — a prom exposition
file, the alert log, a flight dump, a trace export — and prints a
pass/fail verdict table with a non-zero exit on breach: the artifacts
a run already writes become CI-gateable without rerunning anything.
"""

from __future__ import annotations

import dataclasses
import json
import logging
import math
import re
import threading
import time
from pathlib import Path
from typing import Dict, List, Optional, Sequence, Tuple

logger = logging.getLogger(__name__)

# Classic SRE page-tier burn threshold: with a 1% error budget, firing
# needs a sustained >=14.4% breaching fraction in BOTH windows.
DEFAULT_BUDGET = 0.01
DEFAULT_FIRE_BURN = 14.4
CLEAR_RATIO = 0.5  # hysteresis: clear only below half the fire burn

# Version stamped on every alert-log JSONL event and incident record so
# offline consumers can evolve; pre-17 logs have no field and readers
# tolerate that with a single warning.
ALERT_SCHEMA = 1

SLO_HELP = {
    "attendance_slo_burn_rate":
        "SLO burn rate (breaching fraction / error budget) per window",
    "attendance_slo_firing":
        "1 while the SLO's alert is firing, else 0",
    "attendance_slo_alerts_total":
        "Alert transitions to firing, per SLO",
}


@dataclasses.dataclass(frozen=True)
class Slo:
    """One declarative objective over the live registry.

    kind: ``gauge`` (max over the family's samples), ``counter``
    (total), ``rate`` (d(counter)/dt per tick), or ``quantile``
    (p-quantile of the tick interval's fresh histogram observations).
    ``op`` is the HEALTHY direction: ``<=`` is a ceiling, ``>=`` a
    floor; a tick breaches when the value violates it."""
    name: str
    kind: str
    metric: str
    op: str
    threshold: float
    # One (label, value) pair the metric's members must carry, e.g.
    # ("stage", "dequeue_wait"); () matches every member.
    label_filter: Tuple[str, ...] = ()
    quantile: float = 0.0


# The paper's acceptance targets (ROADMAP north star), always installed
# when the engine is on: measured — not estimated — accuracy ceilings,
# and the structural zero-false-negative invariant.
DEFAULT_SLOS = (
    Slo("bloom_measured_fpr", "gauge",
        "attendance_bloom_measured_fpr", "<=", 0.01),
    Slo("bloom_false_negatives", "counter",
        "attendance_bloom_false_negatives_total", "<=", 0.0),
    Slo("hll_measured_rel_error", "gauge",
        "attendance_hll_measured_rel_error", "<=", 0.02),
)

_STAGE_ALIAS = {"dequeue": "dequeue_wait", "device": "device_wait",
                "assembly": "batch_assembly"}
# Every stage name the pipelines actually time (grep `.stage("...")`).
# A quantile spec naming anything else would sit in the registry and
# never fire — reject it at parse (= config) time instead: a dead
# objective is worse than none, because a human (or the controller)
# believes it is being watched.
KNOWN_STAGES = frozenset({
    "dequeue_wait", "decode", "dispatch", "device_wait",
    "snapshot_write", "snapshot_blocked", "batch_assembly", "sketch",
    "persist", "query",
})
_QUANTILE_RE = re.compile(r"^([a-z_]+)_p(\d{1,2})$")


def parse_slo(spec: str) -> Slo:
    """Parse one ``--slo`` spec: ``alias<=value`` / ``alias>=value``.

    Aliases: ``fpr`` / ``false_negatives`` / ``hll_error`` (override
    the default ceilings), ``throughput`` (events/s rate floor), and
    ``<stage>_p<NN>`` latency-quantile ceilings over the stage
    histograms (``dequeue_p99``, ``device_p95``, ``sketch_p50``, ...;
    ``dequeue``/``device``/``assembly`` expand to their full stage
    names)."""
    for op in ("<=", ">="):
        if op in spec:
            alias, _, raw = spec.partition(op)
            alias = alias.strip()
            try:
                threshold = float(raw)
            except ValueError:
                raise ValueError(f"bad SLO threshold in {spec!r}")
            break
    else:
        raise ValueError(
            f"bad SLO spec {spec!r} (want alias<=value or alias>=value)")
    if alias == "fpr":
        return Slo("bloom_measured_fpr", "gauge",
                   "attendance_bloom_measured_fpr", op, threshold)
    if alias == "false_negatives":
        return Slo("bloom_false_negatives", "counter",
                   "attendance_bloom_false_negatives_total", op,
                   threshold)
    if alias == "hll_error":
        return Slo("hll_measured_rel_error", "gauge",
                   "attendance_hll_measured_rel_error", op, threshold)
    if alias == "throughput":
        return Slo("throughput", "rate", "attendance_events_total",
                   op, threshold)
    if alias == "read_staleness":
        # The query plane's freshness objective: the published read
        # epoch's age (bounded by the snapshot barrier cadence).
        return Slo("read_staleness", "gauge",
                   "attendance_read_staleness_seconds", op, threshold)
    if alias == "watermark_lag":
        # The temporal plane's freshness objective: how far the
        # watermark trails the stream head (event-time seconds).
        return Slo("watermark_lag", "gauge",
                   "attendance_watermark_lag_seconds", op, threshold)
    if alias == "snapshot_failures":
        # The PR-robustness hook: a bounded-backoff writer retrying a
        # failing disk is healthy; an unbounded failure COUNT is not.
        return Slo("snapshot_write_failures", "counter",
                   "attendance_snapshot_write_failures_total", op,
                   threshold)
    m = _QUANTILE_RE.match(alias)
    if m:
        stage = _STAGE_ALIAS.get(m.group(1), m.group(1))
        if stage not in KNOWN_STAGES:
            raise ValueError(
                f"unknown stage {stage!r} in SLO spec {spec!r} "
                f"(known stages: {', '.join(sorted(KNOWN_STAGES))})")
        return Slo(alias, "quantile",
                   "attendance_stage_latency_seconds", op, threshold,
                   label_filter=("stage", stage),
                   quantile=int(m.group(2)) / 100.0)
    raise ValueError(f"unknown SLO alias {alias!r} in {spec!r}")


def resolve_slos(specs: Sequence[str]) -> List[Slo]:
    """Defaults + user specs; a spec naming a default REPLACES it."""
    parsed = [parse_slo(s) for s in specs]
    names = {s.name for s in parsed}
    return [s for s in DEFAULT_SLOS if s.name not in names] + parsed


class _SloState:
    __slots__ = ("samples", "fast", "slow", "firing", "last_value",
                 "rate_prev", "hist_prev")

    def __init__(self, fast_gauge, slow_gauge):
        self.samples: List[Tuple[float, bool]] = []
        self.fast = fast_gauge
        self.slow = slow_gauge
        self.firing = False
        self.last_value = float("nan")
        self.rate_prev: Optional[Tuple[float, float]] = None
        self.hist_prev = None  # (buckets, count) at the previous tick


class SloEngine:
    """Tick-driven evaluator. Production runs it on a background
    thread (``start``/``stop``); tests drive :meth:`tick` directly
    with explicit timestamps — the window math is pure function of the
    sample times passed in."""

    def __init__(self, telemetry, specs: Sequence[str] = (),
                 fast_s: float = 60.0, slow_s: float = 300.0,
                 path: str = "", *, budget: float = DEFAULT_BUDGET,
                 fire_burn: float = DEFAULT_FIRE_BURN,
                 interval_s: float = 1.0, _clock=time.monotonic):
        self._telemetry = telemetry
        self.slos = resolve_slos(specs)
        self.fast_s = fast_s
        self.slow_s = slow_s
        self.path = path
        self.budget = budget
        self.fire_burn = fire_burn
        self.interval_s = interval_s
        self._clock = _clock
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        reg = telemetry.registry
        self._alerts = {
            s.name: reg.counter(
                "attendance_slo_alerts_total",
                help=SLO_HELP["attendance_slo_alerts_total"],
                slo=s.name)
            for s in self.slos}
        self._firing_gauges = {
            s.name: reg.gauge("attendance_slo_firing",
                              help=SLO_HELP["attendance_slo_firing"],
                              slo=s.name)
            for s in self.slos}
        self._state: Dict[str, _SloState] = {
            s.name: _SloState(
                reg.gauge("attendance_slo_burn_rate",
                          help=SLO_HELP["attendance_slo_burn_rate"],
                          slo=s.name, window="fast"),
                reg.gauge("attendance_slo_burn_rate",
                          help=SLO_HELP["attendance_slo_burn_rate"],
                          slo=s.name, window="slow"))
            for s in self.slos}
        for g in self._firing_gauges.values():
            g.set(0.0)

    # -- lifecycle -----------------------------------------------------------
    def start(self) -> "SloEngine":
        if self.path:
            # Touch the log so a clean run still leaves the artifact
            # (doctor reads an empty file as "0 transitions" — a
            # MISSING file would be indistinguishable from a run that
            # never had the engine on).
            try:
                Path(self.path).parent.mkdir(parents=True,
                                             exist_ok=True)
                Path(self.path).touch()
            except Exception:
                logger.exception("alert log touch failed")
        self._thread = threading.Thread(target=self._loop,
                                        name="slo-engine", daemon=True)
        self._thread.start()
        return self

    def _loop(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.tick()
            except Exception:
                logger.exception("SLO tick failed")

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None
        self.finalize("engine-stop")

    def finalize(self, reason: str) -> None:
        """One last evaluation so short runs (shorter than a tick
        interval) still classify and any firing alert reaches the log
        before process exit."""
        try:
            self.tick()
        except Exception:
            logger.exception("SLO final tick failed (%s)", reason)

    # -- value extraction ----------------------------------------------------
    def _family(self, metric: str):
        for name, kind, help, members in (
                self._telemetry.registry.collect()):
            if name == metric:
                return members
        return []

    def _members(self, slo: Slo):
        members = self._family(slo.metric)
        if slo.label_filter:
            members = [m for m in members
                       if slo.label_filter in m.labels]
        return members

    def _value(self, slo: Slo, now: float, st: _SloState) -> float:
        members = self._members(slo)
        if slo.kind == "gauge":
            vals = []
            for m in members:
                try:
                    v = float(m.read())
                except Exception:
                    continue  # a dead callback is "no signal", not 0.0
                if not math.isnan(v):
                    vals.append(v)
            return max(vals) if vals else float("nan")
        if slo.kind == "counter":
            return float(sum(m.value for m in members)) \
                if members else float("nan")
        if slo.kind == "rate":
            total = float(sum(m.value for m in members)) \
                if members else 0.0
            prev = st.rate_prev
            st.rate_prev = (now, total)
            if prev is None or now <= prev[0]:
                return float("nan")
            return (total - prev[1]) / (now - prev[0])
        if slo.kind == "quantile":
            from attendance_tpu.obs.registry import (
                quantile_from_buckets)
            if not members:
                return float("nan")
            h = members[0]
            buckets, _, count = h.snapshot()
            prev = st.hist_prev
            st.hist_prev = (buckets, count)
            if prev is None:
                return float("nan")
            db = [b - p for b, p in zip(buckets, prev[0])]
            dc = count - prev[1]
            if dc <= 0:
                return float("nan")  # no fresh observations this tick
            return quantile_from_buckets(db, dc, slo.quantile, h.scale)
        raise ValueError(f"unknown SLO kind {slo.kind!r}")

    @staticmethod
    def _breaches(slo: Slo, value: float) -> bool:
        if math.isnan(value):
            return False  # no signal is not a breach
        if slo.op == "<=":
            return value > slo.threshold
        return value < slo.threshold

    # -- window math ---------------------------------------------------------
    def _burn(self, samples: List[Tuple[float, bool]], now: float,
              window_s: float) -> float:
        """Breaching fraction over the window / error budget. The
        denominator is the window's EXPECTED sample count (window /
        tick interval), not just the samples seen so far: dividing by
        a near-empty window would let the very first breaching tick
        claim a 100%-breach window and fire instantly — exactly the
        single-tick spike the slow window exists to reject. Until a
        window has filled once, missing ticks count as healthy."""
        inside = [b for t, b in samples if t > now - window_s]
        if not inside:
            return 0.0
        expected = max(1, math.ceil(window_s / self.interval_s))
        return (sum(inside) / max(len(inside), expected)) / self.budget

    def tick(self, now: Optional[float] = None) -> None:
        now = self._clock() if now is None else now
        with self._lock:
            for slo in self.slos:
                st = self._state[slo.name]
                value = self._value(slo, now, st)
                st.last_value = value
                st.samples.append((now, self._breaches(slo, value)))
                cutoff = now - self.slow_s
                while st.samples and st.samples[0][0] <= cutoff:
                    st.samples.pop(0)
                burn_fast = self._burn(st.samples, now, self.fast_s)
                burn_slow = self._burn(st.samples, now, self.slow_s)
                st.fast.set(burn_fast)
                st.slow.set(burn_slow)
                if (not st.firing and burn_fast >= self.fire_burn
                        and burn_slow >= self.fire_burn):
                    st.firing = True
                    self._alerts[slo.name].inc()
                    self._firing_gauges[slo.name].set(1.0)
                    self._emit(slo, st, "firing", burn_fast, burn_slow)
                elif (st.firing
                      and burn_fast < self.fire_burn * CLEAR_RATIO):
                    st.firing = False
                    self._firing_gauges[slo.name].set(0.0)
                    self._emit(slo, st, "resolved", burn_fast,
                               burn_slow)

    # -- alert emission ------------------------------------------------------
    def _last_trace(self) -> str:
        """Trace id of the most recent flight-recorder batch record —
        the cross-reference from an SLO transition into the span tree
        (empty when no recorder/tracing is live)."""
        flight = getattr(self._telemetry, "flight", None)
        if flight is None:
            return ""
        records = flight.snapshot()
        for rec in reversed(records):
            t = rec.get("trace") if isinstance(rec, dict) else None
            if t:
                return str(t)
        return ""

    def _emit(self, slo: Slo, st: _SloState, state: str,
              burn_fast: float, burn_slow: float) -> None:
        trace = self._last_trace()
        value = st.last_value
        event = {
            "schema": ALERT_SCHEMA,
            "ts": round(time.time(), 3),
            "slo": slo.name,
            "state": state,
            "metric": slo.metric,
            "op": slo.op,
            "threshold": slo.threshold,
            "value": None if math.isnan(value) else round(value, 6),
            "burn_fast": round(burn_fast, 3),
            "burn_slow": round(burn_slow, 3),
            "window_fast_s": self.fast_s,
            "window_slow_s": self.slow_s,
        }
        if trace:
            event["trace"] = trace
        logger.warning("SLO %s %s (value=%s threshold=%s%s burn "
                       "fast=%.1f slow=%.1f)", slo.name, state.upper(),
                       event["value"], slo.op, slo.threshold,
                       burn_fast, burn_slow)
        if self.path:
            try:
                with open(self.path, "a") as f:
                    f.write(json.dumps(event) + "\n")
            except Exception:
                logger.exception("alert log append failed")
        # Flag the transition in the flight ring: a dump then shows the
        # alert inline with the batch records around it, trace id
        # attached for the jump into the Perfetto tree.
        rec = {"ts": event["ts"], "alert": slo.name, "state": state}
        if trace:
            rec["trace"] = trace
        self._telemetry.record_batch(**rec)


# ---------------------------------------------------------------------------
# doctor: offline artifact replay -> verdict table + exit code
# ---------------------------------------------------------------------------

def _classify(path: str) -> Tuple[str, object]:
    """Sniff one artifact: ('prom', text) | ('alerts', [events]) |
    ('flight', doc) | ('trace', doc)."""
    text = Path(path).read_text()
    stripped = text.lstrip()
    if not stripped:
        # An empty file is a clean run's alert log (the engine touches
        # it at start so "no transitions" and "engine never ran" stay
        # distinguishable artifacts).
        return "alerts", []
    if not stripped.startswith("{"):
        return "prom", text
    try:
        doc = json.loads(text)
        if "traceEvents" in doc:
            return "trace", doc
        if "slo" in doc and "state" in doc:
            # A one-transition alert log is a single valid JSON object
            # — the event signature, not the document shape, decides.
            return "alerts", [doc]
        return "flight", doc
    except json.JSONDecodeError:
        events = []
        for line in stripped.splitlines():
            line = line.strip()
            if line:
                events.append(json.loads(line))
        if not all(isinstance(e, dict) and "slo" in e for e in events):
            raise ValueError(f"unrecognized artifact {path!r}")
        return "alerts", events


def _fmt_value(v: Optional[float]) -> str:
    if v is None:
        return "n/a"
    if math.isnan(v):
        return "NaN"
    if math.isinf(v):
        # A quantile past the last finite bucket bound renders as the
        # exposition spelling (int() on it would raise).
        return "+Inf" if v > 0 else "-Inf"
    if v == int(v) and abs(v) < 1e15:
        return str(int(v))
    return f"{v:.6g}"


def _prom_checks(text: str, fpr_ceiling: float,
                 hll_error_ceiling: float,
                 fire_burn: float,
                 snapshot_stall_ceiling: Optional[float],
                 max_reconnects: Optional[int] = None,
                 lane_skew_ceiling: Optional[float] = None,
                 query_p99_ceiling: Optional[float] = None,
                 staleness_ceiling: Optional[float] = None,
                 merge_lag_ceiling: Optional[float] = None,
                 watermark_lag_ceiling: Optional[float] = None,
                 recompile_ceiling: Optional[int] = None
                 ) -> List[List[str]]:
    from attendance_tpu.obs.exposition import parse_prom

    samples = parse_prom(text)

    def _vals(metric: str, label_part: str = "") -> List[float]:
        out = []
        for name, labels, value in samples:
            if name == metric and label_part in labels:
                try:
                    v = float(value)
                except ValueError:
                    continue
                if not math.isnan(v):
                    out.append(v)
        return out

    rows: List[List[str]] = []

    def ceiling(check: str, metric: str, limit: float) -> None:
        vals = _vals(metric)
        if not vals:
            rows.append([check, "n/a", f"<= {_fmt_value(limit)}",
                         "n/a"])
            return
        worst = max(vals)
        rows.append([check, _fmt_value(worst),
                     f"<= {_fmt_value(limit)}",
                     "PASS" if worst <= limit else "FAIL"])

    ceiling("bloom measured FPR", "attendance_bloom_measured_fpr",
            fpr_ceiling)
    fn = _vals("attendance_bloom_false_negatives_total")
    rows.append(["bloom false negatives",
                 _fmt_value(max(fn) if fn else None), "== 0",
                 "n/a" if not fn
                 else ("PASS" if max(fn) == 0 else "FAIL")])
    ceiling("HLL measured rel error",
            "attendance_hll_measured_rel_error", hll_error_ceiling)
    # Estimator drift: measurement vs the fill^k model, informational
    # (a large drift means the estimator is lying, not that the run
    # breached — the measured ceiling above is the gate).
    measured = _vals("attendance_bloom_measured_fpr")
    estimated = _vals("attendance_bloom_estimated_fpr")
    if measured and estimated:
        drift = abs(max(measured) - max(estimated))
        rows.append(["FPR estimator drift", _fmt_value(drift), "-",
                     "info"])
    # Snapshot stall ceiling: p99 of each snapshot stage histogram
    # (write = one background write's wall, blocked = hot-loop waits
    # on a full staging queue), recovered from the scraped cumulative
    # buckets. Informational without a ceiling; a gate with one.
    from attendance_tpu.obs.exposition import (
        _parse_le, quantiles_from_cumulative)

    for stage in ("snapshot_write", "snapshot_blocked"):
        pairs = []
        for name, labels, value in samples:
            if (name == "attendance_stage_latency_seconds_bucket"
                    and f'stage="{stage}"' in labels):
                le = _parse_le(labels)
                if le is not None:
                    try:
                        pairs.append((le, float(value)))
                    except ValueError:
                        continue
        if not pairs or max(c for _, c in pairs) == 0:
            continue  # run never snapshotted: nothing to judge
        (p99,) = quantiles_from_cumulative(pairs, (0.99,))
        if snapshot_stall_ceiling is None:
            rows.append([f"{stage} p99", _fmt_value(p99), "-", "info"])
        else:
            rows.append([f"{stage} p99", _fmt_value(p99),
                         f"<= {_fmt_value(snapshot_stall_ceiling)}",
                         "PASS" if p99 <= snapshot_stall_ceiling
                         else "FAIL"])
    # Query plane: the read-path latency quantile (stage="query"
    # histogram, same recovery as the snapshot stalls above), read-path
    # accuracy (its own measured gauges, beside the write path's), and
    # epoch staleness. Informational without ceilings; gates with them.
    qpairs = []
    for name, labels, value in samples:
        if (name == "attendance_stage_latency_seconds_bucket"
                and 'stage="query"' in labels):
            le = _parse_le(labels)
            if le is not None:
                try:
                    qpairs.append((le, float(value)))
                except ValueError:
                    continue
    if qpairs and max(c for _, c in qpairs) > 0:
        (p99,) = quantiles_from_cumulative(qpairs, (0.99,))
        if query_p99_ceiling is None:
            rows.append(["query p99", _fmt_value(p99), "-", "info"])
        else:
            rows.append(["query p99", _fmt_value(p99),
                         f"<= {_fmt_value(query_p99_ceiling)}",
                         "PASS" if p99 <= query_p99_ceiling
                         else "FAIL"])
    qfn = _vals("attendance_query_false_negatives_total")
    if qfn:
        worst = max(qfn)
        rows.append(["query-path false negatives", _fmt_value(worst),
                     "== 0", "PASS" if worst == 0 else "FAIL"])
    qfpr = _vals("attendance_query_measured_fpr")
    if qfpr:
        rows.append(["query-path measured FPR",
                     _fmt_value(max(qfpr)),
                     f"<= {_fmt_value(fpr_ceiling)}",
                     "PASS" if max(qfpr) <= fpr_ceiling else "FAIL"])
    qerr = _vals("attendance_query_hll_rel_error")
    if qerr:
        rows.append(["query-path HLL rel error",
                     _fmt_value(max(qerr)),
                     f"<= {_fmt_value(hll_error_ceiling)}",
                     "PASS" if max(qerr) <= hll_error_ceiling
                     else "FAIL"])
    # Federation plane: fence->fold merge lag (gated by
    # --merge-lag-ceiling; informational without), peer liveness at
    # the last scrape, and fold/staleness counters. Peers-down is an
    # informational row, not a gate: a worker that exited cleanly
    # after its final fence looks "down" to an aggregator that
    # outlives it by the silence budget, which is the normal teardown
    # order — the soak gates takeover by its own invariants instead.
    fpairs = []
    for name, labels, value in samples:
        if name == "attendance_fed_merge_lag_seconds_bucket":
            le = _parse_le(labels)
            if le is not None:
                try:
                    fpairs.append((le, float(value)))
                except ValueError:
                    continue
    has_lag = bool(fpairs) and max(c for _, c in fpairs) > 0
    if has_lag and merge_lag_ceiling is None:
        (p99,) = quantiles_from_cumulative(fpairs, (0.99,))
        rows.append(["fed merge lag p99", _fmt_value(p99), "-",
                     "info"])
    elif merge_lag_ceiling is not None:
        # The ceiling is only ever set for runs that gossiped: an
        # absent/empty histogram means the aggregator never folded a
        # fence, so the gate must FAIL loudly, not pass vacuously.
        p99 = (quantiles_from_cumulative(fpairs, (0.99,))[0]
               if has_lag else None)
        rows.append(["fed merge lag p99", _fmt_value(p99),
                     f"<= {_fmt_value(merge_lag_ceiling)}",
                     "FAIL" if p99 is None or p99 > merge_lag_ceiling
                     else "PASS"])
    peers = [(labels, float(v)) for name, labels, v in samples
             if name == "attendance_fed_peer_up"]
    if peers:
        up = sum(1 for _, v in peers if v >= 1.0)
        rows.append(["fed peers up at last scrape",
                     f"{up}/{len(peers)}", "-", "info"])
    merged = _vals("attendance_fed_merged_deltas_total")
    if merged:
        rows.append(["fed merged frames", _fmt_value(max(merged)),
                     "-", "info"])
    fstale = _vals("attendance_fed_stale_frames_total")
    if fstale and max(fstale) > 0:
        rows.append(["fed stale frames (counters ignored)",
                     _fmt_value(max(fstale)), "-", "info"])
    takeovers = _vals("attendance_fed_takeovers_total")
    if takeovers and max(takeovers) > 0:
        rows.append(["fed shard takeovers", _fmt_value(max(takeovers)),
                     "-", "info"])
    geom = _vals("attendance_fed_geometry_rejects_total")
    if geom and max(geom) > 0:
        # A misconfigured peer's frames were rejected: its shard is
        # missing from the merged view — always a failing verdict.
        rows.append(["fed geometry-rejected frames",
                     _fmt_value(max(geom)), "== 0", "FAIL"])
    stale = _vals("attendance_read_staleness_seconds")
    if stale or staleness_ceiling is not None:
        worst = max(stale) if stale else None
        if staleness_ceiling is None:
            rows.append(["read epoch staleness",
                         _fmt_value(worst), "-", "info"])
        else:
            rows.append(["read epoch staleness", _fmt_value(worst),
                         f"<= {_fmt_value(staleness_ceiling)}",
                         "n/a" if worst is None
                         else ("PASS" if worst <= staleness_ceiling
                               else "FAIL")])
    chain = _vals("attendance_snapshot_chain_length")
    if chain:
        rows.append(["snapshot chain length", _fmt_value(max(chain)),
                     "-", "info"])
    # Temporal plane: watermark lag (gated by
    # --watermark-lag-ceiling-s; informational without), late-event
    # outcomes and bucket-rotation totals (always informational — a
    # dropped straggler is a data-quality fact the side channel
    # already preserved, not an SLO breach).
    wlag = _vals("attendance_watermark_lag_seconds")
    if wlag or watermark_lag_ceiling is not None:
        worst = max(wlag) if wlag else None
        if watermark_lag_ceiling is None:
            rows.append(["watermark lag", _fmt_value(worst), "-",
                         "info"])
        else:
            # Like the merge-lag gate: a ceiling set for a run that
            # never ran the temporal plane must FAIL loudly, not pass
            # vacuously.
            rows.append(["watermark lag", _fmt_value(worst),
                         f"<= {_fmt_value(watermark_lag_ceiling)}",
                         "FAIL" if worst is None
                         or worst > watermark_lag_ceiling else "PASS"])
    late_folded = _vals("attendance_late_events_total",
                        'outcome="folded"')
    if late_folded and max(late_folded) > 0:
        rows.append(["late events folded (still-open bucket)",
                     _fmt_value(max(late_folded)), "-", "info"])
    late_dropped = _vals("attendance_late_events_total",
                         'outcome="dropped"')
    if late_dropped and max(late_dropped) > 0:
        rows.append(["late events dropped (side channel)",
                     _fmt_value(max(late_dropped)), "-", "info"])
    rotations = _vals("attendance_window_rotations_total")
    if rotations:
        rows.append(["window bucket rotations",
                     _fmt_value(max(rotations)), "-", "info"])
    evictions = _vals("attendance_window_evictions_total")
    if evictions and max(evictions) > 0:
        rows.append(["window buckets evicted (ring pressure)",
                     _fmt_value(max(evictions)), "-", "info"])
    # Attribution plane (ISSUE 15): where the time went, which stage
    # the dispatch thread spends itself on, how often the device sat
    # idle between dispatches — informational context for every gate
    # above — plus the RECOMPILE gate: steady-state recompiles mean
    # unpadded shapes leak into XLA, and --recompile-ceiling (normally
    # 0) turns that from invisible into a failing verdict.
    from attendance_tpu.obs.exposition import (label_value,
                                               rank_profile_stages)

    prof: Dict[str, float] = {}
    for name, labels, value in samples:
        if name == "attendance_profile_stage_fraction":
            try:
                v = float(value)
            except ValueError:
                continue
            if not math.isnan(v):
                prof[label_value(labels, "stage") or ""] = v
    if prof:
        # One shared ranking (exposition.rank_profile_stages) with
        # the fleet dashboard's top_stage cell: marked stages above
        # the untagged remainder, so the two surfaces can never name
        # different "top" stages for one run.
        rows.append(["profiled top stages",
                     ", ".join(f"{s} {v:.0%}" for s, v
                               in rank_profile_stages(prof)),
                     "-", "info"])
    busy = []
    for name, labels, value in samples:
        if name == "attendance_dispatch_thread_busy_fraction":
            try:
                v = float(value)
            except ValueError:
                continue
            if not math.isnan(v):
                busy.append((label_value(labels, "component") or "",
                             v))
    if busy:
        rows.append(["dispatch thread occupancy",
                     ", ".join(f"{c} {v:.0%}"
                               for c, v in sorted(busy)), "-", "info"])
    gpairs = []
    for name, labels, value in samples:
        if name == "attendance_dispatch_gap_seconds_bucket":
            le = _parse_le(labels)
            if le is not None:
                try:
                    gpairs.append((le, float(value)))
                except ValueError:
                    continue
    if gpairs and max(c for _, c in gpairs) > 0:
        p50, p99 = quantiles_from_cumulative(gpairs, (0.50, 0.99))
        rows.append(["dispatch gap p50/p99 (device idle window)",
                     f"{_fmt_value(p50)}/{_fmt_value(p99)}", "-",
                     "info"])
    recomp = _vals("attendance_recompiles_total")
    if recomp:
        rows.append(["device recompiles (total, incl. warmup)",
                     _fmt_value(sum(recomp)), "-", "info"])
    steady = _vals("attendance_recompiles_steady_total")
    if recompile_ceiling is not None:
        # Like the merge-lag/watermark gates: a ceiling set for a run
        # that never exported the tracker's counters FAILS loudly —
        # vacuous passes hide exactly the storms this gate exists for.
        worst = sum(steady) if steady else None
        rows.append(["steady-state recompiles", _fmt_value(worst),
                     f"<= {recompile_ceiling}",
                     "FAIL" if worst is None
                     or worst > recompile_ceiling else "PASS"])
    elif steady and sum(steady) > 0:
        rows.append(["steady-state recompiles (shape leak?)",
                     _fmt_value(sum(steady)), "-", "info"])
    # Self-healing transport: reconnects are REMEDIATION (each one is
    # a survived outage), so the row is informational by default —
    # --max-reconnects turns it into a gate for runs that should have
    # seen a quiet network.
    recon = _vals("attendance_reconnects_total")
    if recon or max_reconnects is not None:
        worst = max(recon) if recon else 0.0
        if max_reconnects is None:
            rows.append(["broker reconnects", _fmt_value(worst), "-",
                         "info"])
        else:
            rows.append(["broker reconnects", _fmt_value(worst),
                         f"<= {max_reconnects}",
                         "PASS" if worst <= max_reconnects else "FAIL"])
    retries = _vals("attendance_retry_attempts_total")
    if retries:
        rows.append(["broker RPC retries",
                     _fmt_value(sum(retries)), "-", "info"])
    # Striped ingress lane skew: the worst lane's event share vs the
    # median lane. A dead or starved lane (connection wedged below the
    # reconnect threshold, poisoned session) shows up as skew long
    # before it shows up as throughput — informational by default,
    # --lane-skew-ceiling gates it (the dead-lane detector; 0.5 flags
    # a lane running under half the median).
    lane_events = _vals("attendance_ingress_lane_events_total")
    if len(lane_events) >= 2 or (lane_events
                                 and lane_skew_ceiling is not None):
        ordered = sorted(lane_events)
        mid = len(ordered) // 2
        # True median (even counts average the middle pair): the
        # upper-middle element would make the 2-lane gate min/MAX.
        median = (ordered[mid] if len(ordered) % 2
                  else (ordered[mid - 1] + ordered[mid]) / 2.0)
        skew = (ordered[0] / median) if median > 0 else 0.0
        if lane_skew_ceiling is None:
            rows.append(["ingress lane skew (min/median)",
                         _fmt_value(round(skew, 4)), "-", "info"])
        else:
            rows.append(["ingress lane skew (min/median)",
                         _fmt_value(round(skew, 4)),
                         f">= {_fmt_value(lane_skew_ceiling)}",
                         "PASS" if skew >= lane_skew_ceiling
                         else "FAIL"])
    snap_fail = _vals("attendance_snapshot_write_failures_total")
    if snap_fail:
        rows.append(["snapshot write failures",
                     _fmt_value(max(snap_fail)), "-", "info"])
    # Integrity plane (informational rows — scrub and the chaos soak
    # are the hard gates; these surface the conditions in one place):
    # ENOSPC snapshot refusals (the writer backs off at the capped
    # cadence, frames stay unacked), corrupt durable artifacts
    # detected/quarantined, repairs performed, and wire-checksum
    # rejects at the gossip/fleet folds.
    disk_full = _vals("attendance_snapshot_disk_full_total")
    if disk_full and max(disk_full) > 0:
        rows.append(["snapshot disk full (ENOSPC)",
                     _fmt_value(max(disk_full)), "-", "info"])
    corrupt = _vals("attendance_chain_corrupt_files_total")
    if corrupt:
        rows.append(["corrupt chain files quarantined",
                     _fmt_value(sum(corrupt)), "-", "info"])
    repairs = _vals("attendance_chain_repairs_total")
    if repairs:
        rows.append(["chain repairs (local + peer)",
                     _fmt_value(sum(repairs)), "-", "info"])
    wire_rej = _vals("attendance_integrity_wire_rejects_total")
    if wire_rej and max(wire_rej) > 0:
        rows.append(["wire checksum rejects",
                     _fmt_value(sum(wire_rej)), "-", "info"])
    spill_rot = _vals("attendance_spill_corrupt_records_total")
    if spill_rot and max(spill_rot) > 0:
        rows.append(["corrupt spill records dropped",
                     _fmt_value(sum(spill_rot)), "-", "info"])
    circ = [(labels, v) for name, labels, v in samples
            if name == "attendance_circuit_state"]
    if circ:
        worst = max(float(v) for _, v in circ)
        # 0 = closed: a circuit still open/half-open at the last scrape
        # means the sink never healed — spilled batches are stranded.
        rows.append(["persist circuit state at last scrape",
                     _fmt_value(worst), "== 0 (closed)",
                     "PASS" if worst == 0.0 else "FAIL"])
    firing = [(labels, v) for name, labels, v in samples
              if name == "attendance_slo_firing" and float(v) >= 1.0]
    rows.append(["SLO alerts firing at last scrape", str(len(firing)),
                 "== 0", "PASS" if not firing else "FAIL"])
    burns = _vals("attendance_slo_burn_rate", 'window="slow"')
    if burns:
        worst = max(burns)
        rows.append(["worst slow-window burn rate", _fmt_value(worst),
                     f"< {_fmt_value(fire_burn)}",
                     "PASS" if worst < fire_burn else "FAIL"])
    return rows


def _alert_checks(events: List[dict]) -> Tuple[List[List[str]],
                                               List[str]]:
    last_state: Dict[str, str] = {}
    fired: Dict[str, int] = {}
    traces: List[str] = []
    versionless = 0
    for e in events:
        if e.get("schema") is None:
            versionless += 1
        last_state[e["slo"]] = e.get("state", "")
        if e.get("state") == "firing":
            fired[e["slo"]] = fired.get(e["slo"], 0) + 1
            if e.get("trace"):
                traces.append(str(e["trace"]))
    rows: List[List[str]] = []
    if not events:
        rows.append(["alert log", "0 transitions", "-", "PASS"])
    if versionless:
        # Pre-17 alert logs predate the schema field: readable, but flag
        # once so operators know which vintage they are replaying.
        rows.append(["alert log schema",
                     f"{versionless} versionless event(s) (pre-17 log)",
                     f"schema={ALERT_SCHEMA}", "warn"])
    for slo in sorted(last_state):
        unresolved = last_state[slo] == "firing"
        rows.append([f"alert {slo}",
                     f"{fired.get(slo, 0)} fired, last "
                     f"{last_state[slo]}", "resolved",
                     "FAIL" if unresolved else "PASS"])
    return rows, traces


def _quarantine_rows(directory: str) -> List[List[str]]:
    """Quarantine listing as verdict rows: entry count (informational —
    dead-lettered poison is a data-quality fact, not an SLO breach) and
    a per-reason breakdown."""
    from attendance_tpu.transport.quarantine import list_entries

    entries = list_entries(directory)
    rows = [["quarantined frames", str(len(entries)), "-", "info"]]
    by_reason: Dict[str, int] = {}
    for e in entries:
        reason = e.get("reason") or "unspecified"
        by_reason[reason] = by_reason.get(reason, 0) + 1
    for reason in sorted(by_reason):
        rows.append([f"  quarantine[{reason}]",
                     str(by_reason[reason]), "-", "info"])
    return rows


def _fleet_wide_rows(per_role_samples: Dict[str, list],
                     merge_lag_ceiling: Optional[float],
                     staleness_ceiling: Optional[float],
                     watermark_lag_ceiling: Optional[float] = None,
                     recompile_ceiling: Optional[int] = None
                     ) -> List[List[str]]:
    """Fleet-level rows judged over the MERGED data: merge-lag p99
    from the summed cumulative buckets across every artifact that has
    the histogram (normally just the aggregator's), read staleness as
    the worst instance, events as the sum over ingest roles, and a
    fleet-size row. Ceilings turn the first two into gates."""
    from attendance_tpu.obs.exposition import (
        fold_headline_samples, quantiles_from_cumulative)

    rows: List[List[str]] = []
    rows.append(["fleet: roles collected",
                 str(len(per_role_samples)), ">= 1",
                 "PASS" if per_role_samples else "FAIL"])
    # One shared extraction (exposition.fold_headline_samples) with
    # the `fleet` dashboard's headline — folding every role's samples
    # into one accumulator IS the merge (lag buckets sum by le).
    acc = None
    for samples in per_role_samples.values():
        acc = fold_headline_samples(samples, acc)
    acc = fold_headline_samples((), acc)
    staleness = acc["staleness"]
    firing = acc["firing"]
    if acc["have_events"]:
        rows.append(["fleet: events (sum over roles)",
                     _fmt_value(acc["events"]), "-", "info"])
    pairs = sorted(acc["lag_by_le"].items())
    has_lag = bool(pairs) and max(c for _, c in pairs) > 0
    if merge_lag_ceiling is not None:
        # Same vacuous-pass refusal as the single-run doctor: a fleet
        # judged with a merge-lag ceiling MUST have gossiped.
        p99 = (quantiles_from_cumulative(pairs, (0.99,))[0]
               if has_lag else None)
        rows.append(["fleet: merge lag p99", _fmt_value(p99),
                     f"<= {_fmt_value(merge_lag_ceiling)}",
                     "FAIL" if p99 is None or p99 > merge_lag_ceiling
                     else "PASS"])
    elif has_lag:
        (p99,) = quantiles_from_cumulative(pairs, (0.99,))
        rows.append(["fleet: merge lag p99", _fmt_value(p99), "-",
                     "info"])
    if staleness or staleness_ceiling is not None:
        worst = max(staleness, default=None)
        if staleness_ceiling is None:
            rows.append(["fleet: worst read staleness",
                         _fmt_value(worst), "-", "info"])
        else:
            rows.append(["fleet: worst read staleness",
                         _fmt_value(worst),
                         f"<= {_fmt_value(staleness_ceiling)}",
                         "n/a" if worst is None
                         else ("PASS" if worst <= staleness_ceiling
                               else "FAIL")])
    # Temporal plane: worst watermark lag across every role that
    # exports the gauge — informational when present without a
    # ceiling (like staleness/merge-lag above); a ceiling over a
    # fleet with NO temporal role fails loudly, never vacuously.
    lags = []
    for samples in per_role_samples.values():
        for name, _labels, v in samples:
            if name != "attendance_watermark_lag_seconds":
                continue
            try:
                v = float(v)
            except ValueError:
                continue
            if not math.isnan(v):
                lags.append(v)
    if watermark_lag_ceiling is not None:
        worst = max(lags) if lags else None
        rows.append(["fleet: worst watermark lag", _fmt_value(worst),
                     f"<= {_fmt_value(watermark_lag_ceiling)}",
                     "FAIL" if worst is None
                     or worst > watermark_lag_ceiling else "PASS"])
    elif lags:
        rows.append(["fleet: worst watermark lag",
                     _fmt_value(max(lags)), "-", "info"])
    # Attribution plane: steady-state recompiles summed over every
    # role that exports the tracker (dispatching roles); a ceiling
    # over a fleet where NO role exported it fails loudly — and the
    # only dispatching roles are exactly the ones that must export.
    steadies = []
    for samples in per_role_samples.values():
        for name, _labels, v in samples:
            if name != "attendance_recompiles_steady_total":
                continue
            try:
                v = float(v)
            except ValueError:
                continue
            if not math.isnan(v):
                steadies.append(v)
    if recompile_ceiling is not None:
        worst = sum(steadies) if steadies else None
        rows.append(["fleet: steady-state recompiles",
                     _fmt_value(worst), f"<= {recompile_ceiling}",
                     "FAIL" if worst is None
                     or worst > recompile_ceiling else "PASS"])
    elif steadies and sum(steadies) > 0:
        rows.append(["fleet: steady-state recompiles (shape leak?)",
                     _fmt_value(sum(steadies)), "-", "info"])
    rows.append(["fleet: SLO alerts firing across roles",
                 str(firing), "== 0",
                 "PASS" if firing == 0 else "FAIL"])
    return rows


def doctor_fleet_report(fleet_dir: str, *,
                        fpr_ceiling: float = 0.01,
                        hll_error_ceiling: float = 0.02,
                        fire_burn: float = DEFAULT_FIRE_BURN,
                        snapshot_stall_ceiling: Optional[float] = None,
                        max_reconnects: Optional[int] = None,
                        lane_skew_ceiling: Optional[float] = None,
                        query_p99_ceiling: Optional[float] = None,
                        staleness_ceiling: Optional[float] = None,
                        merge_lag_ceiling: Optional[float] = None,
                        watermark_lag_ceiling: Optional[float] = None,
                        recompile_ceiling: Optional[int] = None
                        ) -> Tuple[str, bool]:
    """ONE verdict table over a fleet collector's artifact directory
    (``--fleet-dir``): every ``<role>@<instance>.prom`` the collector
    persisted is judged with the normal per-run checks (rows prefixed
    with the role), then fleet-WIDE rows judge the merged data —
    merge-lag p99 over the summed histograms, worst read staleness,
    roles collected, alerts firing anywhere. Exit semantics match
    :func:`doctor_report`: the CLI maps (text, ok=False) to exit 1,
    unreadable input raises (exit 2)."""
    from attendance_tpu.obs.exposition import _table, parse_prom

    root = Path(fleet_dir)
    if not root.is_dir():
        raise FileNotFoundError(f"no fleet artifact dir: {fleet_dir}")
    prom_files = sorted(root.glob("*.prom"))
    if not prom_files:
        raise ValueError(
            f"fleet dir {fleet_dir} holds no *.prom artifacts — was "
            "the collector given a --fleet-dir?")
    rows: List[List[str]] = []
    per_role_samples: Dict[str, list] = {}
    for path in prom_files:
        role = path.stem  # role@instance
        text = path.read_text()
        per_role_samples[role] = parse_prom(text)
        for row in _prom_checks(text, fpr_ceiling, hll_error_ceiling,
                                fire_burn, snapshot_stall_ceiling,
                                max_reconnects, lane_skew_ceiling,
                                query_p99_ceiling,
                                staleness_ceiling=None,
                                merge_lag_ceiling=None):
            rows.append([f"{role}: {row[0]}", *row[1:]])
    rows.extend(_fleet_wide_rows(per_role_samples, merge_lag_ceiling,
                                 staleness_ceiling,
                                 watermark_lag_ceiling,
                                 recompile_ceiling))
    trace_path = root / "fleet_trace.json"
    if trace_path.exists():
        doc = json.loads(trace_path.read_text())
        other = doc.get("otherData", {})
        names = {e.get("name") for e in doc.get("traceEvents", [])
                 if e.get("ph") == "X"}
        stitched = {"fence_publish", "fed_merge"} <= names
        rows.append(["fleet: stitched trace",
                     f"{other.get('span_count', 0)} spans / "
                     f"{other.get('instances', 0)} instances"
                     + (", fence->merge stitched" if stitched else ""),
                     "-", "info"])
    ok = not any(r[3] == "FAIL" for r in rows)
    failed = sum(1 for r in rows if r[3] == "FAIL")
    head = [f"doctor --fleet: {len(prom_files)} role artifact(s) "
            f"under {fleet_dir}",
            _table(rows, ["check", "value", "target", "verdict"]),
            f"verdict: {'PASS' if ok else f'FAIL ({failed} breached)'}"]
    return "\n".join(head), ok


def doctor_report(paths: Sequence[str], *,
                  fpr_ceiling: float = 0.01,
                  hll_error_ceiling: float = 0.02,
                  fire_burn: float = DEFAULT_FIRE_BURN,
                  snapshot_stall_ceiling: Optional[float] = None,
                  max_reconnects: Optional[int] = None,
                  lane_skew_ceiling: Optional[float] = None,
                  query_p99_ceiling: Optional[float] = None,
                  staleness_ceiling: Optional[float] = None,
                  merge_lag_ceiling: Optional[float] = None,
                  watermark_lag_ceiling: Optional[float] = None,
                  recompile_ceiling: Optional[int] = None,
                  quarantine_dir: str = ""
                  ) -> Tuple[str, bool]:
    """Replay run artifacts offline; returns (verdict text, ok).

    Accepts any mix of: a ``--metrics-prom`` exposition file (the last
    scrape block is judged), a ``--alert-log`` JSONL, a flight-recorder
    dump, a ``--trace-out`` export — plus, via ``quarantine_dir``, an
    on-disk quarantine whose entries are listed informationally.
    ``max_reconnects`` turns the reconnect row from informational into
    a gate. Unknown/unreadable files raise — the CLI maps that to exit
    2, distinct from the SLO-breach exit 1.
    """
    from attendance_tpu.obs.exposition import _table

    rows: List[List[str]] = []
    artifacts: List[str] = []
    alert_traces: List[str] = []
    trace_ids: set = set()
    flight_alerts = 0
    for path in paths:
        kind, payload = _classify(path)
        artifacts.append(f"{kind}: {Path(path).name}")
        if kind == "prom":
            rows.extend(_prom_checks(payload, fpr_ceiling,
                                     hll_error_ceiling, fire_burn,
                                     snapshot_stall_ceiling,
                                     max_reconnects,
                                     lane_skew_ceiling,
                                     query_p99_ceiling,
                                     staleness_ceiling,
                                     merge_lag_ceiling,
                                     watermark_lag_ceiling,
                                     recompile_ceiling))
        elif kind == "alerts":
            arows, traces = _alert_checks(payload)
            rows.extend(arows)
            alert_traces.extend(traces)
        elif kind == "flight":
            recs = payload.get("records", [])
            flight_alerts += sum(1 for r in recs
                                 if isinstance(r, dict) and "alert" in r)
            trace_ids.update(str(r["trace"]) for r in recs
                             if isinstance(r, dict) and r.get("trace"))
        elif kind == "trace":
            for e in payload.get("traceEvents", []):
                t = (e.get("args") or {}).get("trace_id")
                if t:
                    trace_ids.add(str(t))
    if flight_alerts:
        rows.append(["flight records flagged by alerts",
                     str(flight_alerts), "-", "info"])
    if alert_traces:
        found = sum(1 for t in alert_traces if t in trace_ids)
        rows.append(["alert trace ids found in trace/flight artifacts",
                     f"{found}/{len(alert_traces)}", "-", "info"])
    if quarantine_dir:
        artifacts.append(f"quarantine: {Path(quarantine_dir).name}")
        rows.extend(_quarantine_rows(quarantine_dir))
    if not rows:
        raise ValueError("no judgeable artifacts (need a prom "
                         "exposition file, an alert log, or a "
                         "quarantine dir)")
    ok = not any(r[3] == "FAIL" for r in rows)
    failed = sum(1 for r in rows if r[3] == "FAIL")
    head = [f"doctor: {len(artifacts)} artifact(s) — "
            + ", ".join(artifacts),
            _table(rows, ["check", "value", "target", "verdict"]),
            f"verdict: {'PASS' if ok else f'FAIL ({failed} breached)'}"]
    return "\n".join(head), ok
