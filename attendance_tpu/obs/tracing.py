"""Distributed span tracing: per-batch causality on top of PR 1's
aggregate telemetry.

The metrics registry answers "how much" (dequeue_wait p99 is high);
this module answers "where did THIS batch spend its time" across the
generator -> broker -> processor -> device boundary — Dapper-style
spans (Sigelman et al., 2010) with a compact trace context carried in
broker message properties, flushed as Chrome-trace/Perfetto JSON.

Discipline (same as ``obs/__init__`` and ``utils/profiling.py``):
instrumented call sites capture the tracer ONCE at construction and
pay exactly one ``is not None`` branch per event when tracing is off.
The hot-path record cost when ON is one Span allocation and one
list-append under a mutex; the buffer is BOUNDED — when full, new
spans are dropped and counted (``dropped``), never reallocated, so a
multi-hour run cannot OOM the process through its own telemetry.

Wire format of the propagated context (message property
``traceparent``): ``"<trace_id 16hex>-<span_id 16hex>-<seq>"`` —
trace_id names the end-to-end trace (one per published batch),
span_id the publishing span new work should parent under, seq the
publisher's batch sequence number. Unparseable values degrade to
"start a fresh trace", never to an error: a traced consumer must
interoperate with an untraced producer and vice versa.

Export is the Chrome trace-event JSON both Perfetto and
``chrome://tracing`` load: one synthetic ``pid`` per process ROLE
(generator/bridge/fused-pipeline/processor may share one OS process in
hermetic runs and must still separate into lanes), one ``tid`` per
worker thread, complete-events (``ph: "X"``) with trace/span/parent
ids in ``args`` so slices group under one trace.
"""

from __future__ import annotations

import contextlib
import json
import os
import random
import threading
import time
from pathlib import Path
from typing import Dict, List, NamedTuple, Optional, Tuple

DEFAULT_SPAN_LIMIT = 1 << 16  # ~64k completed spans (~15MB exported)

# The single message-property key the trace context travels under.
TRACEPARENT = "traceparent"


class SpanContext(NamedTuple):
    """The compact cross-hop context: everything a downstream hop needs
    to continue the trace (identity + parent link + batch seq)."""
    trace_id: int
    span_id: int
    seq: int


def format_ctx(ctx: SpanContext) -> str:
    return f"{ctx.trace_id:016x}-{ctx.span_id:016x}-{ctx.seq}"


def parse_ctx(value) -> Optional[SpanContext]:
    """Parse a ``traceparent`` property; None on anything malformed
    (an untraced or differently-versioned producer must not crash a
    traced consumer)."""
    if not value:
        return None
    try:
        trace_hex, span_hex, seq = str(value).split("-")
        return SpanContext(int(trace_hex, 16), int(span_hex, 16),
                           int(seq))
    except (ValueError, TypeError):
        return None


class Span:
    """One (possibly still open) span. ``t0``/``dur`` are in the
    tracer's monotonic clock domain (``time.perf_counter`` seconds);
    conversion to wall-anchored microseconds happens once at export."""

    __slots__ = ("name", "trace_id", "span_id", "parent_id", "role",
                 "tid", "thread_name", "t0", "dur", "args")

    def __init__(self, name: str, trace_id: int, span_id: int,
                 parent_id: Optional[int], role: str, tid: int,
                 thread_name: str, t0: float, args: Optional[dict]):
        self.name = name
        self.trace_id = trace_id
        self.span_id = span_id
        self.parent_id = parent_id
        self.role = role
        self.tid = tid
        self.thread_name = thread_name
        self.t0 = t0
        self.dur = 0.0
        self.args = args

    def context(self, seq: int = 0) -> SpanContext:
        """The propagatable context naming THIS span as the parent."""
        return SpanContext(self.trace_id, self.span_id, seq)


class Tracer:
    """Bounded in-memory span collector with Chrome-trace export.

    ``_clock``/``_ids``/``_epoch`` are injectable for deterministic
    tests (the golden-file export); production uses perf_counter,
    a process-local 64-bit PRNG, and a wall-clock anchor captured at
    construction so all spans of one process share one time base.
    """

    def __init__(self, limit: int = DEFAULT_SPAN_LIMIT, *,
                 default_role: str = "process",
                 _clock=time.perf_counter, _ids=None,
                 _epoch: Optional[float] = None):
        if limit <= 0:
            raise ValueError("span buffer limit must be positive")
        self.limit = limit
        self.default_role = default_role
        self._clock = _clock
        self._rng = random.Random()
        self._ids = _ids or (lambda: self._rng.getrandbits(64) or 1)
        # Anchor: wall time at clock()==0, so exported ts are unix-
        # epoch microseconds and two processes' traces roughly align.
        self._epoch = (time.time() - time.perf_counter()
                       if _epoch is None else _epoch)
        self._lock = threading.Lock()
        self._spans: List[Span] = []
        self._dropped = 0
        self._tls = threading.local()

    # -- ids / clock ---------------------------------------------------------
    def new_id(self) -> int:
        return self._ids()

    def now(self) -> float:
        return self._clock()

    @property
    def epoch(self) -> float:
        """Wall time at clock()==0 — what converts a span's monotonic
        ``t0`` to the unix-epoch microseconds the export (and the
        fleet pusher's cross-process stitching) uses."""
        return self._epoch

    @property
    def dropped(self) -> int:
        with self._lock:
            return self._dropped

    def __len__(self) -> int:
        with self._lock:
            return len(self._spans)

    # -- the explicit start/end API ------------------------------------------
    def start_span(self, name: str, *, trace_id: Optional[int] = None,
                   parent_id: Optional[int] = None,
                   role: Optional[str] = None,
                   args: Optional[dict] = None,
                   start: Optional[float] = None) -> Span:
        """Open a span. With no explicit trace/parent, the span joins
        the thread's active span (see :meth:`activate`) or starts a
        fresh trace."""
        if trace_id is None:
            cur = self.current()
            if cur is not None:
                trace_id = cur.trace_id
                if parent_id is None:
                    parent_id = cur.span_id
                if role is None:
                    role = cur.role
            else:
                trace_id = self.new_id()
        th = threading.current_thread()
        return Span(name, trace_id, self.new_id(), parent_id,
                    role or self.default_role, th.ident or 0, th.name,
                    self._clock() if start is None else start, args)

    def end_span(self, span: Span, *, end: Optional[float] = None,
                 **extra_args) -> None:
        """Close a span and commit it to the (bounded) buffer."""
        span.dur = (self._clock() if end is None else end) - span.t0
        if extra_args:
            span.args = {**(span.args or {}), **extra_args}
        with self._lock:
            if len(self._spans) >= self.limit:
                self._dropped += 1
                return
            self._spans.append(span)

    def add_span(self, name: str, start: float, end: float, *,
                 trace_id: int, parent_id: Optional[int] = None,
                 role: Optional[str] = None,
                 args: Optional[dict] = None) -> Span:
        """Commit a span from an already-measured interval — the shape
        hot loops want: measure with two perf_counter reads as they
        already do, attach the span only if tracing is on."""
        span = self.start_span(name, trace_id=trace_id,
                               parent_id=parent_id, role=role,
                               args=args, start=start)
        self.end_span(span, end=end)
        return span

    # -- context-manager sugar + thread-local activation ---------------------
    @contextlib.contextmanager
    def span(self, name: str, **kwargs):
        """``with tracer.span("decode") as sp:`` — opens, ACTIVATES
        (nested spans on this thread inherit trace/parent), and closes
        on exit; an exception is recorded as ``args.error`` and
        re-raised."""
        sp = self.start_span(name, **kwargs)
        try:
            with self.activate(sp):
                yield sp
        except BaseException as exc:
            self.end_span(sp, error=repr(exc))
            raise
        self.end_span(sp)

    @contextlib.contextmanager
    def activate(self, span: Optional[Span]):
        """Make ``span`` the thread's active span for the duration:
        spans opened without an explicit trace join it (how the
        sharded engine's replica spans nest under the batch span
        without threading a handle through every call)."""
        stack = getattr(self._tls, "stack", None)
        if stack is None:
            stack = self._tls.stack = []
        stack.append(span)
        try:
            yield span
        finally:
            stack.pop()

    def current(self) -> Optional[Span]:
        stack = getattr(self._tls, "stack", None)
        return stack[-1] if stack else None

    # -- export --------------------------------------------------------------
    def snapshot(self) -> List[Span]:
        with self._lock:
            return list(self._spans)

    def snapshot_from(self, start: int, limit: Optional[int] = None
                      ) -> Tuple[List[Span], int]:
        """Up to ``limit`` completed spans appended since cursor
        ``start``, plus the cursor of the buffer END (so a caller can
        tell backlog remains) — the fleet pusher's incremental read.
        The buffer only ever appends (drops past the limit never
        reorder it), so an index cursor is stable across snapshots,
        and a bounded read copies only what it ships."""
        with self._lock:
            end = len(self._spans)
            stop = end if limit is None else min(end, start + limit)
            return list(self._spans[start:stop]), end

    def export(self) -> dict:
        """The Chrome trace-event document (Perfetto /
        ``chrome://tracing`` loadable). Synthetic pids: one per role in
        first-registration order (hermetic runs put several roles in
        one OS process, which must still separate into swimlanes);
        tids: one small int per worker thread."""
        spans = self.snapshot()
        pid_of: Dict[str, int] = {}
        tid_of: Dict[tuple, int] = {}
        events: List[dict] = []
        for s in spans:
            pid = pid_of.setdefault(s.role, len(pid_of) + 1)
            tkey = (s.role, s.tid)
            tid = tid_of.get(tkey)
            if tid is None:
                tid = tid_of[tkey] = (
                    sum(1 for k in tid_of if k[0] == s.role) + 1)
                events.append({
                    "name": "thread_name", "ph": "M", "pid": pid,
                    "tid": tid, "args": {"name": s.thread_name}})
            args = {"trace_id": f"{s.trace_id:016x}",
                    "span_id": f"{s.span_id:016x}"}
            if s.parent_id is not None:
                args["parent_span_id"] = f"{s.parent_id:016x}"
            if s.args:
                args.update(s.args)
            events.append({
                "name": s.name, "ph": "X", "pid": pid, "tid": tid,
                "ts": round((self._epoch + s.t0) * 1e6, 3),
                "dur": round(s.dur * 1e6, 3),
                "args": args})
        meta = [{"name": "process_name", "ph": "M", "pid": pid,
                 "tid": 0, "args": {"name": role}}
                for role, pid in pid_of.items()]
        return {
            "traceEvents": meta + events,
            "displayTimeUnit": "ms",
            "otherData": {"pid": os.getpid(),
                          "dropped_spans": self.dropped,
                          "span_count": len(spans)},
        }

    # -- consumer-side helper (shared by both processors) --------------------
    def begin_consume(self, properties, redelivery: int, *, role: str,
                      start: float, got: float, wait_name: str,
                      args: Optional[dict] = None):
        """Open the per-batch consumer span continuing the trace the
        publisher put in the message properties (fresh trace when
        untraced upstream). A redelivered message becomes a ``retry``
        span parented under the SAME publish span as the original
        attempt — the redelivery chain reads as siblings. The receive
        wait [start, got] is committed as the first child under
        ``wait_name``. Callers end_span() when the batch settles."""
        ctx = parse_ctx((properties or {}).get(TRACEPARENT))
        trace_id = ctx.trace_id if ctx is not None else self.new_id()
        parent = ctx.span_id if ctx is not None else None
        a = dict(args or {})
        if ctx is not None:
            a["seq"] = ctx.seq
        if redelivery:
            a["redelivery"] = redelivery
        span = self.start_span("retry" if redelivery else "batch",
                               trace_id=trace_id, parent_id=parent,
                               role=role, start=start, args=a)
        self.add_span(wait_name, start, got, trace_id=trace_id,
                      parent_id=span.span_id, role=role)
        return span

    # -- producer-side helpers (shared by every transport backend) -----------
    def begin_publish(self, topic: str, seq: int,
                      properties: Optional[dict]):
        """Open a ``publish`` span for one message and return
        ``(span, properties)`` with the traceparent installed.

        An incoming traceparent (the bridge forwarding a consumed
        trace) is CONTINUED — the publish span parents under it and
        the outgoing context is rewritten to name the publish span, so
        downstream hops nest publish -> deliver in one trace. Without
        one, the publish span roots a fresh trace (one trace_id per
        published batch). Callers must end_span() after the publish
        completes."""
        ctx = parse_ctx((properties or {}).get(TRACEPARENT))
        span = self.start_span(
            "publish",
            trace_id=ctx.trace_id if ctx else self.new_id(),
            parent_id=ctx.span_id if ctx else None,
            role="producer", args={"topic": topic, "seq": seq})
        props = dict(properties) if properties else {}
        props[TRACEPARENT] = format_ctx(span.context(seq))
        return span, props

    def begin_publish_many(self, topic: str, seq0: int, count: int):
        """Bulk-lane variant: ONE ``publish_many`` span for the call
        (per-message spans at JSON-wire rates would flood the bounded
        buffer) plus a fresh per-message context list — each message
        still gets its own trace_id, parented to the bulk span."""
        span = self.start_span("publish_many", role="producer",
                               args={"topic": topic, "count": count})
        props = [{TRACEPARENT: format_ctx(SpanContext(
            self.new_id(), span.span_id, seq0 + i))}
            for i in range(count)]
        return span, props

    def flush(self, path) -> Path:
        """Write the export as one JSON document (atomic rename — a
        reader mid-run never sees a torn file). Idempotent: callers
        flush at end-of-run AND at teardown; later flushes rewrite
        with whatever accumulated since."""
        path = Path(path)
        path.parent.mkdir(parents=True, exist_ok=True)
        tmp = path.with_suffix(path.suffix + ".tmp")
        with open(tmp, "w") as f:
            json.dump(self.export(), f)
        tmp.replace(path)
        return path
