"""Fleet observability plane: cross-process telemetry collection.

PRs 6-8 turned the single-process reproduction into a fleet — striped
ingress lanes, a separate chain-reader serve process, federation shard
workers plus an aggregator — but the PR 1-3 observability stack stayed
strictly per-process: each role wrote its own prom file, its own
trace.json, its own alert log. This module is the one pane of glass:

* :class:`FleetPusher` — every process role (worker, aggregator, serve
  reader, broker, bench driver) periodically pushes its registry
  snapshot and bounded span batches as length-prefixed frames over the
  shared :mod:`transport.framing` wire. Pushes ride
  ``resilient_call``'s retry/reconnect/chaos seams at the new
  ``fleet.push`` site, and a dead collector NEVER hurts the pushing
  process — pushing is telemetry, not durability.
* :class:`FleetCollector` — accepts pushes, maintains a role+instance-
  labeled merged registry re-exposed on the existing metrics HTTP
  endpoint under ``/fleet/*`` (``/fleet/metrics`` merged exposition,
  ``/fleet/status`` JSON summary, ``/fleet/trace`` stitched trace),
  and stitches every process's span batches into ONE Perfetto-loadable
  export — trace/span ids are process-global 64-bit randoms and the
  federation gossip now carries ``traceparent``, so an aggregator's
  ``fed_merge`` span parents under the originating worker's
  ``fence_publish`` span across process boundaries.
* Artifact persistence — with a directory configured, the collector
  appends each instance's exposition blocks to
  ``<role>@<instance>.prom`` (the FileReporter block format, so every
  existing prom consumer works), and flushes ``fleet_trace.json`` +
  ``FLEET.json`` (the status snapshot) — the inputs ``doctor --fleet``
  merges into one verdict table and CI uploads on failure.

Wire: one opcode (``F_PUSH = 1``), body = ``enc_props(header) +
payload``; header names ``role``, ``instance``, ``kind``
(``metrics`` | ``spans``), ``seq``, ``boot`` (the pusher's
construction timestamp — with ``seq`` it makes pushes idempotent:
``resilient_call`` may re-send a frame whose reply was lost, and the
collector drops ``seq <= last-seen`` within one ``boot`` while a
restarted pusher's fresh ``boot`` resets the window) and ``ts``. ``metrics`` payloads are
the process's rendered Prometheus exposition; ``spans`` payloads are a
JSON array of compact rows ``[name, role, tid, thread, ts_us, dur_us,
trace_id, span_id, parent_id|null, args|null]`` with ``ts``/``dur``
already converted to unix-epoch microseconds (each process's tracer
anchors its monotonic clock at construction, so stitched timelines
roughly align the way the per-process exports already did). Rows, not
span documents: shipping rides the hot loop's cores, and the dict keys
plus hex-id formatting tripled the serialize cost — ids travel as raw
ints and become Perfetto ``args`` strings once, at export.
"""

from __future__ import annotations

import json
import logging
import math
import os
import socket
import threading
import time
from pathlib import Path
from typing import Dict, List, Optional

from attendance_tpu.transport.framing import (
    dec_props, enc_props, recv_frame, send_frame)

logger = logging.getLogger(__name__)

F_PUSH = 1

_ST_OK = 0
_ST_ERROR = 2

# Bound on spans per periodic push frame (a push is a telemetry
# heartbeat, not a bulk transfer: one 512-row frame costs ~5ms on a
# slow 2-core host — invisible at the 2s cadence — where a 64k-span
# backlog serialized at once parks the GIL for over a second), the
# larger frame the stop()-time full drain uses, and the spans retained
# per instance at the collector.
SPAN_BATCH = 512
DRAIN_BATCH = 4096
COLLECTOR_SPAN_LIMIT = 1 << 16

FLEET_ROUTES = ("/fleet/metrics", "/fleet/status", "/fleet/trace")

TRACE_FILE = "fleet_trace.json"
STATUS_FILE = "FLEET.json"


def default_instance(config=None) -> str:
    """Stable-ish instance label: the federated worker id when one is
    configured (the name every soak/bench log already uses), else the
    pid."""
    fed = getattr(config, "fed_worker", "") if config is not None else ""
    return fed or f"pid{os.getpid()}"


def _span_rows(spans, epoch: float) -> list:
    """Completed Spans -> compact wire rows, ts/dur wall-anchored."""
    return [[s.name, s.role, s.tid, s.thread_name,
             round((epoch + s.t0) * 1e6, 3),
             round(s.dur * 1e6, 3),
             s.trace_id, s.span_id, s.parent_id, s.args]
            for s in spans]


def _row_args(row: list) -> dict:
    """One wire row -> the Perfetto ``args`` dict (the same shape
    Tracer.export writes: hex ids + the span's own args)."""
    args = {"trace_id": f"{row[6]:016x}", "span_id": f"{row[7]:016x}"}
    if row[8] is not None:
        args["parent_span_id"] = f"{row[8]:016x}"
    if row[9]:
        args.update(row[9])
    return args


# ---------------------------------------------------------------------------
# Push side
# ---------------------------------------------------------------------------

class FleetPusher:
    """Background thread pushing one process's telemetry to the
    collector: a rendered registry snapshot every interval plus the
    spans completed since the last push (bounded per frame).

    Deliberately decoupled from :class:`obs.Telemetry` construction
    (takes the registry/tracer handles directly) so tests can run
    several pushers with independent registries inside one process —
    exactly how the hermetic fleet tests simulate a multi-role
    deployment."""

    def __init__(self, registry, tracer, address: str, *, role: str,
                 instance: str, interval_s: float = 2.0,
                 policy=None, span_batch: int = SPAN_BATCH):
        from attendance_tpu import chaos
        # Pay the exposition import (it drags http.server in) here at
        # construction, not inside the first push — a pusher starts
        # before the hot loop and must never hiccup it.
        from attendance_tpu.obs.exposition import render
        from attendance_tpu.transport.resilience import RetryPolicy
        from attendance_tpu.transport.socket_broker import _Rpc

        self._render = render
        self.registry = registry
        self.tracer = tracer
        self.address = address
        self.role = role
        self.instance = instance
        self.interval_s = interval_s
        self.span_batch = span_batch
        # Short budget: a push that cannot land within a couple of
        # seconds should yield to the next interval, not park the
        # pusher thread for the transport's full 15s default.
        self._policy = policy or RetryPolicy(budget_s=2.0)
        self._rpc = None
        self._rpc_factory = lambda: _Rpc(address, chaos=chaos.get(),
                                         site="fleet.push")
        self._seq = 0
        self._boot = round(time.time(), 3)
        self._span_cursor = 0
        self._down_logged = False
        self._stop = threading.Event()
        self._push_lock = threading.Lock()
        self._thread: Optional[threading.Thread] = None

    def start(self) -> "FleetPusher":
        self._thread = threading.Thread(
            target=self._loop, name="fleet-pusher", daemon=True)
        self._thread.start()
        return self

    def _loop(self) -> None:
        while not self._stop.wait(self.interval_s):
            self.push_now()

    def _call(self, header: dict, payload: bytes) -> None:
        from attendance_tpu.transport.framing import enc_checksummed
        from attendance_tpu.transport.resilience import resilient_call

        if self._rpc is None:
            self._rpc = self._rpc_factory()
        # Checksummed push frame (integrity plane): the collector
        # verifies the digest before folding — a rotted push is
        # REJECTED (error status), and the resilient_call retry
        # re-sends fresh bytes, idempotent per (boot, seq).
        body = enc_checksummed(enc_props(header) + payload)
        status, reply = resilient_call(
            self._rpc, lambda: (F_PUSH, body), site="fleet.push",
            policy=self._policy, aborted=self._stop.is_set)
        if status != _ST_OK:
            raise RuntimeError(
                f"collector rejected push: "
                f"{reply.decode(errors='replace')}")

    def push_now(self, *, drain: bool = False) -> bool:
        """One push round (metrics + fresh spans); returns whether it
        landed. Spans ship at most ONE bounded frame per round — a big
        backlog paces out over successive intervals instead of parking
        the GIL on one giant serialize (the hot loop shares these
        cores); ``drain=True`` (the stop() path) loops until empty. A
        collector outage logs ONE warning and the pusher keeps trying
        every interval — the pushing process must never degrade
        because its telemetry sink did."""
        with self._push_lock:
            try:
                self._seq += 1
                header = {"role": self.role, "instance": self.instance,
                          "kind": "metrics", "seq": self._seq,
                          "boot": self._boot,
                          "ts": round(time.time(), 3)}
                self._call(header, self._render(self.registry).encode())
                if self.tracer is not None:
                    epoch = self.tracer.epoch
                    limit = DRAIN_BATCH if drain else self.span_batch
                    while True:
                        batch, end = self.tracer.snapshot_from(
                            self._span_cursor, limit)
                        if not batch:
                            break
                        self._seq += 1
                        self._call(
                            {**header, "kind": "spans",
                             "seq": self._seq},
                            json.dumps(_span_rows(batch,
                                                  epoch)).encode())
                        self._span_cursor += len(batch)
                        if not drain:
                            break  # backlog: next interval's problem
                        if self._span_cursor >= end:
                            break
            except Exception as exc:
                try:
                    if self._rpc is not None:
                        self._rpc.close()
                except Exception:
                    pass
                self._rpc = None
                if not self._down_logged:
                    self._down_logged = True
                    logger.warning(
                        "fleet push to %s failed (%r) — collector "
                        "down? pushing keeps retrying every %.1fs",
                        self.address, exc, self.interval_s)
                return False
            if self._down_logged:
                self._down_logged = False
                logger.info("fleet push to %s recovered", self.address)
            return True

    def stop(self) -> None:
        """Final push (short runs must still report), then teardown."""
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None
        self.push_now(drain=True)
        if self._rpc is not None:
            try:
                self._rpc.close()
            except Exception:
                pass
            self._rpc = None


# ---------------------------------------------------------------------------
# Collector side
# ---------------------------------------------------------------------------

class _Instance:
    __slots__ = ("role", "instance", "prom", "spans", "last_seen",
                 "pushes", "span_count", "boot", "last_seq")

    def __init__(self, role: str, instance: str):
        self.role = role
        self.instance = instance
        self.prom = ""  # latest rendered exposition
        self.spans: List[dict] = []
        self.last_seen = 0.0
        self.pushes = 0
        self.span_count = 0
        # Duplicate window: pushes are idempotent per (boot, seq) —
        # resilient_call re-sends a frame whose reply was lost.
        self.boot = None
        self.last_seq = 0

    @property
    def key(self) -> str:
        return f"{self.role}@{self.instance}"


def _safe_stem(key: str) -> str:
    return "".join(c if (c.isalnum() or c in "@._-") else "_"
                   for c in key)


class FleetCollector:
    """TCP collector for :class:`FleetPusher` frames.

    One thread per pushing connection (the broker server's model: a
    fleet is tens of processes, not thousands). State per
    (role, instance): the latest exposition text, a bounded span list,
    and liveness/volume counters. ``attach(metrics_server)`` mounts the
    ``/fleet/*`` routes on an existing :class:`MetricsServer`;
    ``directory`` persists artifacts for ``doctor --fleet`` and CI
    triage."""

    def __init__(self, *, directory: str = "", host: str = "127.0.0.1",
                 port: int = 0, obs=None,
                 flush_interval_s: float = 2.0,
                 span_limit: int = COLLECTOR_SPAN_LIMIT):
        self.directory = directory
        if directory:
            Path(directory).mkdir(parents=True, exist_ok=True)
        self.span_limit = span_limit
        self.flush_interval_s = flush_interval_s
        self._lock = threading.Lock()
        self._instances: Dict[str, _Instance] = {}
        self._no_checksum_warned: set = set()
        self._last_flush = 0.0
        self._stopping = False
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((host, port))
        self._sock.listen(32)
        self.host, self.port = self._sock.getsockname()
        self._accept_thread: Optional[threading.Thread] = None
        self._c_pushes = None
        if obs is not None:
            self.bind_obs(obs)

    def bind_obs(self, obs) -> None:
        """Register the collector's self-metrics on a telemetry
        bundle. Separate from __init__ for the host that creates the
        collector FIRST (to learn its ephemeral address) and the
        telemetry bundle second, pushing to itself — the `federate`
        verb's shape."""
        self._c_pushes = {
            kind: obs.registry.counter(
                "attendance_fleet_pushes_total",
                help="Telemetry frames accepted by the fleet "
                "collector", kind=kind)
            for kind in ("metrics", "spans")}
        obs.registry.gauge(
            "attendance_fleet_instances",
            help="Distinct role@instance pushers the collector "
            "has heard from").set_function(
                lambda: float(len(self._instances)))

    @property
    def address(self) -> str:
        return f"{self.host}:{self.port}"

    def start(self) -> "FleetCollector":
        self._accept_thread = threading.Thread(
            target=self._accept_loop, name="fleet-collector",
            daemon=True)
        self._accept_thread.start()
        logger.info("Fleet collector listening on %s%s", self.address,
                    f" (artifacts -> {self.directory})"
                    if self.directory else "")
        return self

    def stop(self) -> None:
        self._stopping = True
        try:
            self._sock.close()
        except OSError:
            pass
        self.flush(trace=True)

    # -- wire ----------------------------------------------------------------
    def _accept_loop(self) -> None:
        while not self._stopping:
            try:
                conn, addr = self._sock.accept()
            except OSError:
                return
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            threading.Thread(target=self._serve_connection,
                             args=(conn,),
                             name=f"fleet-conn-{addr[1]}",
                             daemon=True).start()

    def _serve_connection(self, conn: socket.socket) -> None:
        try:
            while True:
                try:
                    op, body = recv_frame(conn)
                except (ConnectionError, OSError):
                    break
                try:
                    if op != F_PUSH:
                        raise ValueError(f"unknown fleet opcode {op}")
                    self._ingest(body)
                    status, reply = _ST_OK, b""
                except Exception as exc:
                    status, reply = _ST_ERROR, repr(exc).encode()
                try:
                    send_frame(conn, status, reply)
                except (ConnectionError, OSError):
                    break
        finally:
            conn.close()

    def _ingest(self, body: bytes) -> None:
        from attendance_tpu.transport.framing import dec_checksummed

        # FrameChecksumError propagates: the push is REJECTED with an
        # error status and the pusher's retry re-sends clean bytes —
        # wire rot never reaches the merged registry. Legacy pushers
        # (no checksum magic) fold normally, one warning per instance.
        body, verified = dec_checksummed(body)
        header, off = dec_props(body, 0)
        if not header or "role" not in header:
            raise ValueError("malformed fleet push header")
        if not verified:
            key0 = (f"{header['role']}"
                    f"@{header.get('instance', '?')}")
            if key0 not in self._no_checksum_warned:
                self._no_checksum_warned.add(key0)
                logger.warning(
                    "fleet pushes from %s carry no payload checksum "
                    "(older pusher build?) — folding normally, but "
                    "in-flight rot on its pushes is undetectable",
                    key0)
        payload = body[off:]
        kind = header.get("kind")
        key = f"{header['role']}@{header.get('instance', '?')}"
        boot, seq = header.get("boot"), header.get("seq")
        persist = None
        with self._lock:
            inst = self._instances.get(key)
            if inst is None:
                inst = self._instances[key] = _Instance(
                    header["role"], str(header.get("instance", "?")))
            if boot is not None and seq is not None:
                if inst.boot == boot and seq <= inst.last_seq:
                    # resilient_call re-sent a frame whose reply was
                    # lost: already folded, drop silently (the reply
                    # the pusher is waiting for is this OK).
                    inst.last_seen = time.time()
                    return
                if inst.boot != boot:  # restarted pusher: new window
                    inst.boot, inst.last_seq = boot, 0
                inst.last_seq = max(inst.last_seq, seq)
            inst.last_seen = time.time()
            inst.pushes += 1
            if kind == "metrics":
                inst.prom = payload.decode(errors="replace")
                persist = (inst.key, inst.prom)
            elif kind == "spans":
                rows = json.loads(payload)
                inst.spans.extend(rows)
                inst.span_count += len(rows)
                if len(inst.spans) > self.span_limit:
                    # Keep the newest: the stitched export is a live
                    # forensic surface, not an archive.
                    del inst.spans[:len(inst.spans) - self.span_limit]
            else:
                raise ValueError(f"unknown fleet push kind {kind!r}")
        if persist is not None:
            # File I/O OUTSIDE the collector-wide lock: one slow 9p
            # append must not stall every other pusher and the
            # /fleet/* scrape routes. Per-instance ordering holds —
            # each pusher serializes its own pushes under _push_lock.
            self._persist_prom(*persist)
        if self._c_pushes is not None and kind in self._c_pushes:
            self._c_pushes[kind].inc()
        if (self.directory
                and time.time() - self._last_flush
                >= self.flush_interval_s):
            self.flush()

    def _persist_prom(self, key: str, prom: str) -> None:
        """Append the freshly pushed block to the instance's prom file
        (the FileReporter block format — ``parse_prom`` and the
        ``telemetry`` verb read it unchanged). Called OUTSIDE the
        collector lock."""
        if not self.directory:
            return
        path = Path(self.directory) / f"{_safe_stem(key)}.prom"
        try:
            with open(path, "a") as f:
                f.write(f"# scrape {time.time():.3f}\n" + prom)
        except OSError:
            logger.exception("fleet prom persist failed for %s", key)

    # -- merged views --------------------------------------------------------
    def merged_exposition(self) -> str:
        """One Prometheus exposition over every instance's latest
        snapshot, each sample labeled ``role=``/``instance=`` —
        samples regrouped per family so the merged text stays valid
        exposition (TYPE before samples, families contiguous)."""
        with self._lock:
            blocks = [(i.role, i.instance, i.prom)
                      for i in self._instances.values()]
        families: Dict[str, dict] = {}
        for role, instance, text in sorted(blocks):
            extra = (f'role="{role}",instance="{instance}"')
            fam = None
            for line in text.splitlines():
                if line.startswith("# TYPE "):
                    _, _, name, kind = line.split(" ", 3)
                    fam = families.setdefault(
                        name, {"kind": kind, "help": "", "samples": []})
                    fam["kind"] = kind  # HELP may have pre-created it
                elif line.startswith("# HELP "):
                    _, _, name, help_text = line.split(" ", 3)
                    families.setdefault(
                        name, {"kind": "untyped", "help": "",
                               "samples": []})["help"] = help_text
                elif line and not line.startswith("#"):
                    try:
                        metric, value = line.rsplit(" ", 1)
                    except ValueError:
                        continue
                    if "{" in metric:
                        name_part, rest = metric.split("{", 1)
                        metric = f"{name_part}{{{extra},{rest}"
                    else:
                        metric = f"{metric}{{{extra}}}"
                    # render() always emits samples directly under
                    # their family's TYPE line; a stray untyped sample
                    # (hand-written input) gets its own family.
                    target = fam if fam is not None else \
                        families.setdefault(
                            metric.split("{", 1)[0],
                            {"kind": "untyped", "help": "",
                             "samples": []})
                    target["samples"].append(f"{metric} {value}")
        lines: List[str] = []
        for name in sorted(families):
            fam = families[name]
            if fam["help"]:
                lines.append(f"# HELP {name} {fam['help']}")
            lines.append(f"# TYPE {name} {fam['kind']}")
            lines.extend(fam["samples"])
        return "\n".join(lines) + "\n"

    def export_trace(self) -> dict:
        """Stitch every instance's span batches into one Chrome-trace
        document: one synthetic pid per (role, instance) — the
        federated swimlane layout — one tid per pushing thread, span
        args untouched (trace/span/parent ids are process-global, so
        the gossip-carried ``traceparent`` makes an aggregator's
        ``fed_merge`` nest under the worker's ``fence_publish`` with
        no id translation)."""
        with self._lock:
            per = [(i.role, i.instance, list(i.spans))
                   for i in self._instances.values()]
        meta: List[dict] = []
        events: List[dict] = []
        pid = 0
        for role, instance, spans in sorted(per, key=lambda p: p[:2]):
            pid += 1
            meta.append({"name": "process_name", "ph": "M", "pid": pid,
                         "tid": 0,
                         "args": {"name": f"{role}:{instance}"}})
            tid_of: Dict[tuple, int] = {}
            for row in spans:
                tkey = (row[1] or role, row[2])
                tid = tid_of.get(tkey)
                if tid is None:
                    tid = tid_of[tkey] = len(tid_of) + 1
                    events.append({
                        "name": "thread_name", "ph": "M", "pid": pid,
                        "tid": tid, "args": {"name": row[3] or ""}})
                events.append({"name": row[0], "ph": "X",
                               "pid": pid, "tid": tid,
                               "ts": row[4], "dur": row[5],
                               "args": _row_args(row)})
        return {"traceEvents": meta + events, "displayTimeUnit": "ms",
                "otherData": {"stitched": True,
                              "instances": len(per),
                              "span_count": sum(len(s)
                                                for _, _, s in per)}}

    def status(self) -> dict:
        """The fleet summary the ``fleet`` verb renders: per instance,
        liveness + volume + a few headline samples extracted from the
        latest exposition."""
        now = time.time()
        with self._lock:
            per = [(i.role, i.instance, i.prom, i.last_seen, i.pushes,
                    i.span_count) for i in self._instances.values()]
        doc = {"collected_at": round(now, 3), "instances": {}}
        for role, instance, prom, last_seen, pushes, span_count in per:
            doc["instances"][f"{role}@{instance}"] = {
                "role": role, "instance": instance,
                "age_s": round(now - last_seen, 3),
                "pushes": pushes, "spans": span_count,
                **_headline(prom),
            }
        return doc

    # -- persistence ---------------------------------------------------------
    def flush(self, *, trace: bool = False) -> None:
        """Write the status snapshot (atomic rename; prom files are
        appended per push instead), plus the stitched trace when
        ``trace=True``. The periodic flush during pushes deliberately
        skips the trace: serializing the whole accumulated span set is
        O(total spans) and would grow every interval — it is written
        once at stop() (and served live by the /fleet/trace route)."""
        self._last_flush = time.time()
        if not self.directory:
            return
        root = Path(self.directory)
        docs = [(STATUS_FILE, self.status())]
        if trace:
            docs.append((TRACE_FILE, self.export_trace()))
        try:
            for name, doc in docs:
                tmp = root / (name + ".tmp")
                with open(tmp, "w") as f:
                    json.dump(doc, f)
                tmp.replace(root / name)
        except OSError:
            logger.exception("fleet artifact flush failed")

    # -- HTTP ----------------------------------------------------------------
    def attach(self, server) -> None:
        """Mount ``/fleet/*`` on a MetricsServer (the existing
        ``--metrics-port`` endpoint: one scrape surface per process,
        fleet-wide views beside the local ones)."""

        def metrics(method, path, query, body):
            return (200, "text/plain; version=0.0.4; charset=utf-8",
                    self.merged_exposition().encode())

        def status(method, path, query, body):
            return (200, "application/json; charset=utf-8",
                    json.dumps(self.status()).encode())

        def trace(method, path, query, body):
            return (200, "application/json; charset=utf-8",
                    json.dumps(self.export_trace()).encode())

        server.add_route("/fleet/metrics", metrics)
        server.add_route("/fleet/status", status)
        server.add_route("/fleet/trace", trace)

    def detach(self, server) -> None:
        for path in FLEET_ROUTES:
            server.remove_route(path)


def _headline(prom_text: str) -> dict:
    """A few cross-role headline numbers from one exposition snapshot
    (best-effort: absent families simply don't appear). The extraction
    itself is exposition.fold_headline_samples — shared with
    ``doctor --fleet``'s fleet-wide rows so the dashboard and the gate
    can never disagree about what a headline means."""
    from attendance_tpu.obs.exposition import (
        fold_headline_samples, parse_prom, quantiles_from_cumulative)

    out: dict = {}
    if not prom_text:
        return out
    try:
        acc = fold_headline_samples(parse_prom(prom_text))
    except Exception:
        return out
    if acc["have_events"]:
        out["events"] = int(acc["events"])
    out["slo_firing"] = acc["firing"]
    if acc["staleness"]:
        out["read_staleness_s"] = round(max(acc["staleness"]), 3)
    if acc["series"] is not None:
        out["series"] = acc["series"]
    if acc.get("incidents") is not None:
        out["incidents"] = acc["incidents"]
    if acc["prof_stages"]:
        # The role's busiest profiled stage (sampling profiler on) —
        # the dashboard's per-role "where does the time go" cell,
        # ranked by the one shared ordering doctor's row also uses.
        from attendance_tpu.obs.exposition import rank_profile_stages
        stage, frac = rank_profile_stages(acc["prof_stages"], 1)[0]
        out["top_stage"] = f"{stage} {frac:.0%}"
    pairs = sorted(acc["lag_by_le"].items())
    if pairs and max(c for _, c in pairs) > 0:
        (p99,) = quantiles_from_cumulative(pairs, (0.99,))
        out["merge_lag_p99_s"] = (round(p99, 4)
                                  if math.isfinite(p99) else p99)
    return out
