"""Flight recorder: a fixed-size ring of the last N per-batch records.

Answers "what happened in the last second before it wedged" — the
question end-of-run metrics structurally cannot (SURVEY.md §5; the
1.62s snapshot stall in BENCH_DEDICATED_r05.json was reconstructed from
aggregate counters, exactly the forensics this ring makes direct).

The hot path pays one dict construction and one slot store under a
mutex per batch; the ring never allocates after construction. Dumps are
triggered by SIGUSR1, by an unhandled exception in a run loop, or
explicitly — each writes one self-describing JSON document (atomic
rename, so a reader never sees a torn file).
"""

from __future__ import annotations

import json
import logging
import os
import signal
import threading
import time
from pathlib import Path
from typing import List, Optional

logger = logging.getLogger(__name__)

DEFAULT_RING = 256


class FlightRecorder:
    """Fixed-size ring buffer of per-batch record dicts."""

    def __init__(self, size: int = DEFAULT_RING):
        if size <= 0:
            raise ValueError("flight recorder size must be positive")
        self.size = size
        # REENTRANT: the SIGUSR1 handler runs on the main thread
        # between bytecodes and may interrupt record() while that same
        # thread holds the lock — a plain Lock would deadlock the
        # process at exactly the moment the operator asks for
        # forensics. Worst case under re-entry is one torn record in
        # the dump, which the dump exists to tolerate.
        self._lock = threading.RLock()
        self._buf: List[Optional[dict]] = [None] * size
        self._idx = 0
        self._total = 0

    def record(self, rec: dict) -> None:
        with self._lock:
            self._buf[self._idx] = rec
            self._idx = (self._idx + 1) % self.size
            self._total += 1

    @property
    def total(self) -> int:
        with self._lock:
            return self._total

    def snapshot(self) -> List[dict]:
        """Records oldest-to-newest (at most ``size`` of them)."""
        with self._lock:
            if self._total < self.size:
                return [r for r in self._buf[:self._idx]]
            return ([r for r in self._buf[self._idx:]]
                    + [r for r in self._buf[:self._idx]])

    def dump(self, path, reason: str = "manual") -> Path:
        """Write one JSON document (atomic rename) and return its path."""
        path = Path(path)
        doc = {
            "dumped_at_unix": time.time(),
            "reason": reason,
            "pid": os.getpid(),
            "ring_size": self.size,
            "total_records": self.total,
            "records": self.snapshot(),
        }
        path.parent.mkdir(parents=True, exist_ok=True)
        tmp = path.with_suffix(path.suffix + ".tmp")
        with open(tmp, "w") as f:
            json.dump(doc, f, indent=1, default=str)
        tmp.replace(path)
        return path


_NOT_INSTALLED = object()


def install_sigusr1(recorder: FlightRecorder, path):
    """Dump the ring to ``path`` on SIGUSR1. Returns the PREVIOUS
    handler (so the caller can restore it on teardown — a leaked
    handler would dump a stale ring to a stale path after telemetry
    is disabled), or the _NOT_INSTALLED sentinel off the main thread
    or on platforms without the signal — telemetry must degrade, not
    raise, in embedded/test contexts."""
    if not hasattr(signal, "SIGUSR1"):
        return _NOT_INSTALLED

    def _handler(signum, frame):
        try:
            p = recorder.dump(path, reason="SIGUSR1")
            logger.info("Flight recorder dumped to %s", p)
        except Exception:
            logger.exception("Flight recorder dump failed")

    try:
        return signal.signal(signal.SIGUSR1, _handler)
    except ValueError:  # not the main thread
        logger.warning("SIGUSR1 flight-dump handler not installed "
                       "(not on the main thread)")
        return _NOT_INSTALLED


def uninstall_sigusr1(previous) -> None:
    """Restore the handler ``install_sigusr1`` displaced (no-op for
    the sentinel, or off the main thread)."""
    if previous is _NOT_INSTALLED or not hasattr(signal, "SIGUSR1"):
        return
    try:
        signal.signal(signal.SIGUSR1,
                      previous if previous is not None else signal.SIG_DFL)
    except ValueError:
        pass
