"""Continuous accuracy auditing: measured sketch error, live.

PR 1/2 exposed *estimated* accuracy (``attendance_bloom_estimated_fpr``
is fill^k, an occupancy model). This module closes the loop with
MEASURED accuracy: an exact shadow (ground-truth member/cardinality
sets) is kept for a hash-sampled fraction of the key space, every
sampled sketch answer is cross-checked against it, and the drift
between estimator and measurement becomes its own observable — the
paper's acceptance targets (<=1% Bloom FPR, <=2% HLL relative error)
evaluated at runtime instead of only by offline bench artifacts.

Sampling is a HASH PARTITION of the key space (Knuth multiplicative
hash over the u32 key domain, threshold compare), not a per-call coin
flip: a sampled key is sampled on every add AND every query, so the
shadow is complete ground truth for its subspace —

* a sampled query answered positive whose key was never added is a
  certain FALSE POSITIVE (measured FPR = fp / sampled negative
  queries, an unbiased estimate of the filter's true FPR);
* a sampled query answered negative whose key WAS added is a certain
  FALSE NEGATIVE — structurally impossible for a correct Bloom filter,
  so ``attendance_bloom_false_negatives_total`` must stay 0 and any
  increment is a kernel bug caught in production;
* the distinct sampled members of an HLL key, scaled by 1/sample, are
  an unbiased exact-count estimate (uniform hash partition of the
  DISTINCT key population), so
  ``attendance_hll_measured_rel_error`` measures the sketch's real
  error at sample=1.0 and a sampling-noise-bounded estimate below.

Cost discipline (the <=2% hot-path guardrail extends to auditing, at
the default 1% sample): the per-batch cost is one vectorized
multiply+compare over the batch plus set operations on the ~1% sampled
lanes; shadow sets are capped (a key past :data:`SHADOW_CAP` sampled
members stops being audited, loudly, instead of growing without
bound). The fused pipeline pays even less on the hot path — it only
RECORDS shadow truth per frame; its measured gauges are scrape-time
callbacks that re-query the live device filter (the ``obs/health.py``
discipline: device reads only when a scrape renders the registry).
"""

from __future__ import annotations

import logging
import threading
import weakref
from typing import Dict, Optional, Sequence, Set

import numpy as np

logger = logging.getLogger(__name__)

# Knuth's multiplicative constant (2^32 / phi, odd): multiplication
# mod 2^32 is a bijection of the key domain, so threshold sampling
# takes an (almost exactly) `sample` fraction of ANY key population —
# including the sequential student-id rosters the reference generates,
# which a plain modulus would sample pathologically.
_MIX = np.uint32(2654435761)

# Per-key shadow bound: past this many sampled members the key's audit
# is abandoned (counted, logged once) rather than letting ground-truth
# sets grow without bound on a multi-hour run. 1<<20 sampled members
# at the default 1% sample covers a ~100M-distinct-key population.
SHADOW_CAP = 1 << 20

AUDIT_HELP = {
    "attendance_bloom_measured_fpr":
        "Measured Bloom FPR: false positives / sampled negative "
        "queries against the exact shadow (NaN until a sampled "
        "negative query happens)",
    "attendance_bloom_false_positives_total":
        "Sampled Bloom queries answered positive whose key was never "
        "added (shadow-certain false positives)",
    "attendance_bloom_false_negatives_total":
        "Sampled Bloom queries answered negative whose key WAS added "
        "— must stay 0; any increment is a sketch correctness bug",
    "attendance_audit_negative_checks_total":
        "Sampled Bloom queries whose key is not in the shadow (the "
        "measured-FPR denominator)",
    "attendance_audit_checks_total":
        "Sampled sketch answers cross-checked against the shadow",
    "attendance_hll_measured_rel_error":
        "Measured HLL relative error vs the exact shadow count "
        "(scaled by 1/sample)",
    "attendance_audit_shadow_members":
        "Ground-truth members currently held by the shadow auditor",
    "attendance_audit_shadow_overflow_total":
        "Keys whose shadow hit its cap and stopped being audited",
}


class ShadowAuditor:
    """Sampled exact-shadow cross-checker shared by every instrumented
    sketch surface (SketchStore command dispatch + the fused pipeline).

    Thread-safe the same way the registry is: one mutex around the
    shadow sets; counters/gauges carry their own locks. All public
    methods take the u32-normalized key arrays the call sites already
    computed — auditing never re-hashes members.
    """

    def __init__(self, registry, sample: float):
        if not (0.0 < sample <= 1.0):
            raise ValueError(f"audit sample out of range: {sample}")
        self.sample = sample
        # Threshold compare on the mixed key: u32 < sample * 2^32.
        # sample=1.0 (threshold 2^32, every key) is special-cased so
        # the per-frame compare stays in the uint32 domain — no
        # widening pass over the batch on the hot path.
        self._all = sample >= 1.0
        self._threshold = np.uint32(
            min(round(sample * (1 << 32)), (1 << 32) - 1))
        self._lock = threading.Lock()
        self._bloom_shadow: Dict[str, Set[int]] = {}
        self._hll_shadow: Dict[str, Set[int]] = {}
        self._dead: Set[str] = set()  # keys past SHADOW_CAP
        # Fused traffic reservoir freeze: at cap the set stops GROWING
        # (measured FPR keeps working over the frozen probe population)
        # instead of being evicted per frame — an O(cap) rebuild per
        # frame would silently blow the hot-path guardrail.
        self._traffic_frozen = False
        r = registry
        self._checks = r.counter("attendance_audit_checks_total",
                                 help=AUDIT_HELP[
                                     "attendance_audit_checks_total"])
        self._fp = r.counter(
            "attendance_bloom_false_positives_total",
            help=AUDIT_HELP["attendance_bloom_false_positives_total"])
        self._fn = r.counter(
            "attendance_bloom_false_negatives_total",
            help=AUDIT_HELP["attendance_bloom_false_negatives_total"])
        self._negatives = r.counter(
            "attendance_audit_negative_checks_total",
            help=AUDIT_HELP["attendance_audit_negative_checks_total"])
        self._overflow = r.counter(
            "attendance_audit_shadow_overflow_total",
            help=AUDIT_HELP["attendance_audit_shadow_overflow_total"])
        r.gauge("attendance_audit_shadow_members",
                help=AUDIT_HELP["attendance_audit_shadow_members"]
                ).set_function(self._shadow_size)
        # Measured FPR is derived from the two counters at READ time,
        # so the gauge, the counters, and an offline recount can never
        # disagree; NaN (not 0.0) before any sampled negative query —
        # "no data yet" must not render as "FPR is zero".
        r.gauge("attendance_bloom_measured_fpr",
                help=AUDIT_HELP["attendance_bloom_measured_fpr"]
                ).set_function(self.measured_fpr)
        self._registry = r

    # -- sampling ------------------------------------------------------------
    def sample_mask(self, keys_u32: np.ndarray) -> np.ndarray:
        """bool[B]: which keys belong to the audited subspace."""
        keys = np.asarray(keys_u32, dtype=np.uint32)
        if self._all:
            return np.ones(len(keys), dtype=bool)
        return (keys * _MIX) < self._threshold

    def _shadow_size(self) -> float:
        with self._lock:
            return float(
                sum(len(s) for s in self._bloom_shadow.values())
                + sum(len(s) for s in self._hll_shadow.values()))

    def _shadow_add(self, shadows: Dict[str, Set[int]], key: str,
                    sampled: np.ndarray) -> None:
        with self._lock:
            if key in self._dead:
                return
            s = shadows.setdefault(key, set())
            s.update(int(k) for k in sampled)
            if len(s) > SHADOW_CAP:
                self._dead.add(key)
                shadows.pop(key, None)
                self._overflow.inc()
                logger.warning(
                    "audit shadow for %r exceeded %d sampled members; "
                    "auditing of this key stops (counted in "
                    "attendance_audit_shadow_overflow_total)",
                    key, SHADOW_CAP)

    # -- Bloom surface -------------------------------------------------------
    def record_bf_add(self, key: str, keys_u32: np.ndarray) -> None:
        mask = self.sample_mask(keys_u32)
        if mask.any():
            self._shadow_add(self._bloom_shadow, key,
                             np.asarray(keys_u32, np.uint32)[mask])

    def check_bf_exists(self, key: str, keys_u32: np.ndarray,
                        answers: np.ndarray) -> None:
        """Cross-check one BF.EXISTS answer vector: every sampled lane
        is classified against the shadow."""
        mask = self.sample_mask(keys_u32)
        if not mask.any():
            return
        sampled = np.asarray(keys_u32, np.uint32)[mask]
        got = np.asarray(answers, dtype=bool)[mask]
        with self._lock:
            if key in self._dead:
                return
            shadow = self._bloom_shadow.get(key, set())
            member = np.fromiter((int(k) in shadow for k in sampled),
                                 dtype=bool, count=len(sampled))
        self._checks.inc(len(sampled))
        neg = ~member
        n_neg = int(neg.sum())
        if n_neg:
            self._negatives.inc(n_neg)
            n_fp = int((got & neg).sum())
            if n_fp:
                self._fp.inc(n_fp)
        n_fn = int((member & ~got).sum())
        if n_fn:
            # Structurally impossible for a correct filter — scream,
            # don't just count.
            self._fn.inc(n_fn)
            logger.error(
                "Bloom FALSE NEGATIVE on %r: %d sampled added keys "
                "answered absent — sketch correctness bug", key, n_fn)

    def measured_fpr(self) -> float:
        neg = self._negatives.value
        if neg == 0:
            return float("nan")
        return self._fp.value / neg

    # -- HLL surface ---------------------------------------------------------
    def record_pfadd(self, key: str, keys_u32: np.ndarray,
                     mask: Optional[np.ndarray] = None) -> None:
        keys_u32 = np.asarray(keys_u32, np.uint32)
        if mask is not None:
            keys_u32 = keys_u32[np.asarray(mask, dtype=bool)]
        if len(keys_u32) == 0:
            return
        smask = self.sample_mask(keys_u32)
        if smask.any():
            self._shadow_add(self._hll_shadow, key, keys_u32[smask])

    def shadow_count(self, keys: Sequence[str]) -> Optional[float]:
        """Exact distinct count of the sampled subspace across ``keys``
        (union semantics, like PFCOUNT), scaled by 1/sample — None when
        no shadow exists or any key's shadow overflowed."""
        with self._lock:
            if any(k in self._dead for k in keys):
                return None
            sets = [self._hll_shadow.get(k) for k in keys]
            sets = [s for s in sets if s]
            if not sets:
                return None
            union = set().union(*sets)
        return len(union) / self.sample

    def check_pfcount(self, keys: Sequence[str], answer: int) -> None:
        truth = self.shadow_count(keys)
        if not truth:
            return
        self._checks.inc()
        rel = abs(float(answer) - truth) / truth
        # One gauge per audited key set; multi-key unions (rare) label
        # by arity so the cardinality of the label space stays bounded.
        label = keys[0] if len(keys) == 1 else f"union:{len(keys)}"
        self._registry.gauge(
            "attendance_hll_measured_rel_error",
            help=AUDIT_HELP["attendance_hll_measured_rel_error"],
            key=label).set(rel)

    # -- fused-pipeline surface ----------------------------------------------
    # The fused hot loop only RECORDS ground truth (roster + sampled
    # traffic); measurement happens in the scrape-time callbacks
    # register_fused_audit installs, which re-query the live filter —
    # the hot path never blocks on a device answer for auditing.

    def record_roster(self, keys_u32: np.ndarray) -> None:
        """Shadow the fused preload (the roster IS the filter's full
        membership: the fused hot loop never BF.ADDs)."""
        self.record_bf_add("__fused_roster__", keys_u32)

    def _fused_dead(self) -> bool:
        """True once the roster shadow overflowed: with the ground
        truth gone, EVERY fused measurement must stop (not degrade) —
        classifying traffic against a vanished roster would read every
        valid key as a 'negative' and report an FPR near 1.0 on a
        perfectly healthy filter."""
        return "__fused_roster__" in self._dead

    def observe_fused_frame(self, sid: np.ndarray,
                            days: np.ndarray) -> None:
        """Record one decoded frame's sampled lanes: traffic keys (the
        measured-FPR query population) and, for lanes the shadow knows
        to be valid, per-day HLL ground truth."""
        sid = np.asarray(sid, np.uint32)
        mask = self.sample_mask(sid)
        if not mask.any():
            return
        sampled = sid[mask]
        sdays = np.asarray(days)[mask]
        with self._lock:
            if self._fused_dead():
                return
            roster = self._bloom_shadow.get("__fused_roster__", set())
            traffic = self._bloom_shadow.setdefault(
                "__fused_traffic__", set())
            valid = np.fromiter((int(k) in roster for k in sampled),
                                dtype=bool, count=len(sampled))
            if not self._traffic_frozen:
                traffic.update(int(k) for k in sampled)
                if len(traffic) >= SHADOW_CAP:
                    # Freeze (never evict): the measured FPR keeps
                    # working over the frozen probe population, and
                    # the hot path never pays a per-frame rebuild.
                    self._traffic_frozen = True
                    self._overflow.inc()
                    logger.warning(
                        "fused audit traffic reservoir reached %d "
                        "sampled keys; probe population frozen",
                        SHADOW_CAP)
        for day in np.unique(sdays[valid]):
            self._shadow_add(self._hll_shadow, f"day:{int(day)}",
                             sampled[valid & (sdays == day)])

    def fused_probe_sets(self):
        """(roster_probes, negative_probes) u32 arrays for the scrape-
        time device re-query: sampled roster keys (every one must
        answer present — false-negative check) and sampled observed
        traffic keys outside the roster (the measured-FPR population).
        Both empty once the roster shadow overflowed — no ground
        truth, no measurement."""
        with self._lock:
            if self._fused_dead():
                empty = np.empty(0, np.uint32)
                return empty, empty
            roster = self._bloom_shadow.get("__fused_roster__", set())
            traffic = self._bloom_shadow.get("__fused_traffic__", set())
            negatives = traffic - roster
            return (np.fromiter(roster, np.uint32, len(roster)),
                    np.fromiter(negatives, np.uint32, len(negatives)))

    def roster_membership(self, keys_u32: np.ndarray):
        """(sampled_mask, member) against the fused roster shadow:
        ``sampled_mask`` is bool[B] (which lanes the audit owns) and
        ``member`` is bool[sampled] ground-truth roster membership of
        those lanes — the read path's (serve/audit) classification
        input. (None, None) once the roster shadow overflowed (no
        ground truth, no measurement — same rule as the write path)."""
        keys = np.asarray(keys_u32, np.uint32)
        mask = self.sample_mask(keys)
        if not mask.any():
            return mask, np.zeros(0, dtype=bool)
        sampled = keys[mask]
        with self._lock:
            if self._fused_dead():
                return None, None
            roster = self._bloom_shadow.get("__fused_roster__", set())
            member = np.fromiter((int(k) in roster for k in sampled),
                                 dtype=bool, count=len(sampled))
        return mask, member

    def fused_day_truth(self) -> Dict[int, float]:
        """{lecture_day: exact shadow count scaled by 1/sample};
        empty once the roster shadow overflowed (valid-lane
        classification needs the roster, so the per-day truth stops
        being maintained the same moment)."""
        with self._lock:
            if self._fused_dead():
                return {}
            return {int(k.split(":", 1)[1]): len(s) / self.sample
                    for k, s in self._hll_shadow.items()
                    if k.startswith("day:")}


def register_fused_audit(telemetry, pipe, **labels) -> None:
    """Install the fused pipeline's measured-accuracy gauges: scrape-
    time callbacks that re-query the LIVE filter over the shadow's
    probe sets and compare ``count_all`` against the shadow's exact
    per-day counts. Same weakref/raise discipline as obs/health.py:
    never pins the pipeline, a dead pipeline's sample is skipped with
    a warning, device reads happen only at scrape."""
    import jax

    auditor = telemetry.auditor
    if auditor is None:
        return
    if pipe.sharded and jax.process_count() > 1:
        # The sharded query contains collectives — never run those
        # from one process's scrape thread (see health.register_fused).
        return
    ref = weakref.ref(pipe)

    def _deref():
        p = ref()
        if p is None:
            raise LookupError("fused pipeline was torn down")
        return p

    def _query(p, keys: np.ndarray) -> np.ndarray:
        # Prefer the epoch-pinned mirror: bit-identical to the device
        # filter (run-static between preloads; every preload
        # republishes) and immune to the scrape-vs-dispatch race on
        # donated device arrays. Pipelines that never published an
        # epoch keep the live device query.
        mirror = getattr(p, "read_mirror", None)
        epoch = mirror.pin() if mirror is not None else None
        if epoch is not None and epoch.bloom_words is not None:
            from attendance_tpu.models.bloom import (
                bloom_contains_words_np)
            return bloom_contains_words_np(
                epoch.bloom_words, np.asarray(keys, np.uint32),
                epoch.params)
        if p.sharded:
            return p.engine.contains(keys)
        from attendance_tpu.models.bloom import bloom_contains_words
        return np.asarray(bloom_contains_words(
            p.state.bloom_bits, np.asarray(keys, np.uint32), p.params))

    # Fused misses already reported into the shared false-negative
    # counter: the counter also carries store-path increments, so the
    # fused surface reconciles against its OWN baseline — diffing
    # against the shared total would let a store-path FN mask a real
    # fused kernel bug.
    fn_reported = [0]

    def measured_fpr() -> float:
        p = _deref()
        roster, negatives = auditor.fused_probe_sets()
        if len(roster):
            misses = int((~_query(p, roster)).sum())
            if misses:
                # Filter bits only get set, so the fused miss count
                # can only shrink between scrapes; report the high-
                # water mark once.
                if misses > fn_reported[0]:
                    auditor._fn.inc(misses - fn_reported[0])
                    fn_reported[0] = misses
                logger.error(
                    "Fused Bloom FALSE NEGATIVE: %d sampled roster "
                    "keys answered absent", misses)
        if not len(negatives):
            return float("nan")
        return float(_query(p, negatives).sum()) / len(negatives)

    def hll_rel_error() -> float:
        p = _deref()
        # Under checkpointing, answer from the pinned epoch with the
        # TRUTH SNAPSHOT captured at its publish: estimate and truth
        # then describe the same moment (comparing a barrier-stale
        # estimate against live-growing truth would charge barrier lag
        # to the sketch), and the scrape never touches the device
        # arrays a racing barrier capture is reading.
        mirror = getattr(p, "read_mirror", None)
        epoch = (mirror.pin() if mirror is not None
                 and p.checkpointing else None)
        if epoch is not None and epoch.day_truth is not None:
            # day_truth == {} means the auditor existed but nothing
            # was audited by this epoch's publish (e.g. the preload
            # epoch): "no data yet" is NaN — falling back to a live
            # device read here would reintroduce the scrape-vs-
            # dispatch race this path exists to close.
            from attendance_tpu.models.hll import estimates_from_rows
            truth = epoch.day_truth
            if not truth:
                return float("nan")
            days = [d for d in truth if d in epoch.bank_of]
            if not days:
                return float("nan")
            banks = np.array([epoch.bank_of[d] for d in days],
                             np.int64)
            ests = estimates_from_rows(epoch.hll_regs[banks],
                                       epoch.precision)
            total_truth = sum(truth[d] for d in days)
            return abs(float(ests.sum()) - total_truth) / total_truth
        truth = auditor.fused_day_truth()
        if not truth:
            return float("nan")
        est = p.count_all()
        total_truth = sum(truth.values())
        total_est = float(sum(est.get(day, 0) for day in truth))
        return abs(total_est - total_truth) / total_truth

    telemetry.registry.gauge(
        "attendance_bloom_measured_fpr",
        help=AUDIT_HELP["attendance_bloom_measured_fpr"],
        surface="fused", **labels).set_function(measured_fpr)
    telemetry.registry.gauge(
        "attendance_hll_measured_rel_error",
        help=AUDIT_HELP["attendance_hll_measured_rel_error"],
        key="fused", **labels).set_function(hll_rel_error)
