"""Live telemetry subsystem: registry + exposition + flight recorder.

One process-wide :class:`Telemetry` instance (module global), enabled
iff any telemetry flag is set (``--metrics-prom``, ``--metrics-port``,
``--flight-recorder``). Instrumented call sites follow the
``utils/profiling.py`` discipline: they capture the global ONCE at
construction and pay exactly one ``is not None`` branch per hot-path
event when telemetry is off — nothing is imported, timed, or allocated.

Wiring: pipeline constructors call :func:`ensure` with their config —
first caller with a telemetry-enabled config creates and starts the
subsystem; everyone after (brokers, engines, sibling pipelines in the
same process) picks it up via :func:`get`. Tests drive
:func:`enable`/:func:`disable` directly.

Metric names (the stable scrape contract, asserted by tests):

* ``attendance_events_total`` / ``attendance_frames_total`` — counters
  over both processors.
* ``attendance_wire_frames_total{wire=...}`` — frames per host->device
  wire (word/seg/delta/bytes/arrays), the adaptive ladder made visible.
* ``attendance_stage_latency_seconds{stage=...}`` — log-bucketed
  per-stage histograms (dequeue_wait, decode, dispatch, device_wait,
  batch_assembly, sketch, persist, snapshot_write, snapshot_blocked).
* ``attendance_queue_depth{topic=...,subscription=...}`` — broker
  backlog gauges (callback-read at scrape time).
* ``attendance_broker_*`` / ``attendance_socket_*`` — transport
  counters (messages, bytes, redeliveries).
* ``attendance_shard_events{replica=...}`` — per-replica event totals
  of the sharded engine, aggregated at report time.
* ``attendance_snapshot_delta_bytes`` /
  ``attendance_snapshot_chain_length`` — size of the last incremental
  snapshot delta and delta files since the last full base (the delta
  checkpoint pipeline, pipeline/fast_path).
* Sketch health (callback gauges, device reads ONLY at scrape time —
  see obs/health.py): ``attendance_bloom_fill_fraction`` and
  ``attendance_bloom_estimated_fpr`` (occupancy-based fill^k, the
  paper's <=1% FPR target made live), ``attendance_hll_estimate``
  (summed Ertl estimate over registered banks) and
  ``attendance_hll_saturated_registers`` (registers at rank > q —
  the saturation the <=2% relative-error target degrades under).

Span tracing (obs/tracing.py, ``--trace-out``) rides the same bundle:
one Tracer on the Telemetry object, same capture-once/one-branch
discipline, flushed as Chrome-trace/Perfetto JSON at end of run and on
teardown; trace context propagates through broker message properties
(``traceparent``).

Accuracy auditing + SLOs (obs/audit.py / obs/slo.py, ``--audit-sample``
/ ``--alert-log`` / ``--slo``) complete the correctness pillar: a
sampled exact shadow cross-checks live sketch answers and exports
MEASURED gauges (``attendance_bloom_measured_fpr``,
``attendance_bloom_false_negatives_total`` — must stay 0,
``attendance_hll_measured_rel_error``) next to the PR 2 estimators so
estimator drift is itself visible; the SLO engine evaluates
declarative objectives over fast+slow burn-rate windows
(``attendance_slo_burn_rate``/``attendance_slo_firing``), appends a
JSONL alert log, and flags transitions in the flight ring. The
``doctor`` CLI verb replays those artifacts offline into a
CI-gateable verdict.
"""

from __future__ import annotations

import logging
import threading
from typing import Dict, Optional

from attendance_tpu.obs.recorder import (  # noqa: F401
    _NOT_INSTALLED, DEFAULT_RING, FlightRecorder, install_sigusr1,
    uninstall_sigusr1)
from attendance_tpu.obs.registry import (  # noqa: F401
    Counter, Gauge, Histogram, Registry)
from attendance_tpu.obs.tracing import (  # noqa: F401
    TRACEPARENT, SpanContext, Tracer, format_ctx, parse_ctx)

logger = logging.getLogger(__name__)

# THE process-wide telemetry handle. None = disabled (the common case):
# every instrumented call site short-circuits on it.
TELEMETRY: Optional["Telemetry"] = None
_lock = threading.Lock()

DEFAULT_FLIGHT_PATH = "flight_recorder.json"

_atexit_installed = False


def _atexit_flush() -> None:
    t = TELEMETRY
    if t is None:
        return
    # Order matters: classify the SLOs first (a last-moment breach must
    # land in the alert log AND in the gauges), then write the final
    # exposition block carrying those gauges, then the trace.
    t.finalize_slo("atexit")
    if t.incidents is not None:
        try:
            t.incidents.finalize("atexit")  # persist a still-open record
        except Exception:
            logger.exception("atexit incident finalize failed")
    if t._reporter is not None:
        try:
            t._reporter._write_block()
        except Exception:
            logger.exception("atexit metrics block write failed")
    t.flush_trace("atexit")
    t.flush_profile("atexit")
    if t._fleet is not None:
        try:
            t._fleet.push_now()  # last snapshot reaches the collector
        except Exception:
            logger.exception("atexit fleet push failed")


def _install_atexit_flush() -> None:
    """Register the exit-time telemetry flush exactly once per process;
    it reads the CURRENT global, so stopped instances are neither
    pinned nor flushed. Covers the trace buffer AND a final exposition
    block: a CLI run shorter than the reporter interval would otherwise
    exit with an EMPTY --metrics-prom file (nothing stops the daemon
    reporter at process exit), which `doctor` would read as a missing
    artifact."""
    global _atexit_installed
    if _atexit_installed:
        return
    _atexit_installed = True
    import atexit

    atexit.register(_atexit_flush)


def enabled_in(config) -> bool:
    """Does this config ask for live telemetry at all?"""
    return bool(getattr(config, "metrics_prom", "")
                or getattr(config, "metrics_port", 0)
                or getattr(config, "flight_recorder", 0)
                or getattr(config, "trace_out", "")
                or getattr(config, "audit_sample", 0.0)
                or getattr(config, "alert_log", "")
                or getattr(config, "slo", None)
                or getattr(config, "fleet_push", "")
                or getattr(config, "profile_hz", 0.0)
                or getattr(config, "incident_dir", "")
                or getattr(config, "control_log", ""))


class Telemetry:
    """Registry + optional reporter/server/flight-recorder, one bundle."""

    def __init__(self, *, metrics_prom: str = "", metrics_port: int = 0,
                 metrics_interval_s: float = 1.0,
                 flight_recorder: int = 0,
                 flight_path: str = DEFAULT_FLIGHT_PATH,
                 trace_out: str = "", audit_sample: float = 0.0,
                 alert_log: str = "", slo_specs=(),
                 slo_fast_s: float = 60.0, slo_slow_s: float = 300.0,
                 fleet_push: str = "", fleet_role: str = "",
                 fleet_instance: str = "",
                 fleet_push_interval_s: float = 2.0,
                 metric_series_max: int = 1024,
                 profile_hz: float = 0.0, profile_out: str = "",
                 incident_dir: str = "",
                 incident_clear_ticks: int = 3,
                 control_log: str = "",
                 control_spill_dir: str = "",
                 control_dwell_s: float = 2.0,
                 control_clear_ticks: int = 3,
                 control_flap_limit: int = 8):
        self.registry = Registry(max_series=metric_series_max)
        self.flight: Optional[FlightRecorder] = (
            FlightRecorder(flight_recorder) if flight_recorder > 0
            else None)
        self.flight_path = flight_path or DEFAULT_FLIGHT_PATH
        # Span tracer (obs/tracing.py): instrumented sites capture
        # `telemetry.tracer` once and branch on `is not None` — a
        # metrics-only run (trace_out unset) pays nothing for tracing.
        # A fleet-pushing process traces even without a local
        # --trace-out: its spans ship to the collector's stitched
        # export instead of (or as well as) a local file.
        self.tracer: Optional[Tracer] = (
            Tracer() if (trace_out or fleet_push) else None)
        self.trace_path = trace_out
        self._fleet_push = fleet_push
        self._fleet_role = fleet_role or "process"
        self._fleet_instance = fleet_instance
        self._fleet_interval = fleet_push_interval_s
        self._fleet: Optional[object] = None
        # Accuracy auditor (obs/audit.py): same capture-once handle
        # discipline — sketch stores and the fused pipeline hold
        # `telemetry.auditor` and branch on `is not None`.
        self.auditor = None
        if audit_sample > 0:
            from attendance_tpu.obs.audit import ShadowAuditor
            self.auditor = ShadowAuditor(self.registry, audit_sample)
        # SLO burn-rate engine (obs/slo.py): evaluates declarative
        # objectives over the registry on its own thread; alert
        # transitions land in the JSONL log and the flight ring.
        self.slo = None
        if alert_log or slo_specs:
            from attendance_tpu.obs.slo import SloEngine
            self.slo = SloEngine(self, slo_specs, slo_fast_s,
                                 slo_slow_s, alert_log,
                                 interval_s=min(metrics_interval_s,
                                                max(slo_fast_s / 4,
                                                    0.05)))
        # Attribution plane (obs/profiler.py): the host sampling
        # profiler is created only at --profile-hz > 0 (its stage
        # tracker is what the hot-path marks write into); the
        # recompile tracker is always on when telemetry is — its cost
        # is one set lookup per dispatch, and recompile storms are
        # exactly the thing a metrics-only run must still see.
        from attendance_tpu.obs.profiler import RecompileTracker
        self.recompiles = RecompileTracker(self.registry)
        self.profiler = None
        if profile_hz > 0:
            from attendance_tpu.obs.profiler import SamplingProfiler
            self.profiler = SamplingProfiler(
                profile_hz, registry=self.registry,
                out_dir=profile_out)
        # Incident plane (obs/incident.py): correlates live breach
        # conditions (SLO firings, circuit opens, spill growth, steady
        # recompiles, lag/staleness breaches, dead peers, lane stalls)
        # into incident records with checksummed evidence bundles under
        # --incident-dir. Created after the sources it subscribes to.
        self.incidents = None
        if incident_dir:
            from attendance_tpu.obs.incident import IncidentEngine
            self.incidents = IncidentEngine(
                self, incident_dir,
                role=self._fleet_role,
                instance=fleet_instance,
                clear_ticks=incident_clear_ticks,
                interval_s=min(metrics_interval_s, 1.0))
        # Control plane (attendance_tpu/control): the actuation engine
        # consumes every signal constructed above (slo, recompiles,
        # incidents) and mutates only bounded knobs a pipeline binds at
        # attach() time. Created LAST so its first tick sees the full
        # bundle.
        self.control = None
        if control_log:
            from attendance_tpu.control.engine import ControlEngine
            self.control = ControlEngine(
                self, control_log,
                spill_dir=control_spill_dir,
                dwell_s=control_dwell_s,
                clear_ticks=control_clear_ticks,
                flap_limit=control_flap_limit,
                interval_s=min(metrics_interval_s, 1.0))
        self._reporter = None
        self._server = None
        self._prev_sigusr1 = _NOT_INSTALLED
        self._metrics_prom = metrics_prom
        self._metrics_port = metrics_port
        self._interval = metrics_interval_s
        self._stage_cache: Dict[str, Histogram] = {}
        self._wire_cache: Dict[str, Counter] = {}
        # The shared top-line counters both processors bump.
        self.events = self.registry.counter(
            "attendance_events_total", help="Events processed")
        self.frames = self.registry.counter(
            "attendance_frames_total", help="Frames/batches processed")

    # -- lifecycle -----------------------------------------------------------
    def start(self) -> "Telemetry":
        from attendance_tpu.obs.exposition import (
            FileReporter, MetricsServer)
        if self._metrics_prom:
            self._reporter = FileReporter(
                self.registry, self._metrics_prom, self._interval).start()
        if self._metrics_port:
            # -1 selects an ephemeral port (tests, parallel runs); the
            # bound port is on server.port either way.
            port = 0 if self._metrics_port < 0 else self._metrics_port
            self._server = MetricsServer(self.registry, port).start()
        if self.flight is not None:
            self._prev_sigusr1 = install_sigusr1(self.flight,
                                                 self.flight_path)
        if self.slo is not None:
            self.slo.start()
        if self.incidents is not None:
            # After the SLO engine: the first incident tick must see
            # engine state, not a half-constructed firing map.
            self.incidents.start()
        if self.profiler is not None:
            self.profiler.start()
        if self.control is not None:
            # After the incident engine: an actuation's incident id
            # must come from a tick that already saw the conditions.
            self.control.start()
        if self._fleet_push:
            from attendance_tpu.obs.fleet import (
                FleetPusher, default_instance)
            self._fleet = FleetPusher(
                self.registry, self.tracer, self._fleet_push,
                role=self._fleet_role,
                instance=(self._fleet_instance
                          or default_instance()),
                interval_s=self._fleet_interval).start()
        if (self.tracer is not None or self._reporter is not None
                or self.slo is not None or self.profiler is not None
                or self.incidents is not None
                or self.control is not None):
            # Backstop for CLI runs that never reach a run-loop flush
            # (KeyboardInterrupt, runs shorter than the reporter
            # interval); every flush is idempotent. ONE module-level
            # hook flushing whatever telemetry is live at exit —
            # per-instance registrations would pin every stopped
            # Telemetry (and its up-to-64k-span buffer) for the
            # process lifetime and rewrite possibly-deleted artifact
            # paths (bound-method atexit.unregister does not reliably
            # match, so this never registers per instance).
            _install_atexit_flush()
        return self

    def stop(self) -> None:
        self.flush_trace("telemetry-stop")
        if self.control is not None:
            # The controller stops FIRST: it must not actuate against
            # signal sources that the teardown below is dismantling.
            self.control.stop()
        if self.incidents is not None:
            # Persist a still-open incident record while every evidence
            # source below is alive, then stop the tick thread.
            self.incidents.finalize("telemetry-stop")
            self.incidents.stop()
        if self.profiler is not None:
            # Sampler thread joined BEFORE the fleet drain below: the
            # final push carries the profiler's last stage fractions,
            # and stop() also writes the profile artifacts.
            self.profiler.stop()
            self.flush_profile("telemetry-stop")
        if self._fleet is not None:
            # Final push (incl. any spans recorded above) so a run
            # shorter than the push interval still reaches the
            # collector — the FileReporter's final-block contract.
            self._fleet.stop()
            self._fleet = None
        if self.slo is not None:
            # Final tick first: a firing alert must reach the log (and
            # the flight ring) before the reporter writes its last
            # block below.
            self.slo.stop()
        if self._reporter is not None:
            self._reporter.stop()
            self._reporter = None
        if self._server is not None:
            self._server.stop()
            self._server = None
        if self._prev_sigusr1 is not _NOT_INSTALLED:
            # Restore the displaced handler: a leaked one would keep
            # dumping this (now stale) ring to this (now stale) path.
            uninstall_sigusr1(self._prev_sigusr1)
            self._prev_sigusr1 = _NOT_INSTALLED

    @property
    def http_port(self) -> Optional[int]:
        return self._server.port if self._server is not None else None

    # -- cached handles (hot paths fetch these once at construction) ---------
    def stage(self, name: str) -> Histogram:
        h = self._stage_cache.get(name)
        if h is None:
            h = self._stage_cache[name] = self.registry.histogram(
                "attendance_stage_latency_seconds",
                help="Per-stage latency (power-of-2 buckets)",
                stage=name)
        return h

    def wire(self, name: str) -> Counter:
        c = self._wire_cache.get(name)
        if c is None:
            c = self._wire_cache[name] = self.registry.counter(
                "attendance_wire_frames_total",
                help="Frames dispatched per host->device wire",
                wire=name)
        return c

    # -- flight recorder -----------------------------------------------------
    def record_batch(self, **fields) -> None:
        if self.flight is not None:
            self.flight.record(fields)

    def dump_flight(self, reason: str) -> None:
        if self.flight is None:
            return
        try:
            p = self.flight.dump(self.flight_path, reason=reason)
            logger.info("Flight recorder dumped to %s (%s)", p, reason)
        except Exception:
            logger.exception("Flight recorder dump failed")

    # -- SLO engine ----------------------------------------------------------
    def finalize_slo(self, reason: str) -> None:
        """End-of-run SLO evaluation (no-op without the engine): runs
        one last classification tick so runs shorter than the tick
        interval still judge their objectives and write any firing
        alert before the process exits. The engine keeps running —
        symmetric with flush_trace, which also leaves the tracer live."""
        if self.slo is not None:
            self.slo.finalize(reason)

    # -- profiling -----------------------------------------------------------
    def flush_profile(self, reason: str = "flush") -> None:
        """Write the profile artifacts (collapsed stacks, stage
        timeline, attribution.json) to --profile-out — idempotent,
        no-op without a profiler or an out dir. The recompile ledger
        rides into attribution.json here, so the offline table names
        the shapes that compiled."""
        p = self.profiler
        if p is None or not p.out_dir or not p.samples:
            return
        try:
            path = p.write(p.out_dir, recompiles=self.recompiles)
            logger.info("Profile (%d samples) written under %s (%s)",
                        p.samples, path.parent, reason)
        except Exception:
            logger.exception("Profile flush failed")

    # -- tracing -------------------------------------------------------------
    def flush_trace(self, reason: str = "flush") -> None:
        """Write the span buffer to ``--trace-out`` (atomic; no-op
        without a tracer). Called at the end of every run loop, on
        stop(), and at process exit — a crash loses at most the spans
        since the last completed run."""
        if self.tracer is None or not self.trace_path:
            return
        if not len(self.tracer):
            return  # nothing recorded (e.g. a sibling pipeline's exit)
        try:
            p = self.tracer.flush(self.trace_path)
            logger.info("Trace (%d spans) written to %s (%s)",
                        len(self.tracer), p, reason)
        except Exception:
            logger.exception("Trace flush failed")

    def render(self) -> str:
        from attendance_tpu.obs.exposition import render
        return render(self.registry)


def _slo_specs_from(config) -> tuple:
    """The config's SLO specs plus derived objectives: a set
    ``read_staleness_ceiling_s`` IS a staleness SLO (one number, one
    spelling). Derived here — at the point the specs are consumed —
    so programmatic Config construction gets it exactly like the CLI
    path (validate() is only called by config_from_args)."""
    specs = list(getattr(config, "slo", ()) or ())
    ceiling = getattr(config, "read_staleness_ceiling_s", 0.0)
    if ceiling and not any(
            s.replace(" ", "").startswith("read_staleness")
            for s in specs):
        specs.append(f"read_staleness<={ceiling}")
    return tuple(specs)


def enable(config) -> Telemetry:
    """Create, start, and install the global Telemetry from config."""
    global TELEMETRY
    with _lock:
        if TELEMETRY is not None:
            return TELEMETRY
        t = Telemetry(
            metrics_prom=getattr(config, "metrics_prom", ""),
            metrics_port=getattr(config, "metrics_port", 0),
            metrics_interval_s=getattr(config, "metrics_interval_s", 1.0),
            flight_recorder=getattr(config, "flight_recorder", 0),
            flight_path=getattr(config, "flight_path",
                                DEFAULT_FLIGHT_PATH),
            trace_out=getattr(config, "trace_out", ""),
            audit_sample=getattr(config, "audit_sample", 0.0),
            alert_log=getattr(config, "alert_log", ""),
            slo_specs=_slo_specs_from(config),
            slo_fast_s=getattr(config, "slo_fast_s", 60.0),
            slo_slow_s=getattr(config, "slo_slow_s", 300.0),
            fleet_push=getattr(config, "fleet_push", ""),
            fleet_role=getattr(config, "fleet_role", ""),
            fleet_instance=(getattr(config, "fleet_instance", "")
                            or getattr(config, "fed_worker", "")),
            fleet_push_interval_s=getattr(config,
                                          "fleet_push_interval_s", 2.0),
            metric_series_max=getattr(config, "metric_series_max",
                                      1024),
            profile_hz=getattr(config, "profile_hz", 0.0),
            profile_out=getattr(config, "profile_out", ""),
            incident_dir=getattr(config, "incident_dir", ""),
            incident_clear_ticks=getattr(config, "incident_clear_ticks",
                                         3),
            control_log=getattr(config, "control_log", ""),
            control_spill_dir=getattr(config, "control_spill_dir", ""),
            control_dwell_s=getattr(config, "control_dwell_s", 2.0),
            control_clear_ticks=getattr(config, "control_clear_ticks",
                                        3),
            control_flap_limit=getattr(config, "control_flap_limit", 8))
        t.start()
        TELEMETRY = t
        return t


def ensure(config) -> Optional[Telemetry]:
    """The constructor chokepoint: returns the live global telemetry,
    creating it iff this config enables any telemetry surface. With all
    flags unset this is one global read — the disabled path."""
    if TELEMETRY is not None:
        return TELEMETRY
    if config is not None and enabled_in(config):
        return enable(config)
    return None


def get() -> Optional[Telemetry]:
    return TELEMETRY


def disable() -> None:
    """Stop and clear the global (tests; symmetric with enable)."""
    global TELEMETRY
    with _lock:
        if TELEMETRY is not None:
            TELEMETRY.stop()
            TELEMETRY = None
