"""Sketch-health gauges: live accuracy telemetry at scrape time.

The paper's accuracy targets (<=1% Bloom FPR, <=2% HLL relative error)
are defined over sketch STATE, not traffic — so the scrape surface
should report the live Bloom fill/FPR and HLL estimate/saturation, and
accuracy regressions become visible DURING a run instead of only in
post-hoc parity checks.

Design constraints, in order:

* Never on the hot path: every gauge is a CALLBACK registered lazily
  at construction; the (potentially expensive) device reads —
  popcount + D2H scalar for the filter, register histogram for the
  HLL — run only when a scrape renders the registry. With telemetry
  off nothing here is imported or registered at all.
* Never pin the reporter: callbacks close over a ``weakref`` to the
  pipeline/filter, reporting the registered default once it dies
  (matching the queue-depth gauge discipline in memory_broker).
* Never lie: a callback that RAISES (a dead device, a torn-down mesh)
  propagates — the exposition layer skips the sample with a warning
  (obs.exposition.render) rather than rendering a 0.0 that reads as
  "FPR is zero".

Metric names (part of the stable scrape contract in obs/__init__):

* ``attendance_bloom_fill_fraction`` — fraction of set filter bits.
* ``attendance_bloom_estimated_fpr`` — fill^k, the same estimator as
  ``BloomFilter.estimated_fpr`` / ``FusedPipeline.estimated_fpr``
  (including the packed-words variant), so the gauge and the model's
  own method agree to float tolerance by construction.
* ``attendance_hll_estimate`` — Ertl estimate summed over registered
  banks (``models/hll.py:estimate_from_histogram``).
* ``attendance_hll_saturated_registers`` — registers at rank > q
  (the ``C[q+1]`` histogram bin): the saturation regime where the
  relative-error target starts to degrade.
"""

from __future__ import annotations

import weakref

import numpy as np

HEALTH_HELP = {
    "attendance_bloom_fill_fraction":
        "Fraction of set Bloom filter bits (scrape-time device read)",
    "attendance_bloom_estimated_fpr":
        "Occupancy-based Bloom FPR estimate (fill^k)",
    "attendance_hll_estimate":
        "HLL cardinality estimate summed over registered banks",
    "attendance_hll_saturated_registers":
        "HLL registers at rank > q (saturation)",
}


def _gauge(telemetry, name: str, fn, **labels) -> None:
    telemetry.registry.gauge(
        name, help=HEALTH_HELP[name], **labels).set_function(fn)


def _deref(ref):
    obj = ref()
    if obj is None:
        # Propagate: render() skips the sample with a warning; a dead
        # pipeline has NO fill fraction, and 0.0 would claim an empty
        # filter.
        raise LookupError("sketch owner was torn down")
    return obj


def register_fused(telemetry, pipe, **labels) -> None:
    """Register the four health gauges for a FusedPipeline (single-chip
    packed-words state or the sharded engine). Called from the pipeline
    constructor iff telemetry is live."""
    import jax

    if pipe.sharded and jax.process_count() > 1:
        # Multi-controller lockstep: the fill/count reductions contain
        # collectives, which must never run from a scrape thread on one
        # process only — that would wedge the whole mesh.
        return
    ref = weakref.ref(pipe)

    def _bloom_epoch(p):
        """The pinned epoch, when its filter words can answer for the
        live filter: the fused filter is run-static between preloads
        (the hot loop never BF.ADDs) and every preload republishes, so
        ANY epoch carrying words is bit-current — and reading it
        avoids the scrape-vs-dispatch race on the donated device
        arrays (a scrape racing a step used to observe a deleted
        buffer and drop the sample)."""
        mirror = getattr(p, "read_mirror", None)
        epoch = mirror.pin() if mirror is not None else None
        if epoch is not None and epoch.bloom_words is not None:
            return epoch
        return None

    def _hll_epoch(p):
        """The pinned epoch, when its register rows are the right
        source for the HLL gauges: only under checkpointing, where
        barriers republish at cadence — a scrape racing a barrier's
        capture then reads a CONSISTENT epoch instead of torn bank
        rows mid-gather. Without checkpointing nothing republishes
        mid-run, so the live device read (pre-epoch behavior) stays."""
        if not p.checkpointing:
            return None
        mirror = getattr(p, "read_mirror", None)
        return mirror.pin() if mirror is not None else None

    def fill() -> float:
        p = _deref(ref)
        epoch = _bloom_epoch(p)
        if epoch is not None:
            from attendance_tpu.models.bloom import (
                bloom_packed_fill_fraction_np)
            return bloom_packed_fill_fraction_np(epoch.bloom_words)
        if p.sharded:
            return float(p.engine.fill_fraction())
        from attendance_tpu.models.bloom import (
            bloom_packed_fill_fraction)
        return float(bloom_packed_fill_fraction(p.state.bloom_bits))

    def fpr() -> float:
        return fill() ** _deref(ref).params.k

    def hll_estimate() -> float:
        p = _deref(ref)
        epoch = _hll_epoch(p)
        if epoch is not None:
            from attendance_tpu.models.hll import estimates_from_rows
            if not epoch.bank_of:
                return 0.0
            banks = np.fromiter(epoch.bank_of.values(), np.int64,
                                len(epoch.bank_of))
            ests = estimates_from_rows(epoch.hll_regs[banks],
                                       epoch.precision)
            # Per-bank integer rounding, matching count_all(): the
            # gauge and the model's own method must agree exactly.
            return float(np.rint(ests).sum())
        return float(sum(p.count_all().values()))

    def hll_saturated() -> float:
        p = _deref(ref)
        q = 64 - p.config.hll_precision
        epoch = _hll_epoch(p)
        if epoch is not None:
            return float((epoch.hll_regs > q).sum())
        if p.sharded:
            # Max over the replica axis = the merged register view the
            # query path counts with (register-max union).
            regs = np.asarray(p.engine.regs).max(axis=0)
        else:
            regs = np.asarray(p.state.hll_regs)
        return float((regs > q).sum())

    _gauge(telemetry, "attendance_bloom_fill_fraction", fill, **labels)
    _gauge(telemetry, "attendance_bloom_estimated_fpr", fpr, **labels)
    _gauge(telemetry, "attendance_hll_estimate", hll_estimate, **labels)
    _gauge(telemetry, "attendance_hll_saturated_registers",
           hll_saturated, **labels)


def register_store(telemetry, store, bloom_key: str, **labels) -> None:
    """Register the health gauges for a generic :class:`SketchStore`
    (the ``--sketch-backend=memory/tpu/redis-sim`` command path, which
    previously had NO live health surface — only the fused pipeline
    did).

    The weakref target is the STORE, not its inner filter/HLL objects:
    snapshot restore REPLACES those innards (``_restore_filter`` /
    ``_restore_hll_banked`` build fresh arrays), so a gauge closed over
    an inner object would silently go stale after every restore — the
    callbacks here re-read ``store._blooms``/``store._hll`` on each
    scrape instead. ``utils/snapshot.restore_sketch_store``
    additionally re-invokes this registration (idempotent:
    ``set_function`` on the same (name, labels) gauge), so a store
    restored under a telemetry bundle that registered against an older
    generation resumes reporting either way."""
    from attendance_tpu.models.hll import (
        best_histogram as best_histogram_of,
        estimate_from_histogram as estimate_of)

    ref = weakref.ref(store)

    def _fills(s):
        """(fill, m_bits) per sub-filter of the audited bloom chain;
        None when the key is absent or a backend handle is opaque."""
        bloom = s._blooms.get(bloom_key)
        if bloom is None:
            return None
        out = []
        for handle, params in zip(bloom.filters, bloom.params):
            fill = s._filter_fill(handle, params)
            if fill is None:
                return None
            out.append((fill, params.m_bits))
        return out

    def fill() -> float:
        fills = _fills(_deref(ref))
        if not fills:
            raise LookupError(f"no inspectable filter at {bloom_key!r}")
        total = sum(m for _, m in fills)
        return sum(f * m for f, m in fills) / total

    def fpr() -> float:
        v = _deref(ref).estimated_fpr(bloom_key)
        if v is None:
            raise LookupError(f"no inspectable filter at {bloom_key!r}")
        return float(v)

    def _regs(s) -> np.ndarray:
        hll = getattr(s, "_hll", None)
        if hll is not None:  # banked (tpu)
            return np.asarray(hll.regs)
        per_key = getattr(s, "_hll_regs", None)
        if per_key is None:
            per_key = getattr(s, "_hlls", None)  # redis-sim
        if not per_key:
            raise LookupError("store holds no HLL state yet")
        return np.stack(list(per_key.values()))

    def hll_estimate() -> float:
        s = _deref(ref)
        hll = getattr(s, "_hll", None)
        if hll is not None:
            hists = np.asarray(best_histogram_of(hll.regs, hll.precision))
            return float(sum(
                estimate_of(hists[b], hll.precision)
                for b in hll._bank_of.values()))
        # Per-key stores: sum of per-key estimates (same aggregate the
        # fused gauge reports).
        precision = getattr(s, "precision", 14)
        per_key = getattr(s, "_hll_regs", None) or getattr(
            s, "_hlls", None) or {}
        total = 0.0
        q = 64 - precision
        for regs in per_key.values():
            hist = np.bincount(np.asarray(regs), minlength=q + 2)
            total += estimate_of(hist, precision)
        return total

    def hll_saturated() -> float:
        s = _deref(ref)
        precision = getattr(getattr(s, "_hll", None), "precision",
                            getattr(s, "precision", 14))
        return float((_regs(s) > 64 - precision).sum())

    _gauge(telemetry, "attendance_bloom_fill_fraction", fill, **labels)
    _gauge(telemetry, "attendance_bloom_estimated_fpr", fpr, **labels)
    _gauge(telemetry, "attendance_hll_estimate", hll_estimate, **labels)
    _gauge(telemetry, "attendance_hll_saturated_registers",
           hll_saturated, **labels)
    # Breadcrumb for restore-time re-registration (utils/snapshot).
    store._health_registration = (bloom_key, dict(labels))


def reregister_store(store) -> None:
    """Refresh a store's health gauges after snapshot restore, if it
    was ever registered and telemetry is still live — the literal
    re-registration half of the restore contract (see
    :func:`register_store`)."""
    from attendance_tpu import obs

    reg = getattr(store, "_health_registration", None)
    t = obs.get()
    if reg is None or t is None:
        return
    bloom_key, labels = reg
    register_store(t, store, bloom_key, **labels)


def register_bloom_filter(telemetry, bloom, **labels) -> None:
    """Register fill/FPR gauges for a standalone
    ``models.bloom.BloomFilter`` (the generic TpuSketchStore path);
    label by filter key so multiple filters coexist."""
    ref = weakref.ref(bloom)

    def fill() -> float:
        from attendance_tpu.models.bloom import bloom_fill_fraction
        return float(bloom_fill_fraction(_deref(ref).bits))

    def fpr() -> float:
        return _deref(ref).estimated_fpr()

    _gauge(telemetry, "attendance_bloom_fill_fraction", fill, **labels)
    _gauge(telemetry, "attendance_bloom_estimated_fpr", fpr, **labels)


def register_hll(telemetry, hll, **labels) -> None:
    """Register estimate/saturation gauges for a standalone
    ``models.hll.HyperLogLog``."""
    ref = weakref.ref(hll)

    def estimate() -> float:
        from attendance_tpu.models.hll import (
            best_histogram, estimate_from_histogram)
        h = _deref(ref)
        hists = np.asarray(best_histogram(h.regs, h.precision))
        return float(sum(estimate_from_histogram(hists[b], h.precision)
                         for b in h._bank_of.values()))

    def saturated() -> float:
        h = _deref(ref)
        return float((np.asarray(h.regs) > 64 - h.precision).sum())

    _gauge(telemetry, "attendance_hll_estimate", estimate, **labels)
    _gauge(telemetry, "attendance_hll_saturated_registers", saturated,
           **labels)
