"""Incident plane: correlated breach detection, evidence bundles, diagnosis.

The :class:`IncidentEngine` subscribes to every alert/transition source the
stack already emits — SLO burn-rate firings, circuit-breaker opens, spill
growth, steady recompiles, merge-lag / staleness / watermark-lag breaches,
dead federation peers, lane stalls, integrity wire rejects — and correlates
simultaneous breaches into one first-class *incident record* instead of a
pile of disconnected log lines.

On open the engine captures an evidence bundle under ``--incident-dir``:

    <incident-dir>/<incident-id>/
        incident.json       record + sha256 manifest of every evidence part
        diagnosis.json      ranked rule matches (most likely cause first)
        flight.json         flight-recorder ring dump
        trace_slice.json    bounded trace slice for the breach window
        attribution.json    profiler attribution snapshot (+ recompile state)
        metrics.prom        prom exposition snapshot
        fleet_status.json   fleet collector status when one is attached

Every part is written tmp+fsync+rename and its digest is recorded inside
``incident.json`` (never a ``MANIFEST.json`` — that filename would collide
with the store-chain scrub family), so bundles survive the rot scrubber and
``doctor --incident`` can verify them offline.

Diagnosis is a declarative signature table: each rule names the condition
set that implies a cause ("steady recompiles + new shape fingerprints →
shape churn"). Matching rules are ranked and emitted as ``diagnosis.json``
so the future control plane (ROADMAP item 4) can consume a machine-readable
cause rather than re-correlating raw series.
"""

from __future__ import annotations

import hashlib
import json
import os
import threading
import time
from dataclasses import dataclass
from pathlib import Path
from typing import Any, Dict, List, Optional, Set, Tuple

from .slo import ALERT_SCHEMA

INCIDENT_FILE = "incident.json"
DIAGNOSIS_FILE = "diagnosis.json"

#: The five evidence parts every bundle must contain (absent subsystems
#: contribute an explicit ``{"collected": false}`` stub, never a hole).
EVIDENCE_PARTS = (
    "flight.json",
    "trace_slice.json",
    "attribution.json",
    "metrics.prom",
    "fleet_status.json",
)

#: Bounded trace slice: hard cap on non-meta events kept in a bundle.
TRACE_SLICE_LIMIT = 5000

#: Corroborating-only conditions: they raise a diagnosis' rank and keep
#: an open incident open, but never OPEN one by themselves — a benign
#: idle tail trips the throughput EMA on every stop/start, and neither
#: matches any rule alone (an undiagnosed page for "the pipeline went
#: idle" is exactly the false positive hysteresis exists to prevent).
SECONDARY_CONDITIONS = frozenset({"throughput_drop", "stage_shift"})


# ---------------------------------------------------------------------------
# Diagnosis rules
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class Rule:
    """One row of the diagnosis signature table.

    A rule matches when every ``required`` condition is present in the
    incident's condition set; ``optional`` conditions raise its rank.
    ``evidence`` names the bundle parts a human should open first.
    """

    name: str
    cause: str
    required: Tuple[str, ...]
    optional: Tuple[str, ...] = ()
    evidence: Tuple[str, ...] = ()
    #: Stable actuation id the control plane maps to a policy (an id in
    #: control.engine.ADVISORY_ACTIONS has no knob by design — shape
    #: pinning is a standing gate, rebalance is ROADMAP item 3, wire
    #: quarantine already happened by diagnosis time).
    action: str = ""


RULES: Tuple[Rule, ...] = (
    Rule(
        "persist_sink_down",
        "persist sink down: breaker open while batches spill to disk",
        required=("circuit_open", "spill_growth"),
        optional=("slo_burn",),
        evidence=("metrics.prom", "flight.json"),
        action="shed_ingress",
    ),
    Rule(
        "shape_churn",
        "steady-state recompiles: input shapes are churning XLA compilations",
        required=("steady_recompiles",),
        optional=("throughput_drop", "dispatch_gap"),
        evidence=("attribution.json", "metrics.prom"),
        action="pin_shapes",
    ),
    Rule(
        "dead_worker",
        "federation worker down: peer marked down while merge lag grows",
        required=("peer_down",),
        optional=("merge_lag", "slo_burn"),
        evidence=("fleet_status.json", "metrics.prom"),
        action="defer_rebalance",
    ),
    Rule(
        "temporal_dispatch_pass",
        "temporal host passes running on the dispatch thread: stage "
        "self-time shifted >20pp while throughput dropped",
        required=("throughput_drop", "stage_shift"),
        optional=("dispatch_gap",),
        evidence=("attribution.json", "trace_slice.json"),
        action="pause_temporal",
    ),
    Rule(
        "fed_merge_backlog",
        "federation merge backlog: merge-lag p99 over ceiling",
        required=("merge_lag",),
        optional=("slo_burn",),
        evidence=("metrics.prom", "fleet_status.json"),
        action="stretch_snapshot_cadence",
    ),
    Rule(
        "stale_reads",
        "serving reads stale: snapshot publish cadence behind ceiling",
        required=("read_staleness",),
        optional=("slo_burn",),
        evidence=("metrics.prom", "flight.json"),
        action="tighten_snapshot_cadence",
    ),
    Rule(
        "watermark_stall",
        "watermark stalled: event-time lag over ceiling, windows not closing",
        required=("watermark_lag",),
        optional=("throughput_drop",),
        evidence=("metrics.prom", "trace_slice.json"),
        action="widen_lateness",
    ),
    Rule(
        "lane_stall",
        "ingress lane stalled: one striped lane stopped making progress",
        required=("lane_stall",),
        optional=("throughput_drop",),
        evidence=("flight.json", "metrics.prom"),
        action="rescale_lanes",
    ),
    Rule(
        "sink_circuit_open",
        "persist breaker open: sink failing, spill not (yet) growing",
        required=("circuit_open",),
        optional=("slo_burn",),
        evidence=("metrics.prom", "flight.json"),
        action="shed_ingress",
    ),
    Rule(
        "wire_rot",
        "wire integrity rejects: corrupted frames arriving at ingress",
        required=("integrity_rejects",),
        optional=("throughput_drop",),
        evidence=("metrics.prom", "flight.json"),
        action="quarantine_only",
    ),
    Rule(
        "slo_burn",
        "error-budget burn: SLO firing without a correlated secondary signal",
        required=("slo_burn",),
        evidence=("metrics.prom", "flight.json"),
        action="escalate_ladder",
    ),
    Rule(
        "dispatch_gap",
        "device starvation: dispatch-gap p99 over ceiling",
        required=("dispatch_gap",),
        optional=("throughput_drop",),
        evidence=("attribution.json", "trace_slice.json"),
        action="resize_dispatch",
    ),
)


def diagnose(conditions) -> List[Dict[str, Any]]:
    """Rank the signature table against a condition set.

    Returns matching rules most-likely-first: rules with more required
    conditions satisfied are more specific and outrank broad single-signal
    rules; matched optional conditions break ties.
    """

    conds = set(conditions)
    ranked: List[Dict[str, Any]] = []
    for rule in RULES:
        if not all(c in conds for c in rule.required):
            continue
        opt = [c for c in rule.optional if c in conds]
        ranked.append(
            {
                "rule": rule.name,
                "cause": rule.cause,
                "score": 2 * len(rule.required) + len(opt),
                "matched": sorted(set(rule.required) | set(opt)),
                "evidence": list(rule.evidence),
                "action": rule.action,
            }
        )
    ranked.sort(key=lambda r: (-r["score"], r["rule"]))
    return ranked


# ---------------------------------------------------------------------------
# fsync'd bundle writes (inline to avoid utils<->obs import cycles)
# ---------------------------------------------------------------------------


def _fsync_write(path: Path, data: bytes) -> str:
    """Write ``data`` durably (tmp+fsync+rename) and return its sha256 hex."""

    tmp = path.with_name(path.name + ".tmp")
    with open(tmp, "wb") as fh:
        fh.write(data)
        fh.flush()
        os.fsync(fh.fileno())
    os.replace(tmp, path)
    return hashlib.sha256(data).hexdigest()


def _fsync_dir(dir_path: Path) -> None:
    try:
        fd = os.open(dir_path, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


def _json_bytes(doc: Any) -> bytes:
    return (json.dumps(doc, indent=1, sort_keys=True) + "\n").encode()


# ---------------------------------------------------------------------------
# Incident record
# ---------------------------------------------------------------------------


class Incident:
    """One open-or-cleared correlated breach with its on-disk bundle."""

    __slots__ = (
        "id",
        "path",
        "opened_unix",
        "cleared_unix",
        "conditions",
        "detail",
        "evidence",
        "diagnosis",
    )

    def __init__(self, iid: str, path: Path, opened_unix: float) -> None:
        self.id = iid
        self.path = path
        self.opened_unix = opened_unix
        self.cleared_unix: Optional[float] = None
        self.conditions: Set[str] = set()
        self.detail: Dict[str, Any] = {}
        self.evidence: Dict[str, str] = {}
        self.diagnosis: List[Dict[str, Any]] = []

    @property
    def top_rule(self) -> str:
        return self.diagnosis[0]["rule"] if self.diagnosis else ""

    def record(self, *, role: str, instance: str) -> Dict[str, Any]:
        return {
            "schema": ALERT_SCHEMA,
            "kind": "incident",
            "id": self.id,
            "role": role,
            "instance": instance,
            "opened_unix": round(self.opened_unix, 3),
            "cleared_unix": (
                round(self.cleared_unix, 3) if self.cleared_unix else None
            ),
            "conditions": sorted(self.conditions),
            "detail": self.detail,
            "evidence": dict(self.evidence),
            "diagnosis_top": self.top_rule,
        }


# ---------------------------------------------------------------------------
# Engine
# ---------------------------------------------------------------------------


class IncidentEngine:
    """Correlates live breach conditions into incidents with evidence.

    Rides the same tick discipline as the PR 3 SLO engine: a small daemon
    thread calls :meth:`tick` every ``interval_s``; tests drive ``tick``
    directly with an injected clock. An incident opens on the first tick
    whose condition set holds a primary condition (secondary,
    corroborating-only signals — see :data:`SECONDARY_CONDITIONS` —
    never page alone) and clears after ``clear_ticks``
    consecutive clean ticks (hysteresis, so a flapping signal cannot churn
    bundles). Delta-based conditions (spill growth, recompiles, integrity
    rejects, lane stalls, throughput drops) warm up on the first tick so
    attaching to a long-running registry never back-dates an incident.
    """

    def __init__(
        self,
        telemetry,
        incident_dir: str,
        *,
        role: str = "",
        instance: str = "",
        clear_ticks: int = 3,
        interval_s: float = 1.0,
        breach_window_s: float = 60.0,
        staleness_ceiling_s: float = 5.0,
        watermark_lag_ceiling_s: float = 60.0,
        merge_lag_p99_ceiling_s: float = 5.0,
        dispatch_gap_p99_ceiling_s: float = 0.5,
        stage_shift_pp: float = 0.20,
        throughput_drop_ratio: float = 0.5,
        _clock=time.monotonic,
    ) -> None:
        self._t = telemetry
        self.dir = Path(incident_dir)
        self.role = role
        self.instance = instance or str(os.getpid())
        self.clear_ticks = max(1, int(clear_ticks))
        self.interval_s = interval_s
        self.breach_window_s = breach_window_s
        self.staleness_ceiling_s = staleness_ceiling_s
        self.watermark_lag_ceiling_s = watermark_lag_ceiling_s
        self.merge_lag_p99_ceiling_s = merge_lag_p99_ceiling_s
        self.dispatch_gap_p99_ceiling_s = dispatch_gap_p99_ceiling_s
        self.stage_shift_pp = stage_shift_pp
        self.throughput_drop_ratio = throughput_drop_ratio
        self._clock = _clock

        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._collector = None

        self._seq = 0
        self._open: Optional[Incident] = None
        self._clean = 0
        self._warmed = False
        self._prev_counters: Dict[str, float] = {}
        self._prev_hist: Dict[str, Tuple[List[int], int]] = {}
        self._stage_base: Dict[str, float] = {}
        self._rate_ema = 0.0
        self._rate_ticks = 0
        self.total_opened = 0

        reg = telemetry.registry
        self._g_open = reg.gauge(
            "attendance_incidents_open",
            help="Open correlated incidents on this instance.",
        )
        self._g_open.set(0.0)

    # -- lifecycle -----------------------------------------------------

    def start(self) -> None:
        self.dir.mkdir(parents=True, exist_ok=True)
        if self._thread is not None:
            return
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._loop, name="incident-engine", daemon=True
        )
        self._thread.start()

    def _loop(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.tick()
            except Exception:
                # The incident plane must never take the pipeline down.
                pass

    def stop(self) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=5.0)
            self._thread = None

    def finalize(self, reason: str = "shutdown") -> None:
        """Persist the latest state of a still-open incident at shutdown."""

        with self._lock:
            inc = self._open
            if inc is not None:
                inc.detail["finalized"] = reason
                self._write_record(inc)

    def bind_collector(self, collector) -> None:
        """Attach a fleet collector so bundles capture fleet-wide status."""

        self._collector = collector

    # -- registry access ------------------------------------------------

    def _families(self) -> Dict[str, Tuple[str, list]]:
        out: Dict[str, Tuple[str, list]] = {}
        try:
            for name, kind, _help, members in self._t.registry.collect():
                out[name] = (kind, list(members))
        except Exception:
            pass
        return out

    @staticmethod
    def _gauge_values(fams, name) -> List[Tuple[dict, float]]:
        kind_members = fams.get(name)
        if kind_members is None:
            return []
        out = []
        for m in kind_members[1]:
            try:
                out.append((dict(getattr(m, "labels", {}) or {}), float(m.read())))
            except Exception:
                continue
        return out

    @staticmethod
    def _counter_total(fams, name) -> Optional[float]:
        kind_members = fams.get(name)
        if kind_members is None:
            return None
        total = 0.0
        for m in kind_members[1]:
            try:
                total += float(m.value)
            except Exception:
                continue
        return total

    def _counter_delta(self, fams, name: str) -> Optional[float]:
        cur = self._counter_total(fams, name)
        if cur is None:
            return None
        prev = self._prev_counters.get(name)
        self._prev_counters[name] = cur
        if prev is None:
            return None
        return cur - prev

    def _hist_p99_delta(self, fams, name: str) -> Optional[float]:
        """p99 over the observations that landed since the previous tick."""

        kind_members = fams.get(name)
        if kind_members is None or kind_members[0] != "histogram":
            return None
        from .registry import quantile_from_buckets

        worst: Optional[float] = None
        for m in kind_members[1]:
            try:
                buckets, _total, count = m.snapshot()
            except Exception:
                continue
            key = f"{name}{getattr(m, 'labels', ())}"
            prev = self._prev_hist.get(key)
            self._prev_hist[key] = (list(buckets), count)
            if prev is None:
                continue
            delta = [max(0, b - p) for b, p in zip(buckets, prev[0])]
            dcount = count - prev[1]
            if dcount <= 0:
                continue
            try:
                q = quantile_from_buckets(delta, dcount, 0.99, m.scale)
            except Exception:
                continue
            if q is not None and (worst is None or q > worst):
                worst = q
        return worst

    # -- condition evaluation -------------------------------------------

    def _evaluate(self) -> Tuple[Set[str], Dict[str, Any]]:
        conds: Set[str] = set()
        detail: Dict[str, Any] = {}
        fams = self._families()
        warm = self._warmed

        # SLO burn-rate firings (PR 3 engine state; falls back to gauges).
        firing: List[str] = []
        slo = getattr(self._t, "slo", None)
        if slo is not None:
            try:
                firing = [
                    name for name, st in slo._state.items() if st.firing
                ]
            except Exception:
                firing = []
        if not firing:
            firing = [
                labels.get("slo", "?")
                for labels, v in self._gauge_values(fams, "attendance_slo_firing")
                if v > 0.0
            ]
        if firing:
            conds.add("slo_burn")
            detail["slo_burn"] = sorted(firing)

        # Circuit-breaker opens (0 closed / 1 open / 2 half-open).
        open_sinks = [
            labels.get("sink", "?")
            for labels, v in self._gauge_values(fams, "attendance_circuit_state")
            if v > 0.0
        ]
        if open_sinks:
            conds.add("circuit_open")
            detail["circuit_open"] = sorted(open_sinks)

        # Spill growth while persisting.
        spill = self._counter_delta(
            fams, "attendance_persist_spilled_batches_total"
        )
        if warm and spill is not None and spill > 0:
            conds.add("spill_growth")
            detail["spill_growth"] = spill

        # Steady-state recompiles (PR 15 tracker; registry fallback).
        steady_new = None
        rec = getattr(self._t, "recompiles", None)
        if rec is not None:
            try:
                snap = rec.snapshot()
                cur = float(snap.get("steady", 0))
                prev = self._prev_counters.get("_recompiles_steady")
                self._prev_counters["_recompiles_steady"] = cur
                if prev is not None:
                    steady_new = cur - prev
                if steady_new and steady_new > 0:
                    detail["steady_recompiles"] = {
                        "new": steady_new,
                        "fingerprints": len(snap.get("fingerprints", ()) or ()),
                    }
            except Exception:
                steady_new = None
        if steady_new is None:
            steady_new = self._counter_delta(
                fams, "attendance_recompiles_steady_total"
            )
            if warm and steady_new and steady_new > 0:
                detail["steady_recompiles"] = {"new": steady_new}
        if warm and steady_new and steady_new > 0:
            conds.add("steady_recompiles")

        # Dead federation peers.
        peers = self._gauge_values(fams, "attendance_fed_peer_up")
        down = [labels.get("peer", "?") for labels, v in peers if v <= 0.0]
        if down:
            conds.add("peer_down")
            detail["peer_down"] = sorted(down)

        # Merge-lag p99 over the last tick window.
        lag = self._hist_p99_delta(fams, "attendance_fed_merge_lag_seconds")
        if warm and lag is not None and lag > self.merge_lag_p99_ceiling_s:
            conds.add("merge_lag")
            detail["merge_lag"] = round(lag, 6)

        # Read staleness / watermark lag (level-based gauges).
        for cond, metric, ceiling in (
            (
                "read_staleness",
                "attendance_read_staleness_seconds",
                self.staleness_ceiling_s,
            ),
            (
                "watermark_lag",
                "attendance_watermark_lag_seconds",
                self.watermark_lag_ceiling_s,
            ),
        ):
            vals = [v for _labels, v in self._gauge_values(fams, metric)]
            if vals and max(vals) > ceiling:
                conds.add(cond)
                detail[cond] = round(max(vals), 6)

        # Dispatch-gap p99 over the last tick window.
        gap = self._hist_p99_delta(fams, "attendance_dispatch_gap_seconds")
        if warm and gap is not None and gap > self.dispatch_gap_p99_ceiling_s:
            conds.add("dispatch_gap")
            detail["dispatch_gap"] = round(gap, 6)

        # Integrity wire rejects.
        rejects = self._counter_delta(
            fams, "attendance_integrity_wire_rejects_total"
        )
        if warm and rejects is not None and rejects > 0:
            conds.add("integrity_rejects")
            detail["integrity_rejects"] = rejects

        # Lane stall: one striped lane stopped while siblings progress.
        lane_fam = fams.get("attendance_ingress_lane_events_total")
        if lane_fam is not None and len(lane_fam[1]) >= 2:
            deltas = {}
            for m in lane_fam[1]:
                lane = dict(getattr(m, "labels", {}) or {}).get("lane", "?")
                try:
                    cur = float(m.value)
                except Exception:
                    continue
                prev = self._prev_counters.get(f"_lane_{lane}")
                self._prev_counters[f"_lane_{lane}"] = cur
                if prev is not None:
                    deltas[lane] = cur - prev
            if warm and deltas and max(deltas.values()) > 0:
                stalled = sorted(l for l, d in deltas.items() if d <= 0)
                if stalled:
                    conds.add("lane_stall")
                    detail["lane_stall"] = stalled

        # Throughput drop vs trailing EMA of the per-tick event rate.
        events = self._counter_total(fams, "attendance_events_total")
        if events is not None:
            prev = self._prev_counters.get("_events_total")
            self._prev_counters["_events_total"] = events
            if prev is not None:
                rate = max(0.0, events - prev)
                if (
                    self._rate_ticks >= 3
                    and self._rate_ema > 0
                    and rate < self.throughput_drop_ratio * self._rate_ema
                ):
                    conds.add("throughput_drop")
                    detail["throughput_drop"] = {
                        "rate": round(rate, 3),
                        "ema": round(self._rate_ema, 3),
                    }
                self._rate_ema = (
                    rate
                    if self._rate_ticks == 0
                    else 0.7 * self._rate_ema + 0.3 * rate
                )
                self._rate_ticks += 1

        # Stage self-time shift vs first-seen baseline (>20pp).
        for labels, frac in self._gauge_values(
            fams, "attendance_profile_stage_fraction"
        ):
            stage = labels.get("stage", "?")
            base = self._stage_base.get(stage)
            if base is None:
                self._stage_base[stage] = frac
                continue
            if frac - base > self.stage_shift_pp:
                conds.add("stage_shift")
                shifts = detail.setdefault("stage_shift", {})
                shifts[stage] = round(frac - base, 4)

        self._warmed = True
        return conds, detail

    # -- tick ------------------------------------------------------------

    def tick(self, now: Optional[float] = None) -> Optional[str]:
        """One evaluation pass; returns the open incident id, if any."""

        del now  # parity with SloEngine.tick; wall time taken at open/clear
        conds, detail = self._evaluate()
        with self._lock:
            if conds:
                if self._open is None:
                    # Secondary signals corroborate, never page alone.
                    if conds - SECONDARY_CONDITIONS:
                        self._clean = 0
                        self._open_incident(conds, detail)
                else:
                    self._clean = 0
                    if not conds <= self._open.conditions:
                        self._merge_incident(conds, detail)
            elif self._open is not None:
                self._clean += 1
                if self._clean >= self.clear_ticks:
                    self._clear_incident()
            self._g_open.set(1.0 if self._open is not None else 0.0)
            return self._open.id if self._open is not None else None

    # -- incident transitions -------------------------------------------

    def _open_incident(self, conds: Set[str], detail: Dict[str, Any]) -> None:
        self._seq += 1
        opened = time.time()
        iid = f"inc-{int(opened)}-{os.getpid()}-{self._seq:03d}"
        inc = Incident(iid, self.dir / iid, opened)
        inc.conditions = set(conds)
        inc.detail = dict(detail)
        inc.diagnosis = diagnose(conds)
        self._open = inc
        self.total_opened += 1

        # Raise the gauge BEFORE the bundle snapshot so metrics.prom
        # inside the bundle already shows the incident it belongs to.
        self._g_open.set(1.0)
        try:
            self._write_bundle(inc)
        except Exception:
            pass
        self._t.registry.counter(
            "attendance_incidents_total",
            help="Incidents opened, by top diagnosis rule.",
            rule=inc.top_rule or "undiagnosed",
        ).inc()
        self._span(
            "incident_open",
            {
                "incident": iid,
                "conditions": sorted(conds),
                "rule": inc.top_rule or "undiagnosed",
            },
        )
        self._flight_mark(inc, "open")
        if inc.diagnosis:
            self._span(
                "incident_diagnosis",
                {
                    "incident": iid,
                    "rule": inc.top_rule,
                    "score": inc.diagnosis[0]["score"],
                },
            )

    def _merge_incident(self, conds: Set[str], detail: Dict[str, Any]) -> None:
        inc = self._open
        assert inc is not None
        inc.conditions |= conds
        for k, v in detail.items():
            inc.detail.setdefault(k, v)
        inc.diagnosis = diagnose(inc.conditions)
        try:
            self._write_diagnosis(inc)
            self._write_record(inc)
        except Exception:
            pass

    def _clear_incident(self) -> None:
        inc = self._open
        assert inc is not None
        inc.cleared_unix = time.time()
        try:
            self._write_record(inc)
        except Exception:
            pass
        self._span(
            "incident_clear",
            {
                "incident": inc.id,
                "open_s": round(inc.cleared_unix - inc.opened_unix, 3),
                "rule": inc.top_rule or "undiagnosed",
            },
        )
        self._flight_mark(inc, "clear")
        self._open = None
        self._clean = 0

    # -- evidence bundle -------------------------------------------------

    def _write_bundle(self, inc: Incident) -> None:
        inc.path.mkdir(parents=True, exist_ok=True)
        for name, doc in (
            ("flight.json", self._flight_doc(inc)),
            ("trace_slice.json", self._trace_doc(inc)),
            ("attribution.json", self._attribution_doc()),
            ("fleet_status.json", self._fleet_doc()),
        ):
            inc.evidence[name] = _fsync_write(inc.path / name, _json_bytes(doc))
        inc.evidence["metrics.prom"] = _fsync_write(
            inc.path / "metrics.prom", self._prom_text().encode()
        )
        self._write_diagnosis(inc)
        self._write_record(inc)
        _fsync_dir(inc.path)

    def _write_diagnosis(self, inc: Incident) -> None:
        doc = {
            "schema": ALERT_SCHEMA,
            "incident": inc.id,
            "conditions": sorted(inc.conditions),
            "ranked": inc.diagnosis,
            "top": inc.top_rule or None,
        }
        inc.evidence[DIAGNOSIS_FILE] = _fsync_write(
            inc.path / DIAGNOSIS_FILE, _json_bytes(doc)
        )

    def _write_record(self, inc: Incident) -> None:
        inc.path.mkdir(parents=True, exist_ok=True)
        _fsync_write(
            inc.path / INCIDENT_FILE,
            _json_bytes(inc.record(role=self.role, instance=self.instance)),
        )
        _fsync_dir(inc.path)

    def _flight_doc(self, inc: Incident) -> Dict[str, Any]:
        fl = getattr(self._t, "flight", None)
        if fl is None:
            return {"collected": False, "reason": f"incident:{inc.id}"}
        return {
            "collected": True,
            "dumped_at_unix": round(time.time(), 3),
            "reason": f"incident:{inc.id}",
            "pid": os.getpid(),
            "total_records": fl.total,
            "records": fl.snapshot(),
        }

    def _trace_doc(self, inc: Incident) -> Dict[str, Any]:
        tr = getattr(self._t, "tracer", None)
        if tr is None:
            return {"collected": False, "traceEvents": []}
        try:
            exported = tr.export()
        except Exception:
            return {"collected": False, "traceEvents": []}
        cut_us = (inc.opened_unix - self.breach_window_s) * 1e6
        meta, rest = [], []
        for ev in exported.get("traceEvents", []):
            if ev.get("ph") == "M":
                meta.append(ev)
            elif float(ev.get("ts", 0.0)) >= cut_us:
                rest.append(ev)
        exported["traceEvents"] = meta + rest[-TRACE_SLICE_LIMIT:]
        exported["collected"] = True
        exported["incident"] = inc.id
        exported["window_s"] = self.breach_window_s
        return exported

    def _attribution_doc(self) -> Dict[str, Any]:
        rec = getattr(self._t, "recompiles", None)
        prof = getattr(self._t, "profiler", None)
        if prof is None:
            doc: Dict[str, Any] = {"kind": "attribution", "collected": False}
            if rec is not None:
                try:
                    doc["recompiles"] = rec.snapshot()
                except Exception:
                    pass
            return doc
        try:
            # Force one on-demand sample so the snapshot is never empty.
            prof.sample_once()
        except Exception:
            pass
        try:
            doc = prof.attribution(rec)
        except Exception:
            doc = {"kind": "attribution"}
        doc["collected"] = True
        return doc

    def _fleet_doc(self) -> Dict[str, Any]:
        if self._collector is None:
            return {"collected": False, "instances": {}}
        try:
            doc = dict(self._collector.status())
        except Exception:
            return {"collected": False, "instances": {}}
        doc["collected"] = True
        return doc

    def _prom_text(self) -> str:
        try:
            from .exposition import render

            return render(self._t.registry)
        except Exception:
            return ""

    # -- side channels ---------------------------------------------------

    def _span(self, name: str, args: Dict[str, Any]) -> None:
        tr = getattr(self._t, "tracer", None)
        if tr is None:
            return
        try:
            end = tr.now()
            tr.add_span(
                name, end, end, trace_id=tr.new_id(), role="incident", args=args
            )
        except Exception:
            pass

    def _flight_mark(self, inc: Incident, state: str) -> None:
        fl = getattr(self._t, "flight", None)
        if fl is None:
            return
        try:
            fl.record(
                {
                    "ts": round(time.time(), 3),
                    "schema": ALERT_SCHEMA,
                    "incident": inc.id,
                    "state": state,
                    "conditions": sorted(inc.conditions),
                    "rule": inc.top_rule or "undiagnosed",
                }
            )
        except Exception:
            pass


# ---------------------------------------------------------------------------
# Offline replay: doctor --incident DIR
# ---------------------------------------------------------------------------


def find_bundles(path) -> List[Path]:
    """Bundle dirs under ``path`` (itself a bundle, or a root of bundles)."""

    root = Path(path)
    if (root / INCIDENT_FILE).is_file():
        return [root]
    if not root.is_dir():
        raise FileNotFoundError(f"incident dir not found: {path}")
    found = sorted(
        d for d in root.iterdir() if d.is_dir() and (d / INCIDENT_FILE).is_file()
    )
    if not found:
        raise FileNotFoundError(f"no incident bundles under {path}")
    return found


def _verify_part(bundle: Path, name: str, expected: str) -> Tuple[str, bool]:
    part = bundle / name
    if not part.is_file():
        return "missing", False
    digest = hashlib.sha256(part.read_bytes()).hexdigest()
    if expected and digest != expected:
        return "digest mismatch", False
    return "sha256 ok", True


def _actuation_matches(action: str, rec: Dict[str, Any]) -> bool:
    """Does one actuation record satisfy a diagnosis rule's action id?
    ``escalate_ladder`` is satisfied by any escalating ladder move."""

    if rec.get("action") == action:
        return True
    return (
        action == "escalate_ladder"
        and rec.get("policy") == "degradation_ladder"
        and rec.get("direction") == "escalate"
    )


def incident_report(path, actuation_log=None) -> Tuple[str, bool]:
    """Replay bundles offline into the doctor verdict table.

    Returns ``(text, ok)``. ``ok`` is False when any bundle is incomplete,
    fails digest verification, or holds an *undiagnosed open* incident.
    Raises ``FileNotFoundError``/``ValueError`` for unreadable input so the
    CLI can exit 2 rather than report a false verdict.

    ``actuation_log`` (a control-plane JSONL path) adds a row per
    diagnosed bundle saying whether the controller's recorded actuation
    matched the top-ranked rule's ``action`` id (advisory actions have
    no knob by design and report as such). Mismatches are warnings, not
    failures: a bundle may predate the controller, or the controller
    may legitimately have acted on a lower-ranked rule first.
    """

    from .exposition import _table

    actuations: List[Dict[str, Any]] = []
    if actuation_log is not None:
        from attendance_tpu.control.actuation import read_actuations

        actuations, _problems = read_actuations(str(actuation_log))

    bundles = find_bundles(path)
    rows: List[List[str]] = []
    breached = 0
    for bundle in bundles:
        try:
            doc = json.loads((bundle / INCIDENT_FILE).read_text())
        except (OSError, json.JSONDecodeError) as exc:
            raise ValueError(f"unreadable incident record {bundle}: {exc}")
        iid = str(doc.get("id", bundle.name))
        schema = doc.get("schema")
        if schema is None:
            rows.append(
                [f"{iid} schema", "missing (pre-17 record)", "versioned", "warn"]
            )
        cleared = doc.get("cleared_unix")
        top = str(doc.get("diagnosis_top") or "")
        if cleared:
            rows.append([f"{iid} state", f"cleared @{cleared}", "-", "PASS"])
        elif top:
            rows.append([f"{iid} state", f"open, diagnosed: {top}", "-", "PASS"])
        else:
            rows.append([f"{iid} state", "open, undiagnosed", "diagnosed", "FAIL"])
            breached += 1
        rows.append(
            [
                f"{iid} conditions",
                ",".join(doc.get("conditions", ())) or "-",
                "-",
                "info",
            ]
        )

        evidence = dict(doc.get("evidence", {}))
        for name in EVIDENCE_PARTS + (DIAGNOSIS_FILE,):
            status, good = _verify_part(bundle, name, evidence.get(name, ""))
            rows.append(
                [f"{iid} {name}", status, "present+verified", "PASS" if good else "FAIL"]
            )
            if not good:
                breached += 1

        dx_path = bundle / DIAGNOSIS_FILE
        if dx_path.is_file():
            try:
                dx = json.loads(dx_path.read_text())
            except (OSError, json.JSONDecodeError) as exc:
                raise ValueError(f"unreadable diagnosis {dx_path}: {exc}")
            ranked = dx.get("ranked", [])
            if ranked:
                first = ranked[0]
                rows.append(
                    [
                        f"{iid} diagnosis",
                        f"{first.get('rule')} (score {first.get('score')})",
                        "-",
                        "info",
                    ]
                )
                action = str(first.get("action") or "")
                if actuation_log is not None and action:
                    from attendance_tpu.control.engine import (
                        ADVISORY_ACTIONS,
                    )

                    mine = [a for a in actuations if a.get("incident") == iid]
                    if action in ADVISORY_ACTIONS:
                        rows.append(
                            [
                                f"{iid} actuation",
                                f"{action}: advisory (no knob)",
                                "-",
                                "info",
                            ]
                        )
                    elif any(_actuation_matches(action, a) for a in mine):
                        rows.append(
                            [
                                f"{iid} actuation",
                                f"matched top rule ({action})",
                                action,
                                "PASS",
                            ]
                        )
                    else:
                        rows.append(
                            [
                                f"{iid} actuation",
                                f"no recorded actuation for {action} "
                                f"({len(mine)} record(s) for incident)",
                                action,
                                "warn",
                            ]
                        )
    ok = breached == 0
    lines = [
        f"incident replay: {len(bundles)} bundle(s) under {path}",
        _table(rows, ["check", "value", "target", "verdict"]),
        f"verdict: {'PASS' if ok else 'FAIL'} ({breached} breached)",
    ]
    return "\n".join(lines), ok
