"""Redis-command-compatible sketch store facade.

The reference talks to its sketches exclusively through redis-py call
shapes — ``execute_command('BF.ADD'|'BF.EXISTS'|'BF.RESERVE', ...)``,
``pfadd``, ``pfcount`` (reference attendance_processor.py:78,83-88,109-113,
129,152 and data_generator.py:59-63). This package keeps those call shapes
API-stable across four interchangeable backends selected by
``--sketch-backend``:

  * "tpu"       — device-resident sketches, micro-batched JAX kernels
  * "memory"    — pure-host numpy sketches, bit-identical hashing
                  (hermetic tests + differential oracle for the device
                  path)
  * "redis"     — real Redis Stack via redis-py (import-gated)
  * "redis-sim" — hermetic simulation of Redis's actual algorithms
                  (RedisBloom sizing + MurmurHash64A double hashing,
                  dense-HLL hllPatLen); the server-free parity oracle
"""

from attendance_tpu.sketch.base import (  # noqa: F401
    ResponseError, SketchStore, member_to_u32, members_to_u32)
from attendance_tpu.sketch.memory_store import MemorySketchStore  # noqa: F401
from attendance_tpu.sketch.tpu_store import TpuSketchStore  # noqa: F401


def make_sketch_store(config) -> SketchStore:
    """Build the sketch store selected by config.sketch_backend.

    When live telemetry is on, the inspectable backends (everything
    but the real Redis server, whose filter state lives remotely) also
    register the sketch-health gauges — the same fill/FPR/estimate
    surface the fused pipeline has had since PR 2, now on the generic
    command path too (obs/health.register_store; weakref'd, device
    reads only at scrape time, refreshed on snapshot restore)."""
    if config.sketch_backend == "tpu":
        store = TpuSketchStore(config)
    elif config.sketch_backend == "memory":
        store = MemorySketchStore(config)
    elif config.sketch_backend == "redis":
        from attendance_tpu.sketch.redis_store import RedisSketchStore
        return RedisSketchStore(config)  # no inspectable local state
    elif config.sketch_backend == "redis-sim":
        from attendance_tpu.sketch.redis_sim import RedisSimSketchStore
        store = RedisSimSketchStore(config)
    else:
        raise ValueError(
            f"unknown sketch backend {config.sketch_backend!r}")
    from attendance_tpu import obs
    t = obs.ensure(config)
    if t is not None:
        from attendance_tpu.obs import health
        health.register_store(
            t, store, getattr(config, "bloom_filter_key", "bf"),
            backend=config.sketch_backend)
    return store
