"""Hermetic Redis-exact sketch oracle (``--sketch-backend=redis-sim``).

The parity harness (attendance_tpu.parity) needs a backend that answers
the way a real Redis Stack would, *without* a server. The memory store
can't serve that role: it mirrors the TPU hash design bit-for-bit, so a
systematic bias shared by both (seed choice, rank extraction) would pass
parity silently. This module simulates Redis's actual algorithms in pure
numpy — a hash family and sizing math with nothing in common with the
TPU path except the member values themselves:

* **Bloom** — RedisBloom's published design (its ``deps/bloom/bloom.c``):
  ``bits_per_entry = -ln(error)/ln(2)^2``; ``hashes = ceil(ln(2)*bpe)``;
  the bit count ``entries*bpe`` rounded UP to the next power of two
  (RedisBloom's default ``BLOOM_OPT_ROUND_SIZE`` behavior, which also
  scales the declared capacity up to ``bits/bpe``); probe positions by
  Kirsch–Mitzenmacher double hashing ``(a + i*b) mod bits`` where
  ``a = MurmurHash64A(member, seed=M64)`` and
  ``b = MurmurHash64A(member, seed=a)``. Auto-scaling chains a new
  sub-filter at capacity with expansion 2 and error tightening 0.5,
  like RedisBloom's SBChain. Contract call sites: reference
  attendance_processor.py:78,83-88,109-113; data_generator.py:59-63.
* **HyperLogLog** — Redis's dense HLL (its ``src/hyperloglog.c``):
  ``hash = MurmurHash64A(member, seed=0xadc83b19)``; register index =
  low 14 bits; rank = 1 + trailing zeros of ``(hash >> 14) | 1<<50``
  (so rank <= 51); PFCOUNT via the Ertl estimator Redis adopted for
  ``hllCount`` (shared implementation:
  models.hll.estimate_from_histogram, which *is* that estimator).
  Contract call sites: reference attendance_processor.py:129,152.
* **Members hash as their byte-string form** — redis-py sends int
  member 12345 as the bytes ``b"12345"``, so the sim renders each
  normalized uint32 key to its decimal byte string before hashing,
  exactly the bytes a real server would see for the reference's integer
  student IDs (reference data_generator.py:53-54; SURVEY.md §7 hard
  part c). Non-numeric members enter through the same u32
  normalization as every other backend (sketch.base.member_to_u32) and
  hash as that value's decimal form — uniform, but not byte-identical
  to Redis for arbitrary strings; the reference only ever uses integer
  IDs and the throwaway probe token "test".

Everything is implemented from the published algorithm descriptions —
no code is taken from Redis or RedisBloom; the point is an independent
hash family with Redis's exact structure, so the parity budgets
(FPR <= 1%, HLL error <= 2%, BASELINE.md) are tested against Redis's
real math instead of a mirror of our own.
"""

from __future__ import annotations

import math
from typing import Dict, List, NamedTuple, Optional, Sequence, Tuple

import numpy as np

from attendance_tpu.models.hll import estimate_from_histogram
from attendance_tpu.sketch.base import (
    DEFAULT_CAPACITY, DEFAULT_ERROR_RATE, EXPANSION, ResponseError,
    SketchStore)

# ---------------------------------------------------------------------------
# MurmurHash64A, vectorized over same-length byte strings.
# ---------------------------------------------------------------------------

_M64 = np.uint64(0xC6A4A7935BD1E995)
_R64 = np.uint64(47)
_HLL_SEED = np.uint64(0xADC83B19)  # Redis hyperloglog.c hllPatLen seed

_BYTE_SHIFTS = (np.uint64(8) * np.arange(8, dtype=np.uint64))


def murmur64a_fixed(data: np.ndarray, seed) -> np.ndarray:
    """MurmurHash64A over N byte strings sharing one length.

    data: uint8[N, L]; seed: scalar or uint64[N] (per-element seeds are
    what the Bloom double hash needs for its second lane).
    Returns uint64[N]. Transcribed from Appleby's published algorithm
    (public domain), vectorized: 8-byte little-endian blocks mixed with
    the M64 constant, the <8-byte tail XORed in byte-by-byte, then the
    standard 3-step finalizer.
    """
    n, length = data.shape
    with np.errstate(over="ignore"):
        h = np.full(n, np.uint64(seed), dtype=np.uint64) \
            if np.isscalar(seed) or np.ndim(seed) == 0 \
            else np.asarray(seed, dtype=np.uint64).copy()
        h ^= np.uint64(length) * _M64
        nblocks = length // 8
        for b in range(nblocks):
            k = (data[:, b * 8:(b + 1) * 8].astype(np.uint64)
                 << _BYTE_SHIFTS[None, :]).sum(axis=1, dtype=np.uint64)
            k *= _M64
            k ^= k >> _R64
            k *= _M64
            h ^= k
            h *= _M64
        rem = length & 7
        if rem:
            tail = (data[:, nblocks * 8:].astype(np.uint64)
                    << _BYTE_SHIFTS[None, :rem]).sum(axis=1, dtype=np.uint64)
            h ^= tail
            h *= _M64
        h ^= h >> _R64
        h *= _M64
        h ^= h >> _R64
    return h


def murmur64a_scalar(data: bytes, seed: int) -> int:
    """One-string MurmurHash64A (plain-Python mirror of the vectorized
    path; tests cross-check the two on random inputs)."""
    mask = (1 << 64) - 1
    m = 0xC6A4A7935BD1E995
    h = (seed ^ (len(data) * m)) & mask
    nblocks = len(data) // 8
    for b in range(nblocks):
        k = int.from_bytes(data[b * 8:(b + 1) * 8], "little")
        k = (k * m) & mask
        k ^= k >> 47
        k = (k * m) & mask
        h = ((h ^ k) * m) & mask
    rem = len(data) & 7
    if rem:
        h ^= int.from_bytes(data[nblocks * 8:], "little")
        h = (h * m) & mask
    h ^= h >> 47
    h = (h * m) & mask
    h ^= h >> 47
    return h


_POW10 = np.array([10 ** d for d in range(1, 11)], dtype=np.uint64)


def _decimal_groups(keys: np.ndarray):
    """Group uint32 keys by decimal length; yield (indices, digit bytes).

    Rendering b"12345" for key 12345 — the exact bytes redis-py puts on
    the wire for an integer member — vectorized per digit-count group.
    """
    keys = np.asarray(keys, dtype=np.uint64)
    lengths = np.searchsorted(_POW10, keys, side="right") + 1
    for length in np.unique(lengths):
        idx = np.flatnonzero(lengths == length)
        k = keys[idx]
        digits = np.empty((len(idx), int(length)), dtype=np.uint8)
        for j in range(int(length)):
            digits[:, j] = ((k // np.uint64(10 ** (int(length) - 1 - j)))
                            % np.uint64(10)) + np.uint8(ord("0"))
        yield idx, digits


def hash_members_u64(keys_u32: np.ndarray, seed) -> np.ndarray:
    """MurmurHash64A of each key's decimal byte string: uint64[N]."""
    out = np.empty(len(keys_u32), dtype=np.uint64)
    for idx, digits in _decimal_groups(keys_u32):
        out[idx] = murmur64a_fixed(digits, seed)
    return out


def bloom_hash_pairs(keys_u32: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
    """RedisBloom's (a, b) double-hash lanes per member.

    a = mm64a(member, M64); b = mm64a(member, a) — the second lane is
    seeded by the first, exactly the bloom.c ``bloom_calc_hash64``
    structure, which is why murmur64a_fixed takes per-element seeds.
    """
    a = np.empty(len(keys_u32), dtype=np.uint64)
    b = np.empty(len(keys_u32), dtype=np.uint64)
    for idx, digits in _decimal_groups(keys_u32):
        ga = murmur64a_fixed(digits, _M64)
        a[idx] = ga
        b[idx] = murmur64a_fixed(digits, ga)
    return a, b


# ---------------------------------------------------------------------------
# RedisBloom-sized Bloom filter + scalable chain.
# ---------------------------------------------------------------------------

_LN2 = 0.693147180559945
_LN2_SQUARED = 0.480453013918201  # the constant bloom.c divides by


class SimBloomParams(NamedTuple):
    """Sizing of one sub-filter, after RedisBloom's power-of-two round.

    ``m_bits`` keeps the base class's field name so SketchStore.BF.INFO
    and estimated_fpr work unchanged on sim chains.
    """
    m_bits: int
    k: int
    capacity: int    # scaled-up entries the rounded filter can hold
    error_rate: float


def sim_bloom_params(entries: int, error: float) -> SimBloomParams:
    """RedisBloom bloom_init sizing: bpe from the error target, bit
    count rounded up to the next power of two, capacity scaled to the
    rounded size, ``hashes = ceil(ln2 * bpe)``."""
    if not (0.0 < error < 1.0):
        raise ResponseError(f"error rate must be in (0,1), got {error}")
    if entries < 1:
        raise ResponseError(f"capacity must be >= 1, got {entries}")
    bpe = -math.log(error) / _LN2_SQUARED
    k = int(math.ceil(_LN2 * bpe))
    raw_bits = float(entries) * bpe
    n2 = int(math.floor(math.log2(raw_bits))) + 1  # always rounds UP
    if n2 > 40:
        raise ResponseError(f"sim filter of 2^{n2} bits is unreasonable")
    m_bits = 1 << n2
    return SimBloomParams(m_bits=m_bits, k=k,
                          capacity=int(m_bits / bpe), error_rate=error)


def sim_bloom_positions(keys_u32: np.ndarray,
                        params: SimBloomParams) -> np.ndarray:
    """Probe positions int64[N, k]: (a + i*b) & (bits-1)."""
    a, b = bloom_hash_pairs(keys_u32)
    i = np.arange(params.k, dtype=np.uint64)
    with np.errstate(over="ignore"):
        probes = a[:, None] + i[None, :] * b[:, None]
        return (probes & np.uint64(params.m_bits - 1)).astype(np.int64)


class _SimChain:
    """Auto-scaling chain of RedisBloom-sized sub-filters.

    Duck-types the attributes SketchStore's BF.INFO / estimated_fpr
    read from a chain (filters, params, item_count, total_capacity).
    Sub-filter i gets capacity*EXPANSION^i and error*0.5^i (RedisBloom's
    expansion=2 / ERROR_TIGHTENING_RATIO=0.5 defaults).
    """

    def __init__(self, capacity: int, error_rate: float):
        self.base_capacity = int(capacity)
        self.base_error = float(error_rate)
        self.filters: List[np.ndarray] = []   # uint8 bit-per-byte arrays
        self.params: List[SimBloomParams] = []
        self.counts: List[int] = []
        self._grow()

    def _grow(self) -> None:
        i = len(self.filters)
        params = sim_bloom_params(self.base_capacity * (EXPANSION ** i),
                                  self.base_error * (0.5 ** i))
        self.filters.append(np.zeros(params.m_bits, dtype=np.uint8))
        self.params.append(params)
        self.counts.append(0)

    def contains_many(self, keys_u32: np.ndarray) -> np.ndarray:
        out = np.zeros(len(keys_u32), dtype=bool)
        for bits, params in zip(self.filters, self.params):
            rem = ~out
            if not rem.any():
                break
            pos = sim_bloom_positions(keys_u32[rem], params)
            out[rem] = bits[pos].all(axis=1)
        return out

    def add_many(self, keys_u32: np.ndarray) -> np.ndarray:
        """Insert; per-key 1 if (probably) new. Like RedisBloom, a key
        found in ANY link is not re-inserted; new keys go to the newest
        link, growing the chain when it reaches declared capacity.

        A real server processes BF.MADD members sequentially, so the
        second copy of a duplicate inside one call sees the bits the
        first just set: BF.MADD k 7 7 answers [1, 0]. Mirror that — only
        the FIRST occurrence of each distinct new member reports added,
        and capacity accounting counts distinct members once, even
        across chunk/grow boundaries. New members are inserted in CALL
        order so grow boundaries split the call exactly where a real
        server would.

        Known deviation (the cost of the vectorized membership check):
        a real server's later members also see bits set by earlier
        DISTINCT members of the same call, so an intra-call false
        positive suppresses that member's insertion ("already present")
        — here membership is evaluated once against the pre-call state,
        so such a member is still inserted and reported added. The
        divergence needs an FP between two members of one call
        (probability ~ eps per member) and only perturbs which exact
        bits/counters a scaling chain carries, never membership
        answers.
        """
        existed = self.contains_many(keys_u32)
        added = np.zeros(len(keys_u32), dtype=np.int64)
        new_idx = np.flatnonzero(~existed)
        if len(new_idx) == 0:
            return added
        uniq, first = np.unique(keys_u32[new_idx], return_index=True)
        added[new_idx[first]] = 1
        # Insert in CALL order, not np.unique's sorted order: when one
        # BF.MADD crosses a grow boundary, which keys land in the old
        # vs the new sub-filter must match a real server's sequential
        # processing (bit-state fidelity for the live-Redis parity
        # gate; membership answers are unaffected either way).
        order = np.argsort(first, kind="stable")
        uniq = uniq[order]
        i = 0
        while i < len(uniq):
            room = self.params[-1].capacity - self.counts[-1]
            if room <= 0:
                self._grow()
                continue
            chunk = uniq[i:i + room]
            self.counts[-1] += len(chunk)
            pos = sim_bloom_positions(chunk, self.params[-1])
            self.filters[-1][pos.reshape(-1)] = 1
            i += len(chunk)
        return added

    @property
    def item_count(self) -> int:
        return sum(self.counts)

    @property
    def total_capacity(self) -> int:
        return sum(p.capacity for p in self.params)


# ---------------------------------------------------------------------------
# Redis dense HLL (p=14, q=50).
# ---------------------------------------------------------------------------

HLL_P = 14                       # Redis hyperloglog.c HLL_P
HLL_Q = 64 - HLL_P               # 50
_HLL_REGISTERS = 1 << HLL_P
_HLL_P_MASK = np.uint64(_HLL_REGISTERS - 1)


def sim_hll_bucket_rank(keys_u32: np.ndarray
                        ) -> Tuple[np.ndarray, np.ndarray]:
    """(register index, rank) per member, Redis hllPatLen semantics:
    index = low p bits of mm64a(member, 0xadc83b19); rank = 1 + trailing
    zeros of the remaining 50 bits with a guard bit at position 50."""
    h = hash_members_u64(keys_u32, _HLL_SEED)
    with np.errstate(over="ignore"):
        idx = (h & _HLL_P_MASK).astype(np.int64)
        rest = (h >> np.uint64(HLL_P)) | (np.uint64(1) << np.uint64(HLL_Q))
        lsb = rest & (np.uint64(0) - rest)
        # lsb is a power of two <= 2^50: exact in float64, so log2 is too.
        rank = np.log2(lsb.astype(np.float64)).astype(np.int64) + 1
    return idx, rank


class RedisSimSketchStore(SketchStore):
    """Drop-in SketchStore whose answers come from simulated Redis.

    Selected by ``--sketch-backend=redis-sim``; the default hermetic
    oracle for the parity harness (tests/test_redis_sim.py) and a
    server-free stand-in anywhere the redis backend would be used.
    """

    def __init__(self, config):
        super().__init__(config)
        self._hlls: Dict[str, np.ndarray] = {}

    # Base-class Bloom/HLL primitives are never reached: the public
    # surface below implements Redis's own algorithms wholesale.
    def _filter_create(self, params):  # pragma: no cover
        raise NotImplementedError

    def _filter_add(self, handle, params, keys):  # pragma: no cover
        raise NotImplementedError

    def _filter_contains(self, handle, params, keys):  # pragma: no cover
        raise NotImplementedError

    def _hll_add(self, key, keys_u32, mask=None,
                 want_changed=True):  # pragma: no cover
        raise NotImplementedError

    def _hll_count(self, keys):  # pragma: no cover
        raise NotImplementedError

    # -- Bloom surface ------------------------------------------------------
    def bf_reserve(self, key: str, error_rate, capacity) -> bool:
        if key in self._blooms:
            raise ResponseError("item exists")
        self._blooms[key] = _SimChain(int(capacity), float(error_rate))
        # Structural write: incremental snapshots must carry this key
        # (the base class marks its own bf_reserve the same way).
        self._dirty_blooms.add(key)
        return True

    def _chain_or_create(self, key: str) -> _SimChain:
        chain = self._blooms.get(key)
        if chain is None:
            chain = _SimChain(DEFAULT_CAPACITY, DEFAULT_ERROR_RATE)
            self._blooms[key] = chain
        return chain

    # Overrides land on the _u32 chokepoints (not the public methods)
    # so the base class's audit cross-check still sees every simulated
    # answer — the shadow auditor judges Redis's algorithms with the
    # same harness as the tpu/memory backends.
    def _bf_add_u32(self, key: str, u32: np.ndarray) -> np.ndarray:
        return self._chain_or_create(key).add_many(u32)

    def _bf_exists_u32(self, key: str, u32: np.ndarray) -> np.ndarray:
        chain = self._blooms.get(key)
        if chain is None:
            return np.zeros(len(u32), dtype=bool)
        return chain.contains_many(u32)

    # -- HLL surface --------------------------------------------------------
    def _regs_of(self, key: str) -> np.ndarray:
        regs = self._hlls.get(key)
        if regs is None:
            regs = self._hlls[key] = np.zeros(_HLL_REGISTERS, dtype=np.uint8)
        return regs

    def _pf_create(self, key: str) -> int:
        # Redis: PFADD with no members creates the key; returns 1 iff
        # it did not exist.
        existed = key in self._hlls
        self._regs_of(key)
        return int(not existed)

    def _pfadd_u32(self, key: str, u32: np.ndarray,
                   mask: Optional[np.ndarray],
                   want_changed: bool) -> int:
        if mask is not None:
            u32 = u32[np.asarray(mask, dtype=bool)]
        regs = self._regs_of(key)
        if len(u32) == 0:
            return 0
        idx, rank = sim_hll_bucket_rank(u32)
        changed = bool((rank > regs[idx]).any())
        np.maximum.at(regs, idx, rank.astype(np.uint8))
        return int(changed)

    def _pfcount_keys(self, keys) -> int:
        known = [self._hlls[k] for k in keys if k in self._hlls]
        if not known:
            return 0
        merged = known[0]
        for r in known[1:]:
            merged = np.maximum(merged, r)
        hist = np.bincount(merged, minlength=HLL_Q + 2)
        return int(round(estimate_from_histogram(hist, HLL_P)))

    def flush(self) -> None:
        super().flush()
        self._hlls.clear()
