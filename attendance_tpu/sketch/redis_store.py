"""Redis Stack sketch store (import-gated).

The ``--sketch-backend=redis`` parity backend: a thin adapter over redis-py
exactly matching the reference's usage (reference
attendance_processor.py:37-41,78,83-88,109-113,129,152). Used by the
differential parity harness when a Redis Stack server is reachable; the
rest of the framework never imports this module unless selected.
"""

from __future__ import annotations

from typing import Optional, Sequence

import numpy as np

from attendance_tpu.sketch.base import ResponseError, SketchStore

try:
    import redis as _redis
    HAVE_REDIS = True
except ImportError:  # pragma: no cover - environment without redis-py
    _redis = None
    HAVE_REDIS = False

_BATCH = 512  # members per BF.MADD/MEXISTS chunk


def _translated(fn):
    """Re-raise redis.exceptions.ResponseError as the facade's
    ResponseError so callers (processor bootstrap, parity harness) catch
    ONE exception type across every backend."""
    def wrapper(*args, **kwargs):
        try:
            return fn(*args, **kwargs)
        except _redis.exceptions.ResponseError as e:
            raise ResponseError(str(e)) from e
    return wrapper


class RedisSketchStore(SketchStore):
    def __init__(self, config):
        if not HAVE_REDIS:
            raise RuntimeError(
                "sketch_backend='redis' requires the redis-py package")
        super().__init__(config)
        self.client = _redis.Redis(
            host=config.redis_host, port=config.redis_port,
            decode_responses=True)

    # The public surface forwards wholesale; the local-filter primitives
    # are never reached.
    def _filter_create(self, params):  # pragma: no cover
        raise NotImplementedError

    def _filter_add(self, handle, params, keys):  # pragma: no cover
        raise NotImplementedError

    def _filter_contains(self, handle, params, keys):  # pragma: no cover
        raise NotImplementedError

    def _hll_add(self, key, keys_u32, mask=None,
                 want_changed=True):  # pragma: no cover
        raise NotImplementedError

    def _hll_count(self, keys):  # pragma: no cover
        raise NotImplementedError

    @_translated
    def execute_command(self, *args):
        return self.client.execute_command(*args)

    @_translated
    def bf_reserve(self, key, error_rate, capacity):
        return self.client.execute_command(
            "BF.RESERVE", key, error_rate, capacity)

    def bf_add_many(self, key: str, members) -> np.ndarray:
        out = []
        members = list(np.asarray(members).tolist())
        pipe = self.client.pipeline()
        for i in range(0, len(members), _BATCH):
            pipe.execute_command("BF.MADD", key, *members[i:i + _BATCH])
        for res in pipe.execute():
            out.extend(int(x) for x in res)
        return np.array(out, dtype=np.int64)

    def bf_exists_many(self, key: str, members) -> np.ndarray:
        out = []
        members = list(np.asarray(members).tolist())
        pipe = self.client.pipeline()
        for i in range(0, len(members), _BATCH):
            pipe.execute_command("BF.MEXISTS", key, *members[i:i + _BATCH])
        for res in pipe.execute():
            out.extend(bool(int(x)) for x in res)
        return np.array(out, dtype=bool)

    def pfadd(self, key: str, *members) -> int:
        return int(self.client.pfadd(key, *members))

    def pfadd_many(self, key: str, members,
                   mask: Optional[np.ndarray] = None,
                   want_changed: bool = False) -> int:
        members = np.asarray(members)
        if mask is not None:
            members = members[mask]
        changed = 0
        members = list(members.tolist())
        pipe = self.client.pipeline()
        for i in range(0, len(members), _BATCH):
            pipe.pfadd(key, *members[i:i + _BATCH])
        for res in pipe.execute():
            changed |= int(res)
        return changed

    def pfcount(self, *keys: str) -> int:
        return int(self.client.pfcount(*keys))

    def flush(self) -> None:
        self.client.flushall()

    def close(self) -> None:
        self.client.close()
