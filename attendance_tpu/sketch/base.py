"""SketchStore interface, member normalization, and scalable-Bloom logic.

Semantics contract (matches Redis Stack behavior at the reference's call
sites, SURVEY.md §2.2):
  * BF.EXISTS on a missing key returns 0 (no error).
  * BF.ADD on a missing key auto-creates a filter with RedisBloom defaults
    (capacity 100, error 0.01) and auto-scales by chaining sub-filters
    (expansion x2, halved error) when a sub-filter reaches capacity.
  * BF.RESERVE on an existing key raises ResponseError("item exists").
  * PFADD returns 1 iff some register changed; PFCOUNT of a missing key
    is 0; multi-key PFCOUNT is the union estimate.
"""

from __future__ import annotations

import abc
from typing import Any, Dict, List, Optional, Sequence

import numpy as np

from attendance_tpu.models.bloom import BloomParams, derive_bloom_params
from attendance_tpu.ops.murmur3 import murmur3_bytes

# RedisBloom's defaults for an implicitly-created filter.
DEFAULT_CAPACITY = 100
DEFAULT_ERROR_RATE = 0.01
EXPANSION = 2

_STR_SEED = 0x9E3779B9


class ResponseError(Exception):
    """Command-level error, mirroring redis.exceptions.ResponseError."""


def member_to_u32(member: Any) -> int:
    """Normalize a sketch member to the framework's uint32 key domain.

    Redis hashes the byte-string form of every member, so int 5 and "5"
    are the same member; we preserve that: integer-valued members (ints or
    numeric strings) in [0, 2^32) map to their value, everything else maps
    to a murmur3 digest of its bytes.
    """
    if isinstance(member, (bool,)):
        member = int(member)
    if isinstance(member, (int, np.integer)):
        v = int(member)
        if 0 <= v < 2 ** 32:
            return v
        return murmur3_bytes(str(v).encode(), _STR_SEED)
    if isinstance(member, bytes):
        data = member
    else:
        data = str(member).encode()
    try:
        v = int(data)
        if 0 <= v < 2 ** 32:
            return v
    except ValueError:
        pass
    return murmur3_bytes(data, _STR_SEED)


def members_to_u32(members: Sequence[Any]) -> np.ndarray:
    """Vector form of member_to_u32; fast-paths integer arrays."""
    if isinstance(members, np.ndarray) and members.dtype.kind in "iu":
        return members.astype(np.uint32)
    return np.array([member_to_u32(x) for x in members], dtype=np.uint32)


class ScalableBloom:
    """RedisBloom-style auto-scaling chain of fixed-size Bloom filters.

    Sub-filter i has capacity c0 * EXPANSION^i and error e0 / 2^i, so the
    whole chain's FPR stays <= 2*e0. The backend supplies the three
    per-filter primitives; chaining logic is shared across backends.
    """

    def __init__(self, store: "SketchStore", capacity: int,
                 error_rate: float, layout: str):
        self.store = store
        self.base_capacity = capacity
        self.base_error = error_rate
        self.layout = layout
        self.filters: List[Any] = []  # backend filter handles
        self.params: List[BloomParams] = []
        self.counts: List[int] = []  # approx distinct inserts per filter
        self._grow()

    def _grow(self) -> None:
        i = len(self.filters)
        params = derive_bloom_params(
            self.base_capacity * (EXPANSION ** i),
            self.base_error / (2.0 ** i),
            self.layout)
        self.filters.append(self.store._filter_create(params))
        self.params.append(params)
        self.counts.append(0)

    def contains_many(self, keys: np.ndarray) -> np.ndarray:
        out = np.zeros(len(keys), dtype=bool)
        for handle, params in zip(self.filters, self.params):
            rem = ~out
            if not rem.any():
                break
            out[rem] = self.store._filter_contains(handle, params, keys[rem])
        return out

    def add_many(self, keys: np.ndarray) -> np.ndarray:
        """Insert keys; returns per-key 1 if (probably) new, else 0.

        Inserts are sliced across sub-filters so no sub-filter ever takes
        more distinct keys than its declared capacity — an arbitrarily
        large batch (larger than the whole remaining chain) grows the
        chain as many times as needed instead of overfilling the newest
        sub-filter and blowing its FPR budget.
        """
        existed = self.contains_many(keys)
        new_keys = keys[~existed]
        i = 0
        while i < len(new_keys):
            room = self.params[-1].capacity - self.counts[-1]
            if room <= 0:
                self._grow()
                continue
            chunk = new_keys[i:i + room]
            # Distinct inserts, counting within-batch duplicates once
            # (duplicates crossing a slice boundary re-add idempotently
            # to the newer sub-filter — membership stays correct).
            self.counts[-1] += len(np.unique(chunk))
            self.filters[-1] = self.store._filter_add(
                self.filters[-1], self.params[-1], chunk)
            i += len(chunk)
        return (~existed).astype(np.int64)

    @property
    def item_count(self) -> int:
        return sum(self.counts)

    @property
    def total_capacity(self) -> int:
        return sum(p.capacity for p in self.params)


class SketchStore(abc.ABC):
    """Abstract sketch store exposing the redis-py call shapes.

    Concrete stores implement the per-filter primitives (_filter_*) and
    the HLL primitives; the Redis backend overrides the public methods
    wholesale and never touches the primitives.
    """

    def __init__(self, config):
        self.config = config
        self._blooms: Dict[str, ScalableBloom] = {}
        # Dirty-key tracking for incremental (base+delta) snapshots
        # (utils/snapshot.snapshot_sketch_store_chain): the PUBLIC
        # command surface marks keys written since the last drain, so
        # every backend routed through this dispatch (memory / tpu /
        # redis-sim) tracks identically. _dirty_all forces the next
        # chain snapshot to write a full base (fresh store, flush, or
        # a restore mismatch).
        self._dirty_blooms: set = set()
        self._dirty_hll: set = set()
        self._dirty_all = True
        # Accuracy auditor (obs/audit.py): captured ONCE here, one
        # `is not None` branch per public command when auditing is off
        # — the utils/profiling.py discipline. The hooks live on the
        # PUBLIC command surface (not the _filter_*/_hll_* primitives),
        # so internal membership probes (ScalableBloom.add_many's
        # dedup contains) never pollute the measured-FPR denominator,
        # and every backend that routes through this dispatch
        # (memory / tpu / redis-sim) is audited identically.
        from attendance_tpu import obs
        t = obs.ensure(config) if config is not None else None
        self._auditor = t.auditor if t is not None else None

    # -- backend primitives -------------------------------------------------
    @abc.abstractmethod
    def _filter_create(self, params: BloomParams):
        ...

    @abc.abstractmethod
    def _filter_add(self, handle, params: BloomParams, keys: np.ndarray):
        """Returns the (possibly replaced) filter handle."""

    @abc.abstractmethod
    def _filter_contains(self, handle, params: BloomParams,
                         keys: np.ndarray) -> np.ndarray:
        ...

    @abc.abstractmethod
    def _hll_add(self, key: str, keys_u32: np.ndarray,
                 mask: Optional[np.ndarray] = None,
                 want_changed: bool = True) -> int:
        """Batched PFADD; returns 1 if any register changed.

        want_changed=False lets device backends skip the host round-trip
        that computing the flag costs; the return value is then 0 and
        meaningless (the micro-batch hot loop never reads it)."""

    @abc.abstractmethod
    def _hll_count(self, keys: Sequence[str]) -> int:
        ...

    # -- Bloom command surface (redis-py execute_command shapes) ------------
    def bf_reserve(self, key: str, error_rate, capacity) -> bool:
        if key in self._blooms:
            raise ResponseError("item exists")
        self._blooms[key] = ScalableBloom(
            self, int(capacity), float(error_rate),
            getattr(self.config, "bloom_layout", "flat"))
        self._dirty_blooms.add(key)
        return True

    def _bloom_or_create(self, key: str) -> ScalableBloom:
        bloom = self._blooms.get(key)
        if bloom is None:
            bloom = ScalableBloom(self, DEFAULT_CAPACITY, DEFAULT_ERROR_RATE,
                                  getattr(self.config, "bloom_layout", "flat"))
            self._blooms[key] = bloom
        return bloom

    def bf_add_many(self, key: str, members) -> np.ndarray:
        u32 = members_to_u32(members)
        out = self._bf_add_u32(key, u32)
        self._dirty_blooms.add(key)
        if self._auditor is not None:
            self._auditor.record_bf_add(key, u32)
        return out

    def bf_exists_many(self, key: str, members) -> np.ndarray:
        u32 = members_to_u32(members)
        out = self._bf_exists_u32(key, u32)
        if self._auditor is not None:
            self._auditor.check_bf_exists(key, u32, out)
        return out

    # Backend chokepoints under the audited surface: subclasses that
    # reimplement the command semantics wholesale (redis_sim) override
    # THESE, so the audit cross-check above still sees their answers.
    def _bf_add_u32(self, key: str, u32: np.ndarray) -> np.ndarray:
        return self._bloom_or_create(key).add_many(u32)

    def _bf_exists_u32(self, key: str, u32: np.ndarray) -> np.ndarray:
        bloom = self._blooms.get(key)
        if bloom is None:
            return np.zeros(len(u32), dtype=bool)
        return bloom.contains_many(u32)

    # -- HLL command surface ------------------------------------------------
    def pfadd(self, key: str, *members) -> int:
        self._dirty_hll.add(key)
        if not members:
            return self._pf_create(key)
        u32 = members_to_u32(members)
        out = self._pfadd_u32(key, u32, None, True)
        if self._auditor is not None:
            self._auditor.record_pfadd(key, u32)
        return out

    def pfadd_many(self, key: str, members,
                   mask: Optional[np.ndarray] = None,
                   want_changed: bool = False) -> int:
        self._dirty_hll.add(key)
        u32 = members_to_u32(members)
        out = self._pfadd_u32(key, u32, mask, want_changed)
        if self._auditor is not None:
            self._auditor.record_pfadd(key, u32, mask)
        return out

    def pfcount(self, *keys: str) -> int:
        out = self._pfcount_keys(keys)
        if self._auditor is not None:
            self._auditor.check_pfcount(keys, out)
        return out

    def pfcount_many(self, keys: Sequence[str]) -> List[int]:
        """Batched per-key PFCOUNT: one estimate per key (NOT the
        union ``pfcount(*keys)`` computes) — the query plane's batched
        read entry point over generic stores. The default loops
        :meth:`pfcount` so every answer still crosses the audit
        chokepoint; banked backends override with one vectorized
        histogram pass (TpuSketchStore)."""
        return [self.pfcount(k) for k in keys]

    def _pf_create(self, key: str) -> int:
        """PFADD with no members (create-only form); the generic
        backends treat it as a no-op returning 0."""
        return 0

    def _pfadd_u32(self, key: str, u32: np.ndarray,
                   mask: Optional[np.ndarray],
                   want_changed: bool) -> int:
        return self._hll_add(key, u32, mask, want_changed)

    def _pfcount_keys(self, keys: Sequence[str]) -> int:
        return self._hll_count(keys)

    # -- observability ------------------------------------------------------
    def _filter_fill(self, handle, params: BloomParams) -> Optional[float]:
        """Fraction of set bits of one sub-filter. Works for any backend
        whose handle is a 0/1 bit-per-element array (tpu, memory);
        backends without state access (redis) return None."""
        try:
            return float(np.mean(np.asarray(handle, dtype=np.float32)))
        except Exception:  # noqa: BLE001 - opaque handle
            return None

    def estimated_fpr(self, key: str) -> Optional[float]:
        """Occupancy-based FPR estimate for one Bloom key: per sub-filter
        fill^k, combined across the scalable chain as
        1 - prod(1 - fpr_i) (a query false-positives if ANY sub-filter
        does). None when the key is absent or the backend's filter state
        is not inspectable (redis). SURVEY.md §5 per-batch metrics."""
        bloom = self._blooms.get(key)
        if bloom is None:
            return None
        miss = 1.0
        for handle, params in zip(bloom.filters, bloom.params):
            fill = self._filter_fill(handle, params)
            if fill is None:
                return None
            miss *= 1.0 - fill ** params.k
        return 1.0 - miss

    # -- redis-py compatible entry point ------------------------------------
    def execute_command(self, *args):
        """The exact call shape the reference uses for BF.* commands.

        Arity mistakes raise :class:`ResponseError` like a real server
        ("wrong number of arguments"), not a bare unpacking ValueError
        or IndexError — callers written against redis-py catch exactly
        one type for command-shape errors. The check is explicit per
        command (no blanket exception conversion: a genuine backend bug
        must never be mislabelled as a caller arity mistake).
        """
        if not args:
            raise ResponseError("empty command")
        cmd = str(args[0]).upper()
        n = len(args) - 1

        def need(lo: int, hi: Optional[float] = None) -> None:
            """hi=None means exactly ``lo`` args; pass float('inf')
            for variadic commands."""
            top = lo if hi is None else hi
            if n < lo or n > top:
                raise ResponseError(
                    f"wrong number of arguments for {cmd!r}")

        if cmd == "BF.RESERVE":
            need(3)
            return self.bf_reserve(str(args[1]), args[2], args[3])
        if cmd == "BF.ADD":
            need(2)
            return int(self.bf_add_many(str(args[1]), [args[2]])[0])
        if cmd == "BF.MADD":
            need(2, float("inf"))
            key = str(args[1])
            return [int(x) for x in self.bf_add_many(key, list(args[2:]))]
        if cmd == "BF.EXISTS":
            need(2)
            return int(self.bf_exists_many(str(args[1]), [args[2]])[0])
        if cmd == "BF.MEXISTS":
            need(2, float("inf"))
            key = str(args[1])
            return [int(x) for x in self.bf_exists_many(key, list(args[2:]))]
        if cmd == "BF.INFO":
            need(1)
            key = str(args[1])
            bloom = self._blooms.get(key)
            if bloom is None:
                raise ResponseError("not found")
            return {
                "Capacity": bloom.total_capacity,
                "Size": sum(p.m_bits // 8 for p in bloom.params),
                "Number of filters": len(bloom.filters),
                "Number of items inserted": bloom.item_count,
                "Expansion rate": EXPANSION,
            }
        if cmd == "PFADD":
            need(1, float("inf"))
            return self.pfadd(str(args[1]), *args[2:])
        if cmd == "PFCOUNT":
            need(1, float("inf"))
            return self.pfcount(*[str(k) for k in args[1:]])
        raise ResponseError(f"unknown command {cmd!r}")

    # -- incremental-snapshot support ---------------------------------------
    def drain_dirty(self):
        """(dirty_all, bloom_keys, hll_keys) written since the last
        drain, clearing the marks — the chain snapshotter's capture
        point (utils/snapshot.snapshot_sketch_store_chain)."""
        out = (self._dirty_all, self._dirty_blooms, self._dirty_hll)
        self._dirty_all = False
        self._dirty_blooms = set()
        self._dirty_hll = set()
        return out

    def mark_clean(self) -> None:
        """After restore: disk chain == memory state, nothing dirty."""
        self._dirty_all = False
        self._dirty_blooms.clear()
        self._dirty_hll.clear()

    # -- lifecycle ----------------------------------------------------------
    def flush(self) -> None:
        self._blooms.clear()
        self._dirty_all = True
        self._dirty_blooms.clear()
        self._dirty_hll.clear()

    def close(self) -> None:
        pass
