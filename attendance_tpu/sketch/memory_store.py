"""Pure-host numpy sketch store.

Bit-identical hashing/layout with the TPU store (shared parameter
derivation, numpy mirrors of the position/rank math), but zero JAX: the
hermetic backend for tests and the independent differential oracle for the
device kernels (SURVEY.md §4 "parity" tier).
"""

from __future__ import annotations

from typing import Dict, Optional, Sequence

import numpy as np

from attendance_tpu.models.bloom import BloomParams, bloom_positions_np
from attendance_tpu.models.hll import (
    estimate_from_histogram, hll_bucket_rank_np)
from attendance_tpu.sketch.base import SketchStore


class MemorySketchStore(SketchStore):
    def __init__(self, config):
        super().__init__(config)
        self.precision = getattr(config, "hll_precision", 14)
        self._hll_regs: Dict[str, np.ndarray] = {}

    # -- Bloom primitives ---------------------------------------------------
    def _filter_create(self, params: BloomParams):
        return np.zeros(params.m_bits, dtype=np.uint8)

    def _filter_add(self, handle, params: BloomParams, keys: np.ndarray):
        pos = bloom_positions_np(keys, params)
        handle[pos.reshape(-1).astype(np.int64)] = 1
        return handle

    def _filter_contains(self, handle, params: BloomParams,
                         keys: np.ndarray) -> np.ndarray:
        pos = bloom_positions_np(keys, params).astype(np.int64)
        return handle[pos].all(axis=1)

    # -- HLL primitives -----------------------------------------------------
    def _hll_add(self, key: str, keys_u32: np.ndarray,
                 mask: Optional[np.ndarray] = None,
                 want_changed: bool = True) -> int:
        regs = self._hll_regs.get(key)
        if regs is None:
            regs = self._hll_regs[key] = np.zeros(
                1 << self.precision, dtype=np.uint8)
        bucket, rank = hll_bucket_rank_np(keys_u32, self.precision)
        if mask is not None:
            rank = np.where(mask, rank, 0)
        changed = bool((rank > regs[bucket]).any())
        np.maximum.at(regs, bucket, rank.astype(np.uint8))
        return int(changed)

    def _hll_count(self, keys: Sequence[str]) -> int:
        known = [self._hll_regs[k] for k in keys if k in self._hll_regs]
        if not known:
            return 0
        merged = known[0]
        for r in known[1:]:
            merged = np.maximum(merged, r)
        q = 64 - self.precision
        hist = np.bincount(merged, minlength=q + 2)
        return int(round(estimate_from_histogram(hist, self.precision)))

    # -- snapshot/restore hooks (attendance_tpu.utils.snapshot) -------------
    def _restore_filter(self, params: BloomParams, bits: np.ndarray):
        return np.array(bits, dtype=np.uint8)

    def _restore_hll_per_key(self, regs: Dict[str, np.ndarray],
                             precision: int) -> None:
        self.precision = precision
        self._hll_regs = {k: np.array(v, dtype=np.uint8)
                          for k, v in regs.items()}

    def flush(self) -> None:
        super().flush()
        self._hll_regs.clear()
