"""TPU-backed sketch store: device-resident state, batched jitted kernels.

This is the ``--sketch-backend=tpu`` execution backend of the north star:
the per-event ``BF.EXISTS``/``PFADD`` round-trips of the reference hot loop
(reference attendance_processor.py:109-129) become gathers/scatters over
HBM-resident arrays, dispatched once per micro-batch.

Batches are padded to the next power of two (min 8) so XLA compiles a
bounded set of program shapes; masked lanes scatter out of bounds and are
dropped by the kernels.
"""

from __future__ import annotations

from typing import Dict, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from attendance_tpu.models.bloom import (
    BloomParams, bloom_add, bloom_contains, bloom_init)
from attendance_tpu.models.hll import (
    HyperLogLog, best_histogram, estimate_from_histogram,
    hll_bucket_rank_np)
from attendance_tpu.sketch.base import SketchStore


def pad_to_pow2(n: int, minimum: int = 8) -> int:
    p = minimum
    while p < n:
        p *= 2
    return p


class TpuSketchStore(SketchStore):
    def __init__(self, config):
        super().__init__(config)
        self._hll = HyperLogLog(
            initial_banks=getattr(config, "hll_initial_banks", 8),
            precision=getattr(config, "hll_precision", 14))
        # jit caches keyed by (params, padded batch size)
        self._add_jits: Dict[Tuple[BloomParams, int], callable] = {}
        self._contains_jits: Dict[Tuple[BloomParams, int], callable] = {}

    # -- Bloom primitives ---------------------------------------------------
    def _filter_create(self, params: BloomParams):
        return bloom_init(params)

    def _pad(self, keys: np.ndarray) -> Tuple[jax.Array, jax.Array, int]:
        n = len(keys)
        padded = pad_to_pow2(n)
        buf = np.zeros(padded, dtype=np.uint32)
        buf[:n] = keys
        mask = np.zeros(padded, dtype=bool)
        mask[:n] = True
        return jnp.asarray(buf), jnp.asarray(mask), n

    def _filter_add(self, handle, params: BloomParams, keys: np.ndarray):
        kbuf, mask, _ = self._pad(keys)
        fn = self._add_jits.get((params, len(kbuf)))
        if fn is None:
            fn = jax.jit(lambda bits, k, m: bloom_add(bits, k, params, m),
                         donate_argnums=(0,))
            self._add_jits[(params, len(kbuf))] = fn
        return fn(handle, kbuf, mask)

    def _filter_contains(self, handle, params: BloomParams,
                         keys: np.ndarray) -> np.ndarray:
        kbuf, _, n = self._pad(keys)
        fn = self._contains_jits.get((params, len(kbuf)))
        if fn is None:
            fn = jax.jit(lambda bits, k: bloom_contains(bits, k, params))
            self._contains_jits[(params, len(kbuf))] = fn
        return np.asarray(fn(handle, kbuf))[:n]

    # -- HLL primitives -----------------------------------------------------
    def _hll_add(self, key: str, keys_u32: np.ndarray,
                 mask: Optional[np.ndarray] = None,
                 want_changed: bool = True) -> int:
        idx = self._hll.bank_index(key)
        changed = False
        if want_changed:
            # "Did any register change?" computed host-side from the
            # pre-update row. Costs a blocking device->host row copy, so
            # the micro-batch hot loop requests want_changed=False; only
            # the scalar redis-compatible pfadd() pays for it.
            bucket, rank = hll_bucket_rank_np(keys_u32, self._hll.precision)
            if mask is not None:
                rank = np.where(mask, rank, 0)
            row = np.asarray(self._hll.regs[idx])
            changed = bool((rank > row[bucket]).any())
        n = len(keys_u32)
        padded = pad_to_pow2(n)
        kbuf = np.zeros(padded, dtype=np.uint32)
        kbuf[:n] = keys_u32
        mbuf = np.zeros(padded, dtype=bool)
        mbuf[:n] = True if mask is None else mask
        self._hll.add(np.full(padded, idx, dtype=np.int32), kbuf, mbuf)
        return int(changed)

    def pfcount_many(self, keys: Sequence[str]):
        """Vectorized batched per-key PFCOUNT: ONE device histogram
        pass over every requested bank instead of a dispatch per key
        (the base-class default) — the banked backend's batched read
        entry point. Audit parity with the scalar path: each answer is
        still cross-checked per key."""
        idxs = [self._hll.bank_index(k, create=False) for k in keys]
        known = sorted({i for i in idxs if i >= 0})
        by_bank = {}
        if known:
            hists = np.asarray(best_histogram(
                self._hll.regs[np.asarray(known, np.int32)],
                self._hll.precision))
            by_bank = {b: int(round(estimate_from_histogram(
                h, self._hll.precision)))
                for b, h in zip(known, hists)}
        out = []
        for key, idx in zip(keys, idxs):
            v = by_bank.get(idx, 0)
            if self._auditor is not None:
                self._auditor.check_pfcount((key,), v)
            out.append(v)
        return out

    def _hll_count(self, keys: Sequence[str]) -> int:
        known = [k for k in keys if self._hll.bank_index(k, create=False) >= 0]
        if not known:
            return 0
        if len(known) == 1:
            return self._hll.count(known[0])
        return self._hll.count_union(known)

    # -- direct state access (used by the fused pipeline + snapshots) -------
    @property
    def hll(self) -> HyperLogLog:
        return self._hll

    def bloom_chain(self, key: str):
        """The ScalableBloom chain for a key (None if absent)."""
        return self._blooms.get(key)

    # -- snapshot/restore hooks (attendance_tpu.utils.snapshot) -------------
    def _restore_filter(self, params: BloomParams, bits: np.ndarray):
        return jnp.asarray(np.asarray(bits, dtype=np.uint8))

    def _restore_hll_banked(self, regs: np.ndarray, bank_of: Dict[str, int],
                            precision: int) -> None:
        self._hll = HyperLogLog(initial_banks=regs.shape[0],
                                precision=precision)
        self._hll.regs = jnp.asarray(np.asarray(regs, dtype=np.uint8))
        self._hll._bank_of = {str(k): int(v) for k, v in bank_of.items()}

    def flush(self) -> None:
        super().flush()
        self._hll = HyperLogLog(
            initial_banks=getattr(self.config, "hll_initial_banks", 8),
            precision=getattr(self.config, "hll_precision", 14))
