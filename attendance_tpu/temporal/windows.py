"""Windowed HLL bank ring: (day, period) buckets over shared bank rows.

Pure host bookkeeping — the device work (register scatter-max, row
zeroing) happens through the two callbacks the owning pipeline
provides, against the SAME ``uint8[num_banks, 2^p]`` register array
the per-day banks live in. A bucket is one bank row keyed by its
:func:`temporal.buckets.bucket_key`; because those keys ride the
pipeline's ordinary ``bank_of`` map, the delta snapshot chain, the
epoch mirror, and the federation frames all carry buckets with zero
new machinery.

Lifecycle:

  * **open** — the bucket's period has not been passed by the
    watermark; events fold in (scatter-max, order-free);
  * **rotated (closed)** — ``watermark >= (period+1) * T``: the bucket
    is immutable; late events targeting it are DROPPED to the side
    channel (counted, never misbucketed). Closed buckets stay
    queryable until ring pressure evicts them;
  * **evicted** — the ring holds at most ``capacity`` buckets; when a
    new bucket needs a row, the oldest CLOSED bucket is evicted: its
    bank row is zeroed on device and returned to the pipeline's
    free-bank list, and its key leaves ``bank_of`` (the next delta's
    manifest stops naming it). Open buckets are never evicted — the
    ring over-commits with a one-time warning instead of dropping
    live data.
"""

from __future__ import annotations

import logging
from typing import Callable, Dict, List, Tuple

import numpy as np

from attendance_tpu.temporal.buckets import (
    bucket_keys, decode_bucket_key, is_bucket_key)

logger = logging.getLogger(__name__)


class BucketRing:
    def __init__(self, period_us: int, capacity: int,
                 alloc_bank: Callable[[int], int],
                 free_buckets: Callable[[List[int], List[int]], None]):
        if capacity < 2:
            raise ValueError("temporal ring needs >= 2 bucket rows")
        self.period_us = int(period_us)
        self.capacity = int(capacity)
        self._alloc_bank = alloc_bank
        self._free_buckets = free_buckets
        self.buckets: Dict[int, int] = {}  # bucket key -> bank row
        self._first_open = 0  # periods below this are rotated/closed
        self.rotations_total = 0
        self.evictions_total = 0
        self._warned_overcommit = False

    # -- assignment ----------------------------------------------------------
    def assign(self, days: np.ndarray, micros: np.ndarray
               ) -> Tuple[np.ndarray, int, List[int]]:
        """Bank row per event (int32[B], -1 = dropped: the bucket had
        already rotated, so the event is side-channeled instead of
        misbucketed). Returns ``(banks, dropped, touched)`` where
        ``touched`` is the distinct bucket keys that received events —
        what the caller marks dirty for the delta chain (returned from
        the SAME unique pass instead of a second key computation).
        Allocation happens here; rotation is the caller's NEXT step —
        events are judged against the pre-advance frontier, so
        releases freed by this very watermark advance can never
        drop."""
        periods = (np.asarray(micros, np.int64)
                   // np.int64(self.period_us))
        keys = bucket_keys(np.asarray(days, np.int64), periods)
        if not len(keys):
            return np.zeros(0, np.int32), 0, []
        uniq, inverse = np.unique(keys, return_inverse=True)
        lut = np.empty(len(uniq), np.int32)
        for i, key in enumerate(uniq.tolist()):
            _, period = decode_bucket_key(key)
            if period < self._first_open:
                # Rotated buckets are IMMUTABLE, retained or not: a
                # closed window's answer must never change after the
                # fact, so the event side-channels instead.
                lut[i] = -1
                continue
            bank = self.buckets.get(key)
            if bank is None:
                bank = self._allocate(key)
            lut[i] = bank
        banks = lut[inverse].astype(np.int32, copy=False)
        dropped = int(np.bincount(inverse)[lut < 0].sum())
        return banks, dropped, uniq[lut >= 0].tolist()

    def _allocate(self, key: int) -> int:
        if len(self.buckets) >= self.capacity:
            self._evict_one()
        bank = self._alloc_bank(key)
        self.buckets[key] = bank
        return bank

    def _evict_one(self) -> None:
        """Evict the oldest rotated bucket (period, then day order);
        over-commit with a warning when everything is still open."""
        oldest_key = None
        oldest = None
        for key in self.buckets:
            day, period = decode_bucket_key(key)
            if period >= self._first_open:
                continue
            rank = (period, day)
            if oldest is None or rank < oldest:
                oldest, oldest_key = rank, key
        if oldest_key is None:
            if not self._warned_overcommit:
                self._warned_overcommit = True
                logger.warning(
                    "temporal ring over capacity (%d buckets) with "
                    "every bucket still open — raise "
                    "--temporal-ring-banks or widen the period; open "
                    "buckets are never dropped", len(self.buckets))
            return
        bank = self.buckets.pop(oldest_key)
        self._free_buckets([oldest_key], [bank])
        self.evictions_total += 1

    # -- rotation ------------------------------------------------------------
    def rotate(self, watermark_us: int) -> int:
        """Advance the open frontier to the watermark; returns how
        many buckets rotated (open -> closed) at this boundary."""
        new_first = max(int(watermark_us) // self.period_us, 0)
        if new_first <= self._first_open:
            return 0
        n = sum(1 for key in self.buckets
                if self._first_open
                <= decode_bucket_key(key)[1] < new_first)
        self._first_open = new_first
        self.rotations_total += n
        return n

    @property
    def open_buckets(self) -> int:
        return sum(1 for key in self.buckets
                   if decode_bucket_key(key)[1] >= self._first_open)

    def __len__(self) -> int:
        return len(self.buckets)

    # -- restore -------------------------------------------------------------
    def restore(self, bank_of: Dict[int, int]) -> int:
        """Re-seed the ring from a restored ``bank_of`` map (every
        bucket key in it). All restored buckets start OPEN — the
        watermark is ephemeral and rebuilds from the redelivered
        stream, so a restart can only widen the fold window, never
        misbucket (scatter-max re-adds are idempotent). Returns the
        bucket count."""
        self.buckets = {int(k): int(b) for k, b in bank_of.items()
                        if is_bucket_key(int(k))}
        self._first_open = 0
        return len(self.buckets)
