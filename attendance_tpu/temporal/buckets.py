"""Bucket-key encoding: (lecture day, time period) -> one int bank key.

The whole temporal design hinges on this module being tiny: a bucket
is addressed by ONE integer that (a) can never collide with a real
lecture-day key (calendar ``yyyymmdd`` < 10^8; hashed lecture ids <
10^8 + 2^26 — events._HASH_DAY_BASE/_HASH_DAY_LIMIT), (b) fits int64
(the serve plane's day vectors and the manifest JSON round-trip), and
(c) decodes back to (day, period) without any side table — the epoch's
``bank_of`` map alone is enough for every window query, so a chain
reader or federation aggregator that has never seen the live ring can
still answer ``window_pfcount``.

Layout (63 bits):  1 << 62  |  period << 28  |  day

  * day: 28 bits — covers calendar yyyymmdd AND the hashed-lecture
    bucket space (< 2^28);
  * period: 34 bits — ``micros // (period_s * 1e6)``; at the minimum
    1-second period that reaches year ~2514 before overflow.
"""

from __future__ import annotations

from typing import Tuple

import numpy as np

BUCKET_KEY_BASE = 1 << 62
_DAY_BITS = 28
_DAY_MASK = (1 << _DAY_BITS) - 1
_PERIOD_BITS = 34
MAX_PERIOD = (1 << _PERIOD_BITS) - 1

MICROS_PER_S = 1_000_000


def period_micros(period_s: float) -> int:
    """Bucket width in microseconds (validated at config time)."""
    us = int(round(period_s * MICROS_PER_S))
    if us < MICROS_PER_S:
        raise ValueError(
            f"temporal period must be >= 1s (got {period_s}s) — the "
            "34-bit period field is sized for 1-second buckets")
    return us


def period_of(micros, period_us: int):
    """Period index of event-time micros (scalar or array)."""
    return np.asarray(micros, np.int64) // np.int64(period_us)


def bucket_key(day: int, period: int) -> int:
    """Encode one (day, period) bucket as its synthetic bank key."""
    if not (0 <= day <= _DAY_MASK):
        raise ValueError(f"day {day} exceeds the {_DAY_BITS}-bit field")
    if not (0 <= period <= MAX_PERIOD):
        raise ValueError(
            f"period {period} exceeds the {_PERIOD_BITS}-bit field")
    return BUCKET_KEY_BASE | (int(period) << _DAY_BITS) | int(day)


def bucket_keys(days: np.ndarray, periods: np.ndarray) -> np.ndarray:
    """Vectorized :func:`bucket_key`: int64[B] (callers guarantee the
    field bounds — frame days/periods come from the validated codec
    columns)."""
    return (np.int64(BUCKET_KEY_BASE)
            | (np.asarray(periods, np.int64) << np.int64(_DAY_BITS))
            | np.asarray(days, np.int64))


def is_bucket_key(key: int) -> bool:
    """Is this bank key a temporal bucket (vs a plain lecture day)?"""
    return int(key) >= BUCKET_KEY_BASE


def decode_bucket_key(key: int) -> Tuple[int, int]:
    """(day, period) of a bucket key; raises on a non-bucket key."""
    key = int(key)
    if key < BUCKET_KEY_BASE:
        raise ValueError(f"{key} is a plain day key, not a bucket key")
    body = key - BUCKET_KEY_BASE
    return body & _DAY_MASK, body >> _DAY_BITS
