"""TemporalPlane: the fused pipeline's temporal sidecar.

One instance per (single-chip) FusedPipeline when
``--temporal-period-s`` > 0. Per frame it does three things:

1. **Windowed HLL adds, at arrival.** Every event's bucket is a pure
   function of its own timestamp, and the register update is a
   scatter-max CRDT — order-free — so the add dispatches with the
   frame itself (one extra jitted Bloom-probe + hll_add into the
   SHARED register array) and therefore rides the PR 4 group-commit
   ack barrier: an acked frame's window contribution is durably in
   the delta chain. Only the drop/fold CLASSIFICATION consults the
   watermark; events whose bucket already rotated are side-channeled
   (counted, sampled) instead of misbucketed.

2. **Watermarked reorder for the order-sensitive consumers.** The
   bounded reorder stage (temporal/reorder.py) releases events in
   event-time order; rotation/eviction advance at watermark
   boundaries, entry/exit pairs fold into the dwell histogram, and
   the CMS heavy-hitter estimates stage toward the top-K.

3. **Count-Min gate-fraud tracking.** Every released swipe (valid or
   not — fraud cares about raw attempts) increments the device CMS;
   the fused step's lazy estimate vector is staged host-side and
   folded into the bounded top-K at rotation boundaries, so the hot
   loop never waits on a device readback.

Durability contract: the windowed HLL banks are durable (delta chain,
see fast_path); the reorder buffer, CMS counts, top-K, and dwell
state are advisory and reset on restore — redelivered frames rebuild
the windows exactly (idempotent scatter-max), while the advisory
detectors restart their estimates.
"""

from __future__ import annotations

import logging
from collections import deque
from typing import Dict, List, Optional

import numpy as np

from attendance_tpu.models.cms import (
    TopK, cms_init, make_jitted_cms_step)
from attendance_tpu.temporal.buckets import period_micros
from attendance_tpu.temporal.reorder import ReorderStage
from attendance_tpu.temporal.windows import BucketRing

logger = logging.getLogger(__name__)

_DROP_SAMPLE = 256  # side-channel ring of recent dropped events
_CMS_FOLD_BLOCKS = 64  # staged (keys, est) blocks before an early fold


class TemporalPlane:
    def __init__(self, config, *, alloc_bank, free_buckets, mark_dirty,
                 dispatch_add, obs=None):
        self.period_us = period_micros(config.temporal_period_s)
        self.reorder = ReorderStage(
            int(round(config.allowed_lateness_s * 1e6)),
            idle_s=config.watermark_idle_s)
        self.ring = BucketRing(self.period_us,
                               config.temporal_ring_banks,
                               alloc_bank, free_buckets)
        self._mark_dirty = mark_dirty
        self._dispatch_add = dispatch_add
        # Second fused sketch: device CMS + bounded host top-K.
        self._cms = cms_init(config.cms_depth, config.cms_width)
        self._cms_steps: Dict[int, object] = {}
        self._cms_staged: List[tuple] = []  # (keys np, est device)
        self.topk = TopK(config.cms_topk)
        # Dwell pairing: pending entry times keyed by (day << 32 | sid)
        # as SORTED parallel arrays (vectorized searchsorted matching —
        # a per-boundary Python dict op measurably dominated the
        # temporal plane's cost) plus a log2-bucketed histogram of
        # paired dwell times.
        self._dwell_keys = np.zeros(0, np.int64)
        self._dwell_times = np.zeros(0, np.int64)
        self.dwell_hist = np.zeros(40, np.int64)  # 2^b us buckets
        self.dwell_pairs_total = 0
        self.dwell_unmatched_exits = 0
        # Side channel + counters.
        self._evictions_seen = 0
        self.dropped_sample: deque = deque(maxlen=_DROP_SAMPLE)
        self.late_folded_total = 0
        self.late_dropped_total = 0
        self.events_total = 0
        # Exact shadow (the window audit oracle): per-bucket sets of
        # VALID students, kept when the full-population audit is on
        # (the soak/test configuration — a sampled shadow would make
        # the zero-false-negative window gate probabilistic).
        self._shadow: Optional[Dict[int, set]] = (
            {} if getattr(config, "audit_sample", 0.0) >= 1.0 else None)
        self._roster: Optional[np.ndarray] = None
        self._obs = obs
        # Attribution plane (obs/profiler.py): the CMS step is a
        # jitted entry point too — its padded-shape fingerprints ride
        # the same recompile tracker as the fused steps, so a CMS
        # recompile storm is as visible as a dispatch one.
        self._recomp = (obs.recompiles if obs is not None else None)
        self._c_late = {}
        if obs is not None:
            reg = obs.registry
            for outcome in ("folded", "dropped"):
                self._c_late[outcome] = reg.counter(
                    "attendance_late_events_total",
                    help="Late events per outcome: folded = landed in "
                    "the correct still-open bucket; dropped = bucket "
                    "already rotated, event side-channeled",
                    outcome=outcome)
            self._c_rotations = reg.counter(
                "attendance_window_rotations_total",
                help="Bucket rotations (open -> closed) at watermark "
                "boundaries")
            self._c_evictions = reg.counter(
                "attendance_window_evictions_total",
                help="Closed buckets evicted by ring pressure (bank "
                "row zeroed and recycled)")
            reg.gauge(
                "attendance_watermark_lag_seconds",
                help="Event-time lag between the stream head and the "
                "watermark (steady state = allowed lateness; NaN "
                "before the first event)").set_function(
                    self.reorder.watermark_lag_s)
            reg.gauge(
                "attendance_window_open_buckets",
                help="Temporal buckets not yet rotated").set_function(
                    lambda: float(self.ring.open_buckets))
            reg.gauge(
                "attendance_temporal_reorder_buffered",
                help="Events held by the watermark reorder buffer"
            ).set_function(lambda: float(self.reorder.buffered))
            reg.gauge(
                "attendance_cms_topk_size",
                help="Heavy-hitter candidates currently tracked"
            ).set_function(lambda: float(len(self.topk)))
            self._c_dwell = reg.counter(
                "attendance_dwell_pairs_total",
                help="Entry/exit pairs folded into the dwell-time "
                "histogram")

    # -- roster / shadow -----------------------------------------------------
    def record_roster(self, keys: np.ndarray) -> None:
        """The preloaded roster (the filter's full membership): what
        the exact window shadow uses to classify validity."""
        self._roster = np.sort(np.asarray(keys, np.uint32))

    def shadow_truth(self) -> Dict[int, int]:
        """Exact unique-valid-student count per bucket key (empty when
        the full shadow is off)."""
        if self._shadow is None:
            return {}
        return {k: len(s) for k, s in self._shadow.items()}

    # -- per-frame hook ------------------------------------------------------
    def observe_frame(self, cols: Dict[str, np.ndarray]) -> None:
        days = np.asarray(cols["lecture_day"])
        micros = np.asarray(cols["micros"], np.int64)
        sids = np.asarray(cols["student_id"], np.uint32)
        n = len(micros)
        if n == 0:
            return
        self.events_total += n
        # (2) reorder first: bumps max_seen, returns the ordered
        # releases for the order-sensitive consumers below.
        released = self.reorder.offer(cols)
        wm = self.reorder.effective_watermark_us
        arrival_late = self.reorder.last_arrival_late
        # (1) windowed adds at arrival, judged against the
        # PRE-rotation frontier (releases freed by this very advance
        # can never drop — see windows.assign).
        banks, dropped, touched = self.ring.assign(days, micros)
        if dropped:
            self.late_dropped_total += dropped
            if self._c_late:
                self._c_late["dropped"].inc(dropped)
            drop_idx = np.flatnonzero(banks < 0)[:_DROP_SAMPLE]
            for i in drop_idx.tolist():
                self.dropped_sample.append(
                    (int(sids[i]), int(days[i]), int(micros[i])))
        folded = int(((banks >= 0) & arrival_late).sum())
        if folded:
            self.late_folded_total += folded
            if self._c_late:
                self._c_late["folded"].inc(folded)
        keep = banks >= 0
        if keep.any():
            self._mark_dirty(touched)
            self._dispatch_add(sids, banks)
        if self._shadow is not None and self._roster is not None \
                and len(self._roster):
            self._record_shadow(sids[keep], days[keep], micros[keep])
        # (3) rotation AFTER the adds; eviction/top-K fold ride it.
        if self._rotate(wm):
            self._fold_cms()
        if released is not None:
            self._consume_released(released)

    def _rotate(self, watermark_us: int) -> int:
        """Advance the ring's frontier AND sync the rotation/eviction
        counters — the one rotate path for per-frame advances, idle
        flushes, and end-of-run flushes alike (a flush-path rotate
        that bypassed the counters exported 0 rotations for any run
        shorter than one period)."""
        rotated = self.ring.rotate(watermark_us)
        if self._c_late:
            if rotated:
                self._c_rotations.inc(rotated)
            ev = self.ring.evictions_total
            if ev > self._evictions_seen:
                self._c_evictions.inc(ev - self._evictions_seen)
                self._evictions_seen = ev
        return rotated

    def _record_shadow(self, sids, days, micros) -> None:
        valid_pos = np.searchsorted(self._roster, sids)
        valid_pos = np.clip(valid_pos, 0, len(self._roster) - 1)
        valid = self._roster[valid_pos] == sids
        if not valid.any():
            return
        from attendance_tpu.temporal.buckets import bucket_keys
        periods = micros // np.int64(self.period_us)
        keys = bucket_keys(days.astype(np.int64), periods)
        for key, sid in zip(keys[valid].tolist(),
                            sids[valid].tolist()):
            self._shadow.setdefault(key, set()).add(sid)

    # -- order-sensitive consumers -------------------------------------------
    def _consume_released(self, rel: Dict[str, np.ndarray]) -> None:
        sids = rel["student_id"]
        n = len(sids)
        if n == 0:
            return
        # CMS: one fused update+query dispatch; estimates stage lazily.
        padded = 256
        while padded < n:
            padded *= 2
        kbuf = np.zeros(padded, np.uint32)
        kbuf[:n] = sids
        mask = np.zeros(padded, bool)
        mask[:n] = True
        step = self._cms_steps.get(padded)
        if step is None:
            step = self._cms_steps[padded] = make_jitted_cms_step()
        if self._recomp is not None:
            self._recomp.observe("cms_step", (padded,))
        import jax.numpy as jnp
        self._cms, est = step(self._cms, jnp.asarray(kbuf),
                              jnp.asarray(mask))
        self._cms_staged.append((np.array(sids, np.uint32), est, n))
        if len(self._cms_staged) >= _CMS_FOLD_BLOCKS:
            self._fold_cms()
        self._pair_dwell(rel)

    def _fold_cms(self) -> None:
        """Fold staged (keys, lazy estimates) into the top-K. Runs at
        rotation boundaries (and on staging overflow) — by then the
        staged device arrays have long materialized, so np.asarray is
        a copy, not a stall."""
        staged, self._cms_staged = self._cms_staged, []
        for keys, est, n in staged:
            self.topk.offer(keys, np.asarray(est)[:n])

    def _pair_dwell(self, rel: Dict[str, np.ndarray]) -> None:
        """Entry/exit pairing over the ORDERED release stream (the
        reorder stage is what makes entry-before-exit sound): adjacent
        (student, day) entry->exit pairs fold vectorized; pairs that
        straddle release blocks go through the bounded pending map."""
        sid = rel["student_id"].astype(np.int64)
        day = rel["lecture_day"].astype(np.int64)
        et = np.asarray(rel["event_type"])
        mic = np.asarray(rel["micros"], np.int64)
        pkey = (day << np.int64(32)) | sid
        order = np.argsort(pkey, kind="stable")  # stable: time order
        k, e, m = pkey[order], et[order], mic[order]
        same_prev = np.concatenate([[False], k[1:] == k[:-1]])
        prev_entry = np.concatenate([[False], e[:-1] == 0])
        paired = (e == 1) & same_prev & prev_entry
        if paired.any():
            m_prev = np.concatenate([[np.int64(0)], m[:-1]])
            self._fold_dwell(m[paired] - m_prev[paired])
        # Mid-run repeated exits (exit directly after exit) have no
        # entry to pair with in any interpretation: count them.
        self.dwell_unmatched_exits += int(
            ((e == 1) & same_prev & ~prev_entry).sum())
        # Cross-block boundaries, fully vectorized against the sorted
        # pending arrays: run-leading exits match (and consume)
        # pending entries; run-trailing unconsumed entries feed the
        # map (a re-entry's LATEST entry time wins).
        lead = np.flatnonzero((e == 1) & ~same_prev)
        pk, pt = self._dwell_keys, self._dwell_times
        if len(lead):
            lk, lt = k[lead], m[lead]  # sorted, unique (one per run)
            pos = np.searchsorted(pk, lk)
            found = (pos < len(pk))
            found[found] = pk[np.minimum(pos[found], len(pk) - 1)] \
                == lk[found]
            if found.any():
                self._fold_dwell(lt[found] - pt[pos[found]])
                keep = np.ones(len(pk), bool)
                keep[pos[found]] = False
                pk, pt = pk[keep], pt[keep]
            self.dwell_unmatched_exits += int((~found).sum())
        last_of_run = np.concatenate([k[1:] != k[:-1], [True]])
        tail = np.flatnonzero((e == 0) & last_of_run)
        if len(tail):
            tk, tt = k[tail], m[tail]  # sorted, unique
            pos = np.searchsorted(pk, tk)
            found = (pos < len(pk))
            found[found] = pk[np.minimum(pos[found], len(pk) - 1)] \
                == tk[found]
            if found.any():
                pt = pt.copy()
                pt[pos[found]] = tt[found]  # latest entry wins
            fresh = ~found
            if fresh.any():
                pk = np.concatenate([pk, tk[fresh]])
                pt = np.concatenate([pt, tt[fresh]])
                order = np.argsort(pk, kind="stable")
                pk, pt = pk[order], pt[order]
        if len(pk) > 1 << 21:  # bound a pathological stream
            pk = np.zeros(0, np.int64)
            pt = np.zeros(0, np.int64)
            logger.warning("dwell pending map overflowed; cleared")
        self._dwell_keys, self._dwell_times = pk, pt

    def _fold_dwell(self, dwell_us: np.ndarray) -> None:
        dwell_us = dwell_us[dwell_us >= 0]
        if not len(dwell_us):
            return
        b = np.log2(np.maximum(dwell_us, 1)).astype(np.int64)
        np.add.at(self.dwell_hist, np.clip(b, 0, 39), 1)
        self.dwell_pairs_total += len(dwell_us)
        if self._c_late:
            self._c_dwell.inc(len(dwell_us))

    # -- liveness ------------------------------------------------------------
    # -- control-plane knobs -------------------------------------------------
    def widen_lateness(self, lateness_us: int) -> None:
        """GROW the allowed-lateness budget (control plane, late-drop
        adaptation). Widening is always safe mid-stream: the watermark
        only trails further, so events buffer longer and fewer arrive
        behind it — no event that would have been released on time can
        now drop. Shrinking mid-stream could jump the watermark forward
        over buffered events, so it is refused here (the knob's lower
        bound is the configured value for the same reason)."""
        lateness_us = int(lateness_us)
        if lateness_us > self.reorder.lateness_us:
            self.reorder.lateness_us = lateness_us

    def grow_ring(self, capacity: int) -> None:
        """GROW the bucket-ring capacity (control plane, late-drop
        adaptation). Grow-only: eviction triggers on len >= capacity,
        so raising it mid-stream just delays the next eviction;
        shrinking would strand already-allocated buckets past the new
        bound and is refused."""
        capacity = int(capacity)
        if capacity > self.ring.capacity:
            self.ring.capacity = capacity

    def maybe_idle_flush(self) -> bool:
        """Watermark idle advancement: silent past --watermark-idle-s
        with events buffered -> release everything and rotate to the
        stream head. Called from the run loop's receive-timeout path."""
        if not self.reorder.idle_due():
            return False
        self.flush()
        return True

    def flush(self) -> None:
        """End-of-stream: release the reorder buffer, rotate to the
        head, fold staged CMS estimates."""
        released = self.reorder.flush()
        if released is not None:
            self._consume_released(released)
        self._rotate(self.reorder.effective_watermark_us)
        self._fold_cms()

    def restore(self, bank_of: Dict[int, int]) -> None:
        """Post-restore re-seed: buckets come back from the chain's
        bank_of; watermark/CMS/top-K/dwell are advisory and restart."""
        n = self.ring.restore(bank_of)
        if n:
            logger.info("temporal ring restored %d bucket(s) from the "
                        "snapshot chain", n)

    def stats(self) -> Dict:
        return {
            "events": self.events_total,
            "buckets": len(self.ring),
            "open_buckets": self.ring.open_buckets,
            "rotations": self.ring.rotations_total,
            "evictions": self.ring.evictions_total,
            "late_folded": self.late_folded_total,
            "late_dropped": self.late_dropped_total,
            "reorder_buffered": self.reorder.buffered,
            "watermark_lag_s": self.reorder.watermark_lag_s(),
            "dwell_pairs": self.dwell_pairs_total,
            "dwell_unmatched_exits": self.dwell_unmatched_exits,
            "topk": [(int(k), int(v)) for k, v in self.topk.items()],
        }
