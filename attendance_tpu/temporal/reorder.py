"""Bounded watermark reorder stage at the codec seam.

Sits between frame decode and the ORDER-SENSITIVE temporal consumers
(bucket rotation, entry/exit dwell pairing, the CMS rate fold). The
order-FREE consumers deliberately bypass it: the windowed HLL add is a
scatter-max CRDT whose bucket is a pure function of the event's own
timestamp, so it rides the frame's own device dispatch — and therefore
the PR 4 group-commit ack barrier — exactly like the per-day banks.
Buffering those adds host-side would silently break the "every acked
event is durable" contract (a barrier could ack a frame whose events
still sat in a host buffer).

Semantics (standard event-time streaming):

  * the **watermark** trails the maximum event time seen by
    ``allowed_lateness``: ``W = max_seen - lateness``;
  * events with ``t > W`` are **buffered**; once W advances past
    them they are **released in event-time order** (one concatenate +
    argsort over the bounded buffer per offer);
  * events arriving with ``t <= W`` are genuine stragglers: they are
    released immediately (merged into this offer's sorted release)
    and flagged ``late`` — the downstream bucket ring decides folded
    (bucket still open) vs dropped (bucket rotated, side-channel);
  * an idle stream (``watermark_idle_s`` of wall-clock silence)
    advances W to ``max_seen``, flushing the buffer so final buckets
    close without waiting for traffic that will never come.
"""

from __future__ import annotations

import time
from typing import Dict, List, Optional, Tuple

import numpy as np

_COLS = ("student_id", "lecture_day", "micros", "event_type")


def _take(cols: Dict[str, np.ndarray], idx: np.ndarray
          ) -> Dict[str, np.ndarray]:
    return {c: cols[c][idx] for c in _COLS}


def _concat(blocks: List[Dict[str, np.ndarray]]) -> Dict[str, np.ndarray]:
    if len(blocks) == 1:
        return blocks[0]
    return {c: np.concatenate([b[c] for b in blocks]) for c in _COLS}


class ReorderStage:
    """One consumer's bounded event-time reorder buffer."""

    def __init__(self, lateness_us: int, idle_s: float = 0.0):
        if lateness_us < 0:
            raise ValueError("allowed lateness must be >= 0")
        self.lateness_us = int(lateness_us)
        self.idle_s = float(idle_s)
        self.max_seen_us: int = -(1 << 62)
        self._pending: List[Dict[str, np.ndarray]] = []
        self._pending_events = 0
        self._last_event_mono = time.monotonic()
        self.late_released_total = 0  # t <= W at arrival (stragglers)
        self.released_total = 0

    # -- state ---------------------------------------------------------------
    @property
    def watermark_us(self) -> int:
        return self.max_seen_us - self.lateness_us

    @property
    def buffered(self) -> int:
        return self._pending_events

    def watermark_lag_s(self) -> float:
        """How far the watermark trails, as a LIVE health signal (NaN
        before the first event): the event-time trail behind the
        stream head (allowed lateness while flowing; 0 after an idle/
        end-of-run flush) PLUS, while events sit buffered, the
        wall-clock seconds since traffic stopped — a stalled stream
        holding data past its idle budget is exactly the failure the
        doctor's ``--watermark-lag-ceiling-s`` gate watches, and the
        event-time term alone is a constant that can never show it."""
        if self.max_seen_us <= -(1 << 61):
            return float("nan")
        lag = (self.max_seen_us - self.effective_watermark_us) / 1e6
        if self._pending_events:
            lag += max(0.0,
                       time.monotonic() - self._last_event_mono)
        return lag

    # -- ingest --------------------------------------------------------------
    def arrival_late_mask(self, micros: np.ndarray) -> np.ndarray:
        """Per-event lateness AT ARRIVAL: event i is late iff it
        trails the stream head AS OF its own arrival (previous frames'
        max folded with the frame's own running prefix max) by more
        than the allowed lateness. Judging a whole frame against the
        post-frame watermark would misclassify the leading half of any
        frame spanning more event time than the lateness budget."""
        micros = np.asarray(micros, np.int64)
        if not len(micros):
            return np.zeros(0, bool)
        prefix = np.maximum.accumulate(micros)
        head_before = np.empty(len(micros), np.int64)
        head_before[0] = self.max_seen_us
        np.maximum(prefix[:-1], np.int64(self.max_seen_us),
                   out=head_before[1:])
        return micros <= head_before - np.int64(self.lateness_us)

    def offer(self, cols: Dict[str, np.ndarray]
              ) -> Optional[Dict[str, np.ndarray]]:
        """Stage one decoded frame; returns the released block (sorted
        by event time, with a ``late`` bool column marking stragglers)
        or None when nothing crossed the watermark yet."""
        micros = np.asarray(cols["micros"], np.int64)
        late_mask = self.arrival_late_mask(micros)
        self.last_arrival_late = late_mask  # the plane's fold counter
        if len(micros):
            self._last_event_mono = time.monotonic()
            self.note_activity()  # traffic resumed post-flush
            frame_max = int(micros.max())
            if frame_max > self.max_seen_us:
                self.max_seen_us = frame_max
        wm = self.watermark_us
        n_late = int(late_mask.sum())
        hold_mask = ~late_mask
        block = {c: np.asarray(cols[c]) for c in _COLS}
        if n_late:
            # Stragglers release NOW (their watermark already passed);
            # the rest of the frame buffers until W reaches it.
            straggler = _take(block, np.flatnonzero(late_mask))
            if hold_mask.any():
                self._stash(_take(block, np.flatnonzero(hold_mask)))
        else:
            straggler = None
            if len(micros):
                self._stash(block)
        ready = self._drain_ready(wm)
        if straggler is not None:
            self.late_released_total += n_late
            ready = ready + [straggler] if ready else [straggler]
            n_ready = sum(len(b["micros"]) for b in ready) - n_late
            late_col = np.zeros(n_ready + n_late, bool)
        elif ready:
            late_col = np.zeros(sum(len(b["micros"]) for b in ready),
                                bool)
        else:
            return None
        out = _concat(ready)
        if straggler is not None:
            # Mark the straggler lanes BEFORE the sort so the flag
            # travels with its events into event-time order.
            late_col[-len(straggler["micros"]):] = True
        order = np.argsort(out["micros"], kind="stable")
        out = _take(out, order)
        out["late"] = late_col[order]
        self.released_total += len(out["micros"])
        return out

    def _stash(self, block: Dict[str, np.ndarray]) -> None:
        # Own the bytes: buffered events outlive their frame (and a
        # shm slot recycles at ack), so views must not escape here.
        self._pending.append({c: np.array(block[c]) for c in _COLS})
        self._pending_events += len(block["micros"])

    def _drain_ready(self, wm: int) -> List[Dict[str, np.ndarray]]:
        if not self._pending or self._pending_events == 0:
            return []
        combined = _concat(self._pending)
        micros = combined["micros"]
        ready_mask = micros <= wm
        if not ready_mask.any():
            # Re-pack as the single combined block (bounds the list).
            self._pending = [combined]
            return []
        ready = _take(combined, np.flatnonzero(ready_mask))
        rest_idx = np.flatnonzero(~ready_mask)
        if len(rest_idx):
            self._pending = [_take(combined, rest_idx)]
            self._pending_events = len(rest_idx)
        else:
            self._pending = []
            self._pending_events = 0
        return [ready]

    # -- liveness ------------------------------------------------------------
    def idle_due(self) -> bool:
        """Has the stream been silent past ``watermark_idle_s`` with
        events still buffered? (0 disables idle advancement.)"""
        return (self.idle_s > 0 and self._pending_events > 0
                and time.monotonic() - self._last_event_mono
                >= self.idle_s)

    def flush(self) -> Optional[Dict[str, np.ndarray]]:
        """Advance the watermark to the stream head and release
        everything buffered (idle advancement / end of run)."""
        if self._pending_events == 0:
            return None
        combined = _concat(self._pending)
        self._pending = []
        self._pending_events = 0
        order = np.argsort(combined["micros"], kind="stable")
        out = _take(combined, order)
        out["late"] = np.zeros(len(out["micros"]), bool)
        self.released_total += len(out["micros"])
        # The watermark itself jumps to the head: buckets behind it
        # may now rotate (the ring reads watermark_us after a flush).
        self.max_seen_us = max(self.max_seen_us,
                               int(out["micros"][-1]))
        self._advance_to_head = True
        return out

    @property
    def effective_watermark_us(self) -> int:
        """The watermark the bucket ring rotates against: normally
        ``max_seen - lateness``; after a flush (idle/end-of-run) the
        stream head itself, so final buckets can close."""
        if getattr(self, "_advance_to_head", False):
            return self.max_seen_us
        return self.watermark_us

    def note_activity(self) -> None:
        """New traffic after an idle flush: the watermark resumes
        trailing by the allowed lateness."""
        self._advance_to_head = False
