"""Temporal sketch plane: windowed HLL banks, watermarked disorder
handling, and the Count-Min gate-fraud detector.

Everything here rides the EXISTING planes rather than duplicating
them: temporal buckets are (day, period) pairs encoded as synthetic
bank keys (:mod:`temporal.buckets`) living in the same
``uint8[num_banks, 2^p]`` HLL register array and the same ``bank_of``
map as the per-day banks — so the PR 4 dirty-bank delta chain
persists them unchanged, the PR 7 epoch mirror serves them
merge-on-read, and the PR 8 federation frames replicate them with no
new wire. The watermark/reorder stage (:mod:`temporal.reorder`) and
the ring bookkeeping (:mod:`temporal.windows`) are pure host logic;
:mod:`temporal.plane` wires them into the fused pipeline behind one
``is not None`` branch.
"""

from attendance_tpu.temporal.buckets import (  # noqa: F401
    BUCKET_KEY_BASE, bucket_key, decode_bucket_key, is_bucket_key,
    period_of)
