"""Config/flag layer.

The reference has no flag system: every script imports 10 module-level
constants from a ``config/config.py`` that is absent from its repo (contract
defined by the imports at reference data_generator.py:13-16,
attendance_processor.py:13-17, attendance_analysis.py:9). This module keeps
those 10 names as the compatibility contract (same defaults as the
reference's README where stated) and adds a real argparse flag layer whose
first citizen is ``--sketch-backend={redis,tpu,memory}``.
"""

from __future__ import annotations

import argparse
import dataclasses
from typing import List, Optional

# ---------------------------------------------------------------------------
# The 10 reference constants (contract: SURVEY.md §1 L0).
# ---------------------------------------------------------------------------
PULSAR_HOST = "pulsar://localhost:6650"
PULSAR_TOPIC = "attendance-events"
REDIS_HOST = "localhost"
REDIS_PORT = 6379
CASSANDRA_HOSTS = ["localhost"]
CASSANDRA_KEYSPACE = "attendance_system"
BLOOM_FILTER_KEY = "bf:students"
BLOOM_FILTER_ERROR_RATE = 0.01  # reference README.md:238-239
BLOOM_FILTER_CAPACITY = 100_000  # reference README.md:104
HLL_KEY_PREFIX = "hll:unique:"  # reference attendance_processor.py:128


@dataclasses.dataclass
class Config:
    """Full framework configuration.

    The first block mirrors the reference constants verbatim; the second
    block is new, TPU-native configuration (micro-batching, sketch layout,
    sharding) with conservative defaults.
    """

    # --- reference contract ---
    pulsar_host: str = PULSAR_HOST
    pulsar_topic: str = PULSAR_TOPIC
    redis_host: str = REDIS_HOST
    redis_port: int = REDIS_PORT
    cassandra_hosts: List[str] = dataclasses.field(
        default_factory=lambda: list(CASSANDRA_HOSTS))
    cassandra_keyspace: str = CASSANDRA_KEYSPACE
    bloom_filter_key: str = BLOOM_FILTER_KEY
    bloom_filter_error_rate: float = BLOOM_FILTER_ERROR_RATE
    bloom_filter_capacity: int = BLOOM_FILTER_CAPACITY
    hll_key_prefix: str = HLL_KEY_PREFIX

    # --- TPU-native additions ---
    # Backend for the sketch path (BF.*/PFADD/PFCOUNT). "tpu" = device
    # arrays + jitted kernels; "memory" = pure-python host sketches (hermetic
    # tests, no JAX); "redis" = real Redis Stack (import-gated).
    sketch_backend: str = "tpu"
    # Transport/storage backends: "memory" (hermetic, in-process),
    # "socket" (the framework's own cross-process broker,
    # transport.socket_broker — multi-process competing consumers
    # without an external service), or the real services
    # ("pulsar"/"cassandra", import-gated).
    transport_backend: str = "memory"
    storage_backend: str = "memory"
    # Address of a running BrokerServer for --transport-backend=socket
    # (start one with: python -m attendance_tpu.transport.socket_broker).
    # Port matches transport.socket_broker.DEFAULT_PORT so the no-flag
    # broker recipe and this default reach each other out of the box.
    socket_broker: str = "127.0.0.1:6655"
    # Micro-batch size for the processor hot loop. Events are padded to this
    # size so every device dispatch has a static shape (XLA: one compile).
    batch_size: int = 8192
    # Striped ingress plane (pipeline.lanes): number of independent
    # ingress lanes feeding the fused pipeline. 0 (default) = the
    # classic single consumer in the run loop; N >= 1 runs N broker
    # sessions (one TCP connection each on the socket backend), each
    # with a bridge worker decoding its micro-batches off the dispatch
    # thread, coalesced into full device batches by one dispatcher —
    # so N=1 is the striped plane at minimum width (the parity
    # measurement), not the classic path. Reconnect/resume and poison
    # handling apply per lane; snapshot group-commit acks release
    # across lanes.
    ingress_lanes: int = 0
    # Decoded blocks each lane may park in its bounded SPSC queue
    # before the worker blocks (backpressure toward the broker).
    lane_queue_depth: int = 4
    # Lane decode engine: "auto" picks the native schema scanner when
    # the C runtime is loadable (fastest, but holds the GIL) and the
    # numpy-vectorized batch scanner otherwise; "native"/"vector"
    # force one (codec.scan_json_batch_columns is the vector engine).
    lane_decode: str = "auto"
    # Ingress wire selection (pipeline.codec / transport.shm_ring).
    # "auto" (default) keeps the sniffing behavior: every broker
    # payload routes through the codec its magic names (json, binary,
    # COLW columnar) — producers pick the wire, consumers adapt per
    # frame. "shm" replaces the broker transport for the EVENT topic
    # with the shared-memory ring (co-located producers; --shm-dir
    # names the ring directory, one ring file per ingress lane; the
    # fed gossip/query planes keep their configured transports —
    # a federated worker on shm ingress needs --fed-gossip-broker).
    # "json"/"binary"/"columnar" are documentation of intent for
    # broker wires (the consumer sniffs regardless).
    ingress_wire: str = "auto"
    # Shared-memory ring geometry (only read when ingress_wire=shm).
    # One ring file per (topic, lane) under shm_dir; slots hold one
    # planar frame each, so shm_slot_bytes must cover batch_size
    # events (20 B/event + 8 B header; the producer fails loudly on
    # overflow). nslots bounds the published-but-unacked window — the
    # backpressure depth, and the redelivery bound after a consumer
    # crash.
    shm_dir: str = ""
    shm_slots: int = 64
    shm_slot_bytes: int = 1 << 21
    # Classic-consumer JSON chunk decode (ISSUE 11 satellite): with
    # ingress_lanes=0 a JSON payload used to decode PER MESSAGE inside
    # the run loop (one event per dispatch on per-event wires). True
    # (default) drains a whole chunk of JSON messages from chunk-
    # capable consumers and batch-decodes them through the codec seam
    # (the scan_json_batch_columns engine when the native list scan is
    # unavailable). False keeps the per-message path — the bench's
    # before/after measurement, and the bisection fallback.
    json_chunk_decode: bool = True
    # Max time to wait filling a batch before flushing a partial one.
    batch_timeout_s: float = 0.05
    # Bloom layout: "flat" (standard double-hashed, Redis-parity FPR math)
    # or "blocked" (512-bit cache blocks, HBM-locality-friendly).
    bloom_layout: str = "flat"
    # HLL precision: p=14 -> 16384 registers, matching Redis dense HLL.
    hll_precision: int = 14
    # Initial number of HLL banks (one bank per HLL key, grown on demand).
    hll_initial_banks: int = 8
    # Sharding: number of sketch shards (hash-prefix partitions) and data-
    # parallel replicas for multi-chip runs. 1/1 = single chip.
    num_shards: int = 1
    num_replicas: int = 1
    # Replica sync cadence for the sharded engine: "query" (default)
    # defers the HLL register-max union across replicas to PFCOUNT/
    # snapshot time (no per-step dp collective — what lets "dp" span
    # DCN in a multi-host mesh, parallel.multihost); "step" converges
    # every replica after each batch. Observationally identical.
    replica_sync: str = "query"
    # Snapshot directory for sketch checkpoint/restore ("" = disabled).
    # When set, processors restore on start and snapshot at ack barriers
    # every snapshot_every_batches batches (<= 0 = a default cadence of
    # 64 — a set dir always checkpoints, because restoring stale state
    # while acking would lose events).
    snapshot_dir: str = ""
    snapshot_every_batches: int = 0
    # Snapshot pipeline mode. "delta" (default): barriers capture only
    # the HLL banks touched since the last barrier (a host-side dirty
    # set fed by the frames' day columns) into double-buffered async
    # D2H staging; the background writer serializes staging ->
    # delta-NNNN.npz files chained off the last full base snapshot by
    # an fsync'd CHAIN.json manifest (atomic rename = the durability
    # point), and acks for the barrier interval's frames release when
    # the DELTA is durable (group commit) — the crash contract ("every
    # acked event is in a durable snapshot") is unchanged while the
    # barrier itself costs one buffer swap. "barrier": every snapshot
    # writes the full sketch state (the pre-delta behavior; kept as
    # the bisection/debug fallback).
    snapshot_mode: str = "delta"
    # Delta-chain compaction cadence: after this many delta files the
    # writer folds the chain back into a full base snapshot (off the
    # hot path, from its host register mirror) and deletes the deltas,
    # bounding restore cost and chain length.
    snapshot_compact_every: int = 16
    # Structured metrics sink ("" = disabled): append ONE JSON line of
    # run metrics (ProcessorMetrics.to_dict) per processor/bridge run —
    # the machine-readable counterpart of the human metrics log line
    # (the reference's README narrates "structured logging" without
    # implementing it; SURVEY.md §5).
    metrics_json: str = ""
    # Profiling ("" = disabled): directory for a jax.profiler trace of
    # the processing run (TensorBoard/XProf-loadable). Device dispatches
    # are TraceAnnotation-labelled so kernel time attributes to stages.
    profile_dir: str = ""
    # Continuous host sampling profiler (obs/profiler.py; 0 = off):
    # a background thread samples sys._current_frames() at this rate,
    # folds per-thread collapsed stacks, and attributes every sample
    # to the thread's current pipeline stage (dequeue/decode/dispatch/
    # device_wait/temporal/snapshot/serve/...) — the per-stage
    # SELF-TIME table `telemetry --attribution` renders and the trend
    # gate diffs. Stage fractions export live as
    # attendance_profile_stage_fraction{stage=} gauges (they ride
    # fleet pushes for the dashboard's top-stage column). Hot threads
    # pay only the stage-mark dict writes; sampling runs on its own
    # thread. 29-97 Hz are good prime choices (avoid aliasing the
    # snapshot cadence).
    profile_hz: float = 0.0
    # Artifact directory for the sampling profiler ("" = in-memory
    # only: live gauges still export): profile.folded (flamegraph
    # collapsed stacks), profile_trace.json (Perfetto stage
    # timeline), attribution.json (the offline attribution table,
    # incl. the recompile-fingerprint ledger).
    profile_out: str = ""
    # Live telemetry (obs/): all four default OFF, and with every flag
    # unset the instrumented hot paths pay exactly one branch per event
    # (same discipline as profile_dir). metrics_prom appends a
    # Prometheus text-exposition block per interval to a file;
    # metrics_port serves GET /metrics from a stdlib HTTP endpoint
    # (-1 = ephemeral port, for tests/parallel runs); flight_recorder
    # keeps a ring of the last N per-batch records, dumped as JSON to
    # flight_path on SIGUSR1 / run-loop crash / the `telemetry` verb.
    metrics_prom: str = ""
    metrics_port: int = 0
    metrics_interval_s: float = 1.0
    flight_recorder: int = 0
    flight_path: str = "flight_recorder.json"
    # Span tracing ("" = disabled): collect per-batch spans (publish ->
    # dequeue -> decode -> dispatch -> device_wait, trace context
    # propagated through broker message properties) into a bounded
    # in-memory buffer, flushed to this path as Chrome-trace/Perfetto
    # JSON at end of run / teardown. Same disabled-path guarantee as
    # the metrics flags: unset = one branch per hook.
    trace_out: str = ""
    # Continuous accuracy auditing (0.0 = disabled): keep an exact
    # shadow (ground-truth member/cardinality sets) for this hash-
    # sampled fraction of the key space, cross-check every sampled
    # BF.EXISTS/PFADD/PFCOUNT answer against it, and export MEASURED
    # accuracy gauges (attendance_bloom_measured_fpr,
    # attendance_bloom_false_negatives_total,
    # attendance_hll_measured_rel_error) alongside the occupancy-based
    # estimators — obs/audit.py. Same disabled-path guarantee.
    audit_sample: float = 0.0
    # SLO engine ("" = disabled): evaluate declarative objectives
    # (accuracy ceilings, throughput floor, latency quantiles) over
    # fast+slow burn-rate windows and append one JSON line per alert
    # transition (firing/resolved) here — obs/slo.py.
    alert_log: str = ""
    # Extra/override SLO specs, e.g. "fpr<=0.01", "throughput>=1e6",
    # "dequeue_p99<=0.05" (see obs.slo.parse_slo for the full alias
    # table). The accuracy defaults from ROADMAP's targets are always
    # installed when the engine is on.
    slo: List[str] = dataclasses.field(default_factory=list)
    # Burn-rate windows (seconds): the fast window gates alert
    # freshness and hysteresis clearing, the slow window rejects
    # single-window spikes (SRE multi-window multi-burn-rate).
    slo_fast_s: float = 60.0
    slo_slow_s: float = 300.0
    # Incident plane ("" = disabled): correlate live breach conditions
    # (SLO firings, circuit opens, spill growth, steady recompiles,
    # merge-lag/staleness/watermark breaches, dead peers, lane stalls,
    # integrity rejects) into incident records and write a checksummed
    # evidence bundle per incident under this directory — obs/incident.py.
    incident_dir: str = ""
    # Hysteresis: consecutive clean evaluation ticks before an open
    # incident clears (rides the SLO engine's own firing hysteresis).
    incident_clear_ticks: int = 3
    # Wire format for the fused pipeline's host->device transfer.
    # Either the link or the host-side pack is the e2e bottleneck,
    # depending on the moment's link rate vs host load; "auto" starts
    # at the cheap word wire and adapts per frame from observed
    # backpressure (narrowing word->seg->delta when the device side
    # falls behind — see fast_path._auto_wire). On the single chip the
    # narrow packs need the native host runtime (auto stays on word
    # without it); the mesh path packs per-replica buffers in numpy and
    # narrows either way. "delta"/"seg"/"word"/"bytes" force one.
    wire_format: str = "auto"
    # Optional side topic for computed-invalid events ("" = disabled).
    # The reference's README promises an "attendance-invalid" routing
    # topic its code never implements (README.md:163,262; SURVEY.md
    # §0.3 item 4). When set, the generic processor REPUBLISHES each
    # invalid event there (reference JSON wire) in addition to the
    # code-contract behavior of storing it with is_valid=false.
    invalid_topic: str = ""
    # Poison-message handling: a frame that fails decode/processing is
    # nacked for redelivery at most this many times, then dead-lettered
    # (acked + counted). The reference nacks forever (no DLQ despite its
    # README: SURVEY.md §5 failure detection) which livelocks the
    # subscription on a poison frame; a bounded retry is strictly safer.
    max_redeliveries: int = 3
    # On-disk quarantine for dead-lettered frames ("" = drop on ack,
    # the old behavior): handle_poison writes the frame bytes + a
    # metadata sidecar here before acking, and `doctor --quarantine`
    # lists / `--replay-quarantine` republishes the entries.
    quarantine_dir: str = ""
    # Deterministic fault injection ("" = no fault plane; "off" =
    # plane installed but every probability zero — the bench's
    # disabled-cost measurement). Spec grammar (chaos/__init__.py):
    # comma-separated fault=prob tokens, timed faults fault=dur:prob,
    # e.g. "drop=0.01,delay=5ms:0.05,dup=0.005,conn_reset=0.002,
    # persist_fail=0.01,writer_stall=200ms:0.01,corrupt=0.001". All
    # draws come from per-(site,fault) PRNG streams derived from
    # chaos_seed, so a failing run replays from its seed.
    chaos: str = ""
    chaos_seed: int = 0
    # Live query-serving plane (attendance_tpu/serve): when nonzero,
    # the fused pipeline answers BF.EXISTS / PFCOUNT / occupancy /
    # attendance-rate queries from an epoch-pinned host mirror of the
    # sketch state — snapshot-isolated reads that never touch the
    # device hot loop — over a length-prefixed binary batch RPC on
    # this port (-1 = ephemeral, exposed as pipeline.query_server.port)
    # plus JSON routes on the --metrics-port HTTP endpoint. Epochs are
    # published at snapshot barriers (and preload/restore), so serving
    # live state needs checkpointing on; without it the epoch stays at
    # the preload/restore state until publish_epoch() is called.
    serve_port: int = 0
    # Largest key/day batch one query RPC may carry (the server rejects
    # bigger ones; the client chunks transparently).
    query_batch_max: int = 1 << 16
    # Read-staleness objective (seconds; 0 = off): adds a
    # `read_staleness<=X` SLO over the attendance_read_staleness_seconds
    # gauge (the published epoch's age — bounded by the snapshot
    # barrier cadence when serving from a live pipeline, barrier +
    # refresh cadence from a chain reader).
    read_staleness_ceiling_s: float = 0.0
    # Federated multi-host scale-out (attendance_tpu/federation):
    # fed_worker names this ingest worker ("" = federation off). A
    # federated worker owns hash shard fed_shard of fed_shards and, on
    # every snapshot fence, gossips its dirty-bank delta (and full
    # frames at preload/restore/base fences) as versioned merge frames
    # onto fed_gossip_topic — Bloom-OR / HLL-register-max CRDT
    # replication an aggregator (`federate` verb) folds into one
    # queryable global view. fed_gossip_broker points gossip at a
    # dedicated socket broker address ("" = ride this pipeline's own
    # transport); fed_heartbeat_s keeps liveness observable between
    # fences, and a peer silent past fed_dead_after_s is declared dead
    # (shard orphaned at a bumped map version, durable chain recovered
    # by the aggregator).
    fed_worker: str = ""
    fed_shard: int = 0
    fed_shards: int = 1
    fed_gossip_topic: str = "attendance-fed-gossip"
    fed_gossip_broker: str = ""
    fed_heartbeat_s: float = 2.0
    fed_dead_after_s: float = 10.0
    # Fleet observability plane (obs/fleet.py). fleet_push names a
    # FleetCollector HOST:PORT ("" = off): when set, this process's
    # telemetry bundle starts a background pusher shipping its
    # registry snapshot + bounded span batches there every
    # fleet_push_interval_s (the pusher rides the transport
    # retry/reconnect/chaos seams at site "fleet.push"; a dead
    # collector costs log noise, never throughput or correctness).
    # fleet_role/fleet_instance label this process in the merged
    # registry and the stitched trace ("" = derived: the CLI verbs set
    # their role, instance falls back to fed_worker or the pid).
    fleet_push: str = ""
    fleet_role: str = ""
    fleet_instance: str = ""
    fleet_push_interval_s: float = 2.0
    # Collector side: fleet_port != 0 runs a FleetCollector in this
    # process (-1 = ephemeral; the `federate` verb is the natural
    # host), re-exposing the merged registry under /fleet/* on the
    # --metrics-port endpoint; fleet_dir persists the collected
    # per-role prom files + stitched trace for `doctor --fleet` / CI.
    fleet_port: int = 0
    fleet_dir: str = ""
    # Label-cardinality guard: max distinct label sets per metric name
    # before new sets fold into an unexported per-family sink (ERROR
    # logged once). The per-day audit/read gauges grow one series per
    # lecture day — unbounded on a long multi-day run without a cap.
    # <= 0 disables the guard.
    metric_series_max: int = 1024
    # Temporal sketch plane (attendance_tpu/temporal): when
    # temporal_period_s > 0 the fused pipeline grows a windowed-HLL
    # bucket ring — one HLL bank row per (lecture day, time period)
    # bucket, living in the SAME register array / bank_of map /
    # delta-snapshot chain as the per-day banks — plus a watermarked
    # reorder stage at the codec seam and a Count-Min + top-K
    # gate-fraud kernel (models/cms.py). Window queries
    # (window_pfcount / window_occupancy / rate_series) serve
    # merge-on-read from the epoch mirror. Single-chip only: the
    # sharded engine has no bank-recycle path yet (validated below).
    temporal_period_s: float = 0.0
    # Event-time lateness budget: the watermark trails the stream
    # head by this much, out-of-order events within it land in their
    # correct still-open bucket, and events behind a rotated bucket
    # are counted + side-channeled instead of misbucketed.
    allowed_lateness_s: float = 5.0
    # Wall-clock silence after which the watermark advances to the
    # stream head (releasing the reorder buffer and letting final
    # buckets rotate). 0 = only end-of-run flushes.
    watermark_idle_s: float = 2.0
    # Bucket rows the temporal ring retains (open + queryable-closed);
    # ring pressure evicts the oldest CLOSED bucket, zeroing and
    # recycling its bank row. Open buckets are never evicted.
    temporal_ring_banks: int = 256
    # Count-Min geometry + heavy-hitter set size for the fraud kernel.
    cms_depth: int = 4
    cms_width: int = 1 << 14
    cms_topk: int = 16
    # Storage-integrity plane (utils/integrity): when on (the
    # default), every durable chain artifact's payload digest is
    # recorded in its manifest (CHAIN.json base_digest/digests,
    # MANIFEST.json digests) and verified before restore / the serve
    # chain readers trust a file; spill records carry per-record
    # checksums; gossip merge frames and fleet pushes ride the
    # checksummed wire framing. False skips digest COMPUTATION at the
    # writers (the bench's integrity-off baseline) — verification
    # still runs wherever digests already exist on disk.
    integrity: bool = True
    # Total retry budget for one logical broker RPC over the socket
    # transport: transient failures reconnect + retry with jittered
    # exponential backoff inside this window, then surface ONE
    # BrokerUnavailable.
    retry_budget_s: float = 15.0
    # Circuit breaker + durable spill buffer around the persist sink
    # ("" = raw sink, the default): consecutive insert failures open
    # the circuit, writes degrade to fsync'd spill files in this
    # directory, and a half-open probe after the cooldown drains them
    # back once the sink heals (storage/resilient.py).
    persist_spill_dir: str = ""
    persist_breaker_failures: int = 3
    persist_breaker_cooldown_s: float = 1.0
    # Self-driving control plane (attendance_tpu/control): a controller
    # thread that actuates bounded knobs (ingress admission, the
    # degradation ladder, lane scaling, snapshot cadence, watermark/
    # ring sizing) off the signals the obs plane already measures.
    # Enabled by control_log — the schema'd JSONL actuation log is the
    # plane's defining artifact (`doctor --actuations` replays it).
    control_log: str = ""
    # When set, shed-rung admission spills raw ingress frames durably
    # here (checksummed + fsync'd) and acks them; empty = nack back to
    # the broker (retention is the backpressure).
    control_spill_dir: str = ""
    # Minimum seconds between controller moves on the same knob (and
    # between degradation-ladder rung changes).
    control_dwell_s: float = 2.0
    # Consecutive clean controller ticks before de-escalation.
    control_clear_ticks: int = 3
    # Max ladder transitions per rolling minute before the controller
    # holds (anti-flap backstop).
    control_flap_limit: int = 8

    def validate(self) -> "Config":
        if self.sketch_backend not in ("tpu", "memory", "redis",
                                       "redis-sim"):
            raise ValueError(f"unknown sketch backend: {self.sketch_backend}")
        if self.bloom_layout not in ("flat", "blocked"):
            raise ValueError(f"unknown bloom layout: {self.bloom_layout}")
        if not (4 <= self.hll_precision <= 18):
            raise ValueError(f"hll precision out of range: {self.hll_precision}")
        if self.wire_format not in ("auto", "delta", "seg", "word",
                                    "bytes"):
            raise ValueError(f"unknown wire format: {self.wire_format}")
        if self.replica_sync not in ("step", "query"):
            raise ValueError(f"unknown replica sync: {self.replica_sync}")
        if self.batch_size <= 0:
            raise ValueError("batch_size must be positive")
        if self.ingress_lanes < 0:
            raise ValueError(
                "ingress_lanes must be >= 0 (0 = classic single "
                "consumer, N = striped plane with N lanes)")
        if self.lane_queue_depth < 1:
            raise ValueError("lane_queue_depth must be >= 1")
        if self.lane_decode not in ("auto", "native", "vector"):
            raise ValueError(
                f"unknown lane decode engine: {self.lane_decode}")
        if self.ingress_wire not in ("auto", "json", "binary",
                                     "columnar", "shm"):
            raise ValueError(
                f"unknown ingress wire: {self.ingress_wire}")
        if self.ingress_wire == "shm":
            if not self.shm_dir:
                raise ValueError(
                    "--ingress-wire=shm needs --shm-dir (the ring-"
                    "file directory both ends map)")
            if self.fed_worker and not self.fed_gossip_broker:
                raise ValueError(
                    "a federated worker on shm ingress has no broker "
                    "transport for gossip frames — set "
                    "--fed-gossip-broker")
        if self.shm_slots < 2:
            raise ValueError("shm_slots must be >= 2 (ring depth)")
        if self.shm_slot_bytes % 8 or self.shm_slot_bytes < 64:
            raise ValueError(
                "shm_slot_bytes must be a multiple of 8, >= 64")
        if self.snapshot_mode not in ("barrier", "delta"):
            raise ValueError(
                f"unknown snapshot mode: {self.snapshot_mode}")
        if self.snapshot_compact_every <= 0:
            raise ValueError(
                "snapshot_compact_every must be positive (delta files "
                "per chain before the writer folds a full base)")
        if self.profile_hz < 0:
            raise ValueError("profile_hz must be >= 0 (0 = off)")
        if self.profile_hz > 1000:
            raise ValueError(
                "profile_hz above 1000 would make the sampler itself "
                "the hot path — pick something in 10-250")
        if self.profile_out and not self.profile_hz:
            raise ValueError(
                "--profile-out without --profile-hz writes nothing "
                "(the sampler is off) — set a rate, e.g. "
                "--profile-hz 29")
        if not (-1 <= self.metrics_port <= 65535):
            raise ValueError(
                f"metrics_port out of range: {self.metrics_port} "
                "(0 = off, -1 = ephemeral)")
        if self.metrics_interval_s <= 0:
            raise ValueError("metrics_interval_s must be positive")
        if self.flight_recorder < 0:
            raise ValueError("flight_recorder must be >= 0 (ring size)")
        if not (0.0 <= self.audit_sample <= 1.0):
            raise ValueError(
                f"audit_sample out of range: {self.audit_sample} "
                "(a fraction of the key space, 0 = off, 1 = audit all)")
        if self.slo_fast_s <= 0 or self.slo_slow_s <= 0:
            raise ValueError("SLO windows must be positive")
        if self.slo_fast_s > self.slo_slow_s:
            raise ValueError(
                "slo_fast_s must not exceed slo_slow_s (the slow "
                "window is what rejects single-window spikes)")
        if self.chaos:
            # Parse eagerly: a bad spec must fail at flag time with a
            # grammar message, not mid-run at the first fault roll.
            from attendance_tpu.chaos import ChaosSpec
            ChaosSpec.parse(self.chaos)
        if self.retry_budget_s <= 0:
            raise ValueError("retry_budget_s must be positive")
        if self.fed_shards < 1:
            raise ValueError("fed_shards must be >= 1")
        if not (0 <= self.fed_shard < self.fed_shards):
            raise ValueError(
                f"fed_shard {self.fed_shard} out of range "
                f"[0, {self.fed_shards})")
        if self.fed_heartbeat_s < 0:
            raise ValueError(
                "fed_heartbeat_s must be >= 0 (0 = no heartbeats)")
        if self.fed_dead_after_s <= 0:
            raise ValueError("fed_dead_after_s must be positive")
        if self.fed_worker and not self.fed_gossip_topic:
            raise ValueError(
                "a federated worker needs a fed_gossip_topic")
        if not (-1 <= self.serve_port <= 65535):
            raise ValueError(
                f"serve_port out of range: {self.serve_port} "
                "(0 = off, -1 = ephemeral)")
        if self.query_batch_max < 1:
            raise ValueError("query_batch_max must be >= 1")
        if self.read_staleness_ceiling_s < 0:
            raise ValueError(
                "read_staleness_ceiling_s must be >= 0 (0 = no "
                "staleness objective)")
        if self.fleet_push_interval_s <= 0:
            raise ValueError("fleet_push_interval_s must be positive")
        if not (-1 <= self.fleet_port <= 65535):
            raise ValueError(
                f"fleet_port out of range: {self.fleet_port} "
                "(0 = off, -1 = ephemeral)")
        if self.incident_clear_ticks <= 0:
            raise ValueError("incident_clear_ticks must be positive "
                             "(clear hysteresis)")
        if self.slo:
            # Parse eagerly: an SLO spec with a typo'd stage name used
            # to sit silently in the registry and never fire — reject
            # at config time so neither a human nor the controller can
            # watch a dead objective.
            from attendance_tpu.obs.slo import parse_slo
            for spec in self.slo:
                parse_slo(spec)
        if self.control_dwell_s <= 0:
            raise ValueError("control_dwell_s must be positive "
                             "(per-knob/per-rung dwell minimum)")
        if self.control_clear_ticks <= 0:
            raise ValueError("control_clear_ticks must be positive "
                             "(de-escalation hysteresis)")
        if self.control_flap_limit <= 0:
            raise ValueError("control_flap_limit must be positive "
                             "(transitions per minute cap)")
        if self.control_spill_dir and not self.control_log:
            raise ValueError(
                "control_spill_dir without control_log: the ingress "
                "spill is an actuation target — enable the control "
                "plane (and its actuation log) to use it")
        if self.persist_breaker_failures <= 0:
            raise ValueError("persist_breaker_failures must be positive")
        if self.persist_breaker_cooldown_s <= 0:
            raise ValueError(
                "persist_breaker_cooldown_s must be positive")
        if self.temporal_period_s < 0:
            raise ValueError("temporal_period_s must be >= 0 (0 = off)")
        if self.temporal_period_s:
            from attendance_tpu.temporal.buckets import period_micros
            period_micros(self.temporal_period_s)  # >= 1s, loud
            if self.num_shards * self.num_replicas > 1:
                raise ValueError(
                    "the temporal plane is single-chip only (the "
                    "sharded engine has no bank-recycle path): unset "
                    "--temporal-period-s or run 1 shard x 1 replica")
        if self.allowed_lateness_s < 0:
            raise ValueError("allowed_lateness_s must be >= 0")
        if self.watermark_idle_s < 0:
            raise ValueError(
                "watermark_idle_s must be >= 0 (0 = only end-of-run "
                "flushes advance an idle watermark)")
        if self.temporal_ring_banks < 2:
            raise ValueError("temporal_ring_banks must be >= 2")
        if self.cms_depth < 1 or self.cms_width < 1:
            raise ValueError(
                f"bad CMS geometry {self.cms_depth}x{self.cms_width}")
        if self.cms_topk < 1:
            raise ValueError("cms_topk must be >= 1")
        if self.invalid_topic and self.invalid_topic == self.pulsar_topic:
            # Republishing invalid events onto the processor's own
            # input topic would re-consume and republish them forever.
            raise ValueError(
                "invalid_topic must differ from pulsar_topic (equal "
                "topics make an unbounded reprocessing loop)")
        return self


DEFAULT_CONFIG = Config()


def add_flags(parser: Optional[argparse.ArgumentParser] = None
              ) -> argparse.ArgumentParser:
    """Register framework flags on an argparse parser."""
    p = parser or argparse.ArgumentParser(description="attendance_tpu")
    d = DEFAULT_CONFIG
    p.add_argument("--sketch-backend",
                   choices=["redis", "tpu", "memory", "redis-sim"],
                   default=d.sketch_backend,
                   help="execution backend for BF.*/PFADD/PFCOUNT "
                   "(redis-sim = hermetic simulation of Redis's "
                   "algorithms, the server-free parity oracle)")
    p.add_argument("--transport-backend",
                   choices=["memory", "socket", "pulsar"],
                   default=d.transport_backend,
                   help="socket = the framework's own cross-process "
                   "broker (transport.socket_broker)")
    p.add_argument("--socket-broker", default=d.socket_broker,
                   help="BrokerServer address for "
                   "--transport-backend=socket")
    p.add_argument("--storage-backend",
                   choices=["memory", "columnar", "cassandra"],
                   default=d.storage_backend)
    p.add_argument("--pulsar-host", default=d.pulsar_host)
    p.add_argument("--pulsar-topic", default=d.pulsar_topic)
    p.add_argument("--redis-host", default=d.redis_host)
    p.add_argument("--redis-port", type=int, default=d.redis_port)
    p.add_argument("--cassandra-hosts", default=",".join(d.cassandra_hosts))
    p.add_argument("--cassandra-keyspace", default=d.cassandra_keyspace)
    p.add_argument("--bloom-filter-key", default=d.bloom_filter_key)
    p.add_argument("--bloom-error-rate", type=float,
                   default=d.bloom_filter_error_rate)
    p.add_argument("--bloom-capacity", type=int,
                   default=d.bloom_filter_capacity)
    p.add_argument("--bloom-layout", choices=["flat", "blocked"],
                   default=d.bloom_layout)
    p.add_argument("--hll-key-prefix", default=d.hll_key_prefix)
    p.add_argument("--hll-precision", type=int, default=d.hll_precision)
    p.add_argument("--batch-size", type=int, default=d.batch_size)
    p.add_argument("--batch-timeout-s", type=float, default=d.batch_timeout_s)
    p.add_argument("--ingress-lanes", type=int, default=d.ingress_lanes,
                   help="striped ingress lanes feeding the fused "
                   "pipeline (0 = classic single consumer; N >= 1 "
                   "runs N broker sessions with parallel decode "
                   "workers — 1 is the striped plane at minimum width)")
    p.add_argument("--lane-queue-depth", type=int,
                   default=d.lane_queue_depth,
                   help="decoded blocks buffered per ingress lane "
                   "before the worker backpressures the broker")
    p.add_argument("--lane-decode", choices=["auto", "native", "vector"],
                   default=d.lane_decode,
                   help="lane JSON decode engine (auto = native "
                   "scanner when loadable, else the numpy-vectorized "
                   "batch scanner)")
    p.add_argument("--ingress-wire",
                   choices=["auto", "json", "binary", "columnar",
                            "shm"],
                   default=d.ingress_wire,
                   help="ingress transport/wire: auto sniffs broker "
                   "payloads per frame (json/binary/columnar all "
                   "decode through the codec seam); shm consumes the "
                   "shared-memory ring under --shm-dir instead of a "
                   "broker (co-located zero-copy ingress)")
    p.add_argument("--shm-dir", default=d.shm_dir,
                   help="ring-file directory for --ingress-wire=shm "
                   "(one ring per ingress lane; put it on /dev/shm "
                   "for a memory-backed ring)")
    p.add_argument("--shm-slots", type=int, default=d.shm_slots,
                   help="slots per shm ring (the published-but-"
                   "unacked backpressure window)")
    p.add_argument("--shm-slot-bytes", type=int,
                   default=d.shm_slot_bytes,
                   help="bytes per shm ring slot (must fit one "
                   "planar frame: ~20 B/event x batch-size)")
    p.add_argument("--no-json-chunk-decode", action="store_true",
                   help="classic consumer decodes JSON per message "
                   "again (the pre-ISSUE-11 path; bench before/after "
                   "and bisection only)")
    p.add_argument("--num-shards", type=int, default=d.num_shards)
    p.add_argument("--num-replicas", type=int, default=d.num_replicas)
    p.add_argument("--replica-sync", choices=["step", "query"],
                   default=d.replica_sync,
                   help="HLL replica union cadence: per step, or "
                   "deferred to query/snapshot (DCN-friendly default)")
    p.add_argument("--snapshot-dir", default=d.snapshot_dir)
    p.add_argument("--snapshot-every-batches", type=int,
                   default=d.snapshot_every_batches)
    p.add_argument("--snapshot-mode", choices=["barrier", "delta"],
                   default=d.snapshot_mode,
                   help="delta = incremental dirty-bank snapshots "
                   "chained off a base by an fsync'd manifest, acks "
                   "group-committed per durable delta; barrier = full "
                   "sketch state per snapshot (pre-delta behavior)")
    p.add_argument("--snapshot-compact-every", type=int,
                   default=d.snapshot_compact_every,
                   help="delta files per chain before the background "
                   "writer folds them into a full base snapshot")
    p.add_argument("--wire-format",
                   choices=["auto", "delta", "seg", "word", "bytes"],
                   default=d.wire_format,
                   help="fused-path host->device wire (auto adapts "
                   "word->seg->delta from observed backpressure)")
    p.add_argument("--invalid-topic", default=d.invalid_topic,
                   help="side topic for computed-invalid events (the "
                   "README-promised attendance-invalid DLQ; empty = off)")
    p.add_argument("--max-redeliveries", type=int, default=d.max_redeliveries)
    p.add_argument("--quarantine-dir", default=d.quarantine_dir,
                   help="dead-letter frames into this on-disk "
                   "quarantine before acking (empty = drop); doctor "
                   "lists/replays the entries")
    p.add_argument("--chaos", default=d.chaos,
                   help="deterministic fault-injection spec, e.g. "
                   "'drop=0.01,delay=5ms:0.05,conn_reset=0.002,"
                   "persist_fail=0.01,writer_stall=200ms:0.01,"
                   "corrupt=0.001' ('off' = plane installed, never "
                   "fires; empty = no plane)")
    p.add_argument("--chaos-seed", type=int, default=d.chaos_seed,
                   help="master seed of the per-(site,fault) fault "
                   "streams — replay a failing chaos run from its seed")
    p.add_argument("--serve-port", type=int, default=d.serve_port,
                   help="serve the live query plane (BF.EXISTS/"
                   "PFCOUNT/occupancy/rate from the epoch-pinned "
                   "read mirror) on this binary RPC port "
                   "(0 = off, -1 = ephemeral)")
    p.add_argument("--query-batch-max", type=int,
                   default=d.query_batch_max,
                   help="largest key/day batch one query RPC may "
                   "carry")
    p.add_argument("--read-staleness-ceiling-s", type=float,
                   default=d.read_staleness_ceiling_s,
                   help="SLO ceiling on the published read epoch's "
                   "age (0 = no objective)")
    p.add_argument("--fed-worker", default=d.fed_worker,
                   help="federated worker id; empty = federation off "
                   "(attendance_tpu/federation)")
    p.add_argument("--fed-shard", type=int, default=d.fed_shard,
                   help="hash shard of the key space this worker owns")
    p.add_argument("--fed-shards", type=int, default=d.fed_shards,
                   help="total shards in the federation")
    p.add_argument("--fed-gossip-topic", default=d.fed_gossip_topic,
                   help="broker topic carrying the fence-gossip merge "
                   "frames")
    p.add_argument("--fed-gossip-broker", default=d.fed_gossip_broker,
                   help="socket broker HOST:PORT for gossip (empty = "
                   "ride the configured transport)")
    p.add_argument("--fed-heartbeat-s", type=float,
                   default=d.fed_heartbeat_s,
                   help="gossip heartbeat cadence between fences "
                   "(0 = none)")
    p.add_argument("--fed-dead-after-s", type=float,
                   default=d.fed_dead_after_s,
                   help="silence budget before the aggregator "
                   "declares a peer dead and recovers its shard")
    p.add_argument("--fleet-push", default=d.fleet_push,
                   help="push this process's telemetry (registry "
                   "snapshot + span batches) to a fleet collector at "
                   "HOST:PORT every --fleet-push-interval-s "
                   "(empty = off)")
    p.add_argument("--fleet-role", default=d.fleet_role,
                   help="role label for fleet pushes (default: the "
                   "CLI verb's role, else 'process')")
    p.add_argument("--fleet-instance", default=d.fleet_instance,
                   help="instance label for fleet pushes (default: "
                   "--fed-worker or pid<PID>)")
    p.add_argument("--fleet-push-interval-s", type=float,
                   default=d.fleet_push_interval_s,
                   help="fleet push cadence (seconds)")
    p.add_argument("--fleet-port", type=int, default=d.fleet_port,
                   help="run a fleet collector in this process on "
                   "this TCP port (0 = off, -1 = ephemeral); merged "
                   "views mount under /fleet/* on --metrics-port")
    p.add_argument("--fleet-dir", default=d.fleet_dir,
                   help="persist collected fleet artifacts (per-role "
                   "prom files, stitched trace, status snapshot) "
                   "here — the `doctor --fleet` input")
    p.add_argument("--metric-series-max", type=int,
                   default=d.metric_series_max,
                   help="label-cardinality cap per metric name "
                   "(<= 0 = unlimited); overflow folds into an "
                   "unexported sink and logs once at ERROR")
    p.add_argument("--temporal-period-s", type=float,
                   default=d.temporal_period_s,
                   help="bucket width of the temporal sketch plane in "
                   "seconds (>= 1; 0 = temporal plane off): windowed "
                   "HLL banks per (lecture day, period), watermarked "
                   "reorder, CMS gate-fraud kernel")
    p.add_argument("--allowed-lateness", type=float,
                   default=d.allowed_lateness_s, metavar="SECONDS",
                   dest="allowed_lateness",
                   help="event-time lateness budget: the watermark "
                   "trails the stream head by this much; later events "
                   "fold into still-open buckets or side-channel")
    p.add_argument("--watermark-idle-s", type=float,
                   default=d.watermark_idle_s,
                   help="wall-clock silence after which the watermark "
                   "advances to the stream head (0 = only end-of-run)")
    p.add_argument("--temporal-ring-banks", type=int,
                   default=d.temporal_ring_banks,
                   help="bucket rows the temporal ring retains; "
                   "pressure evicts the oldest CLOSED bucket")
    p.add_argument("--cms-depth", type=int, default=d.cms_depth,
                   help="Count-Min rows (fraud kernel)")
    p.add_argument("--cms-width", type=int, default=d.cms_width,
                   help="Count-Min buckets per row")
    p.add_argument("--cms-topk", type=int, default=d.cms_topk,
                   help="heavy-hitter candidates tracked by the "
                   "fraud kernel")
    p.add_argument("--no-integrity", action="store_true",
                   help="skip payload-digest computation at the "
                   "durable writers (bench baseline; verification "
                   "still runs where digests exist on disk)")
    p.add_argument("--retry-budget-s", type=float,
                   default=d.retry_budget_s,
                   help="total reconnect+retry window per broker RPC "
                   "before BrokerUnavailable")
    p.add_argument("--persist-spill-dir", default=d.persist_spill_dir,
                   help="enable the persist-sink circuit breaker and "
                   "spill degraded writes to fsync'd files here")
    p.add_argument("--persist-breaker-failures", type=int,
                   default=d.persist_breaker_failures,
                   help="consecutive persist failures that open the "
                   "circuit")
    p.add_argument("--persist-breaker-cooldown-s", type=float,
                   default=d.persist_breaker_cooldown_s,
                   help="seconds an open circuit waits before the "
                   "half-open probe")
    p.add_argument("--control-log", default=d.control_log,
                   help="enable the self-driving control plane and "
                   "append its schema'd JSONL actuation log here "
                   "(replay with `doctor --actuations`)")
    p.add_argument("--control-spill-dir", default=d.control_spill_dir,
                   help="shed-rung admission spills raw ingress frames "
                   "durably here and acks them (empty = nack back to "
                   "the broker)")
    p.add_argument("--control-dwell-s", type=float,
                   default=d.control_dwell_s,
                   help="minimum seconds between controller moves on "
                   "the same knob / ladder rung")
    p.add_argument("--control-clear-ticks", type=int,
                   default=d.control_clear_ticks,
                   help="consecutive clean controller ticks before "
                   "de-escalation")
    p.add_argument("--control-flap-limit", type=int,
                   default=d.control_flap_limit,
                   help="max degradation-ladder transitions per "
                   "rolling minute before the controller holds")
    p.add_argument("--profile-dir", default=d.profile_dir,
                   help="write a jax.profiler trace of the run here")
    p.add_argument("--profile-hz", type=float, default=d.profile_hz,
                   help="host sampling-profiler rate (0 = off): "
                   "per-stage self-time attribution, collapsed-stack "
                   "flamegraph + Perfetto stage timeline under "
                   "--profile-out")
    p.add_argument("--profile-out", default=d.profile_out,
                   help="artifact dir for the sampling profiler "
                   "(profile.folded, profile_trace.json, "
                   "attribution.json)")
    p.add_argument("--metrics-json", default=d.metrics_json,
                   help="append one JSON metrics line per run here")
    p.add_argument("--metrics-prom", default=d.metrics_prom,
                   help="append a Prometheus text-exposition block "
                   "per interval to this file (live telemetry)")
    p.add_argument("--metrics-port", type=int, default=d.metrics_port,
                   help="serve GET /metrics on this port "
                   "(0 = off, -1 = ephemeral)")
    p.add_argument("--metrics-interval-s", type=float,
                   default=d.metrics_interval_s,
                   help="reporter cadence for --metrics-prom")
    p.add_argument("--flight-recorder", type=int,
                   default=d.flight_recorder,
                   help="ring size of per-batch flight records "
                   "(0 = off); dumped on SIGUSR1 or run-loop crash")
    p.add_argument("--flight-path", default=d.flight_path,
                   help="JSON dump path for the flight recorder")
    p.add_argument("--trace-out", default=d.trace_out,
                   help="write per-batch spans as Chrome-trace/"
                   "Perfetto JSON here (empty = tracing off)")
    p.add_argument("--audit-sample", type=float, default=d.audit_sample,
                   help="exact-shadow accuracy audit over this hash-"
                   "sampled fraction of the key space (0 = off); "
                   "exports measured FPR / HLL-error gauges")
    p.add_argument("--alert-log", default=d.alert_log,
                   help="enable the SLO burn-rate engine and append "
                   "one JSON line per alert transition here")
    p.add_argument("--slo", action="append", default=None,
                   metavar="SPEC",
                   help="extra/override SLO spec, repeatable (e.g. "
                   "'fpr<=0.01', 'throughput>=1e6', "
                   "'dequeue_p99<=0.05')")
    p.add_argument("--slo-fast-s", type=float, default=d.slo_fast_s,
                   help="fast burn-rate window (seconds)")
    p.add_argument("--slo-slow-s", type=float, default=d.slo_slow_s,
                   help="slow burn-rate window (seconds)")
    p.add_argument("--incident-dir", default=d.incident_dir,
                   help="enable the incident engine and write one "
                   "checksummed evidence bundle per correlated breach "
                   "under this directory (empty = off)")
    p.add_argument("--incident-clear-ticks", type=int,
                   default=d.incident_clear_ticks,
                   help="consecutive clean ticks before an open "
                   "incident clears (hysteresis)")
    return p


def config_from_args(args: argparse.Namespace) -> Config:
    return Config(
        pulsar_host=args.pulsar_host,
        pulsar_topic=args.pulsar_topic,
        redis_host=args.redis_host,
        redis_port=args.redis_port,
        cassandra_hosts=args.cassandra_hosts.split(","),
        cassandra_keyspace=args.cassandra_keyspace,
        bloom_filter_key=args.bloom_filter_key,
        bloom_filter_error_rate=args.bloom_error_rate,
        bloom_filter_capacity=args.bloom_capacity,
        hll_key_prefix=args.hll_key_prefix,
        sketch_backend=args.sketch_backend,
        transport_backend=args.transport_backend,
        storage_backend=args.storage_backend,
        socket_broker=args.socket_broker,
        batch_size=args.batch_size,
        batch_timeout_s=args.batch_timeout_s,
        ingress_lanes=args.ingress_lanes,
        lane_queue_depth=args.lane_queue_depth,
        lane_decode=args.lane_decode,
        ingress_wire=args.ingress_wire,
        shm_dir=args.shm_dir,
        shm_slots=args.shm_slots,
        shm_slot_bytes=args.shm_slot_bytes,
        json_chunk_decode=not args.no_json_chunk_decode,
        bloom_layout=args.bloom_layout,
        hll_precision=args.hll_precision,
        num_shards=args.num_shards,
        num_replicas=args.num_replicas,
        replica_sync=args.replica_sync,
        snapshot_dir=args.snapshot_dir,
        snapshot_every_batches=args.snapshot_every_batches,
        snapshot_mode=args.snapshot_mode,
        snapshot_compact_every=args.snapshot_compact_every,
        wire_format=args.wire_format,
        invalid_topic=args.invalid_topic,
        max_redeliveries=args.max_redeliveries,
        quarantine_dir=args.quarantine_dir,
        chaos=args.chaos,
        chaos_seed=args.chaos_seed,
        fed_worker=args.fed_worker,
        fed_shard=args.fed_shard,
        fed_shards=args.fed_shards,
        fed_gossip_topic=args.fed_gossip_topic,
        fed_gossip_broker=args.fed_gossip_broker,
        fed_heartbeat_s=args.fed_heartbeat_s,
        fed_dead_after_s=args.fed_dead_after_s,
        fleet_push=args.fleet_push,
        fleet_role=args.fleet_role,
        fleet_instance=args.fleet_instance,
        fleet_push_interval_s=args.fleet_push_interval_s,
        fleet_port=args.fleet_port,
        fleet_dir=args.fleet_dir,
        metric_series_max=args.metric_series_max,
        temporal_period_s=args.temporal_period_s,
        allowed_lateness_s=args.allowed_lateness,
        watermark_idle_s=args.watermark_idle_s,
        temporal_ring_banks=args.temporal_ring_banks,
        cms_depth=args.cms_depth,
        cms_width=args.cms_width,
        cms_topk=args.cms_topk,
        integrity=not args.no_integrity,
        retry_budget_s=args.retry_budget_s,
        serve_port=args.serve_port,
        query_batch_max=args.query_batch_max,
        read_staleness_ceiling_s=args.read_staleness_ceiling_s,
        persist_spill_dir=args.persist_spill_dir,
        persist_breaker_failures=args.persist_breaker_failures,
        persist_breaker_cooldown_s=args.persist_breaker_cooldown_s,
        control_log=args.control_log,
        control_spill_dir=args.control_spill_dir,
        control_dwell_s=args.control_dwell_s,
        control_clear_ticks=args.control_clear_ticks,
        control_flap_limit=args.control_flap_limit,
        profile_dir=args.profile_dir,
        profile_hz=args.profile_hz,
        profile_out=args.profile_out,
        metrics_json=args.metrics_json,
        metrics_prom=args.metrics_prom,
        metrics_port=args.metrics_port,
        metrics_interval_s=args.metrics_interval_s,
        flight_recorder=args.flight_recorder,
        flight_path=args.flight_path,
        trace_out=args.trace_out,
        audit_sample=args.audit_sample,
        alert_log=args.alert_log,
        slo=list(args.slo or []),
        slo_fast_s=args.slo_fast_s,
        slo_slow_s=args.slo_slow_s,
        incident_dir=args.incident_dir,
        incident_clear_ticks=args.incident_clear_ticks,
    ).validate()
