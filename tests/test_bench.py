"""Smoke coverage for the benchmark rig (bench.py).

bench.py is the driver's per-round artifact: if any mode crashes, the
round records nothing. These tests run every bench function at toy
sizes on the hermetic CPU backend — they assert structure and sanity,
never performance (CPU numbers are meaningless; the real numbers come
from the driver's solo run on the chip).
"""

import json
import sys
from pathlib import Path

import numpy as np
import pytest

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))
import bench  # noqa: E402


def test_fused_step_smoke():
    r = bench.bench_fused_step(batch_size=2048, seconds=0.2,
                               capacity=10_000, num_banks=8,
                               layout="blocked")
    assert r["events_per_sec"] > 0
    assert r["steps"] >= 1


def test_bloom_smoke():
    r = bench.bench_bloom(batch_size=2048, seconds=0.2,
                          capacity=10_000, layout="blocked")
    assert r["events_per_sec"] > 0
    assert r["insert_keys_per_sec"] > 0


def test_hll_smoke():
    r = bench.bench_hll(batch_size=2048, seconds=0.2, num_banks=8)
    assert r["events_per_sec"] > 0
    assert r["num_banks"] == 8


def test_e2e_smoke():
    r = bench.bench_e2e(batch_size=2048, seconds=0.2, capacity=10_000,
                        num_banks=8)
    assert r["events_per_sec"] > 0
    assert r["events"] >= 2048
    assert r["wire"] in ("word", "seg", "delta", "bytes", "arrays")
    assert len(r["rates"]) == 5


def test_json_smoke():
    r = bench.bench_json(seconds=0.2, capacity=10_000, num_banks=8,
                         bridge_batch=1024)
    assert r["events_per_sec"] > 0
    assert r["bridge_events_per_sec"] > 0
    assert r["fused_events_per_sec"] > 0
    assert r["events"] % 1024 == 0


def test_sharded_step_smoke():
    r = bench.bench_sharded_step(batch_size=1024, seconds=0.2,
                                 capacity=10_000, num_banks=8)
    assert r["events_per_sec"] > 0


def test_wires_smoke():
    r = bench.bench_wires(seconds=0.2, capacity=10_000, num_banks=8,
                          frame_size=2048)
    per = r["per_wire_events_per_sec"]
    assert set(per) == {"word", "seg", "delta"}
    assert all(v > 0 for v in per.values())
    assert r["link_bytes_per_sec"] > 0


def test_main_emits_one_json_line(capsys, monkeypatch):
    """The driver contract: ONE parseable JSON line with the headline
    metric/value/unit/vs_baseline fields plus the json-ingress extra."""
    monkeypatch.setattr(
        sys, "argv",
        ["bench.py", "--seconds", "0.2", "--capacity", "10000",
         "--num-banks", "8", "--batch-size", "2048",
         "--e2e-batch-size", "2048"])
    bench.main()
    out = capsys.readouterr().out.strip().splitlines()
    line = json.loads(out[-1])
    assert line["metric"] == "e2e_pipeline_throughput"
    assert line["unit"] == "events/sec"
    assert line["value"] > 0
    assert "vs_baseline" in line
    assert "kernel_events_per_sec" in line
    assert "json_ingress_events_per_sec" in line


def test_vs_baseline_share():
    """vs_baseline compares to this run's fair share of the 8-chip
    target: with n local devices, the denominator is 50M * n/8."""
    import jax

    n = max(1, len(jax.devices()))
    expect = 1.0 / (bench.NORTH_STAR_EVENTS_PER_SEC
                    * min(n, bench.TARGET_CHIPS) / bench.TARGET_CHIPS)
    assert bench._vs_baseline(1.0) == pytest.approx(expect)
