"""Smoke coverage for the benchmark rig (bench.py).

bench.py is the driver's per-round artifact: if any mode crashes, the
round records nothing. These tests run every bench function at toy
sizes on the hermetic CPU backend — they assert structure and sanity,
never performance (CPU numbers are meaningless; the real numbers come
from the driver's solo run on the chip).
"""

import json
import sys
from pathlib import Path

import numpy as np
import pytest

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))
import bench  # noqa: E402


def test_fused_step_smoke():
    r = bench.bench_fused_step(batch_size=2048, seconds=0.2,
                               capacity=10_000, num_banks=8,
                               layout="blocked")
    assert r["events_per_sec"] > 0
    assert r["steps"] >= 1


def test_bloom_smoke():
    r = bench.bench_bloom(batch_size=2048, seconds=0.2,
                          capacity=10_000, layout="blocked")
    assert r["events_per_sec"] > 0
    assert r["insert_keys_per_sec"] > 0


def test_hll_smoke():
    r = bench.bench_hll(batch_size=2048, seconds=0.2, num_banks=8)
    assert r["events_per_sec"] > 0
    assert r["num_banks"] == 8


def test_e2e_smoke():
    r = bench.bench_e2e(batch_size=2048, seconds=0.2, capacity=10_000,
                        num_banks=8)
    assert r["events_per_sec"] > 0
    assert r["events"] >= 2048
    assert r["wire"] in ("word", "seg", "delta", "bytes", "arrays")
    # Converge-then-measure: between CONVERGE_TAIL and the cap, with
    # per-pass attribution recorded alongside.
    assert bench.CONVERGE_TAIL <= len(r["rates"]) <= \
        bench.CONVERGE_MAX_PASSES
    assert len(r["pass_walls_s"]) == len(r["rates"])
    assert len(r["pass_load1"]) == len(r["rates"])
    assert isinstance(r["converged"], bool)
    assert r["tail_spread"] >= 1.0


def test_e2e_snapshot_smoke(tmp_path):
    """Checkpointing at rate: snapshots actually fire during the
    measured passes and their stalls are recorded."""
    r = bench.bench_e2e(batch_size=1024, seconds=0.2, capacity=10_000,
                        num_banks=8, snapshot_dir=str(tmp_path),
                        snapshot_every=2, max_passes=3)
    assert r["events_per_sec"] > 0
    assert r["snapshots_taken"] >= 1
    assert r["snapshot_stall_s"] > 0
    assert r["snapshot_stall_max_s"] >= r["snapshot_stall_s"]
    from attendance_tpu.pipeline.fast_path import (
        EVENTS_SEGMENTS, SKETCH_SNAPSHOT)
    assert (tmp_path / SKETCH_SNAPSHOT).exists()
    assert list((tmp_path / EVENTS_SEGMENTS).glob("segment-*.npz"))


def test_socket_smoke():
    # strict=False: real bench runs hard-fail on a non-converged row
    # (ISSUE 6 satellite); this smoke's windows are far too short to
    # converge on a noisy host and only checks the plumbing.
    r = bench.bench_socket(batch_size=1024, seconds=0.2,
                           capacity=10_000, num_banks=8, strict=False)
    assert r["events_per_sec"] > 0
    assert r["events"] >= 1024
    assert ":" in r["broker_address"]
    # Striped-lane columns ride the same broker (ISSUE 6 tentpole).
    assert r["ingress_lanes"] == 4
    assert r["striped_events_per_sec"] > 0
    assert r["striped_json_events_per_sec"] > 0
    assert sum(r["lane_event_totals"]) > 0
    # The JSON bridge lane rides the same TCP broker (VERDICT r04 #4).
    assert r["json_events_per_sec"] > 0
    assert r["json_events"] > 0
    # ISSUE 11 columns: direct-JSON before/after, the COLW columnar
    # wire (with its measured-bytes honesty column), and the
    # co-located shm ring.
    assert r["json_direct_events_per_sec"] > 0
    assert r["json_direct_permsg_events_per_sec"] > 0
    assert r["colw_events_per_sec"] > 0
    assert 0 < r["colw_bytes_per_event"] <= 8.0
    assert r["colw_bytes_gate_pass"]
    assert r["shm_events_per_sec"] > 0
    assert isinstance(r["shm_gate"], str)
    assert isinstance(r["colw_gate"], str)


def test_roster10m_tpu_smoke():
    """The real-chip 10M mode at toy capacity: structure + acceptance
    fields (the 10M run itself is a driver/round artifact)."""
    r = bench.bench_roster10m_tpu(batch_size=1024, seconds=0.2,
                                  capacity=50_000)
    assert r["events_per_sec"] > 0
    assert r["false_negatives_of_100k"] == 0
    assert r["fpr_of_100k_disjoint"] <= 0.02
    assert 0 < r["fill_fraction"] < 1
    assert r["preload_keys_per_sec"] > 0


def test_json_smoke():
    r = bench.bench_json(seconds=0.2, capacity=10_000, num_banks=8,
                         bridge_batch=1024)
    assert r["events_per_sec"] > 0
    assert r["bridge_events_per_sec"] > 0
    assert r["fused_events_per_sec"] > 0
    assert r["events"] % 1024 == 0
    assert r["scanner"] in ("python", "c-list", "c-buffer")


def test_sharded_step_smoke():
    r = bench.bench_sharded_step(batch_size=1024, seconds=0.2,
                                 capacity=10_000, num_banks=8)
    assert r["events_per_sec"] > 0
    # Honest-artifact marker (VERDICT r04 weak #3): the artifact itself
    # must say the number measures the degenerate-mesh build.
    assert r["degenerate_mesh"] is True
    assert "unusable" in r["partitioned_executables"]


def test_wires_smoke():
    r = bench.bench_wires(seconds=0.2, capacity=10_000, num_banks=8,
                          frame_size=2048)
    per = r["per_wire_events_per_sec"]
    assert set(per) == {"word", "seg", "delta"}
    assert all(v > 0 for v in per.values())
    assert r["link_bytes_per_sec"] > 0


def test_main_emits_one_json_line(capsys, monkeypatch):
    """The driver contract: ONE parseable JSON line with the headline
    metric/value/unit/vs_baseline fields plus the json-ingress extra."""
    monkeypatch.setattr(
        sys, "argv",
        ["bench.py", "--seconds", "0.2", "--capacity", "10000",
         "--num-banks", "8", "--batch-size", "2048",
         "--e2e-batch-size", "2048",
         # Smoke windows are too short to converge on a busy host;
         # artifact runs keep the loud failure (ISSUE 6 satellite).
         "--no-strict-convergence"])
    bench.main()
    out = capsys.readouterr().out.strip().splitlines()
    line = json.loads(out[-1])
    assert line["metric"] == "e2e_pipeline_throughput"
    assert line["unit"] == "events/sec"
    assert line["value"] > 0
    assert "vs_baseline" in line
    assert "kernel_events_per_sec" in line
    assert "json_ingress_events_per_sec" in line
    # r05 self-attribution fields: per-section link probes, converged
    # flags, the socket lane, and checkpointing-at-rate.
    assert set(line["link_bytes_per_sec"]) == \
        {"e2e", "kernel", "json", "socket", "snapshot"}
    # Probes must have run isolated (subprocess) — the in-process
    # fallback poisons the sections measured after it.
    assert line["link_probes_isolated"] is True
    assert isinstance(line["e2e_converged"], bool)
    assert line["socket_events_per_sec"] > 0
    assert line["e2e_snapshot_events_per_sec"] > 0
    assert line["snapshots_taken"] >= 1


def test_vs_baseline_share():
    """vs_baseline compares to this run's fair share of the 8-chip
    target: with n local devices, the denominator is 50M * n/8."""
    import jax

    n = max(1, len(jax.devices()))
    expect = 1.0 / (bench.NORTH_STAR_EVENTS_PER_SEC
                    * min(n, bench.TARGET_CHIPS) / bench.TARGET_CHIPS)
    assert bench._vs_baseline(1.0) == pytest.approx(expect)
