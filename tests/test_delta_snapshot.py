"""Crash consistency of the incremental (base+delta) snapshot chain.

The durability point of a delta snapshot is the fsync'd CHAIN.json /
MANIFEST.json rename — a crash BETWEEN the delta file write and that
rename must leave a restorable directory whose state equals the last
COMPLETE manifest, with every acked event still present (frames of the
orphaned delta were never acked, so the broker redelivers them and the
idempotent sinks absorb the replay). Covered for the fused pipeline
(tpu-path state) and the generic SketchStore chain (memory + tpu
backends), plus chain compaction and bank growth across a delta
boundary.
"""

import json

import numpy as np
import pytest

from attendance_tpu.config import Config
from attendance_tpu.pipeline.fast_path import CHAIN_MANIFEST, FusedPipeline
from attendance_tpu.pipeline.loadgen import generate_frames
from attendance_tpu.transport.memory_broker import MemoryBroker, MemoryClient

NUM_EVENTS, BATCH = 16_384, 2_048


def _mkframes(seed=61):
    return generate_frames(NUM_EVENTS, BATCH, roster_size=6_000,
                           num_lectures=6, invalid_fraction=0.15,
                           seed=seed)


def _mkcfg(snap_dir="", every=2, **kw):
    return Config(bloom_filter_capacity=20_000,
                  transport_backend="memory",
                  snapshot_dir=snap_dir,
                  snapshot_every_batches=every if snap_dir else 0, **kw)


def _state(pipe):
    df = pipe.store.to_dataframe().sort_values(
        ["lecture_day", "micros", "student_id"]).reset_index(drop=True)
    return df, {day: pipe.count(day) for day in pipe.lecture_days()}


def test_orphaned_delta_is_ignored_on_restore(tmp_path):
    """A delta file on disk that no manifest rename ever published is
    exactly what a crash between the two writes leaves behind; restore
    must not apply it (poisoned registers prove it never loads)."""
    roster, frames = _mkframes()
    frames = list(frames)
    snap = tmp_path / "snaps"
    config = _mkcfg(str(snap))
    client = MemoryClient(MemoryBroker())
    pipe = FusedPipeline(config, client=client, num_banks=8)
    pipe.preload(roster)
    producer = client.create_producer(config.pulsar_topic)
    for f in frames:
        producer.send(f)
    pipe.run(max_events=NUM_EVENTS, idle_timeout_s=0.5)
    chain = json.loads((snap / CHAIN_MANIFEST).read_text())
    assert chain["deltas"], "delta mode should write incremental files"
    expect = {day: pipe.count(day) for day in pipe.lecture_days()}

    # Saturated-rank registers for every bank: if restore applied this
    # orphan, every PFCOUNT would explode.
    poison = {
        "bank_idx": np.arange(8, dtype=np.int32),
        "regs_rows": np.full((8, 1 << 14), 31, np.uint8),
        "counts": np.zeros((2, 2), np.uint32),
        "manifest": np.frombuffer(json.dumps(
            {"bank_of": {str(d): b for d, b in pipe._bank_of.items()},
             "events": 10 ** 9, "num_banks": 8}).encode(), np.uint8),
    }
    with open(snap / "delta-9999.npz", "wb") as f:
        np.savez(f, **poison)

    pipe2 = FusedPipeline(config, client=MemoryClient(MemoryBroker()),
                          num_banks=8)
    assert {day: pipe2.count(day) for day in pipe2.lecture_days()} \
        == expect
    assert tuple(pipe2.validity_counts()) == \
        tuple(pipe.validity_counts())
    # ... and the next barrier's sequence number skips past the orphan
    # instead of overwriting it.
    assert pipe2._delta_seq == 9999


def test_writer_crash_before_manifest_rename_loses_nothing(tmp_path):
    """Kill the writer between the delta file and the manifest rename:
    the restored pipeline equals the last COMPLETE manifest, and
    draining the redelivered (never-acked) frames lands exactly on the
    uninterrupted oracle — no acked event lost, no event double-counted."""
    roster, frames = _mkframes(seed=67)
    frames = list(frames)

    client = MemoryClient(MemoryBroker())
    ref = FusedPipeline(_mkcfg(), client=client, num_banks=8)
    ref.preload(roster)
    producer = client.create_producer(ref.config.pulsar_topic)
    for f in frames:
        producer.send(f)
    ref.run(max_events=NUM_EVENTS, idle_timeout_s=0.5)
    ref_df, ref_counts = _state(ref)

    snap = tmp_path / "snaps"
    config = _mkcfg(str(snap))
    broker = MemoryBroker()
    a = FusedPipeline(config, client=MemoryClient(broker), num_banks=8)
    calls = {"n": 0}
    orig = a._write_chain_manifest

    def crashing_manifest():
        calls["n"] += 1
        if calls["n"] >= 3:  # base + 1 delta survive; then "power cut"
            raise OSError("simulated crash before manifest rename")
        orig()

    a._write_chain_manifest = crashing_manifest
    a.preload(roster)
    producer = a.client.create_producer(config.pulsar_topic)
    for f in frames:
        producer.send(f)
    a.run(max_events=NUM_EVENTS, idle_timeout_s=0.5)
    a.consumer.close()  # crash: every unacked frame redelivers

    # On disk: the chain ends at the last complete manifest; at least
    # one orphaned delta file exists past it.
    chain = json.loads((snap / CHAIN_MANIFEST).read_text())
    on_disk = {p.name for p in snap.glob("delta-*.npz")}
    assert set(chain["deltas"]) < on_disk

    b = FusedPipeline(config, client=MemoryClient(broker), num_banks=8)
    # The restored sketch equals the last complete manifest exactly:
    # its counters add up to the events that barrier covered.
    if chain["deltas"]:
        with np.load(snap / chain["deltas"][-1]) as d:
            events_at = json.loads(
                bytes(d["manifest"]).decode())["events"]
    else:
        with np.load(snap / chain["base"]) as d:
            events_at = json.loads(
                bytes(d["manifest"]).decode())["events"]
    v, i = b.validity_counts()
    assert v + i == events_at
    assert events_at < NUM_EVENTS  # the crash genuinely cut the run

    b.run(idle_timeout_s=0.5)
    assert b.consumer.backlog() == 0
    got_df, got_counts = _state(b)
    assert got_counts == ref_counts
    assert len(got_df) == len(ref_df)
    for col in ("student_id", "lecture_day", "micros", "is_valid"):
        np.testing.assert_array_equal(got_df[col].to_numpy(),
                                      ref_df[col].to_numpy())


def test_failed_base_write_fails_queued_deltas_and_self_heals(tmp_path):
    """A failed BASE write must also fail any delta already staged
    behind it (never chain a delta onto a stale on-disk base and ack
    its frames); the next barrier writes a fresh base and the run
    self-heals — a final restore equals the uninterrupted oracle."""
    roster, frames = _mkframes(seed=79)
    frames = list(frames)

    client = MemoryClient(MemoryBroker())
    ref = FusedPipeline(_mkcfg(), client=client, num_banks=8)
    ref.preload(roster)
    producer = client.create_producer(ref.config.pulsar_topic)
    for f in frames:
        producer.send(f)
    ref.run(max_events=NUM_EVENTS, idle_timeout_s=0.5)
    ref_df, ref_counts = _state(ref)

    snap = tmp_path / "snaps"
    config = _mkcfg(str(snap))
    broker = MemoryBroker()
    a = FusedPipeline(config, client=MemoryClient(broker), num_banks=8)
    orig = a._write_snapshot_files
    calls = {"n": 0}

    def failing_base(*args, **kwargs):
        calls["n"] += 1
        if calls["n"] == 1:  # the run's FIRST base write dies
            raise OSError("simulated base write failure")
        return orig(*args, **kwargs)

    a._write_snapshot_files = failing_base
    a.preload(roster)
    producer = a.client.create_producer(config.pulsar_topic)
    for f in frames:
        producer.send(f)
    a.run(max_events=NUM_EVENTS, idle_timeout_s=0.5)
    assert calls["n"] >= 2  # the writer retried a full base
    a.consumer.close()  # requeue whatever never became durable

    b = FusedPipeline(config, client=MemoryClient(broker), num_banks=8)
    b.run(idle_timeout_s=0.5)
    assert b.consumer.backlog() == 0
    got_df, got_counts = _state(b)
    assert got_counts == ref_counts
    assert len(got_df) == len(ref_df)


def test_chain_compaction_folds_into_base(tmp_path):
    """Every snapshot_compact_every deltas the writer folds the chain
    into a fresh full base and deletes the superseded files; restore
    from the compacted dir equals the live pipeline."""
    roster, frames = _mkframes(seed=71)
    frames = list(frames)
    snap = tmp_path / "snaps"
    config = _mkcfg(str(snap), every=1, snapshot_compact_every=3)
    client = MemoryClient(MemoryBroker())
    pipe = FusedPipeline(config, client=client, num_banks=8)
    pipe.preload(roster)
    producer = client.create_producer(config.pulsar_topic)
    for f in frames:
        # One frame per run: every run-end barrier flushes, so exactly
        # one durable write per frame (deterministic chain growth).
        producer.send(f)
        pipe.run(max_events=BATCH, idle_timeout_s=0.3)
    chain = json.loads((snap / CHAIN_MANIFEST).read_text())
    assert pipe._delta_seq >= 3  # enough deltas to trigger a fold
    assert len(chain["deltas"]) < 3  # ... and the fold happened
    # Superseded files are gone: disk holds exactly the live chain.
    assert {p.name for p in snap.glob("delta-*.npz")} \
        == set(chain["deltas"])

    pipe2 = FusedPipeline(config, client=MemoryClient(MemoryBroker()),
                          num_banks=8)
    a_df, a_counts = _state(pipe)
    b_df, b_counts = _state(pipe2)
    assert a_counts == b_counts
    assert len(a_df) == len(b_df)
    assert tuple(pipe2.validity_counts()) == \
        tuple(pipe.validity_counts())


def test_stale_deltas_after_base_replace_crash_are_skipped(tmp_path):
    """The one crash window the in-place base replace opens: a new
    fused_sketch.npz lands but the crash hits before CHAIN.json is
    reset, so the manifest still names deltas OLDER than the base.
    Restore must skip them (their events counter is <= the base's) —
    applying them would regress registers and shear the bank map off
    the register banks."""
    roster, frames = _mkframes(seed=83)
    frames = list(frames)
    snap = tmp_path / "snaps"
    config = _mkcfg(str(snap))
    client = MemoryClient(MemoryBroker())
    pipe = FusedPipeline(config, client=client, num_banks=8)
    pipe.preload(roster)
    producer = client.create_producer(config.pulsar_topic)
    for f in frames:
        producer.send(f)
    pipe.run(max_events=NUM_EVENTS, idle_timeout_s=0.5)
    assert json.loads((snap / CHAIN_MANIFEST).read_text())["deltas"]
    expect_counts = {d: pipe.count(d) for d in pipe.lecture_days()}
    expect_vc = tuple(pipe.validity_counts())

    # Full snapshot whose manifest reset "crashes": the base file is
    # replaced, the old delta list survives on disk.
    def crash(*a, **kw):
        raise OSError("simulated crash before chain-manifest reset")

    pipe._write_chain_manifest = crash
    with pytest.raises(OSError):
        pipe.snapshot()
    assert json.loads((snap / CHAIN_MANIFEST).read_text())["deltas"]

    pipe2 = FusedPipeline(config, client=MemoryClient(MemoryBroker()),
                          num_banks=8)
    assert pipe2._snap_chain == []  # stale entries dropped
    assert {d: pipe2.count(d) for d in pipe2.lecture_days()} \
        == expect_counts
    assert tuple(pipe2.validity_counts()) == expect_vc


def test_delta_restores_across_bank_growth(tmp_path):
    """Bank growth between two barriers rides the delta (num_banks in
    its manifest): restore grows the register array before applying
    rows instead of dropping high banks."""
    from attendance_tpu.pipeline.events import encode_planar_batch

    config = Config(bloom_filter_capacity=4_096,
                    snapshot_dir=str(tmp_path / "snap"),
                    snapshot_every_batches=1)
    client = MemoryClient(MemoryBroker())
    a = FusedPipeline(config, client=client, num_banks=4)
    roster = np.arange(10_000, 12_000, dtype=np.uint32)
    a.preload(roster)
    producer = client.create_producer(config.pulsar_topic)

    def frame(days):
        n = len(days)
        cols = {
            "student_id": np.resize(roster[:4], n).astype(np.uint32),
            "lecture_day": np.asarray(days, np.uint32),
            "micros": 1_000_000 + np.arange(n, dtype=np.int64),
            "is_valid": np.ones(n, bool),
            "event_type": np.zeros(n, np.int8),
        }
        return encode_planar_batch(cols)

    producer.send(frame([20260101, 20260102]))
    a.run(max_events=2, idle_timeout_s=0.2)  # barrier -> full base
    days2 = [20260110 + i for i in range(12)]  # growth: 4 -> 16 banks
    producer.send(frame(days2))
    a.run(max_events=12, idle_timeout_s=0.2)  # barrier -> delta
    a.cleanup()
    counts = {d: a.count(d) for d in a.lecture_days()}
    assert a.state.hll_regs.shape[0] > 4

    b = FusedPipeline(config, client=MemoryClient(MemoryBroker()),
                      num_banks=4)
    assert b.state.hll_regs.shape[0] >= a.state.hll_regs.shape[0] or \
        b.state.hll_regs.shape[0] > 4
    assert {d: b.count(d) for d in b.lecture_days()} == counts


@pytest.mark.parametrize("backend", ["memory", "tpu"])
def test_store_chain_crash_consistency_and_health_gauges(
        tmp_path, backend, monkeypatch):
    """Generic SketchStore chain: crash between the delta file and the
    manifest rename restores to the last complete manifest (memory AND
    tpu backends), and the restored store still reports its health
    gauges at scrape time (restore-then-scrape, PR 3 contract)."""
    import attendance_tpu.utils.snapshot as snap_mod
    from attendance_tpu import obs
    from attendance_tpu.sketch import make_sketch_store
    from attendance_tpu.utils.snapshot import (
        restore_sketch_store, snapshot_sketch_store_chain)

    obs.disable()
    cfg = Config(sketch_backend=backend, metrics_port=-1)
    t = obs.enable(cfg)
    try:
        store = make_sketch_store(cfg)
        store.bf_add_many(cfg.bloom_filter_key,
                          np.arange(2_000, dtype=np.int64))
        key = f"{cfg.hll_key_prefix}LECTURE_1"
        store.pfadd_many(key, np.arange(1_000, dtype=np.int64))
        chain_dir = tmp_path / "chain"
        snapshot_sketch_store_chain(store, chain_dir)  # base
        store.pfadd_many(key, np.arange(1_000, 1_500, dtype=np.int64))
        snapshot_sketch_store_chain(store, chain_dir)  # durable delta
        count_at_manifest = store.pfcount(key)

        store.pfadd_many(key, np.arange(1_500, 4_000, dtype=np.int64))
        real = snap_mod.write_manifest_atomic

        def boom(dir_path, doc, name=snap_mod.CHAIN_MANIFEST):
            raise OSError("simulated crash before manifest rename")

        monkeypatch.setattr(snap_mod, "write_manifest_atomic", boom)
        with pytest.raises(OSError):
            snapshot_sketch_store_chain(store, chain_dir)
        monkeypatch.setattr(snap_mod, "write_manifest_atomic", real)

        # The orphaned delta file exists but the manifest never named
        # it: restore lands on the last complete manifest.
        manifest = json.loads(
            (chain_dir / "MANIFEST.json").read_text())
        assert {p.name for p in chain_dir.glob("delta-*.npz")} \
            > set(manifest["deltas"])
        restored = make_sketch_store(cfg)
        restore_sketch_store(restored, chain_dir)
        assert restored.pfcount(key) == count_at_manifest
        probe = np.arange(0, 4_000, dtype=np.int64)
        np.testing.assert_array_equal(
            np.asarray(restored.bf_exists_many(cfg.bloom_filter_key,
                                               probe)),
            np.asarray(store.bf_exists_many(cfg.bloom_filter_key,
                                            probe)))

        # Restore-then-scrape: the replaced innards did not strand the
        # weakref'd health gauges.
        del store
        g = t.registry.gauge("attendance_hll_estimate",
                             backend=backend)
        assert g.value > 0
        assert f'attendance_bloom_fill_fraction{{backend="{backend}"}}' \
            in t.render()
    finally:
        obs.disable()


def test_reader_vs_compactor_interleaving(tmp_path):
    """Merge-on-read under churn (ISSUE 7 satellite): a separate
    reader hammering chain reloads while the ingest writer appends
    deltas AND periodically compacts the chain into a fresh base must
    always observe a WHOLE published epoch — reloads never fail, the
    served event count never regresses (group-commit order), and the
    final reload equals the writer's own final state. The vanished-
    delta race (manifest read -> compaction GC -> file open) is
    absorbed by the reader's retry (see the next test for the
    deterministic version)."""
    import threading
    import time

    from attendance_tpu.serve.chain import ChainEpochSource
    from attendance_tpu.serve.engine import QueryEngine

    roster, frames = _mkframes(seed=71)
    frames = list(frames)
    snap = tmp_path / "snaps"
    config = _mkcfg(str(snap), every=1, snapshot_compact_every=3)
    client = MemoryClient(MemoryBroker())
    pipe = FusedPipeline(config, client=client, num_banks=8)
    pipe.preload(roster)
    producer = client.create_producer(config.pulsar_topic)
    producer.send(frames[0])
    pipe.run(max_events=BATCH, idle_timeout_s=0.5)  # base on disk

    src = ChainEpochSource(str(snap))
    stop = threading.Event()
    events_seen, errors = [], []

    def reader() -> None:
        try:
            while not stop.is_set():
                if src.reload():
                    events_seen.append(src.pin().events)
        except Exception as exc:  # noqa: BLE001 - the assertion
            errors.append(repr(exc))

    t = threading.Thread(target=reader, daemon=True)
    t.start()
    for f in frames[1:]:
        producer.send(f)
    # every=1 + compact_every=3: multiple compaction folds (base
    # rewrite + delta GC) land WHILE the reader reloads.
    pipe.run(max_events=NUM_EVENTS, idle_timeout_s=0.5)
    time.sleep(0.2)
    stop.set()
    t.join(timeout=30.0)
    assert not errors, f"reader failed mid-compaction: {errors[:2]}"
    assert events_seen, "reader never observed a republished chain"
    assert events_seen == sorted(events_seen), \
        "served event count regressed across reloads"
    src.reload()
    final = QueryEngine(src).occupancy()
    assert final == {day: pipe.count(day) for day in pipe.lecture_days()}
    assert src.pin().events == NUM_EVENTS
    pipe.cleanup()


def test_reader_retries_vanished_delta(tmp_path, monkeypatch):
    """Deterministic half of the reader-vs-compactor race: the FIRST
    chain read observes a manifest whose named delta was GC'd by a
    concurrent compaction (ValueError from the loader); the reader
    must re-read the fresh manifest and serve the new epoch — never
    propagate the transient error, never serve a mix."""
    import attendance_tpu.pipeline.fast_path as fp
    from attendance_tpu.serve.chain import ChainEpochSource

    roster, frames = _mkframes(seed=73)
    snap = tmp_path / "snaps"
    config = _mkcfg(str(snap))
    client = MemoryClient(MemoryBroker())
    pipe = FusedPipeline(config, client=client, num_banks=8)
    pipe.preload(roster)
    producer = client.create_producer(config.pulsar_topic)
    for f in frames:
        producer.send(f)
    pipe.run(max_events=NUM_EVENTS, idle_timeout_s=0.5)
    expect = {day: pipe.count(day) for day in pipe.lecture_days()}
    pipe.cleanup()

    real = fp.read_chain_state
    calls = []

    def flaky(*args, **kwargs):
        calls.append(1)
        if len(calls) == 1:
            raise ValueError(
                "chain manifest names delta-0042.npz but the delta "
                "file is missing — snapshot directory is corrupt")
        return real(*args, **kwargs)

    monkeypatch.setattr(fp, "read_chain_state", flaky)
    src = ChainEpochSource(str(snap))
    assert len(calls) >= 2, "reader did not retry the vanished delta"
    from attendance_tpu.serve.engine import QueryEngine
    assert QueryEngine(src).occupancy() == expect


def test_reader_fails_loudly_on_corrupt_chain(tmp_path):
    """A PERMANENTLY missing manifest-named delta (REAL corruption, not
    the transient compaction race) must surface as a classified
    RuntimeError at construction — a reader with no prior epoch has
    nothing safe to serve (a reader WITH one keeps serving it; see
    tests/test_integrity.py)."""
    from attendance_tpu.serve.chain import ChainEpochSource

    roster, frames = _mkframes(seed=77)
    snap = tmp_path / "snaps"
    config = _mkcfg(str(snap))
    client = MemoryClient(MemoryBroker())
    pipe = FusedPipeline(config, client=client, num_banks=8)
    pipe.preload(roster)
    producer = client.create_producer(config.pulsar_topic)
    for f in frames:
        producer.send(f)
    pipe.run(max_events=NUM_EVENTS, idle_timeout_s=0.5)
    pipe.cleanup()
    chain = json.loads((snap / CHAIN_MANIFEST).read_text())
    assert chain["deltas"]
    (snap / chain["deltas"][0]).unlink()  # permanent corruption
    with pytest.raises(RuntimeError, match="corrupt"):
        ChainEpochSource(str(snap))
