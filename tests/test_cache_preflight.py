"""Bad-`.jax_cache` preflight tests (utils/cache.py, ISSUE 9).

The persistent XLA cache on this 9p filesystem has a documented
corruption mode after concurrent or crashed writers (halved device
counters / numpy segfaults; `rm -rf .jax_cache` folklore). The
preflight replaces the folklore: every writer claims the dir with a
bust-key file, and a claimant finding the dir on 9p with a STALE
(unreleased, other-session) key clears it with a logged note. These
tests drive every verdict; the 9p probe is monkeypatched so they are
hermetic on any filesystem.
"""

import json
import os

import pytest

from attendance_tpu.utils import cache as cache_mod
from attendance_tpu.utils.cache import (
    KEY_FILE, _release_claims, preflight_cache)


@pytest.fixture(autouse=True)
def _on_9p(monkeypatch):
    """Pretend every path is on 9p (the corruption precondition);
    individual tests override to False to prove the guard is scoped."""
    monkeypatch.setattr(cache_mod, "_on_9p", lambda p: True)


def _key(cache_dir) -> dict:
    return json.loads((cache_dir / KEY_FILE).read_text())


def test_fresh_dir_is_claimed(tmp_path):
    cache = tmp_path / ".jax_cache"
    assert preflight_cache(cache) == "fresh"
    key = _key(cache)
    assert key["pid"] == os.getpid() and not key["released"]


def test_same_session_reclaim_keeps_entries(tmp_path):
    cache = tmp_path / ".jax_cache"
    preflight_cache(cache)
    (cache / "entry.bin").write_bytes(b"compiled")
    # A child of the claiming run (bench helper modes, spawned
    # workers) shares the session env var and must NOT clear.
    assert preflight_cache(cache) == "kept"
    assert (cache / "entry.bin").exists()


def test_live_same_session_parent_claim_is_not_overwritten(
        tmp_path, monkeypatch):
    """A child process of the claiming run (bench spawning helper
    subprocesses) must NOT overwrite the parent's LIVE claim: doing so
    would mark the key released at the CHILD's exit while the parent
    still writes, hiding the concurrent-writer precondition from other
    sessions."""
    cache = tmp_path / ".jax_cache"
    cache.mkdir()
    session = os.environ.get(cache_mod._SESSION_ENV) or "sess-x"
    monkeypatch.setenv(cache_mod._SESSION_ENV, session)
    parent_pid = os.getppid()  # a live pid that is not ours
    (cache / KEY_FILE).write_text(json.dumps(
        {"pid": parent_pid, "session": session, "t0": 1.0,
         "released": False}))
    assert preflight_cache(cache) == "kept"
    key = _key(cache)
    assert key["pid"] == parent_pid  # untouched — the parent owns it
    assert not key["released"]


def test_released_key_keeps_entries(tmp_path):
    """A clean prior exit released its claim: the next run (another
    session) trusts the entries — warm caches survive sequential
    runs."""
    cache = tmp_path / ".jax_cache"
    cache.mkdir()
    (cache / "entry.bin").write_bytes(b"compiled")
    (cache / KEY_FILE).write_text(json.dumps(
        {"pid": 999999, "session": "other-session", "t0": 1.0,
         "released": True}))
    assert preflight_cache(cache) == "kept"
    assert (cache / "entry.bin").exists()


def test_pre_bustkey_dir_is_adopted(tmp_path):
    """A dir with no key (CI-restored cache from before this check):
    kept — unknown history is not the documented precondition."""
    cache = tmp_path / ".jax_cache"
    cache.mkdir()
    (cache / "entry.bin").write_bytes(b"compiled")
    assert preflight_cache(cache) == "adopted"
    assert (cache / "entry.bin").exists()
    assert _key(cache)["pid"] == os.getpid()


def test_stale_unreleased_key_on_9p_clears(tmp_path, caplog):
    """THE documented precondition: dir on 9p, unreleased key from a
    dead other-session writer (crashed mid-write). Auto-clear with a
    logged note."""
    cache = tmp_path / ".jax_cache"
    cache.mkdir()
    (cache / "entry.bin").write_bytes(b"poisoned")
    (cache / KEY_FILE).write_text(json.dumps(
        {"pid": 2 ** 22 + 1, "session": "dead-session", "t0": 1.0,
         "released": False}))
    import logging

    with caplog.at_level(logging.ERROR,
                         logger="attendance_tpu.utils.cache"):
        assert preflight_cache(cache) == "cleared"
    assert not (cache / "entry.bin").exists()  # entries discarded
    assert _key(cache)["pid"] == os.getpid()  # fresh claim written
    assert any("bad-cache precondition" in r.message
               for r in caplog.records)


def test_stale_key_off_9p_is_kept(tmp_path, monkeypatch):
    """The corruption is only documented on 9p: a healthy local
    filesystem NEVER auto-clears, whatever the key says."""
    monkeypatch.setattr(cache_mod, "_on_9p", lambda p: False)
    cache = tmp_path / ".jax_cache"
    cache.mkdir()
    (cache / "entry.bin").write_bytes(b"compiled")
    (cache / KEY_FILE).write_text(json.dumps(
        {"pid": 2 ** 22 + 1, "session": "dead-session", "t0": 1.0,
         "released": False}))
    assert preflight_cache(cache) == "kept"
    assert (cache / "entry.bin").exists()


def test_release_marks_key_for_next_session(tmp_path):
    cache = tmp_path / ".jax_cache"
    preflight_cache(cache)
    assert not _key(cache)["released"]
    _release_claims()
    assert _key(cache)["released"]
    # The released key is exactly what lets a DIFFERENT session keep
    # the entries later.
    key = _key(cache)
    key["session"] = "some-other-session"
    key["pid"] = 999999
    (cache / KEY_FILE).write_text(json.dumps(key))
    assert preflight_cache(cache) == "kept"
