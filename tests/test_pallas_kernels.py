"""Pallas kernel parity tests (interpret mode on the CPU backend).

Each kernel must be bit-identical to its XLA reference implementation;
the TPU-compiled path was additionally validated on a real v5e chip (see
ops/pallas_kernels.py docstring for the measured Mosaic gather limits).
"""

import numpy as np
import jax.numpy as jnp
import pytest

from attendance_tpu.models.bloom import (
    bloom_add, bloom_contains, bloom_init, derive_bloom_params)
from attendance_tpu.models.hll import hll_add, hll_histogram, hll_init
from attendance_tpu.ops.pallas_kernels import (
    bloom_contains_packed, hll_histogram_pallas, kernel_tile_width,
    pack_bits_transposed)


def test_pack_bits_transposed_layout():
    params = derive_bloom_params(1000, 0.01, "blocked")
    bits = bloom_init(params)
    # set bit 0 of block 0, bit 37 of block 1, bit 511 of block 2
    bits = bits.at[0].set(1)
    bits = bits.at[512 + 37].set(1)
    bits = bits.at[2 * 512 + 511].set(1)
    packed = np.asarray(pack_bits_transposed(bits))
    assert packed[0, 0] == 1                      # word 0, bit 0
    assert packed[37 // 32, 1] == 1 << (37 % 32)  # word 1, bit 5
    assert packed[15, 2] == np.uint32(1 << 31)    # word 15, bit 31


# 20_000 capacity -> ~431 blocks -> a 4-tile table, exercising the
# tiled-gather path past the single native 128-lane tile.
@pytest.mark.parametrize("capacity", [1000, 5000, 20_000])
def test_bloom_kernel_matches_xla(capacity):
    params = derive_bloom_params(capacity, 0.01, "blocked")
    bits = bloom_init(params)
    roster = jnp.asarray(
        np.arange(10_000, 10_000 + capacity, dtype=np.uint32))
    bits = bloom_add(bits, roster, params)
    packed = pack_bits_transposed(bits)
    tile = kernel_tile_width(packed)

    rng = np.random.default_rng(0)
    keys = jnp.asarray(np.concatenate([
        rng.choice(np.asarray(roster), tile),
        rng.integers(1 << 20, 1 << 31, tile).astype(np.uint32),
    ]))
    ref = np.asarray(bloom_contains(bits, keys, params))
    got = np.asarray(bloom_contains_packed(packed, keys, params))
    np.testing.assert_array_equal(ref, got)
    assert got[:tile].all()  # members never missed


def test_bloom_kernel_rejects_flat_layout():
    params = derive_bloom_params(1000, 0.01, "flat")
    packed = jnp.zeros((16, 128), jnp.uint32)
    with pytest.raises(ValueError):
        bloom_contains_packed(packed, jnp.zeros(1024, jnp.uint32), params)


@pytest.mark.parametrize("num_banks", [1, 8, 64])
def test_hist_kernel_matches_xla(num_banks):
    regs = hll_init(num_banks)
    rng = np.random.default_rng(num_banks)
    n = 200_000
    regs = hll_add(
        regs,
        jnp.asarray(rng.integers(0, num_banks, n, dtype=np.int32)),
        jnp.asarray(rng.integers(0, 1 << 31, n).astype(np.uint32)))
    ref = np.asarray(hll_histogram(regs))
    got = np.asarray(hll_histogram_pallas(regs))
    np.testing.assert_array_equal(ref, got)
    assert got.sum(axis=1).tolist() == [16384] * num_banks


@pytest.mark.parametrize("capacity", [2_000, 100_000])
def test_bloom_hbm_kernel_matches_xla(capacity):
    """The HBM-resident per-key-DMA probe (VERDICT r02 #7) answers
    bit-identically to the XLA byte path — including on filters larger
    than the VMEM kernel's tiled-gather budget."""
    from attendance_tpu.ops.pallas_kernels import (
        _HBM_TILE, bloom_contains_hbm, pack_bits_rows)

    params = derive_bloom_params(capacity, 0.01, "blocked")
    rng = np.random.default_rng(capacity)
    roster = rng.choice(1 << 20, capacity // 2, replace=False
                        ).astype(np.uint32)
    bits = bloom_add(bloom_init(params), jnp.asarray(roster), params)
    table = pack_bits_rows(bits)
    # Members and non-members INTERLEAVED across every kernel tile, so
    # a grid-offset bug in the scalar-prefetch indexing (wrong block
    # fetched for tiles past the first) shows as false negatives.
    keys_np = np.where(
        rng.random(4 * _HBM_TILE) < 0.5,
        rng.choice(np.asarray(roster), 4 * _HBM_TILE),
        rng.integers(1 << 20, 1 << 31, 4 * _HBM_TILE).astype(np.uint32))
    member = np.isin(keys_np, np.asarray(roster))
    keys = jnp.asarray(keys_np)
    ref = np.asarray(bloom_contains(bits, keys, params))
    got = np.asarray(bloom_contains_hbm(table, keys, params))
    np.testing.assert_array_equal(ref, got)
    assert got[member].all()
