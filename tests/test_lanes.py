"""Striped ingress plane tests (ISSUE 6): multi-lane order
insensitivity, barrier-gated acks, per-lane poison handling, lane
observability, and chaos on a subset of lanes."""

import dataclasses
import threading
import time

import numpy as np
import pytest

from attendance_tpu import chaos, obs
from attendance_tpu.config import Config
from attendance_tpu.pipeline.events import AttendanceEvent, encode_event
from attendance_tpu.pipeline.fast_path import FusedPipeline
from attendance_tpu.pipeline.lanes import StripedConsumer
from attendance_tpu.pipeline.loadgen import generate_frames
from attendance_tpu.transport.memory_broker import MemoryBroker, MemoryClient


@pytest.fixture(autouse=True)
def _clean_planes():
    chaos.disable()
    obs.disable()
    yield
    chaos.disable()
    obs.disable()


def _json_payloads(n, roster, seed=0):
    rng = np.random.default_rng(seed)
    ids = roster[rng.integers(0, len(roster), n)]
    days = 20_260_701 + rng.integers(0, 4, n)
    return [encode_event(AttendanceEvent(
        int(ids[i]), "2026-07-14T08:30:00",
        f"LECTURE_{int(days[i])}", True, "entry")) for i in range(n)]


def _exact_counts(payloads):
    from attendance_tpu.pipeline.events import decode_event
    seen = {}
    for p in payloads:
        e = decode_event(p)
        seen.setdefault(int(e.lecture_id.rsplit("_", 1)[-1]),
                        set()).add(e.student_id)
    return {day: len(s) for day, s in seen.items()}


def _run_pipeline(config, broker, payloads=None, frames=None,
                  roster=None, **run_kw):
    pipe = FusedPipeline(config, client=MemoryClient(broker),
                         num_banks=8)
    if roster is not None:
        pipe.preload(roster)
    producer = MemoryClient(broker).create_producer(config.pulsar_topic)
    if payloads is not None:
        producer.send_many(payloads)
    if frames is not None:
        for f in frames:
            producer.send(f)
    pipe.run(**run_kw)
    return pipe


def test_multi_lane_json_matches_single_lane_oracle():
    """Per-key effects are order-insensitive (sketch commutativity):
    4 lanes racing over the same JSON backlog land on the same HLL
    counts as the unstriped path."""
    rng = np.random.default_rng(0)
    roster = rng.choice(np.arange(10_000, 60_000, dtype=np.uint32),
                        800, replace=False)
    payloads = _json_payloads(4000, roster)
    results = {}
    for lanes in (0, 4):
        config = Config(bloom_filter_capacity=10_000, batch_size=512,
                        ingress_lanes=lanes,
                        pulsar_topic=f"lanes-eq-{lanes}").validate()
        broker = MemoryBroker()
        if lanes == 0:
            # The classic path consumes per-event JSON through the
            # codec seam one message at a time — too slow for 4000
            # events; bridge it via a striped single lane instead and
            # treat lanes=1 as the baseline oracle shape.
            config = dataclasses.replace(config, ingress_lanes=1)
        pipe = _run_pipeline(config, broker, payloads=payloads,
                             roster=roster, max_events=len(payloads),
                             idle_timeout_s=1.0)
        assert pipe.metrics.events == len(payloads)
        results[lanes] = pipe.count_all()
        if lanes == 4:
            totals = pipe.consumer.lane_event_totals()
            assert sum(totals) == len(payloads)
        pipe.cleanup()
    assert results[0] == results[4]
    exact = _exact_counts(payloads)
    for day, est in results[4].items():
        assert abs(est - exact[day]) <= max(3, 0.05 * exact[day])


def test_multi_lane_binary_matches_oracle():
    roster, frames = generate_frames(8 * 1024, 1024, roster_size=500,
                                     num_lectures=4)
    frames = list(frames)
    results = {}
    for lanes in (0, 4):
        config = Config(bloom_filter_capacity=10_000, batch_size=1024,
                        ingress_lanes=lanes,
                        pulsar_topic=f"lanes-bin-{lanes}").validate()
        broker = MemoryBroker()
        pipe = _run_pipeline(config, broker, frames=frames,
                             roster=roster, max_events=8 * 1024,
                             idle_timeout_s=1.0)
        assert pipe.metrics.events == 8 * 1024
        results[lanes] = pipe.count_all()
        pipe.cleanup()
    assert results[0] == results[4]


def test_acks_gated_on_barrier_durability(tmp_path):
    """Group-commit contract across lanes: when every snapshot write
    fails (chaos snap_fail=1.0), NO frame is acknowledged — a fresh
    pipeline on the same broker redelivers the whole backlog. With
    working snapshots the backlog is acked empty."""
    roster, frames = generate_frames(6 * 512, 512, roster_size=300,
                                     num_lectures=4)
    frames = list(frames)

    def staged_run(snap_dir, chaos_spec):
        config = Config(bloom_filter_capacity=10_000, batch_size=512,
                        ingress_lanes=3, snapshot_dir=str(snap_dir),
                        snapshot_every_batches=2, chaos=chaos_spec,
                        pulsar_topic="lanes-barrier").validate()
        broker = MemoryBroker()
        if chaos_spec:
            chaos.ensure(config)
        pipe = _run_pipeline(config, broker, frames=frames,
                             roster=roster, max_events=6 * 512,
                             idle_timeout_s=1.0)
        assert pipe.metrics.events == 6 * 512
        pipe.cleanup()
        chaos.disable()
        # Fresh (chaos-free) consumer on the SAME broker: whatever was
        # never acked redelivers to it.
        config2 = dataclasses.replace(config, chaos="", snapshot_dir="",
                                      ingress_lanes=0)
        pipe2 = FusedPipeline(config2, client=MemoryClient(broker),
                              num_banks=8)
        pipe2.run(max_events=None, idle_timeout_s=0.5)
        redelivered = pipe2.metrics.events
        pipe2.cleanup()
        return redelivered

    # Every snapshot write fails -> nothing may be acked.
    assert staged_run(tmp_path / "fail",
                      "snap_fail=1.0") == 6 * 512
    # Healthy snapshots -> group commits released every frame.
    assert staged_run(tmp_path / "ok", "") == 0


def test_lane_poison_dead_letters_only_bad_payloads():
    rng = np.random.default_rng(1)
    roster = rng.choice(np.arange(10_000, 40_000, dtype=np.uint32),
                        200, replace=False)
    good = _json_payloads(900, roster, seed=2)
    payloads = good[:400] + [b"{broken json"] + good[400:]
    config = Config(bloom_filter_capacity=10_000, batch_size=256,
                    ingress_lanes=2, max_redeliveries=2,
                    pulsar_topic="lanes-poison").validate()
    broker = MemoryBroker()
    pipe = _run_pipeline(config, broker, payloads=payloads,
                         roster=roster, max_events=None,
                         idle_timeout_s=1.0)
    assert pipe.metrics.events == len(good)
    # The poison payload was dead-lettered on its lane, not re-queued
    # forever: nothing redelivers to a fresh consumer.
    pipe.cleanup()
    config2 = dataclasses.replace(config, ingress_lanes=0,
                                  pulsar_topic="lanes-poison")
    pipe2 = FusedPipeline(config2, client=MemoryClient(broker),
                          num_banks=8)
    pipe2.run(max_events=None, idle_timeout_s=0.3)
    assert pipe2.metrics.events == 0
    pipe2.cleanup()


def test_lane_observability_counters_and_skew_row(tmp_path):
    obs.enable(Config(metrics_prom=str(tmp_path / "prom.txt")))
    try:
        rng = np.random.default_rng(3)
        roster = rng.choice(np.arange(10_000, 40_000, dtype=np.uint32),
                            300, replace=False)
        payloads = _json_payloads(2000, roster, seed=4)
        config = Config(bloom_filter_capacity=10_000, batch_size=256,
                        ingress_lanes=3,
                        metrics_prom=str(tmp_path / "prom.txt"),
                        pulsar_topic="lanes-obs").validate()
        broker = MemoryBroker()
        pipe = _run_pipeline(config, broker, payloads=payloads,
                             roster=roster, max_events=2000,
                             idle_timeout_s=1.0)
        tel = obs.get()
        text = tel.render()
        pipe.cleanup()
    finally:
        obs.disable()
    assert "attendance_ingress_lane_events_total" in text
    assert 'lane="0"' in text or 'lane="1"' in text
    assert "attendance_ingress_lane_queue_depth" in text
    # Doctor rows: informational without a ceiling, gated with one.
    from attendance_tpu.obs.slo import doctor_report
    prom = tmp_path / "doctor.prom"
    prom.write_text(text)
    report, ok = doctor_report([str(prom)])
    assert "ingress lane skew" in report
    assert ok
    skewed = tmp_path / "skewed.prom"
    skewed.write_text(
        "attendance_ingress_lane_events_total{lane=\"0\"} 1000\n"
        "attendance_ingress_lane_events_total{lane=\"1\"} 1000\n"
        "attendance_ingress_lane_events_total{lane=\"2\"} 10\n")
    report, ok = doctor_report([str(skewed)], lane_skew_ceiling=0.5)
    assert not ok and "FAIL" in report
    report, ok = doctor_report([str(skewed)])
    assert ok  # informational without the ceiling


def test_striped_consumer_timeout_and_close():
    config = Config(ingress_lanes=2, batch_size=64,
                    pulsar_topic="lanes-idle").validate()
    broker = MemoryBroker()
    cons = StripedConsumer(config, MemoryClient(broker),
                           "lanes-idle", "sub")
    from attendance_tpu.transport.memory_broker import ReceiveTimeout
    t0 = time.monotonic()
    with pytest.raises(ReceiveTimeout):
        cons.receive(timeout_millis=80)
    assert time.monotonic() - t0 < 5.0
    cons.close()
    for lane in cons.lanes:
        assert not lane.thread.is_alive()


def test_chaos_on_lane_subset_self_heals(server):
    """PR 5 soak invariants on the striped plane: conn_reset/drop
    injected across 4 socket lanes — severed lanes reconnect and
    resume, the drained state equals the oracle, and no acked frame is
    lost (redelivered duplicates are absorbed by the idempotent
    sketches)."""
    from attendance_tpu.transport.socket_broker import SocketClient

    rng = np.random.default_rng(5)
    roster = rng.choice(np.arange(10_000, 60_000, dtype=np.uint32),
                        400, replace=False)
    payloads = _json_payloads(3000, roster, seed=6)
    exact = _exact_counts(payloads)
    config = Config(bloom_filter_capacity=10_000, batch_size=256,
                    ingress_lanes=4, transport_backend="socket",
                    socket_broker=server.address,
                    chaos="conn_reset=0.02,drop=0.02", chaos_seed=11,
                    retry_budget_s=30.0,
                    pulsar_topic="lanes-chaos").validate()
    inj = chaos.ensure(config)
    assert inj is not None
    from attendance_tpu.transport import make_client
    client = make_client(config)
    pipe = FusedPipeline(config, client=client, num_banks=8)
    pipe.preload(roster)
    pub_client = SocketClient(server.address)
    producer = pub_client.create_producer(config.pulsar_topic)
    producer.send_many(payloads)
    # Drain to idle, not to a count: redelivered frames double-count
    # metrics.events, but the sketches are idempotent.
    pipe.run(max_events=None, idle_timeout_s=2.0)
    assert pipe.metrics.events >= len(payloads)
    counts = pipe.count_all()
    for day, n in exact.items():
        assert abs(counts[day] - n) <= max(3, 0.05 * n)
    # The fault plane actually fired and the lanes actually healed.
    reconnects = 0
    for lane in pipe.consumer.lanes:
        consumer = lane.consumer
        inner = getattr(consumer, "_inner", consumer)  # chaos proxy
        reconnects += inner._rpc.reconnects + inner.resubscribes
    assert reconnects > 0, "chaos seed 11 should sever at least one lane"
    totals = pipe.consumer.lane_event_totals()
    assert all(t > 0 for t in totals), totals
    pipe.cleanup()
    pub_client.close()


# ---------------------------------------------------------------------------
# ISSUE 11: COLW columnar wire through the lanes + the classic chunk
# decode
# ---------------------------------------------------------------------------

def _colw_frames(n_frames, per_frame, roster, seed=0):
    from attendance_tpu.pipeline.codec import encode_columnar_batch
    rng = np.random.default_rng(seed)
    frames, all_cols = [], []
    base = 1_753_000_000_000_000
    for _ in range(n_frames):
        micros = base + np.cumsum(
            rng.integers(1, 2_000, per_frame)).astype(np.int64)
        base = int(micros[-1]) + 1
        cols = {
            "student_id": roster[rng.integers(0, len(roster),
                                              per_frame)],
            "lecture_day": (20_260_701 + rng.integers(
                0, 4, per_frame)).astype(np.uint32),
            "micros": micros,
            "is_valid": np.ones(per_frame, bool),
            "event_type": np.zeros(per_frame, np.int8),
        }
        all_cols.append(cols)
        frames.append(encode_columnar_batch(cols))
    return frames, all_cols


@pytest.mark.parametrize("lanes", [0, 2])
def test_columnar_wire_matches_binary_oracle(lanes):
    """COLW frames land event-identical to the same columns shipped as
    planar binary — classic consumer and striped lanes both."""
    from attendance_tpu.pipeline.events import encode_planar_batch
    rng = np.random.default_rng(5)
    roster = rng.choice(np.arange(10_000, 60_000, dtype=np.uint32),
                        800, replace=False)
    colw, all_cols = _colw_frames(8, 1024, roster)
    nev = 8 * 1024
    results = {}
    for wire, frames in (("columnar", colw),
                         ("binary", [encode_planar_batch(c)
                                     for c in all_cols])):
        config = Config(bloom_filter_capacity=10_000, batch_size=1024,
                        ingress_lanes=lanes,
                        pulsar_topic=f"colw-{lanes}-{wire}").validate()
        pipe = _run_pipeline(config, MemoryBroker(), frames=frames,
                             roster=roster, max_events=nev,
                             idle_timeout_s=1.0)
        assert pipe.metrics.events == nev
        assert pipe.metrics.dead_lettered == 0
        results[wire] = pipe.count_all()
        pipe.cleanup()
    assert results["columnar"] == results["binary"]


def test_columnar_corrupt_frame_dead_letters_never_mutates():
    """A corrupt COLW frame mid-backlog dead-letters LOUDLY (checksum
    reject -> poison path) while every clean frame folds — final state
    equals the clean-frames-only oracle, proving no silent event
    mutation leaked through."""
    rng = np.random.default_rng(6)
    roster = rng.choice(np.arange(10_000, 60_000, dtype=np.uint32),
                        500, replace=False)
    colw, _ = _colw_frames(6, 512, roster)
    corrupt = bytearray(colw[3])
    corrupt[len(corrupt) // 2] ^= 0xFF
    backlog = colw[:3] + [bytes(corrupt)] + colw[3:]

    def run(frames, topic):
        config = Config(bloom_filter_capacity=10_000, batch_size=512,
                        ingress_lanes=2, max_redeliveries=2,
                        pulsar_topic=topic).validate()
        pipe = _run_pipeline(config, MemoryBroker(), frames=frames,
                             roster=roster, max_events=6 * 512,
                             idle_timeout_s=1.5)
        stats = (pipe.metrics.events, pipe.count_all())
        pipe.cleanup()
        return stats

    got_events, got_counts = run(backlog, "colw-corrupt")
    want_events, want_counts = run(colw, "colw-clean")
    assert got_events == want_events == 6 * 512
    assert got_counts == want_counts


def test_classic_json_chunk_decode_matches_per_message_path():
    """ISSUE 11 satellite: the classic (lanes=0) consumer batch-
    decodes JSON chunks through the codec seam; results are identical
    to the per-message path it replaces (kept reachable via
    json_chunk_decode=False)."""
    rng = np.random.default_rng(7)
    roster = rng.choice(np.arange(10_000, 60_000, dtype=np.uint32),
                        400, replace=False)
    payloads = _json_payloads(1200, roster, seed=7)
    results = {}
    for chunked in (True, False):
        config = Config(bloom_filter_capacity=10_000, batch_size=256,
                        json_chunk_decode=chunked,
                        pulsar_topic=f"jchunk-{chunked}").validate()
        pipe = _run_pipeline(config, MemoryBroker(), payloads=payloads,
                             roster=roster, max_events=len(payloads),
                             idle_timeout_s=1.0)
        assert pipe.metrics.events == len(payloads)
        results[chunked] = pipe.count_all()
        # chunked: dispatches are coalesced (far fewer batches than
        # messages); per-message: one batch per message.
        if chunked:
            assert pipe.metrics.batches < len(payloads) / 4
        else:
            assert pipe.metrics.batches == len(payloads)
        pipe.cleanup()
    assert results[True] == results[False]
    exact = _exact_counts(payloads)
    for day, est in results[True].items():
        assert abs(est - exact[day]) <= max(3, 0.05 * exact[day])


def test_classic_chunk_consumer_mixed_wires_in_order():
    """A topic mixing bulk binary frames and per-event JSON payloads
    through the classic chunk consumer: everything lands, binary
    passes through untouched."""
    rng = np.random.default_rng(8)
    roster = rng.choice(np.arange(10_000, 60_000, dtype=np.uint32),
                        300, replace=False)
    jsons = _json_payloads(600, roster, seed=8)
    broster, frames = generate_frames(2 * 512, 512, roster_size=300,
                                      num_lectures=4, seed=8)
    config = Config(bloom_filter_capacity=10_000, batch_size=256,
                    pulsar_topic="mixed-chunk").validate()
    broker = MemoryBroker()
    pipe = FusedPipeline(config, client=MemoryClient(broker),
                         num_banks=8)
    pipe.preload(np.union1d(roster, broster))
    producer = MemoryClient(broker).create_producer(config.pulsar_topic)
    producer.send_many(jsons[:300])
    for f in frames:
        producer.send(f)
    producer.send_many(jsons[300:])
    pipe.run(max_events=600 + 2 * 512, idle_timeout_s=1.0)
    assert pipe.metrics.events == 600 + 2 * 512
    assert pipe.metrics.dead_lettered == 0
    pipe.cleanup()
