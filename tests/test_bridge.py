"""JSON->binary ingress bridge + native JSON scanner.

Differential principle: the native schema scanner must be
behavior-identical to the Python codec (decode_event ->
columns_from_events) on everything it accepts, and must cleanly refuse
anything it can't represent so the fallback produces the same result.
"""

import json

import numpy as np
import pytest

from attendance_tpu.config import Config
from attendance_tpu.pipeline.events import (
    AttendanceEvent, columns_from_events, decode_event,
    decode_json_batch_columns)
from attendance_tpu.transport.memory_broker import MemoryBroker, MemoryClient


def _payload(**over):
    d = {"student_id": 12345, "timestamp": "2026-03-02T09:15:00",
         "lecture_id": "LECTURE_20260302", "is_valid": True,
         "event_type": "entry"}
    d.update(over)
    return json.dumps(d).encode()


def _python_columns(payloads):
    return columns_from_events([decode_event(p) for p in payloads])


def _assert_cols_equal(a, b):
    for k in ("student_id", "lecture_day", "micros", "is_valid",
              "event_type"):
        np.testing.assert_array_equal(np.asarray(a[k]), np.asarray(b[k]), k)


FAST_SHAPES = [
    _payload(),
    _payload(event_type="exit", is_valid=False),
    _payload(timestamp="2026-03-02 23:59:59"),            # space separator
    _payload(timestamp="2026-03-02T09:15:00.25"),         # fraction
    _payload(timestamp="2026-03-02T09:15:00.123456"),
    _payload(timestamp="2026-03-02T09:15:00.1234567"),    # 7+ digits:
    # fromisoformat truncates to 6; the scanner matches that exactly

    _payload(student_id=0),
    _payload(student_id=(1 << 32) - 1),
    _payload(lecture_id="LECTURE_166123456"),             # 9-digit hash code
    # key order permuted + extra unknown scalar keys + whitespace
    b'{ "event_type" : "exit" , "gate": 7, "note": "x",\n'
    b'"lecture_id":"LECTURE_20270101","is_valid":false,'
    b'"timestamp":"2027-01-01T08:00:00","student_id":77 }',
    # duplicate is_valid / event_type keys: json.loads keeps the LAST
    # value; the scanner matches (regression: OR-accumulated
    # first-true-wins diverged)
    b'{"student_id": 5, "timestamp": "2026-03-02T09:15:00", '
    b'"lecture_id": "LECTURE_20260302", "is_valid": true, '
    b'"event_type": "exit", "is_valid": false, "event_type": "entry"}',
]

FALLBACK_SHAPES = [
    _payload(lecture_id="PHYS101"),                       # needs murmur3
    _payload(timestamp="2026-03-02T09:15:00+00:00"),      # tz suffix
    _payload(lecture_id="LECT\\u0055RE_20260302"),        # escapes
    _payload(lecture_id="LECTURE_caf\u00e9"),             # non-ASCII utf-8
]


def test_native_scanner_matches_python_codec():
    from attendance_tpu.native import load as load_native
    nat = load_native()
    if nat is None:
        pytest.skip("no C toolchain")
    cols, miss = nat.parse_json_events(FAST_SHAPES)
    assert miss == -1
    _assert_cols_equal(cols, _python_columns(FAST_SHAPES))


def test_native_scanner_refuses_fallback_shapes():
    from attendance_tpu.native import load as load_native
    nat = load_native()
    if nat is None:
        pytest.skip("no C toolchain")
    for p in FALLBACK_SHAPES:
        cols, miss = nat.parse_json_events([_payload(), p])
        assert miss == 1, p
        assert len(cols["student_id"]) == 1  # parsed prefix survives


def test_decode_json_batch_columns_fallback_identical():
    """Mixed batches route through the Python codec and still match."""
    batch = FAST_SHAPES + FALLBACK_SHAPES
    _assert_cols_equal(decode_json_batch_columns(batch),
                       _python_columns(batch))


def test_list_scan_matches_buffer_scan_and_python():
    """The CPython-API list scan (payload bytes read in place, no
    join/offset-table prepare) must agree with the buffer scan and the
    Python codec on accepted payloads, refuse the same fallback
    shapes, and surface non-bytes entries as misses at their index."""
    from attendance_tpu.native import load as load_native
    nat = load_native()
    if nat is None or not nat.has_list_scan:
        pytest.skip("CPython-API hostpipe variant unavailable")

    # decode_json_batch_columns prefers the list scan for list inputs;
    # a mixed batch must still match the pure-Python answer.
    batch = FAST_SHAPES + FALLBACK_SHAPES + FAST_SHAPES[:3]
    _assert_cols_equal(decode_json_batch_columns(list(batch)),
                       _python_columns(batch))

    # Direct: list scan == buffer scan on the all-fast batch.
    out = nat.empty_json_outputs(len(FAST_SHAPES))
    assert nat.parse_json_list(list(FAST_SHAPES), out, 0) == -1
    cols_buf, miss = nat.parse_json_events(FAST_SHAPES)
    assert miss == -1
    _assert_cols_equal(out.columns(), cols_buf)

    # A non-bytes element (memoryview) is a miss at its index — the
    # resume protocol hands exactly that entry to the Python codec.
    mixed = list(FAST_SHAPES) + [memoryview(_payload())] + [_payload()]
    out2 = nat.empty_json_outputs(len(mixed))
    assert nat.parse_json_list(mixed, out2, 0) == len(FAST_SHAPES)
    assert nat.parse_json_list(mixed, out2,
                               len(FAST_SHAPES) + 1) == -1
    _assert_cols_equal(decode_json_batch_columns(mixed),
                       _python_columns([bytes(p) for p in mixed]))


def test_bridge_end_to_end_with_fused_pipeline():
    """Reference-wire JSON producer -> bridge -> fused pipeline: the
    stored events match the generator's ground truth exactly."""
    from attendance_tpu.pipeline.bridge import JsonBinaryBridge
    from attendance_tpu.pipeline.fast_path import FusedPipeline
    from attendance_tpu.pipeline.generator import generate_student_data

    config = Config(transport_backend="memory", batch_size=256,
                    bloom_filter_capacity=10_000)
    broker = MemoryBroker()
    bridge = JsonBinaryBridge(config, client=MemoryClient(broker))
    pipe_cfg = Config(transport_backend="memory",
                      pulsar_topic=bridge.out_topic,
                      bloom_filter_capacity=10_000)
    pipe = FusedPipeline(pipe_cfg, client=MemoryClient(broker),
                         num_banks=16)

    producer = MemoryClient(broker).create_producer(config.pulsar_topic)
    report = generate_student_data(producer=producer, sketch_store=None,
                                   num_students=80, num_invalid=8,
                                   seed=13)
    pipe.preload(np.asarray(sorted(report.valid_student_ids),
                            dtype=np.uint32))

    bridge.run(max_events=report.message_count, idle_timeout_s=0.5)
    assert bridge.metrics.events == report.message_count
    pipe.run(max_events=report.message_count, idle_timeout_s=0.5)

    cols = pipe.store.to_columns(deduplicate=False)
    assert len(cols["student_id"]) == report.message_count
    truth = columns_from_events(report.events)
    got_valid = np.asarray(cols["is_valid"], bool)
    # no false negatives vs the generator's ground truth; FPR tiny
    tv = np.asarray(truth["is_valid"], bool)
    assert not (tv & ~got_valid).any()
    assert (~tv & got_valid).sum() <= max(2, 0.02 * (~tv).sum())
    np.testing.assert_array_equal(np.asarray(cols["student_id"]),
                                  truth["student_id"])
    np.testing.assert_array_equal(np.asarray(cols["micros"]),
                                  truth["micros"])


def test_bridge_dead_letters_poison_json():
    from attendance_tpu.pipeline.bridge import JsonBinaryBridge

    config = Config(transport_backend="memory", batch_size=8,
                    batch_timeout_s=0.05, max_redeliveries=2)
    broker = MemoryBroker()
    bridge = JsonBinaryBridge(config, client=MemoryClient(broker))
    producer = MemoryClient(broker).create_producer(config.pulsar_topic)
    good = [_payload(student_id=i) for i in range(6)]
    for p in good[:3]:
        producer.send(p)
    producer.send(b"{not json at all")
    for p in good[3:]:
        producer.send(p)
    # No max_events: run to idle so the poison message exhausts its
    # bounded redeliveries and dead-letters.
    bridge.run(idle_timeout_s=1.0)
    assert bridge.metrics.events == 6
    assert bridge.metrics.dead_lettered == 1
    # all six good events came out the binary side
    sub = MemoryClient(broker).subscribe(bridge.out_topic, "check")
    from attendance_tpu.pipeline.events import decode_binary_batch
    total = 0
    while True:
        try:
            msg = sub.receive(timeout_millis=100)
        except Exception:
            break
        total += len(decode_binary_batch(msg.data())["student_id"])
    assert total == 6


def test_micros_exact_for_fractional_timestamps():
    """_iso_to_micros is exact integer arithmetic: the old float
    truncation (int(ts * 1e6)) lost 1 us on ~1% of fractional
    timestamps, diverging from the native scanner."""
    from attendance_tpu.pipeline.events import _iso_to_micros
    assert _iso_to_micros("2040-07-11T15:13:45.869920") % 1_000_000 \
        == 869920
    # sweep: python == native for a spread of fractions
    from attendance_tpu.native import load as load_native
    nat = load_native()
    if nat is None:
        pytest.skip("no C toolchain")
    payloads = [_payload(timestamp=f"2033-05-0{1 + i % 9}T0{i % 9}:"
                         f"{10 + i % 50}:{10 + i % 50}.{f:06d}")
                for i, f in enumerate(range(1, 999_983, 7919))]
    cols, miss = nat.parse_json_events(payloads)
    assert miss == -1
    _assert_cols_equal(cols, _python_columns(payloads))


REJECT_BOTH = [
    # valid JSON the Python codec ALSO rejects; the native scanner must
    # refuse them (miss) rather than silently accept
    _payload(timestamp="2026-02-30T10:00:00"),   # nonexistent date
    _payload(timestamp="2026-03-02T10:00:60"),   # leap second
    b'{"student_id": 007, "timestamp": "2026-03-02T09:15:00", '
    b'"lecture_id": "LECTURE_20260302", "is_valid": true, '
    b'"event_type": "entry"}',                   # leading-zero int
    _payload(timestamp="0000-01-01T00:00:00"),   # year < MINYEAR
    # raw control character inside a string: json.loads rejects
    b'{"student_id": 1, "timestamp": "2026-03-02T09:15:00", '
    b'"lecture_id": "LECTURE\n_20260302", "is_valid": true, '
    b'"event_type": "entry"}',
    # trailing comma before }
    b'{"student_id": 1, "timestamp": "2026-03-02T09:15:00", '
    b'"lecture_id": "LECTURE_20260302", "is_valid": true, '
    b'"event_type": "entry",}',
    # bare-word / leading-zero unknown-key values
    b'{"student_id": 1, "timestamp": "2026-03-02T09:15:00", '
    b'"lecture_id": "LECTURE_20260302", "is_valid": true, '
    b'"event_type": "entry", "gate": blah}',
    b'{"student_id": 1, "timestamp": "2026-03-02T09:15:00", '
    b'"lecture_id": "LECTURE_20260302", "is_valid": true, '
    b'"event_type": "entry", "gate": 007}',
]


def test_native_never_accepts_what_python_rejects():
    from attendance_tpu.native import load as load_native
    nat = load_native()
    if nat is None:
        pytest.skip("no C toolchain")
    for p in REJECT_BOTH:
        with pytest.raises(Exception):
            _python_columns([p])
        cols, miss = nat.parse_json_events([p])
        assert miss == 0, p


def test_mixed_stream_keeps_native_segments():
    """Fallback-shaped payloads are Python-parsed individually; the
    native scan resumes for the conforming majority, and the combined
    result equals the all-Python parse."""
    batch = []
    for i in range(50):
        batch.append(_payload(student_id=i))
        if i % 7 == 0:
            batch.append(_payload(lecture_id="PHYS101", student_id=i))
    _assert_cols_equal(decode_json_batch_columns(batch),
                       _python_columns(batch))


def test_bridge_dead_letters_valid_json_bad_timestamp():
    """Valid JSON whose timestamp can't parse is poison too: it must
    dead-letter through the bounded-retry policy, never crash the
    bridge (which would redeliver-crash forever on restart)."""
    from attendance_tpu.pipeline.bridge import JsonBinaryBridge

    config = Config(transport_backend="memory", batch_size=8,
                    batch_timeout_s=0.05, max_redeliveries=2)
    broker = MemoryBroker()
    bridge = JsonBinaryBridge(config, client=MemoryClient(broker))
    producer = MemoryClient(broker).create_producer(config.pulsar_topic)
    for i in range(3):
        producer.send(_payload(student_id=i))
    producer.send(_payload(timestamp="yesterday-ish"))
    for i in range(3, 6):
        producer.send(_payload(student_id=i))
    bridge.run(idle_timeout_s=1.0)
    assert bridge.metrics.events == 6
    assert bridge.metrics.dead_lettered == 1


def test_json_scanner_differential_fuzz():
    """Randomized differential check of the native scanner's parity
    contract: for arbitrary byte-mutated payloads, whenever the scanner
    accepts, the Python codec must also accept AND produce identical
    columns. (The converse — scanner bails, Python accepts — is the
    designed fallback and always safe.)"""
    import random

    from attendance_tpu.native import load as load_native
    nat = load_native()
    if nat is None:
        pytest.skip("no C toolchain")

    rng = random.Random(0xA77E)
    base = [
        _payload(),
        _payload(timestamp="2026-12-31 23:59:59.999999",
                 lecture_id="LECTURE_166123456", event_type="exit"),
        b'{ "event_type" : "exit", "extra": -1.5e3, "is_valid": false, '
        b'"lecture_id":"LECTURE_20270101",'
        b'"timestamp":"2027-01-01T08:00:00","student_id":77 }',
    ]
    mutations = 0
    agree = 0
    for trial in range(3000):
        p = bytearray(rng.choice(base))
        for _ in range(rng.randint(1, 3)):
            op = rng.random()
            pos = rng.randrange(len(p))
            if op < 0.4:
                p[pos] = rng.randrange(256)       # flip a byte
            elif op < 0.7:
                del p[pos]                        # drop a byte
            else:
                p.insert(pos, rng.randrange(32, 127))  # insert ascii
        payload = bytes(p)
        mutations += 1
        cols, miss = nat.parse_json_events([payload])
        if nat.has_list_scan:
            # Both scan front-ends share parse_one_json_event; the
            # fuzz pins that they accept/refuse identically and land
            # on the same columns.
            out = nat.empty_json_outputs(1)
            miss_l = nat.parse_json_list([payload], out, 0)
            assert (miss_l == -1) == (miss == -1), payload
            if miss == -1:
                _assert_cols_equal(out.columns(), cols)
        if miss != -1:
            continue  # scanner bailed: always safe
        # scanner accepted: Python must agree bit-for-bit
        ref = _python_columns([payload])
        _assert_cols_equal(cols, ref)
        agree += 1
    # sanity: the fuzz actually exercised both outcomes
    assert mutations == 3000 and 0 < agree < mutations


def test_empty_timestamp_rejected_like_python():
    """fromisoformat('') raises in the Python codec; the native scan
    (both the fixed-layout fast path and the general grammar) must
    refuse an empty timestamp rather than ingest an indeterminate
    micros value (the 0-consumed == 0-length hole)."""
    from attendance_tpu.pipeline.events import decode_json_batch_columns

    fixed_layout = (b'{"student_id": 1, "timestamp": "", '
                    b'"lecture_id": "LECTURE_20260101", '
                    b'"is_valid": true, "event_type": "entry"}')
    off_layout = (b'{"timestamp": "", "student_id": 1, '
                  b'"lecture_id": "LECTURE_20260101", '
                  b'"is_valid": true, "event_type": "entry"}')
    for payload in (fixed_layout, off_layout):
        with pytest.raises(Exception):
            decode_json_batch_columns([payload])
