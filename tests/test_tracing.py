"""Span tracing + sketch-health tests (the PR-2 observability layer).

Covers the tracer core (context codec, bounded buffer, deterministic
Chrome-trace export against a golden file), trace-context propagation
through broker message properties (memory AND socket, surviving nack
redelivery), the acceptance scenario (a traced fused run produces a
Perfetto-loadable trace with >= 5 distinct stage spans per batch under
one trace_id per published frame, redeliveries as retry child spans),
and the sketch-health gauges (values match the models' own estimators
to float tolerance; no device work happens before a scrape).
"""

import itertools
import json
from pathlib import Path

import numpy as np
import pytest

from attendance_tpu import obs
from attendance_tpu.config import Config
from attendance_tpu.obs.tracing import (
    TRACEPARENT, SpanContext, Tracer, format_ctx, parse_ctx)
from attendance_tpu.transport.memory_broker import (
    MemoryBroker, MemoryClient)

GOLDEN = Path(__file__).parent / "data" / "trace_export.golden"


@pytest.fixture(autouse=True)
def _clean_telemetry():
    obs.disable()
    yield
    obs.disable()


# -- context codec -----------------------------------------------------------

def test_ctx_roundtrip_and_malformed():
    ctx = SpanContext(0xdeadbeef, 0x1234, 17)
    assert parse_ctx(format_ctx(ctx)) == ctx
    # Malformed values degrade to "fresh trace", never an exception —
    # a traced consumer must interoperate with anything upstream.
    for bad in (None, "", "zz", "1-2", "x-y-z", 42, "1-2-3-4"):
        assert parse_ctx(bad) is None


# -- tracer core -------------------------------------------------------------

def test_span_buffer_is_bounded():
    tr = Tracer(limit=4)
    for i in range(7):
        with tr.span(f"s{i}"):
            pass
    assert len(tr) == 4
    assert tr.dropped == 3
    assert tr.export()["otherData"]["dropped_spans"] == 3


def test_activate_nests_spans_and_exceptions_are_recorded():
    tr = Tracer()
    with tr.span("outer") as outer:
        with tr.span("inner"):
            pass
    inner = [s for s in tr.snapshot() if s.name == "inner"][0]
    assert inner.trace_id == outer.trace_id
    assert inner.parent_id == outer.span_id
    with pytest.raises(RuntimeError):
        with tr.span("boom"):
            raise RuntimeError("x")
    boom = [s for s in tr.snapshot() if s.name == "boom"][0]
    assert "RuntimeError" in boom.args["error"]


def _deterministic_tracer() -> Tracer:
    ids = itertools.count(1)
    return Tracer(_clock=lambda: 0.0, _ids=lambda: next(ids),
                  _epoch=0.0)


def test_chrome_export_matches_golden_file():
    """The export format IS the contract (Perfetto loads it byte for
    byte); pin it with a golden file built from injected ids/clock."""
    tr = _deterministic_tracer()
    pub = tr.add_span("publish", 0.0, 0.0005, trace_id=1,
                      role="producer", args={"topic": "t", "seq": 0})
    batch = tr.add_span("batch", 0.001, 0.009, trace_id=1,
                        parent_id=pub.span_id, role="fused-pipeline",
                        args={"seq": 0})
    tr.add_span("decode", 0.001, 0.002, trace_id=1,
                parent_id=batch.span_id, role="fused-pipeline")
    tr.add_span("dispatch", 0.002, 0.009, trace_id=1,
                parent_id=batch.span_id, role="fused-pipeline",
                args={"wire": "word"})
    doc = tr.export()
    doc.pop("otherData")  # carries the live pid
    rendered = json.dumps(doc, indent=1, sort_keys=True) + "\n"
    assert rendered == GOLDEN.read_text()


def test_export_loads_as_chrome_trace_shape():
    tr = _deterministic_tracer()
    with tr.span("a", role="r1"):
        pass
    doc = json.loads(json.dumps(tr.export()))  # JSON-serializable
    assert doc["displayTimeUnit"] == "ms"
    slices = [e for e in doc["traceEvents"] if e["ph"] == "X"]
    metas = [e for e in doc["traceEvents"] if e["ph"] == "M"]
    assert slices and metas
    for e in slices:  # every slice has the linking args
        assert {"pid", "tid", "ts", "dur", "name", "args"} <= set(e)
        assert "trace_id" in e["args"] and "span_id" in e["args"]


# -- propagation: memory broker ----------------------------------------------

def test_memory_broker_properties_survive_nack_and_takeover():
    client = MemoryClient(MemoryBroker())
    producer = client.create_producer("t")
    consumer = client.subscribe("t", "s")
    producer.send(b"payload", properties={TRACEPARENT: "aa-bb-cc",
                                          "k": "v"})
    msg = consumer.receive(timeout_millis=500)
    assert msg.properties() == {TRACEPARENT: "aa-bb-cc", "k": "v"}
    consumer.negative_acknowledge(msg)
    again = consumer.receive(timeout_millis=500)
    assert again.redelivery_count == 1
    assert again.properties() == msg.properties()
    # Crash takeover keeps them too.
    consumer.close()
    survivor = client.subscribe("t", "s")
    taken = survivor.receive(timeout_millis=500)
    assert taken.redelivery_count == 2
    assert taken.properties()["k"] == "v"


def test_producer_injects_traceparent_when_tracing(tmp_path):
    t = obs.enable(Config(trace_out=str(tmp_path / "t.json")))
    client = MemoryClient(MemoryBroker())
    client.create_producer("t").send(b"x")
    msg = client.subscribe("t", "s").receive(timeout_millis=500)
    ctx = parse_ctx(msg.properties()[TRACEPARENT])
    assert ctx is not None
    # ...and the publish span it names is in the buffer.
    pub = [s for s in t.tracer.snapshot() if s.name == "publish"]
    assert pub and pub[0].span_id == ctx.span_id
    assert pub[0].trace_id == ctx.trace_id


# -- propagation: socket broker (incl. forced redelivery) --------------------

def test_socket_broker_propagates_properties_across_redelivery():
    from attendance_tpu.transport.socket_broker import (
        BrokerServer, SocketClient)

    server = BrokerServer().start()
    try:
        client = SocketClient(server.address)
        producer = client.create_producer("t")
        consumer = client.subscribe("t", "s")
        producer.send(b"one", properties={TRACEPARENT: "11-22-0"})
        producer.send_many([b"two", b"three"],
                           properties=[{"n": "2"}, None])
        msg = consumer.receive(timeout_millis=2000)
        assert msg.properties() == {TRACEPARENT: "11-22-0"}
        # Forced redelivery over TCP: the nack only ships the id; the
        # server's subscription re-derives payload AND properties.
        consumer.negative_acknowledge(msg)
        msgs = consumer.receive_many(3, timeout_millis=2000)
        by_data = {m.data(): m for m in msgs}
        assert by_data[b"two"].properties() == {"n": "2"}
        assert by_data[b"three"].properties() == {}
        redelivered = by_data.get(b"one")
        if redelivered is None:  # not in the first drain: fetch it
            redelivered = consumer.receive(timeout_millis=2000)
        assert redelivered.data() == b"one"
        assert redelivered.redelivery_count == 1
        assert redelivered.properties() == {TRACEPARENT: "11-22-0"}
        client.close()
    finally:
        server.stop()


def test_retry_span_parents_under_publish_across_socket(tmp_path):
    """A frame that fails decode is nacked and redelivered; every
    redelivered attempt must appear as a ``retry`` span parented under
    the SAME publish span as the first attempt — across the socket
    broker, whose properties ride the TCP protocol."""
    from attendance_tpu.pipeline.fast_path import FusedPipeline
    from attendance_tpu.transport.socket_broker import (
        BrokerServer, SocketClient)

    trace_path = tmp_path / "trace.json"
    config = Config(bloom_filter_capacity=1_000,
                    transport_backend="socket",
                    trace_out=str(trace_path), max_redeliveries=2)
    t = obs.enable(config)
    server = BrokerServer().start()
    try:
        client = SocketClient(server.address)
        pipe = FusedPipeline(config, client=client, num_banks=4)
        SocketClient(server.address).create_producer(
            config.pulsar_topic).send(b"garbage-not-a-frame")
        pipe.run(max_events=1, idle_timeout_s=0.5)
        assert pipe.metrics.dead_lettered == 1
        pipe.cleanup()
    finally:
        server.stop()
    doc = json.loads(trace_path.read_text())
    evs = [e for e in doc["traceEvents"] if e.get("ph") == "X"]
    pub = [e for e in evs if e["name"] == "publish"]
    retries = [e for e in evs if e["name"] == "retry"]
    batches = [e for e in evs if e["name"] == "batch"]
    assert len(pub) == 1 and len(batches) == 1  # first attempt
    assert len(retries) == 2  # max_redeliveries=2 retry attempts
    pub_span = pub[0]["args"]["span_id"]
    pub_trace = pub[0]["args"]["trace_id"]
    for e in retries + batches:
        assert e["args"]["trace_id"] == pub_trace
        assert e["args"]["parent_span_id"] == pub_span
    assert [e["args"]["redelivery"] for e in retries] == [1, 2]


# -- the acceptance scenario -------------------------------------------------

def _run_traced_fused(tmp_path, num_events=4_096, frame=1_024,
                      flight=0):
    from attendance_tpu.pipeline.fast_path import FusedPipeline
    from attendance_tpu.pipeline.loadgen import generate_frames

    trace_path = tmp_path / "trace.json"
    config = Config(bloom_filter_capacity=5_000,
                    trace_out=str(trace_path), flight_recorder=flight,
                    flight_path=str(tmp_path / "flight.json"))
    t = obs.enable(config)
    client = MemoryClient(MemoryBroker())
    pipe = FusedPipeline(config, client=client, num_banks=8)
    roster, frames = generate_frames(num_events, frame,
                                     roster_size=4_000, num_lectures=4)
    pipe.preload(roster)
    producer = client.create_producer(config.pulsar_topic)
    for f in frames:
        producer.send(f)
    pipe.run(max_events=num_events, idle_timeout_s=0.3)
    return t, pipe, trace_path


def test_traced_fused_run_links_stage_spans_per_batch(tmp_path):
    t, pipe, trace_path = _run_traced_fused(tmp_path)
    doc = json.loads(trace_path.read_text())
    evs = [e for e in doc["traceEvents"] if e.get("ph") == "X"]
    by_trace = {}
    for e in evs:
        by_trace.setdefault(e["args"]["trace_id"], set()).add(e["name"])
    # One trace per published frame, each with >= 5 distinct stage
    # spans (publish -> batch -> dequeue_wait/decode/dispatch[...]).
    batch_traces = [names for names in by_trace.values()
                    if "batch" in names]
    assert len(batch_traces) == 4
    for names in batch_traces:
        assert {"publish", "batch", "dequeue_wait", "decode",
                "dispatch"} <= names
        assert len(names) >= 5
    # Roles separate into per-role pids with process_name metadata.
    roles = {e["args"]["name"]
             for e in doc["traceEvents"]
             if e.get("ph") == "M" and e["name"] == "process_name"}
    assert {"producer", "fused-pipeline"} <= roles


def test_flight_recorder_records_cross_reference_traces(tmp_path):
    t, pipe, trace_path = _run_traced_fused(tmp_path, flight=16)
    t.dump_flight("test")
    doc = json.loads((tmp_path / "flight.json").read_text())
    traces = {r["trace"] for r in doc["records"]}
    assert len(traces) == 4  # one trace per frame
    exported = {e["args"]["trace_id"]
                for e in json.loads(trace_path.read_text())
                ["traceEvents"] if e.get("ph") == "X"}
    assert traces <= exported


def test_bridge_relays_trace_context_end_to_end(tmp_path):
    """generator-wire JSON -> bridge -> fused pipeline is ONE trace:
    the frame's batch span shares the first JSON message's trace_id."""
    import dataclasses

    from attendance_tpu.pipeline.bridge import JsonBinaryBridge
    from attendance_tpu.pipeline.fast_path import FusedPipeline
    from attendance_tpu.pipeline.events import encode_event
    from attendance_tpu.pipeline.generator import generate_student_data

    trace_path = tmp_path / "trace.json"
    config = Config(bloom_filter_capacity=2_000, batch_size=512,
                    trace_out=str(trace_path))
    t = obs.enable(config)
    broker = MemoryBroker()
    bridge = JsonBinaryBridge(config, client=MemoryClient(broker))
    pipe = FusedPipeline(
        dataclasses.replace(config, pulsar_topic=bridge.out_topic),
        client=MemoryClient(broker), num_banks=8)
    report = generate_student_data(
        producer=MemoryClient(broker).create_producer(
            config.pulsar_topic),
        num_students=40, seed=7)
    bridge.run(max_events=report.message_count, idle_timeout_s=0.3)
    pipe.run(max_events=report.message_count, idle_timeout_s=0.3)
    spans = t.tracer.snapshot()
    forwards = [s for s in spans if s.name == "bridge_forward"]
    batches = [s for s in spans if s.name == "batch"]
    assert forwards and batches
    # Each fused batch span's trace is one a bridge_forward belongs to.
    fwd_traces = {s.trace_id for s in forwards}
    assert {s.trace_id for s in batches} <= fwd_traces
    # And that trace roots at a generator-side publish span.
    pub_traces = {s.trace_id for s in spans if s.name == "publish"}
    assert fwd_traces <= pub_traces


# -- sketch-health gauges ----------------------------------------------------

def test_sketch_health_gauges_match_model_estimators(tmp_path):
    from attendance_tpu.obs.exposition import parse_prom, render

    t, pipe, _ = _run_traced_fused(tmp_path)
    samples = {n: float(v) for n, _, v in parse_prom(render(t.registry))}
    assert samples["attendance_bloom_estimated_fpr"] == pytest.approx(
        pipe.estimated_fpr(), rel=1e-6)
    assert samples["attendance_bloom_fill_fraction"] == pytest.approx(
        pipe.estimated_fpr() ** (1.0 / pipe.params.k), rel=1e-6)
    assert samples["attendance_hll_estimate"] == pytest.approx(
        sum(pipe.count_all().values()), abs=1.0)
    assert samples["attendance_hll_saturated_registers"] == 0.0


def test_bloom_filter_gauge_tracks_estimated_fpr_after_inserts(
        tmp_path):
    from attendance_tpu.models.bloom import BloomFilter
    from attendance_tpu.obs import health
    from attendance_tpu.obs.exposition import parse_prom, render

    t = obs.enable(Config(flight_recorder=4,
                          flight_path=str(tmp_path / "f.json")))
    bf = BloomFilter(capacity=5_000, error_rate=0.01)
    health.register_bloom_filter(t, bf, key="bf:test")
    rng = np.random.default_rng(3)
    for _ in range(3):  # N inserts in chunks; the gauge tracks live
        bf.add(rng.integers(0, 1 << 31, 1_000, dtype=np.uint32))
        samples = {n: float(v)
                   for n, _, v in parse_prom(render(t.registry))}
        assert samples["attendance_bloom_estimated_fpr"] == \
            pytest.approx(bf.estimated_fpr(), rel=1e-6)


def test_scrape_is_lazy_and_off_means_no_registration(monkeypatch,
                                                      tmp_path):
    """Telemetry off: nothing registers, nothing reads devices.
    Telemetry on: the health callbacks run at SCRAPE time only."""
    from attendance_tpu.obs.exposition import render
    from attendance_tpu.pipeline.fast_path import FusedPipeline

    pipe = FusedPipeline(Config(bloom_filter_capacity=1_000),
                         client=MemoryClient(MemoryBroker()),
                         num_banks=4)
    assert obs.get() is None and pipe._obs is None

    calls = []
    orig = FusedPipeline.count_all
    monkeypatch.setattr(
        FusedPipeline, "count_all",
        lambda self: (calls.append(1), orig(self))[1])
    t, pipe, _ = _run_traced_fused(tmp_path, num_events=1_024,
                                   frame=1_024)
    assert not calls  # the whole run did no health device reads
    render(t.registry)
    assert calls  # ...until the scrape asked


def test_cli_telemetry_verb_prints_trace_tree(tmp_path, capsys):
    from attendance_tpu.cli import main

    tr = _deterministic_tracer()
    pub = tr.add_span("publish", 0.0, 0.001, trace_id=9,
                      role="producer")
    tr.add_span("batch", 0.001, 0.004, trace_id=9,
                parent_id=pub.span_id, role="fused-pipeline",
                args={"seq": 3})
    path = tmp_path / "trace.json"
    tr.flush(path)
    main(["telemetry", str(path)])
    out = capsys.readouterr().out
    assert "trace" in out and "publish" in out and "batch" in out
    assert "fused-pipeline" in out  # role column rides along
