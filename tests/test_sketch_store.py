"""SketchStore facade tests: command surface, scaling, backend parity.

The execute_command shapes under test are exactly the reference's call
sites (reference attendance_processor.py:78,83-88,109-113,129,152;
data_generator.py:59-63). The tpu-vs-memory differential tests are the
framework's stand-in for the redis-vs-tpu parity harness when no Redis
server is reachable (SURVEY.md §4).
"""

import numpy as np
import pytest

from attendance_tpu.config import Config
from attendance_tpu.sketch import (
    MemorySketchStore, ResponseError, TpuSketchStore, make_sketch_store)


def _stores():
    cfg = Config(hll_initial_banks=2)
    return [TpuSketchStore(cfg), MemorySketchStore(cfg)]


@pytest.mark.parametrize("store", _stores(), ids=["tpu", "memory"])
class TestCommandSurface:
    def test_reference_processor_setup_sequence(self, store):
        store.flush()
        # _setup_bloom_filter probe: BF.EXISTS on a missing key -> 0,
        # then BF.RESERVE, then re-reserve raises (reference
        # attendance_processor.py:74-92 expects ResponseError semantics).
        assert store.execute_command("BF.EXISTS", "bf:students", "test") == 0
        assert store.execute_command("BF.RESERVE", "bf:students", 0.01,
                                     100_000)
        with pytest.raises(ResponseError):
            store.execute_command("BF.RESERVE", "bf:students", 0.01, 100_000)

    def test_add_exists_roundtrip(self, store):
        store.flush()
        store.execute_command("BF.RESERVE", "bf", 0.01, 10_000)
        assert store.execute_command("BF.ADD", "bf", 12345) == 1
        assert store.execute_command("BF.ADD", "bf", 12345) == 0  # dup
        assert store.execute_command("BF.EXISTS", "bf", 12345) == 1
        assert store.execute_command("BF.EXISTS", "bf", "12345") == 1  # str
        assert store.execute_command("BF.EXISTS", "bf", 99999999) == 0

    def test_madd_mexists(self, store):
        store.flush()
        store.execute_command("BF.RESERVE", "bf", 0.01, 10_000)
        assert store.execute_command("BF.MADD", "bf", 1, 2, 3) == [1, 1, 1]
        got = store.execute_command("BF.MEXISTS", "bf", 1, 2, 3, 4)
        assert got[:3] == [1, 1, 1] and got[3] == 0

    def test_add_autocreates_and_scales(self, store):
        store.flush()
        # BF.ADD without BF.RESERVE: RedisBloom default capacity 100,
        # auto-scaling chain growth beyond it; no false negatives ever.
        keys = np.arange(1000, 2000, dtype=np.uint32)
        store.bf_add_many("auto", keys)
        assert store.bf_exists_many("auto", keys).all()
        info = store.execute_command("BF.INFO", "auto")
        assert info["Number of filters"] > 1
        assert info["Number of items inserted"] == 1000

    def test_pfadd_pfcount(self, store):
        store.flush()
        assert store.pfcount("hll:unique:LEC1") == 0
        assert store.pfadd("hll:unique:LEC1", 111) == 1
        assert store.pfadd("hll:unique:LEC1", 111) == 0  # no change
        store.pfadd_many("hll:unique:LEC1",
                         np.arange(500, dtype=np.uint32))
        est = store.pfcount("hll:unique:LEC1")
        assert abs(est - 501) <= 15
        # execute_command spellings too
        assert store.execute_command("PFADD", "hll:u2", 5) == 1
        assert store.execute_command("PFCOUNT", "hll:u2") == 1

    def test_pfcount_union(self, store):
        store.flush()
        store.pfadd_many("a", np.arange(0, 3000, dtype=np.uint32))
        store.pfadd_many("b", np.arange(1500, 4500, dtype=np.uint32))
        est = store.pfcount("a", "b")
        assert abs(est - 4500) / 4500 < 0.03

    def test_pfadd_mask(self, store):
        store.flush()
        keys = np.arange(2000, dtype=np.uint32)
        store.pfadd_many("m", keys, mask=keys < 700)
        assert abs(store.pfcount("m") - 700) / 700 < 0.03


def test_tpu_memory_differential_bloom():
    """Backends share hash math -> identical membership answers."""
    cfg = Config()
    tpu, mem = TpuSketchStore(cfg), MemorySketchStore(cfg)
    rng = np.random.default_rng(7)
    members = rng.integers(0, 2**31, size=20_000, dtype=np.uint32)
    probes = rng.integers(0, 2**31, size=50_000, dtype=np.uint32)
    for s in (tpu, mem):
        s.execute_command("BF.RESERVE", "bf", 0.01, 30_000)
        s.bf_add_many("bf", members)
    np.testing.assert_array_equal(
        tpu.bf_exists_many("bf", probes), mem.bf_exists_many("bf", probes))


def test_tpu_memory_differential_hll():
    cfg = Config()
    tpu, mem = TpuSketchStore(cfg), MemorySketchStore(cfg)
    rng = np.random.default_rng(11)
    keys = rng.integers(0, 2**32, size=100_000, dtype=np.uint32)
    for s in (tpu, mem):
        s.pfadd_many("h", keys)
    # Same hashes + same estimator -> identical counts, not just close.
    assert tpu.pfcount("h") == mem.pfcount("h")


def test_factory_selects_backend():
    assert isinstance(make_sketch_store(Config(sketch_backend="tpu")),
                      TpuSketchStore)
    assert isinstance(make_sketch_store(Config(sketch_backend="memory")),
                      MemorySketchStore)


def test_execute_command_arity_errors_are_response_errors():
    """A real server answers arity mistakes with a command-level error;
    the facade must raise ResponseError, never a bare unpacking
    ValueError — redis-py-written callers catch exactly one type."""
    import pytest

    from attendance_tpu.config import Config
    from attendance_tpu.sketch.base import ResponseError
    from attendance_tpu.sketch.memory_store import MemorySketchStore
    from attendance_tpu.sketch.redis_sim import RedisSimSketchStore

    for store in (MemorySketchStore(Config(sketch_backend="memory")),
                  RedisSimSketchStore(Config(sketch_backend="redis-sim"))):
        with pytest.raises(ResponseError):
            store.execute_command("BF.RESERVE", "k", 0.01)  # missing cap
        with pytest.raises(ResponseError):
            store.execute_command("BF.ADD", "k")            # missing member
        with pytest.raises(ResponseError):
            store.execute_command("BF.EXISTS", "k", "a", "b")  # extra
        with pytest.raises(ResponseError):
            store.execute_command("NOT.A.COMMAND", "k")


def test_execute_command_missing_key_arity_is_response_error():
    """Arity mistakes where even the KEY is missing (args[1] would
    IndexError) must also surface as ResponseError — the conversion is
    explicit per command, not a blanket exception rewrite."""
    import pytest

    from attendance_tpu.config import Config
    from attendance_tpu.sketch.base import ResponseError
    from attendance_tpu.sketch.memory_store import MemorySketchStore

    store = MemorySketchStore(Config(sketch_backend="memory"))
    for cmd in ("PFADD", "PFCOUNT", "BF.INFO", "BF.MADD", "BF.MEXISTS",
                "BF.ADD", "BF.EXISTS", "BF.RESERVE"):
        with pytest.raises(ResponseError):
            store.execute_command(cmd)
    # Correct-arity bad VALUES are not mislabelled as arity errors.
    with pytest.raises(Exception) as e:
        store.execute_command("BF.RESERVE", "k", "not-a-rate", 100)
    assert "wrong number of arguments" not in str(e.value)


def test_invalid_topic_must_differ_from_input_topic():
    import pytest

    from attendance_tpu.config import Config

    with pytest.raises(ValueError, match="invalid_topic"):
        Config(invalid_topic=Config().pulsar_topic).validate()
    Config(invalid_topic="attendance-invalid").validate()  # fine


def test_randomized_command_sequences_hold_invariants():
    """Generative differential check: random BF./PF. command sequences
    driven through every hermetic backend against an exact-set oracle.
    Invariants per backend: BF.EXISTS never false-negative on an added
    member; PFCOUNT within the sketch budget of the exact distinct
    count; PFADD return semantics (1 on first-ever member via the
    scalar path). Backends may disagree on individual false positives
    (different hash families) — that is the documented contract."""
    import numpy as np

    from attendance_tpu.config import Config
    from attendance_tpu.sketch.memory_store import MemorySketchStore
    from attendance_tpu.sketch.redis_sim import RedisSimSketchStore
    from attendance_tpu.sketch.tpu_store import TpuSketchStore

    rng = np.random.default_rng(77)
    stores = {
        "memory": MemorySketchStore(Config(sketch_backend="memory")),
        "redis-sim": RedisSimSketchStore(Config(sketch_backend="redis-sim")),
        "tpu": TpuSketchStore(Config(sketch_backend="tpu")),
    }
    bloom_truth: dict = {}   # key -> set of added members
    hll_truth: dict = {}     # key -> set of counted members

    for _step in range(60):
        op = rng.choice(["reserve", "add", "madd", "exists", "mexists",
                         "pfadd", "pfadd_many", "pfcount"])
        key = f"k{rng.integers(0, 4)}"
        members = rng.integers(1, 50_000, rng.integers(1, 40)).tolist()
        if op == "reserve":
            for name, s in stores.items():
                try:
                    s.execute_command("BF.RESERVE", key, 0.01, 2_000)
                    created = True
                except Exception:
                    created = False
                # Reserve outcome must agree across backends.
                assert created == (key not in bloom_truth) \
                    or key in bloom_truth, name
            bloom_truth.setdefault(key, set())
        elif op in ("add", "madd"):
            bloom_truth.setdefault(key, set()).update(members)
            for s in stores.values():
                if op == "add":
                    s.execute_command("BF.ADD", key, members[0])
                    s.bf_add_many(key, np.array(members[1:], np.int64)) \
                        if len(members) > 1 else None
                else:
                    s.execute_command("BF.MADD", key, *members)
        elif op in ("exists", "mexists"):
            added = bloom_truth.get(key, set())
            probe = members + list(added)[:20]
            for name, s in stores.items():
                got = s.bf_exists_many(key, np.array(probe, np.int64))
                for m, g in zip(probe, got):
                    if m in added:
                        assert g, (name, key, m)  # no false negatives
        elif op == "pfadd":
            first = members[0] not in hll_truth.setdefault(key, set())
            hll_truth[key].add(members[0])
            for name, s in stores.items():
                changed = s.execute_command("PFADD", key, members[0])
                if first:
                    assert changed == 1, (name, key, members[0])
        elif op == "pfadd_many":
            hll_truth.setdefault(key, set()).update(members)
            for s in stores.values():
                s.pfadd_many(key, np.array(members, np.int64))
        else:  # pfcount
            exact = len(hll_truth.get(key, set()))
            for name, s in stores.items():
                est = s.execute_command("PFCOUNT", key)
                if exact == 0:
                    assert est == 0, name
                else:
                    assert abs(est - exact) <= max(3, 0.05 * exact), \
                        (name, key, est, exact)
