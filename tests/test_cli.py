"""CLI smoke tests: the hermetic pipeline subcommand end-to-end."""

from attendance_tpu.cli import main


def test_pipeline_subcommand_memory_backend(capsys):
    main(["pipeline", "--sketch-backend", "memory", "--num-students", "40",
          "--num-invalid", "5", "--seed", "1", "--batch-size", "128",
          "--batch-timeout-s", "0.01"])
    out = capsys.readouterr().out
    assert "Habitual Latecomers" in out
    assert "Invalid Attendance Attempts" in out


def test_pipeline_subcommand_redis_sim_backend(capsys):
    """The Redis-algorithm simulation is a full execution backend, not
    just the parity oracle: the whole reference pipeline (generate ->
    process -> analyze) runs on it and produces the reference's five
    insights."""
    main(["pipeline", "--sketch-backend", "redis-sim",
          "--num-students", "40", "--num-invalid", "5", "--seed", "1",
          "--batch-size", "128", "--batch-timeout-s", "0.01"])
    out = capsys.readouterr().out
    assert "Habitual Latecomers" in out
    assert "Invalid Attendance Attempts" in out


def test_analyze_subcommand_empty(capsys):
    main(["analyze", "--sketch-backend", "memory"])
    assert "No insights available" in capsys.readouterr().out


def test_fused_subcommand(capsys):
    main(["fused", "--num-events", "16384", "--frame-size", "4096",
          "--num-lectures", "4", "--bloom-capacity", "20000"])
    out = capsys.readouterr().out
    assert "Habitual Latecomers" in out
    assert "Invalid Attendance Attempts" in out


def test_events_file_resolver_scopes_segments_to_fused_name(tmp_path):
    """Fused segments in a dir must override only the FUSED legacy npz
    spelling — an explicitly named OTHER events file in the same dir
    (e.g. the generic processor's) keeps its own content."""
    import numpy as np

    from attendance_tpu.cli import _store_for_events_file
    from attendance_tpu.config import Config
    from attendance_tpu.pipeline.fast_path import EVENTS_SEGMENTS
    from attendance_tpu.storage.columnar_store import ColumnarEventStore

    def mkstore(sids):
        s = ColumnarEventStore()
        s.insert_columns({
            "student_id": np.asarray(sids, np.uint32),
            "lecture_day": np.full(len(sids), 20260101, np.uint32),
            "micros": np.arange(len(sids), dtype=np.int64),
            "is_valid": np.ones(len(sids), bool),
            "event_type": np.zeros(len(sids), np.int8)})
        return s

    mkstore([1, 2, 3]).save_segments(tmp_path / EVENTS_SEGMENTS)
    mkstore([7, 8]).save(tmp_path / "other_events.npz")

    config = Config(storage_backend="columnar")
    other = _store_for_events_file(config,
                                   str(tmp_path / "other_events.npz"))
    assert sorted(other.to_columns()["student_id"].tolist()) == [7, 8]
    fused = _store_for_events_file(config,
                                   str(tmp_path / "fused_events.npz"))
    assert sorted(fused.to_columns()["student_id"].tolist()) == [1, 2, 3]


def test_analyze_loads_columnar_events_file(tmp_path, capsys):
    """analyze --events-file must accept the fused pipeline's columnar
    npz snapshot, not just the row stores' JSONL format."""
    main(["fused", "--num-events", "8192", "--frame-size", "2048",
          "--num-lectures", "4", "--bloom-capacity", "20000",
          "--snapshot-dir", str(tmp_path)])
    capsys.readouterr()
    # All three spellings of the incremental snapshot location: the
    # legacy npz path (superseded by the sibling segments dir), the
    # snapshot dir itself, and the segments dir directly.
    for target in (tmp_path / "fused_events.npz", tmp_path,
                   tmp_path / "fused_events_segs"):
        main(["analyze", "--events-file", str(target)])
        out = capsys.readouterr().out
        assert "Habitual Latecomers" in out
        assert "Invalid Attendance Attempts" in out


def test_pipeline_subcommand_columnar_backend(capsys):
    """--storage-backend columnar must be a drop-in for the generic
    processor path (row-store vocabulary adapted on the columnar
    store)."""
    main(["pipeline", "--sketch-backend", "memory",
          "--storage-backend", "columnar", "--num-students", "40",
          "--num-invalid", "5", "--seed", "1", "--batch-size", "128",
          "--batch-timeout-s", "0.01"])
    out = capsys.readouterr().out
    assert "Habitual Latecomers" in out


def test_analyze_loads_jsonl_into_columnar_flag(tmp_path, capsys):
    """analyze --storage-backend columnar with a row-store JSONL file
    must swap to the row store instead of crashing on np.load."""
    from attendance_tpu.pipeline.generator import generate_student_data
    from attendance_tpu.storage.memory_store import (
        AttendanceRow, MemoryEventStore)

    report = generate_student_data(num_students=30, num_invalid=3, seed=5)
    store = MemoryEventStore()
    store.insert_batch([
        AttendanceRow(e.student_id, e.timestamp, e.lecture_id,
                      e.is_valid, e.event_type) for e in report.events])
    path = tmp_path / "events.jsonl"
    store.save(path)
    main(["analyze", "--storage-backend", "columnar",
          "--events-file", str(path)])
    out = capsys.readouterr().out
    assert "Habitual Latecomers" in out


def test_generate_then_process_subcommands(capsys):
    """The reference's two-process flow as CLI subcommands sharing the
    in-process broker (generate -> process). The generator preloads its
    own sketch store instance, so with hermetic memory backends the
    processor recomputes validity against an empty filter — events all
    flow, none validate (the single-process `pipeline` subcommand is
    the shared-state hermetic path; real deployments share state via
    the redis backend)."""
    from attendance_tpu.transport.memory_broker import MemoryBroker

    MemoryBroker.reset_shared()
    try:
        main(["generate", "--sketch-backend", "memory",
              "--num-students", "20", "--num-invalid", "2",
              "--seed", "5"])
        main(["process", "--sketch-backend", "memory",
              "--idle-timeout-s", "0.5"])
    finally:
        MemoryBroker.reset_shared()


def test_bridge_subcommand(capsys):
    """generate (JSON wire) -> bridge -> fused consuming the binary
    topic, all through CLI entry points on the shared broker."""
    from attendance_tpu.config import Config
    from attendance_tpu.pipeline.fast_path import FusedPipeline
    from attendance_tpu.transport import make_client
    from attendance_tpu.transport.memory_broker import MemoryBroker

    MemoryBroker.reset_shared()
    try:
        main(["generate", "--sketch-backend", "memory",
              "--num-students", "15", "--num-invalid", "2",
              "--seed", "8"])
        main(["bridge", "--idle-timeout-s", "0.5"])
        config = Config(transport_backend="memory",
                        pulsar_topic="attendance-events-binary",
                        bloom_filter_capacity=5_000)
        pipe = FusedPipeline(config, client=make_client(config),
                             num_banks=8)
        pipe.run(idle_timeout_s=0.5)
        assert pipe.metrics.events > 0
    finally:
        MemoryBroker.reset_shared()


def test_parity_subcommand_exits_2_without_redis():
    import pytest

    with pytest.raises(SystemExit) as e:
        main(["parity", "--oracle", "redis", "--num-events", "1000"])
    assert e.value.code == 2


def test_parity_subcommand_sim_oracle_is_hermetic(capsys):
    """The default --oracle sim runs the full parity harness against
    the Redis-algorithm simulation with no server (VERDICT r02 #1)."""
    main(["parity", "--num-events", "4000", "--roster-size", "1500",
          "--num-lectures", "2"])
    out = capsys.readouterr().out
    assert "PARITY OK" in out


def test_stats_subcommand(tmp_path, capsys):
    """stats must answer the reference's get_attendance_stats query
    from a saved store: PFCOUNT (0 here - the hermetic sketch store is
    fresh) plus the partition's record count from the events file."""
    main(["fused", "--num-events", "8192", "--frame-size", "2048",
          "--num-lectures", "4", "--bloom-capacity", "20000",
          "--snapshot-dir", str(tmp_path)])
    capsys.readouterr()
    import numpy as np
    segs = sorted((tmp_path / "fused_events_segs").glob("segment-*.npz"))
    assert segs  # the fused snapshot now writes incremental segments
    days = np.concatenate([np.load(p)["lecture_day"] for p in segs])
    sids = np.concatenate([np.load(p)["student_id"] for p in segs])
    day = int(days[0])
    expect = int((days == day).sum())
    # Default storage backend + the legacy npz path: the resolver must
    # find the sibling segments dir (same contract as analyze).
    main(["stats", f"LECTURE_{day}", "--sketch-backend", "memory",
          "--events-file", str(tmp_path / "fused_events.npz")])
    out = capsys.readouterr().out
    assert f"{expect} attendance records" in out
    # The hermetic sketch store holds no HLL state here: the unique
    # count must fall back to the exact per-partition distinct, never
    # print a silently-wrong zero next to a non-empty partition.
    assert "0 unique attendees" not in out
    exact = len(np.unique(sids[days == day]))
    assert f"{exact} unique attendees" in out


def test_stats_student_id(tmp_path, capsys):
    """stats --student-id answers the per-student access pattern from a
    saved store (the README-promised events_by_student_day surface)."""
    main(["fused", "--num-events", "8192", "--frame-size", "2048",
          "--num-lectures", "4", "--bloom-capacity", "20000",
          "--snapshot-dir", str(tmp_path)])
    capsys.readouterr()
    import json

    import numpy as np
    seg = sorted((tmp_path / "fused_events_segs").glob("segment-*.npz"))[0]
    data = np.load(seg)
    sid = int(np.asarray(data["student_id"])[0])
    main(["stats", "--student-id", str(sid),
          "--events-file", str(tmp_path / "fused_events_segs")])
    out = capsys.readouterr().out
    assert f"Student {sid}:" in out
    assert "attendance records" in out


def test_stats_requires_lecture_or_student():
    import pytest

    with pytest.raises(SystemExit) as e:
        main(["stats"])
    assert e.value.code == 2


def test_pipeline_subcommand_socket_backend(server, capsys):
    """--transport-backend=socket drives the whole pipeline subcommand
    through the framework's own cross-process broker: generator and
    processor each dial the server over TCP, sharing topics through it
    instead of an in-process object."""
    main(["pipeline", "--sketch-backend", "memory",
          "--transport-backend", "socket",
          "--socket-broker", server.address,
          "--num-students", "40", "--num-invalid", "5",
          "--seed", "3", "--batch-size", "128"])
    out = capsys.readouterr().out
    assert "Habitual Latecomers" in out
    assert "Invalid Attendance Attempts" in out
