"""CLI smoke tests: the hermetic pipeline subcommand end-to-end."""

from attendance_tpu.cli import main


def test_pipeline_subcommand_memory_backend(capsys):
    main(["pipeline", "--sketch-backend", "memory", "--num-students", "40",
          "--num-invalid", "5", "--seed", "1", "--batch-size", "128",
          "--batch-timeout-s", "0.01"])
    out = capsys.readouterr().out
    assert "Habitual Latecomers" in out
    assert "Invalid Attendance Attempts" in out


def test_analyze_subcommand_empty(capsys):
    main(["analyze", "--sketch-backend", "memory"])
    assert "No insights available" in capsys.readouterr().out


def test_fused_subcommand(capsys):
    main(["fused", "--num-events", "16384", "--frame-size", "4096",
          "--num-lectures", "4", "--bloom-capacity", "20000"])
    out = capsys.readouterr().out
    assert "Habitual Latecomers" in out
    assert "Invalid Attendance Attempts" in out
