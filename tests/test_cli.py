"""CLI smoke tests: the hermetic pipeline subcommand end-to-end."""

from attendance_tpu.cli import main


def test_pipeline_subcommand_memory_backend(capsys):
    main(["pipeline", "--sketch-backend", "memory", "--num-students", "40",
          "--num-invalid", "5", "--seed", "1", "--batch-size", "128",
          "--batch-timeout-s", "0.01"])
    out = capsys.readouterr().out
    assert "Habitual Latecomers" in out
    assert "Invalid Attendance Attempts" in out


def test_analyze_subcommand_empty(capsys):
    main(["analyze", "--sketch-backend", "memory"])
    assert "No insights available" in capsys.readouterr().out


def test_fused_subcommand(capsys):
    main(["fused", "--num-events", "16384", "--frame-size", "4096",
          "--num-lectures", "4", "--bloom-capacity", "20000"])
    out = capsys.readouterr().out
    assert "Habitual Latecomers" in out
    assert "Invalid Attendance Attempts" in out


def test_analyze_loads_columnar_events_file(tmp_path, capsys):
    """analyze --events-file must accept the fused pipeline's columnar
    npz snapshot, not just the row stores' JSONL format."""
    main(["fused", "--num-events", "8192", "--frame-size", "2048",
          "--num-lectures", "4", "--bloom-capacity", "20000",
          "--snapshot-dir", str(tmp_path)])
    capsys.readouterr()
    main(["analyze", "--events-file", str(tmp_path / "fused_events.npz")])
    out = capsys.readouterr().out
    assert "Habitual Latecomers" in out
    assert "Invalid Attendance Attempts" in out


def test_pipeline_subcommand_columnar_backend(capsys):
    """--storage-backend columnar must be a drop-in for the generic
    processor path (row-store vocabulary adapted on the columnar
    store)."""
    main(["pipeline", "--sketch-backend", "memory",
          "--storage-backend", "columnar", "--num-students", "40",
          "--num-invalid", "5", "--seed", "1", "--batch-size", "128",
          "--batch-timeout-s", "0.01"])
    out = capsys.readouterr().out
    assert "Habitual Latecomers" in out


def test_analyze_loads_jsonl_into_columnar_flag(tmp_path, capsys):
    """analyze --storage-backend columnar with a row-store JSONL file
    must swap to the row store instead of crashing on np.load."""
    from attendance_tpu.pipeline.generator import generate_student_data
    from attendance_tpu.storage.memory_store import (
        AttendanceRow, MemoryEventStore)

    report = generate_student_data(num_students=30, num_invalid=3, seed=5)
    store = MemoryEventStore()
    store.insert_batch([
        AttendanceRow(e.student_id, e.timestamp, e.lecture_id,
                      e.is_valid, e.event_type) for e in report.events])
    path = tmp_path / "events.jsonl"
    store.save(path)
    main(["analyze", "--storage-backend", "columnar",
          "--events-file", str(path)])
    out = capsys.readouterr().out
    assert "Habitual Latecomers" in out
