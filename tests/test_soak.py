"""Randomized crash/restart soak.

Repeatedly crashes a checkpointed pipeline at random progress points —
random batch sizes, mesh shapes (single-chip and sharded), capacities,
wire formats, and snapshot cadences — and asserts the final store +
PFCOUNTs always equal an uninterrupted reference run. Exercises the
full at-least-once / idempotent-replay / snapshot-barrier story end to
end (SURVEY.md §5).

Two tiers (VERDICT r02 #8): a reduced run (2 cycles, ~20s) is part of
the DEFAULT suite so the randomized property executes every round; the
full-length version (6 cycles) stays behind ``ATP_SOAK=1``.
"""

import os
import shutil
import tempfile

import numpy as np
import pytest


def _soak(num_cycles: int, seed: int) -> None:
    from attendance_tpu.config import Config
    from attendance_tpu.pipeline.fast_path import FusedPipeline
    from attendance_tpu.pipeline.loadgen import generate_frames
    from attendance_tpu.transport.memory_broker import (
        MemoryBroker, MemoryClient)

    rng = np.random.default_rng(seed)
    for cycle in range(num_cycles):
        B = int(rng.choice([512, 1024, 2048]))
        NF = int(rng.integers(6, 14))
        sharded = bool(rng.random() < 0.5)
        shards, reps = ((int(rng.choice([2, 4])), int(rng.choice([1, 2])))
                        if sharded else (1, 1))
        cap = int(rng.choice([10_000, 30_000]))
        roster, frames = generate_frames(
            B * NF, B, roster_size=cap // 2,
            num_lectures=int(rng.integers(3, 9)),
            seed=int(rng.integers(1e6)))
        frames = list(frames)

        wire = str(rng.choice(["auto", "word", "seg", "delta"]))

        def mkpipe(broker, snap=None):
            cfg = Config(
                bloom_filter_capacity=cap, transport_backend="memory",
                num_shards=shards, num_replicas=reps,
                wire_format=wire if not sharded else "auto",
                snapshot_dir=snap or "",
                snapshot_every_batches=(int(rng.integers(1, 4))
                                        if snap else 0))
            return FusedPipeline(cfg, client=MemoryClient(broker),
                                 num_banks=8)

        b0 = MemoryBroker()
        ref = mkpipe(b0)
        ref.preload(roster)
        p0 = MemoryClient(b0).create_producer(ref.config.pulsar_topic)
        for f in frames:
            p0.send(f)
        ref.run(max_events=B * NF, idle_timeout_s=0.5)
        ref_counts = {d: ref.count(d) for d in ref.lecture_days()}
        ref_cols = {k: np.sort(np.asarray(v))
                    for k, v in ref.store.to_columns().items()}

        snapdir = tempfile.mkdtemp()
        try:
            broker = MemoryBroker()
            pr = MemoryClient(broker).create_producer(
                ref.config.pulsar_topic)
            for f in frames:
                pr.send(f)
            pipe = mkpipe(broker, snapdir)
            pipe.preload(roster)
            for _crash in range(int(rng.integers(1, 4))):
                pipe.run(max_events=int(rng.integers(1, B * NF)),
                         idle_timeout_s=0.4)
                pipe.consumer.close()  # crash: unacked frames redeliver
                pipe = mkpipe(broker, snapdir)  # restores snapshot
            pipe.run(idle_timeout_s=0.8)
            assert pipe.consumer.backlog() == 0
            got_counts = {d: pipe.count(d) for d in pipe.lecture_days()}
            assert got_counts == ref_counts, cycle
            got_cols = {k: np.sort(np.asarray(v))
                        for k, v in pipe.store.to_columns().items()}
            assert (len(got_cols["student_id"])
                    == len(ref_cols["student_id"])), cycle
            for k in ("student_id", "lecture_day", "micros", "is_valid"):
                assert np.array_equal(got_cols[k], ref_cols[k]), (cycle, k)
        finally:
            shutil.rmtree(snapdir, ignore_errors=True)


def test_crash_restart_soak_reduced():
    """Always-on tier: two randomized crash/restart cycles per run."""
    _soak(num_cycles=2, seed=123)


@pytest.mark.skipif(
    os.environ.get("ATP_SOAK") != "1",
    reason="full soak: set ATP_SOAK=1 to run")
def test_randomized_crash_restart_soak():
    """Full-length tier (6 cycles) — opt-in, different seed stream from
    the reduced tier so the two don't replay identical populations."""
    _soak(num_cycles=6, seed=1234)
