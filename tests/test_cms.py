"""Count-Min sketch + top-K kernel (models/cms.py): device-vs-numpy
differential identity, the one-sided error contract against an exact
dict oracle (property tests over random streams), and heavy-hitter
recovery with zero misses.
"""

import numpy as np
import pytest

from attendance_tpu.models.cms import (
    TopK, cms_init, cms_init_np, cms_positions_np, cms_query,
    cms_query_np, cms_step, cms_update, cms_update_np,
    make_jitted_cms_step)


def _exact_counts(keys):
    vals, counts = np.unique(keys, return_counts=True)
    return dict(zip(vals.tolist(), counts.tolist()))


@pytest.mark.parametrize("seed", [11, 22, 33])
def test_device_matches_numpy_twin(seed):
    """Same murmur3 lanes, same scatter semantics: the device CMS and
    the host twin must hold IDENTICAL count arrays after identical
    streams, and answer identical estimates."""
    import jax.numpy as jnp

    rng = np.random.default_rng(seed)
    depth, width = 3, 512
    keys = rng.integers(0, 5_000, 4_096).astype(np.uint32)
    dev = cms_init(depth, width)
    dev = cms_update(dev, jnp.asarray(keys))
    host = cms_init_np(depth, width)
    cms_update_np(host, keys)
    assert (np.asarray(dev) == host).all()
    probes = np.concatenate([keys[:512], rng.integers(
        10_000, 20_000, 256).astype(np.uint32)])
    assert (np.asarray(cms_query(dev, jnp.asarray(probes)))
            == cms_query_np(host, probes)).all()


@pytest.mark.parametrize("seed", [5, 6, 7, 8])
def test_one_sided_error_vs_exact_oracle(seed):
    """The CMS contract, property-tested: estimates NEVER undercount
    (fraud can't hide), and overcount stays within the e*N/width
    bound for every probed key — on both paths."""
    import jax.numpy as jnp

    rng = np.random.default_rng(seed)
    depth, width = 4, 2_048
    n = 20_000
    # Zipf-flavored stream: a few hot keys over a long tail.
    keys = np.where(rng.random(n) < 0.3,
                    rng.integers(0, 8, n),
                    rng.integers(100, 50_000, n)).astype(np.uint32)
    exact = _exact_counts(keys)
    host = cms_init_np(depth, width)
    cms_update_np(host, keys)
    probes = np.unique(keys)
    ests = cms_query_np(host, probes)
    truth = np.array([exact[int(k)] for k in probes])
    assert (ests >= truth).all(), "CMS undercounted (impossible)"
    bound = np.e * n / width  # classic CMS overcount bound
    assert (ests.astype(np.int64) - truth <= bound).all()
    dev = cms_update(cms_init(depth, width), jnp.asarray(keys))
    assert (np.asarray(cms_query(dev, jnp.asarray(probes))) == ests
            ).all()


def test_masked_lanes_do_not_count():
    import jax.numpy as jnp

    keys = np.arange(100, dtype=np.uint32)
    mask = np.zeros(100, bool)
    mask[:50] = True
    dev = cms_update(cms_init(2, 256), jnp.asarray(keys),
                     jnp.asarray(mask))
    est = cms_query_np(np.asarray(dev), keys)
    assert (est[:50] >= 1).all()
    assert int(np.asarray(dev).sum()) == 50 * 2  # only unmasked lanes


def test_fused_step_estimates_post_update():
    """cms_step answers AFTER folding the batch: a key's estimate at
    its last occurrence equals its running count (per duplicates in
    the batch too)."""
    import jax.numpy as jnp

    keys = np.array([7, 7, 7, 9], np.uint32)
    step = make_jitted_cms_step(donate=False)
    counts, est = step(cms_init(3, 128), jnp.asarray(keys),
                      jnp.ones(4, bool))
    est = np.asarray(est)
    assert est[0] == est[1] == est[2] == 3  # post-batch estimate
    assert est[3] == 1
    counts2, est2 = cms_step(counts, jnp.asarray(keys))
    assert np.asarray(est2)[2] == 6


def test_duplicate_scatter_adds_sum():
    """XLA scatter-add must sum colliding in-batch indices — 1000
    copies of one key count 1000, not 1."""
    import jax.numpy as jnp

    keys = np.full(1_000, 42, np.uint32)
    dev = cms_update(cms_init(2, 64), jnp.asarray(keys))
    assert int(cms_query_np(np.asarray(dev),
                            np.array([42], np.uint32))[0]) == 1_000


def test_positions_distinct_rows():
    keys = np.arange(1_000, dtype=np.uint32)
    pos = cms_positions_np(keys, 4, 1 << 12)
    # Independent lanes: rows must not all agree (prob ~0 at width 4k).
    assert not np.array_equal(pos[0], pos[1])
    assert pos.min() >= 0 and pos.max() < (1 << 12)


@pytest.mark.parametrize("seed", [101, 202])
def test_topk_recovers_heavy_hitters_zero_misses(seed):
    """Seeded hot keys at 50x background rate: the CMS+TopK pattern
    must recover EVERY one of them (the fraud gate's zero-miss
    acceptance), judged against the exact dict oracle."""
    import jax.numpy as jnp

    rng = np.random.default_rng(seed)
    hot = rng.choice(10_000, 8, replace=False).astype(np.uint32)
    n = 30_000
    keys = np.where(rng.random(n) < 0.4,
                    hot[rng.integers(0, len(hot), n)],
                    rng.integers(100_000, 1_000_000, n)
                    ).astype(np.uint32)
    exact = _exact_counts(keys)
    top_truth = sorted(exact, key=exact.get, reverse=True)[:8]
    assert set(top_truth) == set(int(h) for h in hot)
    step = make_jitted_cms_step(donate=False)
    counts = cms_init(4, 1 << 13)
    topk = TopK(12)
    for i in range(0, n, 4_096):
        batch = keys[i:i + 4_096]
        pad = np.zeros(4_096, np.uint32)
        pad[:len(batch)] = batch
        mask = np.zeros(4_096, bool)
        mask[:len(batch)] = True
        counts, est = step(counts, jnp.asarray(pad), jnp.asarray(mask))
        topk.offer(batch, np.asarray(est)[:len(batch)])
    got = {k for k, _ in topk.items()}
    assert set(int(h) for h in hot) <= got, "top-K missed a hot key"
    # Estimates for the hot keys are exact-or-over, never under.
    for key, est in topk.items():
        if key in exact:
            assert est >= exact[key] or est >= exact[key] * 0.99


def test_topk_bounds_and_validation():
    with pytest.raises(ValueError):
        TopK(0)
    with pytest.raises(ValueError):
        cms_init(0, 16)
    t = TopK(2)
    t.offer(np.array([1, 2, 3, 4], np.uint32),
            np.array([10, 40, 30, 20], np.uint64))
    assert [k for k, _ in t.items()] == [2, 3]
    assert len(t) == 2
    # A later, larger sighting of an evicted key re-enters.
    t.offer(np.array([1], np.uint32), np.array([99], np.uint64))
    assert [k for k, _ in t.items()] == [1, 2]
