"""Hermetic end-to-end pipeline tests.

The assertion oracle is the one the reference ships implicitly
(SURVEY.md §4): every generated event carries ground-truth ``is_valid``
which the processor must ignore and recompute via the Bloom filter — no
false negatives ever, false positives within the FPR budget.
"""

import numpy as np
import pytest

from attendance_tpu.config import Config
from attendance_tpu.pipeline.analyzer import AttendanceAnalyzer
from attendance_tpu.pipeline.events import (
    AttendanceEvent, columns_from_events, decode_binary_batch, decode_event,
    encode_binary_batch, encode_event)
from attendance_tpu.pipeline.generator import generate_student_data
from attendance_tpu.pipeline.processor import AttendanceProcessor
from attendance_tpu.storage.memory_store import (
    AttendanceRow, MemoryEventStore)
from attendance_tpu.transport.memory_broker import MemoryBroker, MemoryClient


def hermetic_config(**kw) -> Config:
    return Config(sketch_backend=kw.pop("sketch_backend", "memory"),
                  transport_backend="memory", storage_backend="memory",
                  batch_size=kw.pop("batch_size", 256),
                  batch_timeout_s=0.01, **kw)


def test_event_json_roundtrip():
    e = AttendanceEvent(12345, "2026-07-27T08:30:00", "LECTURE_20260727",
                        True, "entry")
    assert decode_event(encode_event(e)) == e


def test_binary_batch_roundtrip():
    events = [
        AttendanceEvent(12345, "2026-07-27T08:30:00", "LECTURE_20260727",
                        True, "entry"),
        AttendanceEvent(543210, "2026-07-27T12:01:00", "LECTURE_20260727",
                        False, "exit"),
    ]
    cols = decode_binary_batch(encode_binary_batch(events))
    ref = columns_from_events(events)
    for name in ("student_id", "lecture_day", "micros", "is_valid",
                 "event_type"):
        np.testing.assert_array_equal(cols[name], ref[name])


def test_generator_population_and_mix():
    report = generate_student_data(seed=7, num_students=100, num_invalid=10)
    assert len(report.valid_student_ids) == 100
    assert len(report.invalid_student_ids) == 10
    assert all(10_000 <= s <= 99_999 for s in report.valid_student_ids)
    assert all(100_000 <= s <= 999_999 for s in report.invalid_student_ids)
    # every student attends 3-7 days, entry+exit per day, >=20 standalone
    # invalid attempts at the end
    entries = [e for e in report.events if e.event_type == "entry"
               and e.is_valid]
    exits = [e for e in report.events if e.event_type == "exit"]
    assert len(entries) == len(exits)
    assert 3 * 100 <= len(entries) <= 7 * 100
    assert report.invalid_attempts >= 20
    assert report.message_count == len(report.events)
    # deterministic under the same seed
    report2 = generate_student_data(seed=7, num_students=100, num_invalid=10)
    assert [e.to_dict() for e in report2.events] == [
        e.to_dict() for e in report.events]


@pytest.mark.parametrize("sketch_backend", ["memory", "tpu"])
def test_end_to_end_validity_oracle(sketch_backend):
    """generator -> broker -> processor -> store; stored validity must
    match the generator's ground truth (no false negatives; FPs allowed
    within budget)."""
    config = hermetic_config(sketch_backend=sketch_backend)
    client = MemoryClient(MemoryBroker())
    processor = AttendanceProcessor(config, client=client)
    processor.setup_bloom_filter()

    producer = client.create_producer(config.pulsar_topic)
    report = generate_student_data(
        producer=producer, sketch_store=processor.sketch,
        bloom_key=config.bloom_filter_key, seed=11,
        num_students=200, num_invalid=20)

    processor.process_attendance(max_events=report.message_count,
                                 idle_timeout_s=0.2)
    assert processor.metrics.events == report.message_count

    truth = {}
    for e in report.events:
        truth[(e.lecture_id, e.timestamp, e.student_id)] = e.is_valid
    rows = processor.store.scan_all()
    assert len(rows) == len(truth)
    false_negatives = 0
    false_positives = 0
    for r in rows:
        gt = truth[(r.lecture_id, r.timestamp, r.student_id)]
        if gt and not r.is_valid:
            false_negatives += 1
        if not gt and r.is_valid:
            false_positives += 1
    assert false_negatives == 0
    # 20 invalid ids, eps=0.01: expected FPs ~0; allow slack for unlucky
    # hash collisions.
    assert false_positives <= max(2, 0.05 * report.invalid_attempts)


def test_hll_counts_match_exact_uniques():
    config = hermetic_config()
    client = MemoryClient(MemoryBroker())
    processor = AttendanceProcessor(config, client=client)
    producer = client.create_producer(config.pulsar_topic)
    report = generate_student_data(
        producer=producer, sketch_store=processor.sketch,
        bloom_key=config.bloom_filter_key, seed=3, num_students=300,
        num_invalid=30)
    processor.process_attendance(max_events=report.message_count,
                                 idle_timeout_s=0.2)

    # exact uniques per lecture among generated-valid events
    exact = {}
    for e in report.events:
        if e.is_valid:
            exact.setdefault(e.lecture_id, set()).add(e.student_id)
    for lecture_id, students in exact.items():
        stats = processor.get_attendance_stats(lecture_id)
        est = stats["unique_attendees"]
        # p=14 sigma ~0.81%; at n<=300 the Ertl estimator is near-exact,
        # but Bloom FPs can add a few distinct invalid ids.
        assert est == pytest.approx(len(students), rel=0.05, abs=3), \
            (lecture_id, est, len(students))


def test_batch_failure_nacks_and_recovers():
    """A poison batch is nacked wholesale and redelivered; replay after the
    fault clears is idempotent (SURVEY.md §5 failure semantics)."""
    config = hermetic_config(batch_size=4)
    client = MemoryClient(MemoryBroker())
    processor = AttendanceProcessor(config, client=client)
    processor.setup_bloom_filter()
    processor.sketch.bf_add_many(config.bloom_filter_key, [111, 222])
    producer = client.create_producer(config.pulsar_topic)
    for sid in (111, 222):
        producer.send(encode_event(AttendanceEvent(
            sid, "2026-07-27T08:00:00", "LECTURE_20260727", True, "entry")))
    producer.send(b"not json at all")  # poison frame
    processor.process_attendance(idle_timeout_s=0.5)
    # the poison frame was retried max_redeliveries times, then
    # dead-lettered; the good events landed exactly once
    assert processor.metrics.dead_lettered == 1
    assert processor.store.count() == 2
    assert processor.consumer.backlog() == 0


def test_analyzer_five_insights():
    config = hermetic_config()
    client = MemoryClient(MemoryBroker())
    processor = AttendanceProcessor(config, client=client)
    producer = client.create_producer(config.pulsar_topic)
    report = generate_student_data(
        producer=producer, sketch_store=processor.sketch,
        bloom_key=config.bloom_filter_key, seed=5, num_students=100,
        num_invalid=10)
    processor.process_attendance(max_events=report.message_count,
                                 idle_timeout_s=0.2)

    analyzer = AttendanceAnalyzer(processor.store)
    insights = analyzer.generate_insights()
    titles = [i["title"] for i in insights]
    assert titles == [
        "Habitual Latecomers", "Attendance by Day",
        "Lecture Attendance Rankings", "Most Consistent Attendees",
        "Invalid Attendance Attempts"]
    rankings = insights[2]["data"]
    assert 1 <= len(rankings["most_attended"]) <= 3
    # invalid attempts insight only contains generated-invalid students
    # (modulo Bloom FPs which would remove, not add, entries)
    for sid in insights[4]["data"]:
        assert sid >= 100_000
    analyzer.print_insights(insights)  # smoke: no exception


def test_analyzer_empty_store():
    analyzer = AttendanceAnalyzer(MemoryEventStore())
    assert analyzer.generate_insights() == []
    analyzer.print_insights([])


def test_analyzer_matches_pandas_oracle():
    """The columnar numpy aggregations must reproduce the reference's
    pandas groupby semantics (reference attendance_analysis.py:65-118) —
    medians, the sample (ddof=1) std, day names, and group counts."""
    import numpy as np
    import pandas as pd

    rng = np.random.default_rng(11)
    store = MemoryEventStore()
    rows = []
    for _ in range(3000):
        sid = int(rng.integers(10_000, 10_060))
        day = int(rng.integers(1, 28))
        hour, minute = int(rng.integers(6, 18)), int(rng.integers(0, 60))
        rows.append(AttendanceRow(
            sid, f"2026-07-{day:02d}T{hour:02d}:{minute:02d}:00",
            f"LECTURE_202607{day:02d}", bool(rng.random() < 0.9), "entry"))
    store.insert_batch(rows)
    insights = AttendanceAnalyzer(store).generate_insights()

    kept = store.scan_all()  # post-upsert-dedup ground truth
    df = pd.DataFrame({
        "student_id": [r.student_id for r in kept],
        "lecture_id": [r.lecture_id for r in kept],
        "ts": pd.to_datetime([r.timestamp for r in kept]),
        "is_valid": [r.is_valid for r in kept]})

    late = df[df.ts.dt.hour >= 9].groupby("student_id").size()
    exp = late[late > late.median()]
    assert insights[0]["data"] == {int(k): int(v) for k, v in exp.items()}

    days = df.groupby(df.ts.dt.day_name()).size()
    assert insights[1]["data"] == {str(k): int(v) for k, v in days.items()}

    counts = df.groupby("student_id").size()
    exp = counts[counts > counts.median() + counts.std()]
    assert insights[3]["data"] == {int(k): int(v) for k, v in exp.items()}

    inv = df[~df.is_valid].groupby("student_id").size()
    assert insights[4]["data"] == {int(k): int(v) for k, v in inv.items()}

    ranked = df.groupby("lecture_id").size().sort_values(ascending=False)
    got = insights[2]["data"]
    assert set(got["most_attended"].values()) == set(
        ranked.head(3).tolist())
    assert set(got["least_attended"].values()) == set(
        ranked.tail(3).tolist())


def test_invalid_topic_routes_computed_invalid_events():
    """The README-promised attendance-invalid routing topic (SURVEY
    §0.3 item 4, a sanctioned stretch feature): with
    config.invalid_topic set, every COMPUTED-invalid event is
    republished there in the reference JSON wire, while the
    code-contract behavior (row stored with is_valid=false) is
    unchanged. Validity is the Bloom verdict, not the generator flag."""
    from attendance_tpu.config import Config
    from attendance_tpu.pipeline.events import decode_event, encode_event
    from attendance_tpu.pipeline.generator import generate_student_data
    from attendance_tpu.pipeline.processor import AttendanceProcessor
    from attendance_tpu.transport.memory_broker import (
        MemoryBroker, MemoryClient)

    config = Config(sketch_backend="memory", transport_backend="memory",
                    invalid_topic="attendance-invalid")
    broker = MemoryBroker()
    proc = AttendanceProcessor(config, client=MemoryClient(broker))
    producer = MemoryClient(broker).create_producer(config.pulsar_topic)
    report = generate_student_data(
        producer=producer, sketch_store=proc.sketch,
        bloom_key=config.bloom_filter_key, num_students=30,
        num_invalid=6, seed=3)
    proc.process_attendance(max_events=report.message_count,
                            idle_timeout_s=0.3)

    from attendance_tpu.transport.memory_broker import ReceiveTimeout

    side = MemoryClient(broker).subscribe("attendance-invalid", "dlq")
    routed = []
    while True:
        try:
            batch = side.receive_many(1024, timeout_millis=50)
        except ReceiveTimeout:
            break
        routed.extend(decode_event(m.data()) for m in batch)
        for m in batch:
            side.acknowledge(m)
    stored_invalid = [r for r in proc.store.scan_all() if not r.is_valid]
    assert routed, "no invalid events routed"
    assert len(routed) == len(stored_invalid)
    assert {e.student_id for e in routed} == \
        {r.student_id for r in stored_invalid}
    # Round-trip stability: routed payloads are the reference wire.
    assert decode_event(encode_event(routed[0])).student_id \
        == routed[0].student_id
