"""Fault-injection plane + self-healing transport/storage tests.

Covers the chaos spec grammar and seed determinism, the socket
transport's reconnect/session-resume/retry machinery (real TCP, real
severed connections), the persist-sink circuit breaker with its durable
spill buffer, the on-disk quarantine round-trip (including via the
``doctor`` CLI), the snapshot writer's bounded failure backoff, and a
compact in-process chaos soak (the CI driver's core invariants at test
scale)."""

import json
import time

import numpy as np
import pytest

from attendance_tpu import chaos, obs
from attendance_tpu.config import Config


@pytest.fixture(autouse=True)
def _reset_globals():
    chaos.disable()
    obs.disable()
    yield
    chaos.disable()
    obs.disable()


# ---------------------------------------------------------------------------
# Spec grammar + determinism
# ---------------------------------------------------------------------------

def test_spec_grammar_full_example():
    spec = chaos.ChaosSpec.parse(
        "drop=0.01,delay=5ms:0.05,dup=0.005,conn_reset=0.002,"
        "persist_fail=0.01,writer_stall=200ms:0.01,corrupt=0.001")
    assert spec.drop == 0.01
    assert spec.delay == 0.05 and spec.delay_s == pytest.approx(0.005)
    assert spec.writer_stall == 0.01
    assert spec.writer_stall_s == pytest.approx(0.2)
    assert spec.active("conn_reset") and not spec.active("snap_fail")


def test_spec_grammar_rejects_bad_tokens():
    for bad in ("bogus=0.1", "drop=1.5", "drop", "delay=0.05",
                "writer_stall=abc:0.1"):
        with pytest.raises(ValueError):
            chaos.ChaosSpec.parse(bad)


def test_spec_off_and_empty():
    off = chaos.ChaosSpec.parse("off")
    assert not any(off.active(f) for f in
                   ("drop", "dup", "conn_reset", "persist_fail",
                    "corrupt", "snap_fail", "delay", "writer_stall"))
    assert chaos.ChaosSpec.parse("") == off


def test_injector_streams_deterministic_and_independent():
    spec = chaos.ChaosSpec.parse("drop=0.1,conn_reset=0.1")
    a = chaos.ChaosInjector(spec, seed=7)
    b = chaos.ChaosInjector(spec, seed=7)
    c = chaos.ChaosInjector(spec, seed=8)
    seq_a = [a.roll("socket.produce", "drop") for _ in range(500)]
    seq_b = [b.roll("socket.produce", "drop") for _ in range(500)]
    assert seq_a == seq_b and sum(seq_a) > 10
    # A different site draws an independent stream from the same seed.
    seq_site = [b.roll("socket.consume", "drop") for _ in range(500)]
    assert seq_site != seq_a
    # A different seed changes the schedule.
    seq_c = [c.roll("socket.produce", "drop") for _ in range(500)]
    assert seq_c != seq_a
    assert a.injected[("socket.produce", "drop")] == sum(seq_a)
    assert a.injected_total("drop") == sum(seq_a)


def test_corruption_is_detectable():
    from attendance_tpu.pipeline.events import decode_binary_batch
    from attendance_tpu.pipeline.loadgen import generate_frames

    _, frames = generate_frames(512, 512, roster_size=64,
                                num_lectures=2, seed=0)
    frame = next(iter(frames))
    inj = chaos.ChaosInjector(chaos.ChaosSpec.parse("corrupt=1.0"), 3)
    bad = inj.corrupt_bytes("transport.consume", frame)
    assert bad != frame
    with pytest.raises(Exception):
        decode_binary_batch(bad)
    # JSON payloads break too (the '{' is flipped).
    assert inj.corrupt_bytes("transport.consume", b'{"a": 1}')[0:1] != b"{"


def test_chaos_proxy_mirrors_capabilities():
    """hasattr feature detection must answer for the real backend, not
    the proxy (the bridge lane choice depends on it)."""
    inj = chaos.ChaosInjector(chaos.ChaosSpec.parse("off"), 0)

    class Bare:
        def receive(self, timeout_millis=None):
            raise NotImplementedError

    wrapped = chaos.ChaosConsumer(Bare(), inj)
    assert hasattr(wrapped, "receive")
    assert not hasattr(wrapped, "receive_chunk")
    assert not hasattr(wrapped, "receive_many_raw")


# ---------------------------------------------------------------------------
# Self-healing socket transport
# ---------------------------------------------------------------------------

def _socket_pair(server, **client_kwargs):
    from attendance_tpu.transport.socket_broker import SocketClient

    client = SocketClient(server.address, **client_kwargs)
    return client, client.create_producer("t"), client.subscribe("t", "s")


def test_transient_reset_is_invisible(server):
    """A severed connection mid-stream: the producer reconnects and the
    consumer re-subscribes (session resume); every message arrives and
    the backlog fully settles — no caller ever sees an error."""
    client, producer, consumer = _socket_pair(server)
    got = []
    for i in range(40):
        producer.send(b"m%d" % i)
        if i in (10, 25):
            # Sever BOTH channels behind the library's back: the next
            # RPC on each must heal transparently.
            producer._rpc._sever_locked()
            consumer._rpc._sever_locked()
        msg = consumer.receive(timeout_millis=5000)
        got.append(msg)
        consumer.acknowledge(msg)
    datas = {m.data() for m in got}
    # At-least-once: every payload delivered (dups possible after a
    # reply-lost retry, but with explicit severs here there are none).
    assert {b"m%d" % i for i in range(40)} <= datas
    assert producer._rpc.reconnects >= 1
    assert consumer.resubscribes >= 1
    # Backlog settles: redelivered duplicates (if any) drain too.
    deadline = time.monotonic() + 5
    while consumer.backlog() and time.monotonic() < deadline:
        try:
            consumer.acknowledge(consumer.receive(timeout_millis=200))
        except Exception:
            break
    assert consumer.backlog() == 0
    client.close()


def test_reconnect_requeues_inflight_for_resumed_session(server):
    """Messages in flight (prefetch buffer included) when the
    connection drops are requeued by the server's takeover and
    REDELIVERED to the resumed session — nothing is lost."""
    client, producer, consumer = _socket_pair(server)
    for i in range(8):
        producer.send(b"x%d" % i)
    first = consumer.receive(timeout_millis=5000)  # prefetches the rest
    assert consumer._buffered  # surplus buffered client-side
    consumer.acknowledge(first)
    consumer._rpc._sever_locked()  # connection drops with 7 in flight
    got = set()
    deadline = time.monotonic() + 10
    while len(got) < 7 and time.monotonic() < deadline:
        msg = consumer.receive(timeout_millis=5000)
        got.add(msg.data())
        consumer.acknowledge(msg)
    assert got == {b"x%d" % i for i in range(1, 8)}
    assert consumer.resubscribes >= 1
    client.close()


def test_broker_unavailable_after_budget(server, monkeypatch):
    """A permanently dead broker fails with ONE clear
    BrokerUnavailable once the retry budget burns out — and it
    subclasses ConnectionError for old callers. The dead broker is
    simulated by refusing every reconnect (this sandbox's network
    shim accepts connections to closed listeners, so a real
    server.stop() cannot model refusal here)."""
    from attendance_tpu.transport import socket_broker as sb
    from attendance_tpu.transport.resilience import (
        BrokerUnavailable, RetryPolicy)

    client, producer, _consumer = _socket_pair(
        server, policy=RetryPolicy(budget_s=0.6, base_s=0.02))
    producer.send(b"ok")

    def refuse(self):
        raise ConnectionRefusedError("broker is gone")

    monkeypatch.setattr(sb._Rpc, "reconnect", refuse)
    producer._rpc._sever_locked()
    t0 = time.monotonic()
    with pytest.raises(BrokerUnavailable) as ei:
        producer.send(b"never")
    assert isinstance(ei.value, ConnectionError)
    assert 0.3 <= time.monotonic() - t0 < 10.0
    client.close()


def test_socket_chaos_conn_reset_self_heals(server):
    """Injected conn_reset faults (both directions) across a real
    publish/consume stream: all messages survive, reconnects observed,
    at-least-once accounting holds."""
    from attendance_tpu.transport.socket_broker import SocketClient

    inj = chaos.ChaosInjector(
        chaos.ChaosSpec.parse("conn_reset=0.05,drop=0.05"), seed=11)
    client = SocketClient(server.address, chaos=inj)
    producer = client.create_producer("t2")
    consumer = client.subscribe("t2", "s2")
    n = 120
    for i in range(n):
        producer.send(b"p%d" % i)
    got = set()
    deadline = time.monotonic() + 30
    while len(got) < n and time.monotonic() < deadline:
        try:
            msg = consumer.receive(timeout_millis=1000)
        except Exception:
            continue
        got.add(msg.data())
        consumer.acknowledge(msg)
    assert got == {b"p%d" % i for i in range(n)}
    assert inj.injected_total("conn_reset") > 0
    client.close()


# ---------------------------------------------------------------------------
# Circuit breaker + spill
# ---------------------------------------------------------------------------

class _FlakySink:
    """insert_* fails while self.down; records committed batches."""

    def __init__(self):
        self.down = False
        self.columns = []
        self.rows = []

    def insert_columns(self, cols):
        if self.down:
            raise RuntimeError("sink down")
        self.columns.append(cols)

    def insert_batch(self, rows):
        if self.down:
            raise RuntimeError("sink down")
        self.rows.append(rows)

    def close(self):
        pass


def _cols(tag):
    return {"student_id": np.array([tag]), "lecture_day": np.array([1]),
            "micros": np.array([tag]), "is_valid": np.array([True]),
            "event_type": np.array([0])}


def test_circuit_breaker_state_machine():
    from attendance_tpu.storage.resilient import (
        CLOSED, HALF_OPEN, OPEN, CircuitBreaker)

    clock = [0.0]
    b = CircuitBreaker(failure_threshold=2, cooldown_s=1.0,
                       clock=lambda: clock[0])
    assert b.state == CLOSED and b.allow()
    b.record_failure()
    assert b.state == CLOSED  # below threshold
    b.record_failure()
    assert b.state == OPEN and b.opened_total == 1
    assert not b.allow()  # cooldown not elapsed
    clock[0] = 1.5
    assert b.allow() and b.state == HALF_OPEN  # the probe
    b.record_failure()  # probe failed: reopen, cooldown restarts
    assert b.state == OPEN and b.opened_total == 2
    clock[0] = 3.1
    assert b.allow() and b.state == HALF_OPEN
    b.record_success()
    assert b.state == CLOSED and b.allow()


def test_resilient_store_spills_and_drains_in_order(tmp_path):
    from attendance_tpu.storage.resilient import (
        CircuitBreaker, ResilientEventStore)

    sink = _FlakySink()
    store = ResilientEventStore(
        sink, tmp_path / "spill",
        breaker=CircuitBreaker(failure_threshold=2, cooldown_s=0.05))
    store.insert_columns(_cols(0))
    sink.down = True
    for tag in (1, 2, 3):  # 1,2 fail (open after 2), 3 short-circuits
        store.insert_columns(_cols(tag))
    assert store.breaker.state == "open"
    assert store.spill_pending == 3
    assert len(list((tmp_path / "spill").glob("spill-*.pkl"))) == 3
    sink.down = False
    time.sleep(0.06)  # cooldown: next write is the half-open probe
    store.insert_columns(_cols(4))
    assert store.breaker.state == "closed"
    assert store.spill_pending == 0
    order = [int(c["micros"][0]) for c in sink.columns]
    assert order == [0, 1, 2, 3, 4]  # dedup order preserved
    assert store.spilled_total == 3 and store.drained_total == 3


def test_resilient_store_adopts_spill_across_restart(tmp_path):
    """The spill buffer is durable: a new process (store instance)
    adopts pending files and drains them before new writes."""
    from attendance_tpu.storage.resilient import (
        CircuitBreaker, ResilientEventStore)

    sink = _FlakySink()
    sink.down = True
    store = ResilientEventStore(
        sink, tmp_path / "spill",
        breaker=CircuitBreaker(failure_threshold=1, cooldown_s=30.0))
    store.insert_columns(_cols(1))
    store.insert_columns(_cols(2))
    assert store.spill_pending == 2

    sink2 = _FlakySink()
    store2 = ResilientEventStore(sink2, tmp_path / "spill")
    assert store2.spill_pending == 2
    store2.insert_columns(_cols(3))
    assert [int(c["micros"][0]) for c in sink2.columns] == [1, 2, 3]
    assert store2.spill_pending == 0


def test_resilient_store_close_drains_with_backoff(tmp_path):
    from attendance_tpu.storage.resilient import (
        CircuitBreaker, ResilientEventStore)

    sink = _FlakySink()
    sink.down = True
    store = ResilientEventStore(
        sink, tmp_path / "spill",
        breaker=CircuitBreaker(failure_threshold=1, cooldown_s=0.02))
    store.insert_batch(["row1"])
    assert store.spill_pending == 1
    sink.down = False
    assert store.flush_spill(budget_s=5.0)
    assert sink.rows == [["row1"]]


def test_wrap_store_layers(tmp_path):
    """wrap_store composes chaos injection under the breaker, and is
    the identity when neither is configured."""
    from attendance_tpu.storage import wrap_store
    from attendance_tpu.storage.resilient import ResilientEventStore

    sink = _FlakySink()
    assert wrap_store(sink, Config()) is sink
    chaos.ensure(Config(chaos="persist_fail=1.0", chaos_seed=1))
    cfg = Config(chaos="persist_fail=1.0", chaos_seed=1,
                 persist_spill_dir=str(tmp_path / "spill"),
                 persist_breaker_failures=1,
                 persist_breaker_cooldown_s=30.0)
    store = wrap_store(sink, cfg, sink="test")
    assert isinstance(store, ResilientEventStore)
    store.insert_columns(_cols(1))  # injected failure -> spill, no raise
    assert store.spill_pending == 1 and sink.columns == []


# ---------------------------------------------------------------------------
# Quarantine
# ---------------------------------------------------------------------------

def test_quarantine_roundtrip_and_replay(tmp_path):
    from attendance_tpu.transport.memory_broker import (
        MemoryBroker, MemoryClient)
    from attendance_tpu.transport.quarantine import (
        Quarantine, list_entries, replay)

    qdir = tmp_path / "q"
    q = Quarantine(qdir)
    q.put(b"frame-one", topic="t", reason="poison-frame",
          redeliveries=3, properties={"traceparent": "abc"})
    q.put(b"frame-two", topic="t", reason="poison-frame")
    entries = list_entries(qdir)
    assert [e["bytes"] for e in entries] == [9, 9]
    assert entries[0]["properties"] == {"traceparent": "abc"}

    broker = MemoryBroker()
    client = MemoryClient(broker)
    producer = client.create_producer("replayed")
    consumer = client.subscribe("replayed", "verify")
    assert replay(qdir, producer, remove=True) == 2
    datas = {consumer.receive(timeout_millis=1000).data()
             for _ in range(2)}
    assert datas == {b"frame-one", b"frame-two"}
    assert list_entries(qdir) == []  # purged after replay

    # A sequence survives restart: new writer continues numbering.
    q2 = Quarantine(qdir)
    q2.put(b"frame-three")
    assert len(list_entries(qdir)) == 1


def test_quarantine_orphan_frame_ignored(tmp_path):
    from attendance_tpu.transport.quarantine import (
        Quarantine, list_entries)

    q = Quarantine(tmp_path)
    q.put(b"committed")
    (tmp_path / "q-000099.frame").write_bytes(b"orphan")  # no sidecar
    assert [e["bytes"] for e in list_entries(tmp_path)] == [9]


def test_doctor_lists_and_replays_quarantine(tmp_path, capsys):
    from attendance_tpu.cli import main as cli_main
    from attendance_tpu.transport.memory_broker import MemoryBroker
    from attendance_tpu.transport.quarantine import Quarantine

    qdir = tmp_path / "q"
    Quarantine(qdir).put(b"bad-frame", reason="poison-frame")
    cli_main(["doctor", "--quarantine", str(qdir)])
    out = capsys.readouterr().out
    assert "quarantined frames" in out and "poison-frame" in out

    # Replay through the memory transport onto a fresh topic.
    MemoryBroker.reset_shared()
    cli_main(["doctor", "--quarantine", str(qdir),
              "--replay-quarantine", "--transport-backend", "memory",
              "--pulsar-topic", "replay-topic"])
    out = capsys.readouterr().out
    assert "replayed 1 quarantined frame" in out
    from attendance_tpu.transport.memory_broker import MemoryClient
    consumer = MemoryClient(MemoryBroker.shared()).subscribe(
        "replay-topic", "v")
    assert consumer.receive(timeout_millis=1000).data() == b"bad-frame"
    MemoryBroker.reset_shared()


# ---------------------------------------------------------------------------
# Socket-broker dead-letter path, end to end (satellite: today only the
# memory broker's DLQ is tested)
# ---------------------------------------------------------------------------

def test_poison_frame_socket_dlq_end_to_end(server, tmp_path):
    """Poison frame over the SOCKET broker: bounded redelivery ->
    dead-letter -> metrics -> on-disk quarantine, while every good
    frame processes normally; the quarantined bytes round-trip via
    doctor's replay."""
    from attendance_tpu.pipeline.fast_path import FusedPipeline
    from attendance_tpu.pipeline.loadgen import generate_frames
    from attendance_tpu.transport.socket_broker import SocketClient

    qdir = tmp_path / "quarantine"
    config = Config(bloom_filter_capacity=20_000,
                    transport_backend="socket",
                    socket_broker=server.address,
                    max_redeliveries=2, quarantine_dir=str(qdir))
    client = SocketClient(server.address)
    pipe = FusedPipeline(config, client=client, num_banks=4)
    roster, frames = generate_frames(2048, 512, roster_size=1000,
                                     num_lectures=4, seed=5)
    frames = list(frames)
    pipe.preload(roster)
    producer = SocketClient(server.address).create_producer(
        config.pulsar_topic)
    poison = b"ATPX this is not a frame"
    producer.send(frames[0])
    producer.send(poison)
    for f in frames[1:]:
        producer.send(f)
    # Idle-bounded (no max_events): the poison's bounded redelivery
    # chain must fully play out before the run ends.
    pipe.run(idle_timeout_s=2.0)

    assert pipe.metrics.events == 2048  # every good frame processed
    assert pipe.metrics.dead_lettered == 1
    from attendance_tpu.transport.quarantine import list_entries
    entries = list_entries(qdir)
    assert len(entries) == 1
    assert entries[0]["redeliveries"] == 2  # bounded retry ran
    assert entries[0]["reason"] == "poison-frame"
    # Round-trip: the quarantined bytes are exactly the poison frame.
    from pathlib import Path
    assert Path(entries[0]["frame"]).read_bytes() == poison
    pipe.cleanup()


def test_poison_tracker_backstop_survives_lru_eviction():
    """A mass-poison burst wider than the tracker's LRU cap must still
    dead-letter (the broker redelivery count backstop), while ordinary
    reconnect-requeue inflation alone must not."""
    import logging as _logging

    from attendance_tpu.pipeline.processor import ProcessorMetrics
    from attendance_tpu.transport import PoisonTracker, handle_poison
    from attendance_tpu.transport.memory_broker import Message

    class Consumer:
        def __init__(self):
            self.acked, self.nacked = [], []

        def acknowledge(self, m):
            self.acked.append(m)

        def negative_acknowledge(self, m):
            self.nacked.append(m)

    cfg = Config(max_redeliveries=3)  # backstop = max(12, 8) = 12
    log = _logging.getLogger("test")
    tracker = PoisonTracker(cap=2)  # evicts constantly
    consumer, metrics = Consumer(), ProcessorMetrics()
    # Tracker evicted (first bump for this mid) but the broker count
    # reached the backstop: dead-letter anyway.
    handle_poison(Message(b"x", 1, 12), consumer, metrics, cfg, log,
                  tracker=tracker)
    assert metrics.dead_lettered == 1 and len(consumer.acked) == 1
    # Inflated-but-below-backstop broker count with a fresh tracker
    # entry: still a bounded nack, not a dead-letter.
    handle_poison(Message(b"y", 2, 5), consumer, metrics, cfg, log,
                  tracker=tracker)
    assert metrics.dead_lettered == 1 and len(consumer.nacked) == 1


# ---------------------------------------------------------------------------
# Snapshot-writer backoff (satellite: a failing disk must not spin hot)
# ---------------------------------------------------------------------------

def test_snapshot_writer_backoff_bounded(tmp_path, monkeypatch):
    from attendance_tpu.pipeline.fast_path import FusedPipeline
    from attendance_tpu.transport.memory_broker import (
        MemoryBroker, MemoryClient)

    t = obs.enable(Config(metrics_port=-1))
    config = Config(bloom_filter_capacity=1000,
                    snapshot_dir=str(tmp_path / "snaps"),
                    snapshot_mode="delta", snapshot_every_batches=1,
                    metrics_port=-1)
    pipe = FusedPipeline(config, client=MemoryClient(MemoryBroker()),
                         num_banks=4)
    assert pipe._writer_backoff_s() == 0.0

    def boom(*a, **k):
        raise OSError("disk on fire")

    monkeypatch.setattr(pipe, "_run_snap_job", boom)
    job = dict(kind="base", msgs=[], events=0, bank_of={}, upto=None)
    for expect_streak in (1, 2, 3):
        with pipe._snap_cv:
            pipe._snap_pending += 1
        pipe._run_snap_job_logged(dict(job))
        assert pipe._snap_fail_streak == expect_streak
    # Exponential, bounded: grows with the streak, capped at 5s.
    assert 0.0 < pipe._writer_backoff_s() <= 5.0
    backs = []
    for streak in range(1, 20):
        pipe._snap_fail_streak = streak
        backs.append(pipe._writer_backoff_s())
    assert backs == sorted(backs) and backs[-1] == 5.0
    assert pipe._base_stale  # next barrier owes a full base

    # The failure counter (the --slo snapshot_failures hook) counted.
    total = 0
    for name, _k, _h, members in t.registry.collect():
        if name == "attendance_snapshot_write_failures_total":
            total = sum(m.value for m in members)
    assert total == 3

    # A successful job resets the streak (backoff returns to zero).
    monkeypatch.setattr(pipe, "_run_snap_job", lambda job: None)
    with pipe._snap_cv:
        pipe._snap_pending += 1
    pipe._run_snap_job_logged(dict(job))
    assert pipe._snap_fail_streak == 0
    pipe.cleanup()


def test_slo_alias_snapshot_failures():
    from attendance_tpu.obs.slo import parse_slo

    slo = parse_slo("snapshot_failures<=0")
    assert slo.metric == "attendance_snapshot_write_failures_total"
    assert slo.kind == "counter" and slo.threshold == 0.0


def test_doctor_reconnect_and_circuit_rows(tmp_path):
    from attendance_tpu.obs.slo import doctor_report

    prom = tmp_path / "m.prom"
    prom.write_text(
        "# TYPE attendance_reconnects_total counter\n"
        "attendance_reconnects_total 4\n"
        "# TYPE attendance_circuit_state gauge\n"
        'attendance_circuit_state{sink="columnar"} 0\n')
    text, ok = doctor_report([str(prom)])
    assert ok and "broker reconnects" in text and "info" in text
    assert "persist circuit state" in text
    # Gated: 4 reconnects > 2 fails.
    text, ok = doctor_report([str(prom)], max_reconnects=2)
    assert not ok
    # An open circuit at the last scrape is a breach.
    prom.write_text('attendance_circuit_state{sink="columnar"} 1\n')
    text, ok = doctor_report([str(prom)])
    assert not ok


# ---------------------------------------------------------------------------
# Mini soak: the CI driver's invariants at test scale
# ---------------------------------------------------------------------------

@pytest.mark.slow
def test_chaos_soak_mini(tmp_path):
    import sys
    from pathlib import Path as _P
    sys.path.insert(0, str(_P(__file__).parent.parent / "tools"))
    import chaos_soak

    report = chaos_soak.run_soak(
        1, spec="conn_reset=0.05,persist_fail=0.2,corrupt=0.02,"
                "dup=0.02,snap_fail=0.1",
        workdir=tmp_path, max_seconds=120.0)
    assert report["ok"], report["failures"]
    assert report["reconnects"] > 0
    assert report["circuit_opened"] > 0
    # >= : a dead-letter ack lost to an injected reset re-quarantines
    # the same poison frame (at-least-once); run_soak already asserted
    # the digest set matches the published poisons exactly.
    assert report["quarantined"] >= chaos_soak.POISON_FRAMES
