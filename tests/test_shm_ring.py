"""Shared-memory ring transport tests (ISSUE 11): seqlock integrity
under a concurrent writer (the hammer), wrap-around + full-ring
backpressure, cursor-resume redelivery after a dead consumer, the
chaos fault sites (torn_slot / writer_stall), and the striped-lane
end-to-end against the broker oracle."""

import threading

import numpy as np
import pytest

from attendance_tpu.chaos import ChaosInjector, ChaosSpec
from attendance_tpu.transport.memory_broker import ReceiveTimeout
from attendance_tpu.transport.shm_ring import (
    ShmClient, ShmRingConsumer, ShmRingFull, ShmRingProducer, ring_path)


def _ring_pair(tmp_path, *, nslots=8, slot_bytes=4096, chaos=None):
    path = tmp_path / "t.lane0.ring"
    prod = ShmRingProducer(path, nslots=nslots, slot_bytes=slot_bytes,
                           chaos=chaos)
    cons = ShmRingConsumer(path, nslots=nslots, slot_bytes=slot_bytes)
    return path, prod, cons


def test_roundtrip_and_ack_cursor(tmp_path):
    path, prod, cons = _ring_pair(tmp_path)
    for i in range(5):
        prod.send(b"frame-%d" % i)
    msgs = [cons.receive(timeout_millis=200) for _ in range(5)]
    assert [bytes(m.data()) for m in msgs] == \
        [b"frame-%d" % i for i in range(5)]
    assert [m.redelivery_count for m in msgs] == [0] * 5
    cons.acknowledge_many(msgs)
    with pytest.raises(ReceiveTimeout):
        cons.receive(timeout_millis=20)
    assert cons.backlog() == 0
    cons.close()
    # Everything acked: a fresh attach redelivers nothing.
    cons2 = ShmRingConsumer(path, nslots=8, slot_bytes=4096)
    with pytest.raises(ReceiveTimeout):
        cons2.receive(timeout_millis=20)
    cons2.close()
    prod.close()


def test_wraparound_many_times_over(tmp_path):
    """Sequences wrap the slot array many times; every frame arrives
    exactly once, in order (ack keeps the window open)."""
    _, prod, cons = _ring_pair(tmp_path, nslots=4)
    got = []

    def consume():
        while len(got) < 64:
            m = cons.receive(timeout_millis=500)
            got.append(bytes(m.data()))
            cons.acknowledge(m)

    t = threading.Thread(target=consume)
    t.start()
    for i in range(64):
        prod.send(b"wrap-%03d" % i)
    t.join(timeout=10)
    assert got == [b"wrap-%03d" % i for i in range(64)]
    prod.close()
    cons.close()


def test_full_ring_backpressure(tmp_path):
    """An unacked ring blocks the producer (ShmRingFull on timeout) —
    backpressure, never overwrite; one ack frees exactly one slot."""
    _, prod, cons = _ring_pair(tmp_path, nslots=4)
    for i in range(4):
        prod.send(b"x%d" % i)
    with pytest.raises(ShmRingFull):
        prod.send(b"overflow", timeout_s=0.1)
    m = cons.receive(timeout_millis=100)
    cons.acknowledge(m)
    prod.send(b"now-fits", timeout_s=1.0)  # freed slot admits one
    with pytest.raises(ShmRingFull):
        prod.send(b"overflow-again", timeout_s=0.1)
    prod.close()
    cons.close()


def test_oversized_frame_rejected(tmp_path):
    _, prod, cons = _ring_pair(tmp_path, slot_bytes=256)
    with pytest.raises(ValueError, match="slot"):
        prod.send(b"z" * 300)
    prod.close()
    cons.close()


def test_geometry_mismatch_fails_loudly(tmp_path):
    path, prod, cons = _ring_pair(tmp_path, nslots=8)
    with pytest.raises(ValueError, match="geometry"):
        ShmRingConsumer(path, nslots=16, slot_bytes=4096)
    prod.close()
    cons.close()


def test_nack_redelivers_with_bumped_count(tmp_path):
    _, prod, cons = _ring_pair(tmp_path)
    prod.send(b"poisonish")
    m = cons.receive(timeout_millis=100)
    assert m.redelivery_count == 0
    cons.negative_acknowledge(m)
    m2 = cons.receive(timeout_millis=100)
    assert bytes(m2.data()) == b"poisonish"
    assert m2.message_id == m.message_id  # stable identity (tracker)
    assert m2.redelivery_count == 1
    cons.acknowledge(m2)
    prod.close()
    cons.close()


def test_crash_resume_redelivers_unacked_tail(tmp_path):
    """Consumer dies (close == SIGKILL for cursor purposes: nothing is
    flushed beyond what acks already persisted) holding unacked
    frames; the next attach resumes from the durable cursor and
    redelivers exactly the unacked tail, in order."""
    path, prod, cons = _ring_pair(tmp_path)
    for i in range(6):
        prod.send(b"r%d" % i)
    msgs = [cons.receive(timeout_millis=100) for _ in range(6)]
    cons.acknowledge_many(msgs[:2])  # group commit covered 0-1 only
    cons.close()
    cons2 = ShmRingConsumer(path, nslots=8, slot_bytes=4096)
    redelivered = [cons2.receive(timeout_millis=100) for _ in range(4)]
    assert [bytes(m.data()) for m in redelivered] == \
        [b"r%d" % i for i in range(2, 6)]
    assert all(m.redelivery_count == 1 for m in redelivered)
    cons2.acknowledge_many(redelivered)
    with pytest.raises(ReceiveTimeout):
        cons2.receive(timeout_millis=20)
    cons2.close()
    prod.close()


def test_out_of_order_acks_hold_cursor(tmp_path):
    """The durable cursor advances only over the contiguous acked
    prefix: a hole (in-flight frame) keeps everything behind it
    redeliverable after a crash."""
    path, prod, cons = _ring_pair(tmp_path)
    for i in range(4):
        prod.send(b"h%d" % i)
    msgs = [cons.receive(timeout_millis=100) for _ in range(4)]
    cons.acknowledge(msgs[0])
    cons.acknowledge(msgs[2])  # hole at seq 1
    cons.acknowledge(msgs[3])
    cons.close()
    cons2 = ShmRingConsumer(path, nslots=8, slot_bytes=4096)
    redelivered = [cons2.receive(timeout_millis=100) for _ in range(3)]
    assert [bytes(m.data()) for m in redelivered] == [b"h1", b"h2",
                                                      b"h3"]
    cons2.close()
    prod.close()


def test_seqlock_hammer_zero_torn_deliveries(tmp_path):
    """The hammer: a writer races the reader over a tiny ring for many
    wraps; every delivered payload must be internally consistent (one
    repeated byte + its sequence) — zero torn reads DELIVERED.  Torn
    observations (retries) are allowed and counted."""
    _, prod, cons = _ring_pair(tmp_path, nslots=4, slot_bytes=8192)
    n_msgs, payload_len = 300, 4096
    errors = []

    def consume():
        for i in range(n_msgs):
            m = cons.receive(timeout_millis=2000)
            buf = np.frombuffer(m.data(), np.uint8)
            seq = int.from_bytes(bytes(buf[:8]), "little")
            if seq != i or not (buf[8:] == buf[8]).all() \
                    or buf[8] != seq % 251:
                errors.append((i, seq, int(buf[8])))
            cons.acknowledge(m)

    t = threading.Thread(target=consume)
    t.start()
    for i in range(n_msgs):
        body = i.to_bytes(8, "little") + bytes([i % 251]) * (
            payload_len - 8)
        prod.send(body)
    t.join(timeout=30)
    assert not t.is_alive(), "consumer wedged"
    assert errors == [], f"torn deliveries: {errors[:5]}"
    prod.close()
    cons.close()


def test_torn_slot_chaos_retried_never_delivered(tmp_path):
    """torn_slot=1.0: EVERY publish leaves the slot visibly mid-write
    for a beat; a concurrent reader must retry (torn observations
    counted) and still deliver every frame intact."""
    inj = ChaosInjector(ChaosSpec.parse("torn_slot=1.0"), seed=7)
    _, prod, cons = _ring_pair(tmp_path, nslots=4, slot_bytes=8192,
                               chaos=inj)
    n_msgs = 24
    got = []

    def consume():
        for _ in range(n_msgs):
            m = cons.receive(timeout_millis=2000)
            buf = bytes(m.data())
            got.append(buf)
            cons.acknowledge(m)

    t = threading.Thread(target=consume)
    t.start()
    want = []
    for i in range(n_msgs):
        body = bytes([i % 251]) * 4000
        want.append(body)
        prod.send(body)
    t.join(timeout=30)
    assert got == want
    assert inj.injected_total("torn_slot") == n_msgs
    # The reader raced at least one mid-write slot and retried it.
    assert cons.torn_reads > 0
    prod.close()
    cons.close()


def test_writer_stall_chaos_stalls_not_corrupts(tmp_path):
    inj = ChaosInjector(ChaosSpec.parse("writer_stall=30ms:1.0"),
                        seed=7)
    _, prod, cons = _ring_pair(tmp_path, chaos=inj)
    got = []

    def consume():
        for _ in range(3):
            m = cons.receive(timeout_millis=2000)
            got.append(bytes(m.data()))
            cons.acknowledge(m)

    t = threading.Thread(target=consume)
    t.start()
    for i in range(3):
        prod.send(b"stalled-%d" % i)
    t.join(timeout=10)
    assert got == [b"stalled-%d" % i for i in range(3)]
    assert inj.injected_total("writer_stall") == 3
    prod.close()
    cons.close()


def test_chunk_lane_settlement(tmp_path):
    """receive_chunk / acknowledge_chunk / nack_chunk — the call shape
    the striped lane workers speak."""
    _, prod, cons = _ring_pair(tmp_path)
    for i in range(4):
        prod.send(b"c%d" % i)
    cid, toks = cons.receive_chunk(4, timeout_millis=200)
    assert [bytes(t[1]) for t in toks] == [b"c%d" % i for i in range(4)]
    cons.nack_chunk(cid)
    cid2, toks2 = cons.receive_chunk(4, timeout_millis=200)
    assert [t[2] for t in toks2] == [1, 1, 1, 1]  # redelivered once
    cons.acknowledge_chunk(cid2)
    with pytest.raises(ReceiveTimeout):
        cons.receive_chunk(4, timeout_millis=20)
    prod.close()
    cons.close()


def test_shm_client_lane_striping(tmp_path):
    """The client stripes producer sends round-robin over lane rings
    and lane subscriptions map the matching files."""
    client = ShmClient(tmp_path, lanes=2, nslots=8, slot_bytes=4096)
    prod = client.create_producer("topic-x")
    for i in range(6):
        prod.send(b"s%d" % i)
    c0 = client.subscribe_lane("topic-x", "sub", 0)
    c1 = client.subscribe_lane("topic-x", "sub", 1)
    lane0 = [bytes(c0.receive(timeout_millis=100).data())
             for _ in range(3)]
    lane1 = [bytes(c1.receive(timeout_millis=100).data())
             for _ in range(3)]
    assert lane0 == [b"s0", b"s2", b"s4"]
    assert lane1 == [b"s1", b"s3", b"s5"]
    assert ring_path(tmp_path, "topic-x", 0).exists()
    assert ring_path(tmp_path, "topic-x", 1).exists()
    client.close()


@pytest.mark.slow
def test_striped_shm_pipeline_matches_oracle(tmp_path):
    """End to end: 2-lane shm ingress == the memory-broker oracle on
    the same workload (sketch counts, store rows, valid totals)."""
    from attendance_tpu.config import Config
    from attendance_tpu.pipeline.fast_path import FusedPipeline
    from attendance_tpu.pipeline.loadgen import generate_frames
    from attendance_tpu.transport import make_client
    from attendance_tpu.transport.memory_broker import (
        MemoryBroker, MemoryClient)

    nev, batch = 16_384, 2048

    def state(pipe):
        df = pipe.store.to_dataframe()
        return ({int(d): pipe.count(int(d))
                 for d in pipe.lecture_days()},
                len(df), int(df.is_valid.sum()))

    cfg = Config(bloom_filter_capacity=50_000, ingress_wire="shm",
                 shm_dir=str(tmp_path), ingress_lanes=2,
                 shm_slots=8, shm_slot_bytes=1 << 21).validate()
    roster, frames = generate_frames(nev, batch, roster_size=10_000,
                                     num_lectures=8)
    frames = list(frames)
    pipe = FusedPipeline(cfg, num_banks=8)
    pipe.preload(roster)
    producer = make_client(cfg).create_producer(cfg.pulsar_topic)
    t = threading.Thread(
        target=lambda: [producer.send(f) for f in frames])
    t.start()
    pipe.run(max_events=nev, idle_timeout_s=2.0)
    t.join()
    assert pipe.metrics.events == nev
    shm_state = state(pipe)
    lane_totals = pipe.consumer.lane_event_totals()
    pipe.cleanup()
    assert sum(lane_totals) == nev and all(lane_totals)

    client = MemoryClient(MemoryBroker())
    ocfg = Config(bloom_filter_capacity=50_000,
                  transport_backend="memory")
    opipe = FusedPipeline(ocfg, client=client, num_banks=8)
    oroster, oframes = generate_frames(nev, batch, roster_size=10_000,
                                       num_lectures=8)
    opipe.preload(oroster)
    op = client.create_producer(ocfg.pulsar_topic)
    for f in oframes:
        op.send(f)
    opipe.run(max_events=nev, idle_timeout_s=2.0)
    assert state(opipe) == shm_state
    opipe.cleanup()


def test_producer_crash_between_stamp_and_head_bump_never_overwrites(
        tmp_path):
    """A producer killed between the stable seqword stamp (publish
    point) and the head bump must NOT overwrite that published slot on
    restart: attach reconstructs head by scanning stable seqwords."""
    from attendance_tpu.transport.shm_ring import _Ring
    path, prod, cons = _ring_pair(tmp_path)
    prod.send(b"published-0")
    prod.send(b"published-1")
    # Simulate the crash window: rewind the header head to pretend the
    # dead producer never recorded its last publish.
    prod._ring.set_head(1)
    prod.close()
    prod2 = ShmRingProducer(path, nslots=8, slot_bytes=4096)
    assert prod2._head == 2  # scan found the uncounted published slot
    prod2.send(b"published-2")
    got = [bytes(cons.receive(timeout_millis=200).data())
           for _ in range(3)]
    assert got == [b"published-0", b"published-1", b"published-2"]
    prod2.close()
    cons.close()
