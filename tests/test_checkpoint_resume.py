"""Checkpoint/resume for the fused pipeline (SURVEY.md §5 obligation).

The snapshot is an ack barrier: frames are acknowledged only once their
outputs are durably in a snapshot, so a crash can only lose work the
broker still holds — replay into idempotent sketches + the last-write-
wins store reproduces the uninterrupted result exactly (the reference
gets the same property from external-service durability + re-entrant
setup, reference attendance_processor.py:56-72,90-92).
"""

import numpy as np
import pytest

from attendance_tpu.config import Config
from attendance_tpu.pipeline.fast_path import FusedPipeline
from attendance_tpu.pipeline.loadgen import generate_frames
from attendance_tpu.transport.memory_broker import MemoryBroker, MemoryClient

NUM_EVENTS, BATCH = 24_000, 2_048


def _mkframes(seed=29):
    return generate_frames(NUM_EVENTS, BATCH, roster_size=8_000,
                           num_lectures=6, invalid_fraction=0.15, seed=seed)


def _final_state(pipe):
    df = pipe.store.to_dataframe()  # deduplicated, Cassandra-style
    df = df.sort_values(["lecture_day", "micros", "student_id"]
                        ).reset_index(drop=True)
    counts = {day: pipe.count(int(day))
              for day in df.lecture_day.unique().tolist()}
    return df, counts


def test_crash_replay_resume_matches_uninterrupted(tmp_path):
    roster, frames = _mkframes()
    frames = list(frames)

    # --- Reference run: one uninterrupted pipeline, no snapshots. ---
    config = Config(bloom_filter_capacity=30_000,
                    transport_backend="memory")
    client = MemoryClient(MemoryBroker())
    ref = FusedPipeline(config, client=client, num_banks=8)
    ref.preload(roster)
    producer = client.create_producer(config.pulsar_topic)
    for f in frames:
        producer.send(f)
    ref.run(max_events=NUM_EVENTS, idle_timeout_s=0.5)
    ref_df, ref_counts = _final_state(ref)

    # --- Crash run: checkpoint every 3 frames, die mid-stream. ---
    snap = tmp_path / "snaps"
    config2 = Config(bloom_filter_capacity=30_000,
                     transport_backend="memory",
                     snapshot_dir=str(snap), snapshot_every_batches=3)
    broker = MemoryBroker()
    client_a = MemoryClient(broker)
    a = FusedPipeline(config2, client=client_a, num_banks=8)
    a.preload(roster)
    producer = client_a.create_producer(config2.pulsar_topic)
    for f in frames:
        producer.send(f)
    # Process ~60% of the stream, then "crash": abandon the pipeline
    # without its final checkpoint — the consumer close returns every
    # unacknowledged frame to the shared subscription (crash takeover).
    a.run(max_events=int(NUM_EVENTS * 0.6), idle_timeout_s=0.5)
    acked_events = None
    with np.load(snap / "fused_sketch.npz") as data:
        import json
        acked_events = json.loads(bytes(data["manifest"]).decode())["events"]
    assert acked_events <= a.metrics.events  # barrier acks lag processing
    a.consumer.close()  # crash: unacked frames redeliver

    # --- Resume: fresh pipeline, same snapshot dir + subscription. ---
    b = FusedPipeline(config2, client=MemoryClient(broker), num_banks=8)
    # restore-on-start happened in the constructor:
    assert b.metrics.events == 0 and b.store.count() > 0
    b.run(idle_timeout_s=0.5)
    assert b.consumer.backlog() == 0

    got_df, got_counts = _final_state(b)
    # Replayed frames were double-processed (at-least-once) but every
    # sink is idempotent, so the final state matches exactly.
    assert got_counts == ref_counts
    assert len(got_df) == len(ref_df)
    for col in ("student_id", "lecture_day", "micros", "is_valid"):
        np.testing.assert_array_equal(got_df[col].to_numpy(),
                                      ref_df[col].to_numpy())


def test_restore_requires_matching_filter_geometry(tmp_path):
    snap = tmp_path / "snaps"
    config = Config(bloom_filter_capacity=10_000,
                    transport_backend="memory",
                    snapshot_dir=str(snap), snapshot_every_batches=1)
    pipe = FusedPipeline(config, client=MemoryClient(MemoryBroker()),
                         num_banks=8)
    pipe.preload(np.arange(100, dtype=np.uint32))
    pipe.snapshot()

    import pytest
    bad = Config(bloom_filter_capacity=99_000,
                 transport_backend="memory",
                 snapshot_dir=str(snap), snapshot_every_batches=1)
    with pytest.raises(ValueError, match="capacity"):
        FusedPipeline(bad, client=MemoryClient(MemoryBroker()),
                      num_banks=8)


def test_restore_rejects_inconsistent_bank_manifest(tmp_path):
    """A manifest whose bank map references banks beyond the restored
    register array must fail loudly — silently re-deriving would
    misroute every PFADD for those days (VERDICT r02 #9)."""
    import json

    from attendance_tpu.pipeline.fast_path import SKETCH_SNAPSHOT

    snap = tmp_path / "snaps"
    config = Config(bloom_filter_capacity=10_000,
                    transport_backend="memory",
                    snapshot_dir=str(snap), snapshot_every_batches=1)
    pipe = FusedPipeline(config, client=MemoryClient(MemoryBroker()),
                         num_banks=8)
    pipe.preload(np.arange(100, dtype=np.uint32))
    pipe.snapshot()

    # Corrupt the manifest: a day routed to a bank past the register
    # array, as a stale manifest paired with older registers would be.
    path = snap / SKETCH_SNAPSHOT
    with np.load(path) as data:
        arrays = {k: data[k] for k in data.files}
    manifest = json.loads(bytes(arrays["manifest"]).decode())
    manifest["bank_of"]["20990101"] = arrays["hll_regs"].shape[0] + 3
    arrays["manifest"] = np.frombuffer(
        json.dumps(manifest).encode(), dtype=np.uint8)
    with open(path, "wb") as f:
        np.savez_compressed(f, **arrays)

    import pytest
    with pytest.raises(ValueError, match="register banks"):
        FusedPipeline(config, client=MemoryClient(MemoryBroker()),
                      num_banks=8)

    # A duplicate bank assignment is equally corrupt.
    manifest["bank_of"] = {"20260101": 0, "20260102": 0}
    arrays["manifest"] = np.frombuffer(
        json.dumps(manifest).encode(), dtype=np.uint8)
    with open(path, "wb") as f:
        np.savez_compressed(f, **arrays)
    with pytest.raises(ValueError, match="corrupt"):
        FusedPipeline(config, client=MemoryClient(MemoryBroker()),
                      num_banks=8)


def test_processor_snapshot_restore_roundtrip(tmp_path):
    """AttendanceProcessor honors snapshot_dir/snapshot_every_batches:
    sketch + store state written at barriers and restored on start."""
    from attendance_tpu.pipeline.generator import generate_student_data
    from attendance_tpu.pipeline.processor import AttendanceProcessor

    snap = tmp_path / "proc"
    config = Config(sketch_backend="memory", transport_backend="memory",
                    storage_backend="memory", batch_size=64,
                    batch_timeout_s=0.05,
                    snapshot_dir=str(snap), snapshot_every_batches=2)
    broker = MemoryBroker()
    a = AttendanceProcessor(config, client=MemoryClient(broker))
    a.setup_bloom_filter()
    producer = a.client.create_producer(config.pulsar_topic)
    report = generate_student_data(
        producer=producer, sketch_store=a.sketch,
        bloom_key=config.bloom_filter_key,
        num_students=60, num_invalid=5, seed=31, keep_events=False)
    a.process_attendance(max_events=report.message_count,
                         idle_timeout_s=0.5)
    # Default mode is delta: the sketch side is a base+delta chain dir.
    chain = snap / AttendanceProcessor.SKETCH_CHAIN
    assert (chain / "MANIFEST.json").exists()
    assert list(chain.glob("base-*.npz"))
    assert (snap / AttendanceProcessor.EVENTS_SNAPSHOT).exists()
    total = a.store.count()
    lectures = a.store.distinct_lecture_ids()
    counts = {lec: a.get_attendance_stats(lec)["unique_attendees"]
              for lec in lectures}
    a.consumer.close()

    # Fresh processor restores sketches + events without reprocessing.
    b = AttendanceProcessor(config, client=MemoryClient(broker))
    assert b.store.count() == total
    for lec in lectures:
        assert b.get_attendance_stats(lec)["unique_attendees"] == \
            counts[lec]
    # The restored Bloom filter still answers: replay one known event
    # stream fragment and confirm the bootstrap probe path works.
    b.setup_bloom_filter()  # "already exists" tolerated


def test_restore_across_bank_dtype_boundary(tmp_path):
    """A snapshot taken after bank growth crossed the uint8 wire-dtype
    limit must restore with the widened dtype: otherwise bank ids above
    the old sentinel narrow-cast into the wrong banks (e.g. 299 -> 43)
    and bank 255 collides with the pad sentinel."""
    import jax.numpy as jnp

    from attendance_tpu.models.fused import bank_wire_dtype
    from attendance_tpu.pipeline.events import encode_planar_batch

    config = Config(bloom_filter_capacity=4_096,
                    snapshot_dir=str(tmp_path / "snap"))
    client = MemoryClient(MemoryBroker())
    a = FusedPipeline(config, client=client, num_banks=8)
    roster = np.arange(10_000, 12_000, dtype=np.uint32)
    a.preload(roster)
    # Register 300 distinct lecture days -> banks grow past 256 and the
    # wire dtype must widen from uint8 to uint16.
    n = 300
    cols = {
        "student_id": np.repeat(roster[:4], n)[:n].astype(np.uint32),
        "lecture_day": (20260101 + np.arange(n)).astype(np.uint32),
        "micros": np.full(n, 1_000_000, np.int64),
        "is_valid": np.ones(n, bool),
        "event_type": np.zeros(n, np.int8),
    }
    producer = client.create_producer(config.pulsar_topic)
    producer.send(encode_planar_batch(cols))
    a.run(max_events=n, idle_timeout_s=0.2)
    assert a._bank_dtype is np.uint16
    day = int(cols["lecture_day"][-1])  # bank index >= 256
    count_before = a.count(day)
    assert count_before >= 1
    a.cleanup()

    # Restart with the DEFAULT small bank count; restore must widen.
    b = FusedPipeline(Config(bloom_filter_capacity=4_096,
                             snapshot_dir=str(tmp_path / "snap")),
                      client=MemoryClient(MemoryBroker()), num_banks=8)
    assert b.state.hll_regs.shape[0] >= 300
    assert b._bank_dtype is bank_wire_dtype(b.state.hll_regs.shape[0])
    assert b._bank_dtype is np.uint16
    assert b.count(day) == count_before
    # New events for a high bank keep landing in the RIGHT bank.
    producer2 = b.client.create_producer(b.config.pulsar_topic)
    cols2 = dict(cols)
    cols2["student_id"] = np.arange(10_000, 10_000 + n, dtype=np.uint32)
    producer2.send(encode_planar_batch(cols2))
    b.run(max_events=n, idle_timeout_s=0.2)
    assert b.count(day) > count_before
    b.cleanup()


def test_sharded_crash_replay_resume_matches_uninterrupted(tmp_path):
    """Checkpoint/resume on the MESH-sharded pipeline: the snapshot
    stores the merged global sketch state (engine.get_state max-unions
    the per-replica register copies), and restore re-shards it — a crash
    mid-stream replays into the same final state as an uninterrupted
    run, across a different mesh shape."""
    roster, frames = _mkframes(seed=37)
    frames = list(frames)

    def mkcfg(snap_dir="", shards=2, reps=4):
        return Config(bloom_filter_capacity=30_000,
                      transport_backend="memory",
                      num_shards=shards, num_replicas=reps,
                      snapshot_dir=snap_dir,
                      snapshot_every_batches=3 if snap_dir else 0)

    client = MemoryClient(MemoryBroker())
    ref = FusedPipeline(mkcfg(), client=client, num_banks=8)
    ref.preload(roster)
    producer = client.create_producer(ref.config.pulsar_topic)
    for f in frames:
        producer.send(f)
    ref.run(max_events=NUM_EVENTS, idle_timeout_s=0.5)
    ref_df, ref_counts = _final_state(ref)

    snap = tmp_path / "snaps"
    broker = MemoryBroker()
    a = FusedPipeline(mkcfg(str(snap)), client=MemoryClient(broker),
                      num_banks=8)
    a.preload(roster)
    producer = a.client.create_producer(a.config.pulsar_topic)
    for f in frames:
        producer.send(f)
    a.run(max_events=int(NUM_EVENTS * 0.6), idle_timeout_s=0.5)
    a.consumer.close()  # crash: unacked frames redeliver

    # Resume on a DIFFERENT mesh shape (4x2 instead of 2x4): snapshots
    # are mesh-shape-agnostic (global state, re-sharded on restore).
    b = FusedPipeline(mkcfg(str(snap), shards=4, reps=2),
                      client=MemoryClient(broker), num_banks=8)
    assert b.store.count() > 0  # restored on construction
    b.run(idle_timeout_s=0.5)
    assert b.consumer.backlog() == 0

    got_df, got_counts = _final_state(b)
    assert got_counts == ref_counts
    assert len(got_df) == len(ref_df)
    for col in ("student_id", "lecture_day", "micros", "is_valid"):
        np.testing.assert_array_equal(got_df[col].to_numpy(),
                                      ref_df[col].to_numpy())


@pytest.mark.parametrize("mode", ["barrier", "delta"])
def test_async_writer_defers_barriers_and_stays_durable(tmp_path, mode):
    """The BGSAVE-style writer: with a cadence faster than the writer,
    barriers are DEFERRED (snapshots coalesce; the hot loop never
    stops for a busy writer below the staging depth), yet every event
    is acked only once durable — a fresh pipeline restoring from the
    dir reproduces the finished run's counters and store. Covers both
    the full-state barrier mode and the dirty-bank delta mode (whose
    writes are the base + delta files of the chain)."""
    import time

    roster, frames = _mkframes(seed=41)
    frames = list(frames)
    snap = tmp_path / "snaps"
    config = Config(bloom_filter_capacity=30_000,
                    transport_backend="memory", snapshot_mode=mode,
                    snapshot_dir=str(snap), snapshot_every_batches=1)
    client = MemoryClient(MemoryBroker())
    pipe = FusedPipeline(config, client=client, num_banks=8)

    def slow(fn):
        def wrapper(*args, **kwargs):
            time.sleep(0.12)  # writer slower than per-frame cadence
            return fn(*args, **kwargs)
        return wrapper

    pipe._write_snapshot_files = slow(pipe._write_snapshot_files)
    pipe._write_delta_files = slow(pipe._write_delta_files)
    pipe.preload(roster)
    producer = client.create_producer(config.pulsar_topic)
    for f in frames:
        producer.send(f)
    pipe.run(max_events=NUM_EVENTS, idle_timeout_s=0.5)

    assert pipe.metrics.events == NUM_EVENTS
    assert pipe.consumer.backlog() == 0  # every frame acked (durable)
    stalls = pipe.metrics.snapshot_stalls
    # At least one durable write happened, each paid the slow writer,
    # and never more than one per barrier (+1 for the end-of-run
    # barrier). (Coalescing — strictly fewer snapshots than batches —
    # is the expected outcome but is timing-dependent on this small
    # host, so it is not asserted strictly.)
    assert 1 <= len(stalls) <= len(frames) + 1
    assert all(s >= 0.12 for s in stalls)

    # Durability: a fresh pipeline restores to the finished run's
    # exact counters, HLL counts, and store content.
    pipe2 = FusedPipeline(
        Config(bloom_filter_capacity=30_000,
               transport_backend="memory", snapshot_dir=str(snap)),
        client=MemoryClient(MemoryBroker()), num_banks=8)
    assert tuple(pipe2.validity_counts()) == \
        tuple(pipe.validity_counts())
    for day in pipe.lecture_days():
        assert pipe2.count(day) == pipe.count(day)
    a, _ = _final_state(pipe)
    b, _ = _final_state(pipe2)
    assert len(a) == len(b)
    np.testing.assert_array_equal(a.is_valid.to_numpy(bool),
                                  b.is_valid.to_numpy(bool))
