"""Worker script for the 2-process competing-consumer bridge test.

Run as a subprocess by tests/test_socket_broker.py (no ``test_``
prefix, never collected):

    python tests/bridge_worker.py <broker_addr> <out_json> <idle_s>

Joins the shared bridge subscription on the socket broker — a second
competing process, the reference's Pulsar Shared-subscription scale-out
model (reference attendance_processor.py:30-34) on the framework's own
cross-process transport — converts JSON messages to binary frames until
the topic idles, then writes its accounting for the parent to aggregate.
"""

import json
import os
import sys


def main() -> None:
    addr, out_path, idle_s = sys.argv[1], sys.argv[2], float(sys.argv[3])

    # Hermetic CPU: the bridge is host-only, but importing the package
    # initializes jax (keep it off the real-TPU tunnel in subprocesses).
    os.environ.setdefault("XLA_FLAGS",
                          "--xla_force_host_platform_device_count=1")
    import jax
    jax.config.update("jax_platforms", "cpu")

    from attendance_tpu.config import Config
    from attendance_tpu.pipeline.bridge import JsonBinaryBridge
    from attendance_tpu.transport.socket_broker import SocketClient

    config = Config(transport_backend="socket", socket_broker=addr,
                    batch_size=int(os.environ.get("ATP_BRIDGE_BATCH",
                                                  "256")),
                    batch_timeout_s=0.02)
    bridge = JsonBinaryBridge(config, client=SocketClient(addr))
    bridge.run(idle_timeout_s=idle_s)
    with open(out_path, "w") as f:
        json.dump({"events": bridge.metrics.events,
                   "batches": bridge.metrics.batches,
                   "dead_lettered": bridge.metrics.dead_lettered}, f)
    bridge.cleanup()
    print("bridge worker done", flush=True)


if __name__ == "__main__":
    main()
