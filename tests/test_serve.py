"""Query-serving plane (attendance_tpu/serve): epoch mirror semantics,
vectorized executor correctness against the write engine's own answers,
the binary batch RPC + HTTP surfaces, merge-on-read chain serving, the
read-path audit, and the doctor/SLO hooks.
"""

import json
import threading
import urllib.request

import numpy as np
import pytest

from attendance_tpu import obs
from attendance_tpu.config import Config
from attendance_tpu.models.bloom import (
    BloomParams, bloom_contains_words, bloom_contains_words_np,
    bloom_packed_fill_fraction, bloom_packed_fill_fraction_np,
    derive_bloom_params)
from attendance_tpu.models.hll import (
    best_histogram, estimate_from_histogram, estimates_from_rows)
from attendance_tpu.pipeline.fast_path import FusedPipeline
from attendance_tpu.pipeline.loadgen import generate_frames
from attendance_tpu.serve.engine import NoEpoch, QueryEngine
from attendance_tpu.serve.mirror import ReadMirror
from attendance_tpu.serve.rpc import QueryClient, QueryServer
from attendance_tpu.transport.memory_broker import (
    MemoryBroker, MemoryClient)

NUM_EVENTS, BATCH = 16_384, 2_048


@pytest.fixture(autouse=True)
def _fresh_telemetry():
    obs.disable()
    yield
    obs.disable()


def _mkcfg(snap_dir="", **kw):
    return Config(bloom_filter_capacity=20_000,
                  transport_backend="memory",
                  snapshot_dir=snap_dir,
                  snapshot_every_batches=2 if snap_dir else 0, **kw)


def _run_pipe(config, seed=7, num_banks=8):
    client = MemoryClient(MemoryBroker())
    pipe = FusedPipeline(config, client=client, num_banks=num_banks)
    roster, frames = generate_frames(
        NUM_EVENTS, BATCH, roster_size=6_000, num_lectures=6,
        invalid_fraction=0.15, seed=seed)
    pipe.preload(roster)
    producer = client.create_producer(config.pulsar_topic)
    for f in frames:
        producer.send(f)
    pipe.run(max_events=NUM_EVENTS, idle_timeout_s=0.5)
    return pipe, roster


# -- numpy read kernels vs device kernels ------------------------------------

def test_numpy_probe_matches_device_probe():
    """The host packed-word probe must answer bit-identically to the
    device kernel (shared bloom_positions) — the query plane's whole
    correctness story rests on this."""
    import jax.numpy as jnp
    from attendance_tpu.models.bloom import (
        bloom_add_packed, bloom_packed_init)

    params = derive_bloom_params(5_000, 0.01, "blocked")
    words = bloom_packed_init(params)
    rng = np.random.default_rng(1)
    members = rng.choice(1 << 31, 3_000, replace=False).astype(np.uint32)
    words = bloom_add_packed(words, jnp.asarray(members), params)
    probes = np.concatenate([
        members[:500],
        rng.integers(1 << 31, 1 << 32, 500).astype(np.uint32)])
    dev = np.asarray(bloom_contains_words(words, jnp.asarray(probes),
                                          params))
    host = bloom_contains_words_np(np.asarray(words), probes, params)
    assert (dev == host).all()
    assert host[:500].all()  # no false negatives on members
    assert bloom_packed_fill_fraction_np(np.asarray(words)) == \
        pytest.approx(float(bloom_packed_fill_fraction(words)), rel=1e-6)


def test_batched_histogram_estimates_match_scalar():
    rng = np.random.default_rng(2)
    rows = rng.integers(0, 30, size=(5, 1 << 14)).astype(np.uint8)
    batched = estimates_from_rows(rows, 14)
    for i in range(5):
        hist = np.asarray(best_histogram(rows[i:i + 1], 14))[0]
        assert batched[i] == pytest.approx(
            estimate_from_histogram(hist, 14), rel=1e-9)


# -- mirror semantics --------------------------------------------------------

def test_mirror_pin_survives_later_publishes():
    """A pinned epoch's registers must stay intact across publishes —
    the recycler may only reuse buffers no reader references."""
    mirror = ReadMirror()
    params = derive_bloom_params(1000, 0.01, "blocked")
    regs = np.full((4, 16), 1, np.uint8)
    mirror.publish(regs=regs, events=1, bank_of={1: 0}, params=params,
                   precision=14, bloom_words=np.zeros(4, np.uint32))
    pinned = mirror.pin()
    assert pinned.seq == 1 and (pinned.hll_regs == 1).all()
    for gen in (2, 3, 4, 5):
        mirror.publish(regs=np.full((4, 16), gen, np.uint8),
                       events=gen, bank_of={1: 0}, params=params,
                       precision=14)
    # The old pin still reads its own epoch's values...
    assert (pinned.hll_regs == 1).all()
    assert pinned.events == 1
    # ...and the current epoch reads the latest.
    cur = mirror.pin()
    assert cur.seq == 5 and (cur.hll_regs == 5).all()
    assert cur.bloom_words is not None  # carried forward by reference


def test_mirror_recycles_unpinned_buffers():
    """Steady republishing with no outside pinner must reuse the
    double buffer, not allocate per epoch."""
    mirror = ReadMirror()
    params = derive_bloom_params(1000, 0.01, "blocked")
    for gen in range(6):
        mirror.publish(regs=np.full((4, 16), gen, np.uint8),
                       events=gen, bank_of={}, params=params,
                       precision=14)
    seen = set()
    for gen in range(6, 12):
        mirror.publish(regs=np.full((4, 16), gen, np.uint8),
                       events=gen, bank_of={}, params=params,
                       precision=14)
        seen.add(id(mirror.pin().hll_regs))
    assert len(seen) <= 2  # alternating between two buffers


def test_staleness_nan_before_first_publish():
    mirror = ReadMirror()
    assert np.isnan(mirror.staleness_s())
    engine = QueryEngine(mirror)
    with pytest.raises(NoEpoch):
        engine.bf_exists(np.array([1], np.uint32))


# -- live pipeline serving ---------------------------------------------------

def test_engine_answers_match_pipeline(tmp_path):
    """Occupancy/PFCOUNT from the epoch mirror must equal the write
    engine's own device answers, and roster membership must carry zero
    false negatives — the read plane serves the same truth the hot
    loop holds."""
    pipe, roster = _run_pipe(_mkcfg(str(tmp_path / "snaps")))
    try:
        engine = QueryEngine(pipe.read_mirror)
        epoch = engine.pin()
        assert epoch.events == NUM_EVENTS
        exact = {d: pipe.count(d) for d in pipe.lecture_days()}
        assert engine.occupancy() == exact
        days = np.array(pipe.lecture_days(), np.int64)
        assert engine.pfcount(days).tolist() == \
            [exact[int(d)] for d in days]
        assert engine.pfcount([123]).tolist() == [0]  # unknown day
        answers = engine.bf_exists(roster)
        assert answers.all(), "read-path false negatives on roster"
        rates = engine.attendance_rate()
        assert set(rates) == set(exact)
        assert all(0.0 < r <= 1.5 for r in rates.values())
        st = engine.stats()
        assert st["events"] == NUM_EVENTS
        assert st["roster_size"] == len(roster)
    finally:
        pipe.cleanup()


def test_rpc_roundtrip_and_chunking(tmp_path):
    pipe, roster = _run_pipe(_mkcfg(str(tmp_path / "snaps"),
                                    serve_port=-1), seed=9)
    try:
        assert pipe.query_server is not None
        engine = pipe.query_engine
        # batch_max far below the probe size: the client must chunk
        # transparently and reassemble in order.
        qc = QueryClient(pipe.query_server.address, batch_max=257)
        probes = np.concatenate([
            roster[:1500],
            np.arange(1 << 31, (1 << 31) + 1500, dtype=np.uint32)])
        assert (qc.bf_exists(probes)
                == engine.bf_exists(probes)).all()
        days = pipe.lecture_days()
        assert qc.pfcount(days).tolist() == \
            engine.pfcount(days).tolist()
        assert qc.occupancy() == engine.occupancy()
        rates = qc.attendance_rate()
        assert rates == pytest.approx(engine.attendance_rate())
        assert qc.stats()["events"] == NUM_EVENTS
        qc.close()
    finally:
        pipe.cleanup()


def test_http_query_routes(tmp_path):
    pipe, roster = _run_pipe(_mkcfg(str(tmp_path / "snaps"),
                                    serve_port=-1, metrics_port=-1),
                             seed=11)
    try:
        port = obs.get().http_port
        base = f"http://127.0.0.1:{port}"
        occ = json.loads(urllib.request.urlopen(
            f"{base}/query/occupancy", timeout=10).read())
        assert {int(k): v for k, v in occ.items()} == \
            pipe.query_engine.occupancy()
        ex = json.loads(urllib.request.urlopen(
            f"{base}/query/exists?keys={roster[0]},{1 << 31}",
            timeout=10).read())
        assert ex[0] is True
        day = pipe.lecture_days()[0]
        pf = json.loads(urllib.request.urlopen(
            f"{base}/query/pfcount?days=LECTURE_{day}",
            timeout=10).read())
        assert pf == [pipe.count(day)]
        req = urllib.request.Request(
            f"{base}/query", method="POST",
            data=json.dumps({"verb": "pfcount",
                             "days": [int(day), 123]}).encode())
        doc = json.loads(urllib.request.urlopen(req, timeout=10).read())
        assert doc["result"] == [pipe.count(day), 0]
        # the scrape surface still works beside the query routes
        body = urllib.request.urlopen(f"{base}/metrics",
                                      timeout=10).read().decode()
        assert "attendance_read_staleness_seconds" in body
        assert "attendance_query_requests_total" in body
    finally:
        pipe.cleanup()


def test_read_audit_zero_fn_and_measured_fpr(tmp_path):
    """Sampled read answers cross-check against the exact shadow:
    roster queries must produce zero read-path false negatives, and
    disjoint-range probes a finite measured read FPR within budget."""
    pipe, roster = _run_pipe(_mkcfg(str(tmp_path / "snaps"),
                                    serve_port=-1, audit_sample=1.0),
                             seed=13)
    try:
        engine = pipe.query_engine
        engine.bf_exists(roster)
        rng = np.random.default_rng(5)
        engine.bf_exists(
            rng.integers(1 << 31, 1 << 32, 20_000).astype(np.uint32))
        engine.pfcount(np.array(pipe.lecture_days(), np.int64))
        reg = obs.get().registry
        assert reg.counter(
            "attendance_query_false_negatives_total").value == 0
        assert reg.counter(
            "attendance_query_audited_total").value > 0
        fpr = reg.gauge("attendance_query_measured_fpr").read()
        assert np.isfinite(fpr) and fpr <= 0.01
        # per-day read HLL error vs the epoch's truth snapshot
        errs = [m.read() for name, kind, help, members
                in reg.collect()
                if name == "attendance_query_hll_rel_error"
                for m in members]
        assert errs and max(errs) <= 0.05
    finally:
        pipe.cleanup()


def test_health_gauges_read_from_epoch(tmp_path):
    """The scrape-time health gauges must answer from the pinned epoch
    under checkpointing (the torn-row fix), and still agree with the
    estimator methods."""
    pipe, roster = _run_pipe(_mkcfg(str(tmp_path / "snaps"),
                                    metrics_port=-1), seed=15)
    try:
        reg = obs.get().registry
        fpr = reg.gauge("attendance_bloom_estimated_fpr").read()
        assert fpr == pytest.approx(pipe.estimated_fpr(), rel=1e-5)
        est = reg.gauge("attendance_hll_estimate").read()
        assert est == pytest.approx(
            sum(pipe.count_all().values()), rel=1e-6)
        stale = reg.gauge("attendance_read_staleness_seconds").read()
        assert np.isfinite(stale) and stale >= 0.0
        assert reg.gauge("attendance_read_epoch_seq").read() >= 1.0
    finally:
        pipe.cleanup()


def test_concurrent_publish_and_read(tmp_path):
    """Readers hammering the engine while epochs publish must only
    ever see whole epochs: every occupancy answer equals the table of
    SOME published epoch, never a mix."""
    mirror = ReadMirror()
    params = derive_bloom_params(1000, 0.01, "blocked")
    # Every epoch's registers are uniform (one value per generation),
    # so a reader observing two values inside one pinned epoch has
    # caught a torn buffer — the exact failure the recycler must
    # make impossible.
    stop = threading.Event()
    torn = []

    def reader():
        while not stop.is_set():
            epoch = mirror.pin()
            if epoch is None:
                continue
            regs = epoch.hll_regs
            lo, hi = int(regs.min()), int(regs.max())
            if lo != hi:  # a torn buffer mixes two generations
                torn.append((epoch.seq, lo, hi))
                return

    threads = [threading.Thread(target=reader, daemon=True)
               for _ in range(2)]
    for t in threads:
        t.start()
    try:
        for gen in range(1, 40):
            mirror.publish(
                regs=np.full((4, 1 << 14), gen % 31, np.uint8),
                events=gen, bank_of={1: 0, 2: 1},
                params=params, precision=14)
    finally:
        stop.set()
    for t in threads:
        t.join(timeout=10.0)
    assert not torn, f"readers observed torn epochs: {torn[:3]}"


def test_pfcount_many_matches_scalar():
    from attendance_tpu.sketch.tpu_store import TpuSketchStore

    store = TpuSketchStore(_mkcfg())
    rng = np.random.default_rng(3)
    for i, key in enumerate(("hll:a", "hll:b", "hll:c")):
        store.pfadd_many(key, rng.integers(0, 1 << 31, 500 * (i + 1)))
    keys = ["hll:a", "hll:b", "hll:missing", "hll:c"]
    assert store.pfcount_many(keys) == \
        [store.pfcount(k) for k in keys]


def test_slo_alias_and_doctor_rows(tmp_path):
    from attendance_tpu.obs.slo import doctor_report, parse_slo

    slo = parse_slo("read_staleness<=2.5")
    assert slo.metric == "attendance_read_staleness_seconds"
    assert slo.threshold == 2.5
    prom = tmp_path / "q.prom"
    prom.write_text(
        "attendance_read_staleness_seconds 1.5\n"
        "attendance_query_false_negatives_total 0\n"
        "attendance_query_measured_fpr 0.004\n"
        'attendance_stage_latency_seconds_bucket{stage="query",'
        'le="0.001024"} 100\n'
        'attendance_stage_latency_seconds_bucket{stage="query",'
        'le="+Inf"} 100\n'
        'attendance_stage_latency_seconds_sum{stage="query"} 0.1\n'
        'attendance_stage_latency_seconds_count{stage="query"} 100\n')
    text, ok = doctor_report([str(prom)], query_p99_ceiling=10.0,
                             staleness_ceiling=2.0)
    assert ok
    assert "query p99" in text and "read epoch staleness" in text
    assert "query-path false negatives" in text
    text, ok = doctor_report([str(prom)], staleness_ceiling=1.0)
    assert not ok  # 1.5s of staleness breaches a 1.0s ceiling
