"""Memory broker semantics: shared subscription, ack/nack, takeover."""

import threading

import pytest

from attendance_tpu.transport.memory_broker import (
    MemoryBroker, MemoryClient, ReceiveTimeout)


def make_client():
    return MemoryClient(MemoryBroker())


def test_publish_receive_ack():
    client = make_client()
    producer = client.create_producer("t")
    consumer = client.subscribe("t", "sub")
    producer.send(b"a")
    producer.send(b"b")
    m1 = consumer.receive(timeout_millis=100)
    m2 = consumer.receive(timeout_millis=100)
    assert (m1.data(), m2.data()) == (b"a", b"b")
    consumer.acknowledge(m1)
    consumer.acknowledge(m2)
    assert consumer.backlog() == 0
    with pytest.raises(ReceiveTimeout):
        consumer.receive(timeout_millis=10)


def test_nack_redelivers():
    client = make_client()
    producer = client.create_producer("t")
    consumer = client.subscribe("t", "sub")
    producer.send(b"x")
    m = consumer.receive(timeout_millis=100)
    consumer.negative_acknowledge(m)
    m2 = consumer.receive(timeout_millis=100)
    assert m2.data() == b"x"
    assert m2.redelivery_count == 1
    consumer.acknowledge(m2)
    assert consumer.backlog() == 0


def test_shared_subscription_competing_consumers():
    """Two consumers on one subscription split the stream disjointly
    (Pulsar Shared semantics, reference attendance_processor.py:30-34)."""
    client = make_client()
    producer = client.create_producer("t")
    c1 = client.subscribe("t", "sub")
    c2 = client.subscribe("t", "sub")
    for i in range(10):
        producer.send(bytes([i]))
    seen = []
    for c in (c1, c2) * 5:
        m = c.receive(timeout_millis=100)
        seen.append(m.data()[0])
        c.acknowledge(m)
    assert sorted(seen) == list(range(10))


def test_new_subscription_sees_retained_messages():
    """The generator may finish before the processor subscribes."""
    client = make_client()
    producer = client.create_producer("t")
    producer.send(b"early")
    consumer = client.subscribe("t", "late-sub")
    assert consumer.receive(timeout_millis=100).data() == b"early"


def test_consumer_close_requeues_inflight():
    """Crash takeover: unacked messages return to the shared queue."""
    client = make_client()
    producer = client.create_producer("t")
    c1 = client.subscribe("t", "sub")
    producer.send(b"m")
    c1.receive(timeout_millis=100)  # delivered, never acked
    c1.close()
    c2 = client.subscribe("t", "sub")
    m = c2.receive(timeout_millis=100)
    assert m.data() == b"m"
    assert m.redelivery_count == 1


def test_close_requeues_only_own_inflight():
    """Closing one competing consumer must not steal/redeliver messages
    delivered to a still-live consumer (Pulsar crash-takeover scope)."""
    client = make_client()
    producer = client.create_producer("t")
    c1 = client.subscribe("t", "sub")
    c2 = client.subscribe("t", "sub")
    producer.send(b"a")
    producer.send(b"b")
    m1 = c1.receive(timeout_millis=100)
    m2 = c2.receive(timeout_millis=100)  # in-flight on live c2
    c1.close()  # requeues only m1
    m1b = c2.receive(timeout_millis=100)
    assert m1b.data() == m1.data()
    assert m1b.redelivery_count == 1
    c2.acknowledge(m1b)
    c2.acknowledge(m2)  # original delivery still acknowledgeable
    assert c2.backlog() == 0
    with pytest.raises(ReceiveTimeout):
        c2.receive(timeout_millis=10)


def test_cross_thread_delivery():
    client = make_client()
    consumer = client.subscribe("t", "sub")
    got = []

    def consume():
        m = consumer.receive(timeout_millis=2000)
        got.append(m.data())
        consumer.acknowledge(m)

    th = threading.Thread(target=consume)
    th.start()
    client.create_producer("t").send(b"threaded")
    th.join(timeout=5)
    assert got == [b"threaded"]


def test_raw_drain_lane_bookkeeping():
    """receive_many_raw returns (id, payload, redeliveries) tuples with
    the SAME inflight bookkeeping as the Message lane: acknowledge_ids
    clears them, a reconstructed Message nacks for redelivery, and a
    consumer crash requeues raw-delivered messages for takeover."""
    from attendance_tpu.transport.memory_broker import Message

    client = make_client()
    consumer = client.subscribe("t", "sub")
    prod = client.create_producer("t")
    for i in range(6):
        prod.send(b"m%d" % i)

    batch = consumer.receive_many_raw(4, timeout_millis=200)
    assert [t[1] for t in batch] == [b"m0", b"m1", b"m2", b"m3"]
    assert all(t[2] == 0 for t in batch)  # first delivery

    # Ack two by id; nack one via a reconstructed Message; leave one
    # in flight and crash.
    consumer.acknowledge_ids([batch[0][0], batch[1][0]])
    consumer.negative_acknowledge(Message(batch[2][1], batch[2][0],
                                          batch[2][2]))
    redelivered = consumer.receive_many_raw(10, timeout_millis=200)
    # m4, m5 still pending plus the nacked m2 with a bumped count.
    got = {t[1]: t[2] for t in redelivered}
    assert got[b"m2"] == 1 and got[b"m4"] == 0 and got[b"m5"] == 0

    consumer.close()  # m3 + everything unacked requeues for takeover
    c2 = client.subscribe("t", "sub")
    taken = c2.receive_many_raw(10, timeout_millis=500)
    assert {t[1] for t in taken} == {b"m2", b"m3", b"m4", b"m5"}
    assert all(t[2] >= 1 for t in taken)  # all are redeliveries now
    c2.acknowledge_ids([t[0] for t in taken])
    assert c2.backlog() == 0
