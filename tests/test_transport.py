"""Memory broker semantics: shared subscription, ack/nack, takeover."""

import threading

import pytest

from attendance_tpu.transport.memory_broker import (
    MemoryBroker, MemoryClient, ReceiveTimeout)


def make_client():
    return MemoryClient(MemoryBroker())


def test_publish_receive_ack():
    client = make_client()
    producer = client.create_producer("t")
    consumer = client.subscribe("t", "sub")
    producer.send(b"a")
    producer.send(b"b")
    m1 = consumer.receive(timeout_millis=100)
    m2 = consumer.receive(timeout_millis=100)
    assert (m1.data(), m2.data()) == (b"a", b"b")
    consumer.acknowledge(m1)
    consumer.acknowledge(m2)
    assert consumer.backlog() == 0
    with pytest.raises(ReceiveTimeout):
        consumer.receive(timeout_millis=10)


def test_nack_redelivers():
    client = make_client()
    producer = client.create_producer("t")
    consumer = client.subscribe("t", "sub")
    producer.send(b"x")
    m = consumer.receive(timeout_millis=100)
    consumer.negative_acknowledge(m)
    m2 = consumer.receive(timeout_millis=100)
    assert m2.data() == b"x"
    assert m2.redelivery_count == 1
    consumer.acknowledge(m2)
    assert consumer.backlog() == 0


def test_shared_subscription_competing_consumers():
    """Two consumers on one subscription split the stream disjointly
    (Pulsar Shared semantics, reference attendance_processor.py:30-34)."""
    client = make_client()
    producer = client.create_producer("t")
    c1 = client.subscribe("t", "sub")
    c2 = client.subscribe("t", "sub")
    for i in range(10):
        producer.send(bytes([i]))
    seen = []
    for c in (c1, c2) * 5:
        m = c.receive(timeout_millis=100)
        seen.append(m.data()[0])
        c.acknowledge(m)
    assert sorted(seen) == list(range(10))


def test_new_subscription_sees_retained_messages():
    """The generator may finish before the processor subscribes."""
    client = make_client()
    producer = client.create_producer("t")
    producer.send(b"early")
    consumer = client.subscribe("t", "late-sub")
    assert consumer.receive(timeout_millis=100).data() == b"early"


def test_consumer_close_requeues_inflight():
    """Crash takeover: unacked messages return to the shared queue."""
    client = make_client()
    producer = client.create_producer("t")
    c1 = client.subscribe("t", "sub")
    producer.send(b"m")
    c1.receive(timeout_millis=100)  # delivered, never acked
    c1.close()
    c2 = client.subscribe("t", "sub")
    m = c2.receive(timeout_millis=100)
    assert m.data() == b"m"
    assert m.redelivery_count == 1


def test_close_requeues_only_own_inflight():
    """Closing one competing consumer must not steal/redeliver messages
    delivered to a still-live consumer (Pulsar crash-takeover scope)."""
    client = make_client()
    producer = client.create_producer("t")
    c1 = client.subscribe("t", "sub")
    c2 = client.subscribe("t", "sub")
    producer.send(b"a")
    producer.send(b"b")
    m1 = c1.receive(timeout_millis=100)
    m2 = c2.receive(timeout_millis=100)  # in-flight on live c2
    c1.close()  # requeues only m1
    m1b = c2.receive(timeout_millis=100)
    assert m1b.data() == m1.data()
    assert m1b.redelivery_count == 1
    c2.acknowledge(m1b)
    c2.acknowledge(m2)  # original delivery still acknowledgeable
    assert c2.backlog() == 0
    with pytest.raises(ReceiveTimeout):
        c2.receive(timeout_millis=10)


def test_cross_thread_delivery():
    client = make_client()
    consumer = client.subscribe("t", "sub")
    got = []

    def consume():
        m = consumer.receive(timeout_millis=2000)
        got.append(m.data())
        consumer.acknowledge(m)

    th = threading.Thread(target=consume)
    th.start()
    client.create_producer("t").send(b"threaded")
    th.join(timeout=5)
    assert got == [b"threaded"]


def test_raw_drain_lane_bookkeeping():
    """receive_many_raw returns (id, payload, redeliveries) tuples with
    the SAME inflight bookkeeping as the Message lane: acknowledge_ids
    clears them, a reconstructed Message nacks for redelivery, and a
    consumer crash requeues raw-delivered messages for takeover."""
    from attendance_tpu.transport.memory_broker import Message

    client = make_client()
    consumer = client.subscribe("t", "sub")
    prod = client.create_producer("t")
    for i in range(6):
        prod.send(b"m%d" % i)

    batch = consumer.receive_many_raw(4, timeout_millis=200)
    assert [t[1] for t in batch] == [b"m0", b"m1", b"m2", b"m3"]
    assert all(t[2] == 0 for t in batch)  # first delivery

    # Ack two by id; nack one via a reconstructed Message; leave one
    # in flight and crash.
    consumer.acknowledge_ids([batch[0][0], batch[1][0]])
    consumer.negative_acknowledge(Message(batch[2][1], batch[2][0],
                                          batch[2][2]))
    redelivered = consumer.receive_many_raw(10, timeout_millis=200)
    # m4, m5 still pending plus the nacked m2 with a bumped count.
    got = {t[1]: t[2] for t in redelivered}
    assert got[b"m2"] == 1 and got[b"m4"] == 0 and got[b"m5"] == 0

    consumer.close()  # m3 + everything unacked requeues for takeover
    c2 = client.subscribe("t", "sub")
    taken = c2.receive_many_raw(10, timeout_millis=500)
    assert {t[1] for t in taken} == {b"m2", b"m3", b"m4", b"m5"}
    assert all(t[2] >= 1 for t in taken)  # all are redeliveries now
    c2.acknowledge_ids([t[0] for t in taken])
    assert c2.backlog() == 0


def test_chunk_lane_semantics():
    """receive_chunk tracks the whole batch as ONE in-flight entry:
    acknowledge_chunk settles it wholesale, nack_chunk requeues every
    message with a bumped count, explode_chunk converts to per-message
    entries for the poison path, and a consumer crash requeues owned
    chunks for takeover."""
    client = make_client()
    consumer = client.subscribe("t", "sub")
    prod = client.create_producer("t")
    prod.send_many([b"m%d" % i for i in range(8)])

    cid, toks = consumer.receive_chunk(4, timeout_millis=200)
    assert [t[1] for t in toks] == [b"m0", b"m1", b"m2", b"m3"]
    assert consumer.backlog() == 8  # 4 pending + 4 chunk-inflight
    consumer.acknowledge_chunk(cid)
    assert consumer.backlog() == 4

    # nack_chunk: wholesale redelivery with bumped counts.
    cid2, toks2 = consumer.receive_chunk(2, timeout_millis=200)
    consumer.nack_chunk(cid2)
    cid3, toks3 = consumer.receive_chunk(10, timeout_millis=200)
    got = {t[1]: t[2] for t in toks3}
    assert got[b"m6"] == 0 and got[b"m7"] == 0
    assert got[b"m4"] == 1 and got[b"m5"] == 1  # requeued after m6/m7

    # explode: per-message ack/nack applies to the chunk's messages.
    consumer.explode_chunk(cid3)
    consumer.acknowledge_ids([t[0] for t in toks3 if t[1] != b"m4"])
    from attendance_tpu.transport.memory_broker import Message
    m4 = next(t for t in toks3 if t[1] == b"m4")
    consumer.negative_acknowledge(Message(m4[1], m4[0], m4[2]))

    # crash takeover: the redelivered m4 is drained into a chunk owned
    # by the dying consumer, then requeued for the survivor.
    cid4, toks4 = consumer.receive_chunk(10, timeout_millis=200)
    assert [t[1] for t in toks4] == [b"m4"]
    consumer.close()
    c2 = client.subscribe("t", "sub")
    cid5, toks5 = c2.receive_chunk(10, timeout_millis=500)
    assert [t[1] for t in toks5] == [b"m4"]
    assert toks5[0][2] >= 2  # nacked once + takeover requeue
    c2.acknowledge_chunk(cid5)
    assert c2.backlog() == 0


def test_send_many_preserves_order_and_interleaves_with_send():
    """publish_many hands one block to every subscription; ordering
    with interleaved single sends stays FIFO and ids stay consecutive
    within the batch."""
    client = make_client()
    consumer = client.subscribe("t", "sub")
    prod = client.create_producer("t")
    prod.send(b"a")
    first = prod.send_many([b"b", b"c", b"d"])
    prod.send(b"e")
    prod.send_many([b"f"])
    msgs = consumer.receive_many(10, timeout_millis=200)
    assert [m.data() for m in msgs] == [b"a", b"b", b"c", b"d", b"e", b"f"]
    mids = [m.message_id for m in msgs]
    assert mids == sorted(mids)
    assert mids[1] == first and mids[3] == first + 2
    consumer.acknowledge_many(msgs)
    assert consumer.backlog() == 0


def test_late_subscription_replays_retained_through_blocks():
    """A late subscription's retained replay and a shared bulk block
    must coexist: two subs draining the same published block see the
    same messages independently."""
    client = make_client()
    prod = client.create_producer("t")
    prod.send_many([b"x%d" % i for i in range(5)])
    c1 = client.subscribe("t", "s1")
    c2 = client.subscribe("t", "s2")
    for c in (c1, c2):
        cid, toks = c.receive_chunk(10, timeout_millis=200)
        assert [t[1] for t in toks] == [b"x%d" % i for i in range(5)]
        c.acknowledge_chunk(cid)
        assert c.backlog() == 0


def test_bulk_publish_wakes_all_blocked_consumers():
    """A bulk block must wake one waiter PER MESSAGE it can feed, not
    one per enqueue call — with two consumers blocked in untimed
    receives, one publish_many of two messages must unblock both
    (lost-wakeup regression on the block-structured queue)."""
    client = make_client()
    c1 = client.subscribe("t", "sub")
    c2 = client.subscribe("t", "sub")
    got = []
    lock = threading.Lock()

    def worker(c):
        m = c.receive(timeout_millis=5000)
        with lock:
            got.append(m.data())
        c.acknowledge(m)

    threads = [threading.Thread(target=worker, args=(c,))
               for c in (c1, c2)]
    for t in threads:
        t.start()
    # Wait until BOTH are parked in cond.wait before publishing.
    sub = client._broker.topic("t").subscription("sub")
    deadline = 50
    import time as _t
    for _ in range(deadline * 10):
        with sub.cond:
            if sub._waiting == 2:
                break
        _t.sleep(0.01)
    client.create_producer("t").send_many([b"a", b"b"])
    for t in threads:
        t.join(timeout=5)
    assert not any(t.is_alive() for t in threads), \
        "a consumer slept through a bulk publish (lost wakeup)"
    assert sorted(got) == [b"a", b"b"]
