"""Memory broker semantics: shared subscription, ack/nack, takeover."""

import threading

import pytest

from attendance_tpu.transport.memory_broker import (
    MemoryBroker, MemoryClient, ReceiveTimeout)


def make_client():
    return MemoryClient(MemoryBroker())


def test_publish_receive_ack():
    client = make_client()
    producer = client.create_producer("t")
    consumer = client.subscribe("t", "sub")
    producer.send(b"a")
    producer.send(b"b")
    m1 = consumer.receive(timeout_millis=100)
    m2 = consumer.receive(timeout_millis=100)
    assert (m1.data(), m2.data()) == (b"a", b"b")
    consumer.acknowledge(m1)
    consumer.acknowledge(m2)
    assert consumer.backlog() == 0
    with pytest.raises(ReceiveTimeout):
        consumer.receive(timeout_millis=10)


def test_nack_redelivers():
    client = make_client()
    producer = client.create_producer("t")
    consumer = client.subscribe("t", "sub")
    producer.send(b"x")
    m = consumer.receive(timeout_millis=100)
    consumer.negative_acknowledge(m)
    m2 = consumer.receive(timeout_millis=100)
    assert m2.data() == b"x"
    assert m2.redelivery_count == 1
    consumer.acknowledge(m2)
    assert consumer.backlog() == 0


def test_shared_subscription_competing_consumers():
    """Two consumers on one subscription split the stream disjointly
    (Pulsar Shared semantics, reference attendance_processor.py:30-34)."""
    client = make_client()
    producer = client.create_producer("t")
    c1 = client.subscribe("t", "sub")
    c2 = client.subscribe("t", "sub")
    for i in range(10):
        producer.send(bytes([i]))
    seen = []
    for c in (c1, c2) * 5:
        m = c.receive(timeout_millis=100)
        seen.append(m.data()[0])
        c.acknowledge(m)
    assert sorted(seen) == list(range(10))


def test_new_subscription_sees_retained_messages():
    """The generator may finish before the processor subscribes."""
    client = make_client()
    producer = client.create_producer("t")
    producer.send(b"early")
    consumer = client.subscribe("t", "late-sub")
    assert consumer.receive(timeout_millis=100).data() == b"early"


def test_consumer_close_requeues_inflight():
    """Crash takeover: unacked messages return to the shared queue."""
    client = make_client()
    producer = client.create_producer("t")
    c1 = client.subscribe("t", "sub")
    producer.send(b"m")
    c1.receive(timeout_millis=100)  # delivered, never acked
    c1.close()
    c2 = client.subscribe("t", "sub")
    m = c2.receive(timeout_millis=100)
    assert m.data() == b"m"
    assert m.redelivery_count == 1


def test_close_requeues_only_own_inflight():
    """Closing one competing consumer must not steal/redeliver messages
    delivered to a still-live consumer (Pulsar crash-takeover scope)."""
    client = make_client()
    producer = client.create_producer("t")
    c1 = client.subscribe("t", "sub")
    c2 = client.subscribe("t", "sub")
    producer.send(b"a")
    producer.send(b"b")
    m1 = c1.receive(timeout_millis=100)
    m2 = c2.receive(timeout_millis=100)  # in-flight on live c2
    c1.close()  # requeues only m1
    m1b = c2.receive(timeout_millis=100)
    assert m1b.data() == m1.data()
    assert m1b.redelivery_count == 1
    c2.acknowledge(m1b)
    c2.acknowledge(m2)  # original delivery still acknowledgeable
    assert c2.backlog() == 0
    with pytest.raises(ReceiveTimeout):
        c2.receive(timeout_millis=10)


def test_cross_thread_delivery():
    client = make_client()
    consumer = client.subscribe("t", "sub")
    got = []

    def consume():
        m = consumer.receive(timeout_millis=2000)
        got.append(m.data())
        consumer.acknowledge(m)

    th = threading.Thread(target=consume)
    th.start()
    client.create_producer("t").send(b"threaded")
    th.join(timeout=5)
    assert got == [b"threaded"]
