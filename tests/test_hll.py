"""HyperLogLog property tests: accuracy across cardinalities, banks, merge.

Accuracy contract: <=2% relative error vs true distinct counts (the
BASELINE.md target; Redis dense HLL at p=14 has ~0.81% standard error).
"""

import numpy as np
import pytest

from attendance_tpu.models.hll import (
    HyperLogLog, estimate_from_histogram, hll_add, hll_histogram, hll_init)


@pytest.mark.parametrize("n", [10, 100, 5_000, 100_000, 1_000_000])
def test_relative_error_across_cardinalities(n):
    hll = HyperLogLog(initial_banks=1)
    keys = np.arange(1, n + 1, dtype=np.uint32)
    for start in range(0, n, 1 << 20):
        hll.add_by_name("lec", keys[start:start + (1 << 20)])
    est = hll.count("lec")
    rel = abs(est - n) / n
    # 2% budget; tiny cardinalities are exact via linear counting.
    tol = 0.005 if n <= 5_000 else 0.02
    assert rel <= tol, (n, est, rel)


def test_duplicates_do_not_inflate():
    hll = HyperLogLog(initial_banks=1)
    keys = np.tile(np.arange(1, 1001, dtype=np.uint32), 50)
    hll.add_by_name("lec", keys)
    est = hll.count("lec")
    assert abs(est - 1000) / 1000 <= 0.03, est


def test_banks_are_isolated_and_grow():
    hll = HyperLogLog(initial_banks=2)
    for i in range(10):  # forces two doublings
        ids = np.arange(i * 100_000, i * 100_000 + 500, dtype=np.uint32)
        hll.add_by_name(f"lec{i}", ids)
    for i in range(10):
        est = hll.count(f"lec{i}")
        assert abs(est - 500) / 500 <= 0.05, (i, est)
    assert hll.count("unknown") == 0


def test_masked_add_drops_lanes():
    hll = HyperLogLog(initial_banks=1)
    keys = np.arange(1, 2001, dtype=np.uint32)
    mask = keys <= 1000
    idx = np.zeros_like(keys, dtype=np.int32)
    hll.add(idx, keys, mask)
    est = hll.count_union(["?"])  # unknown key
    assert est == 0
    hll._bank_of["lec"] = 0
    est = hll.count("lec")
    assert abs(est - 1000) / 1000 <= 0.03, est


def test_merge_equals_union():
    a = hll_init(1)
    b = hll_init(1)
    ka = np.arange(0, 40_000, dtype=np.uint32)
    kb = np.arange(20_000, 60_000, dtype=np.uint32)
    zeros_a = np.zeros(len(ka), np.int32)
    zeros_b = np.zeros(len(kb), np.int32)
    a = hll_add(a, zeros_a, ka)
    b = hll_add(b, zeros_b, kb)
    merged = np.maximum(np.asarray(a), np.asarray(b))
    hist = np.asarray(hll_histogram(merged))[0]
    est = estimate_from_histogram(hist)
    assert abs(est - 60_000) / 60_000 <= 0.02, est


def test_empty_bank_estimates_zero():
    hist = np.asarray(hll_histogram(hll_init(1)))[0]
    assert estimate_from_histogram(hist) == 0.0


def test_histogram_compare_matches_bincount():
    """The compare-reduce histogram (the wide-bank path best_histogram
    takes past 128 banks, where the per-bank formulations hit
    pathological compile times) must agree exactly with the vmapped
    bincount on populated registers."""
    from attendance_tpu.models.hll import (
        best_histogram, hll_histogram_compare)

    rng = np.random.default_rng(3)
    regs = hll_add(
        hll_init(6),
        np.asarray(rng.integers(0, 6, 50_000), np.int32),
        np.asarray(rng.integers(0, 1 << 32, 50_000, dtype=np.uint64
                                ).astype(np.uint32)))
    np.testing.assert_array_equal(np.asarray(hll_histogram(regs)),
                                  np.asarray(hll_histogram_compare(regs)))
    wide = np.asarray(best_histogram(hll_init(256)))
    assert wide.shape == (256, 52)
    assert (wide[:, 0] == 1 << 14).all()
    # Routing (device backends are outside the hermetic CPU suite, so
    # the decision function is pinned directly): wide register arrays
    # must avoid the formulations whose device compile never finishes.
    from attendance_tpu.models.hll import _histogram_route

    assert _histogram_route(1024, "tpu") == "compare"
    assert _histogram_route(64, "tpu") == "pallas"
    assert _histogram_route(1024, "cpu") == "bincount"
    assert _histogram_route(64, "cpu") == "bincount"
