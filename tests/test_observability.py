"""Profiling + FPR observability tests (SURVEY.md §5 obligation).

Covers: the occupancy-based Bloom FPR estimator on the store facade and
the fused pipeline, its appearance in the per-run metrics line, and the
flag-gated jax.profiler trace artifact.
"""

import logging

import numpy as np
import pytest

from attendance_tpu.config import Config
from attendance_tpu.pipeline.processor import ProcessorMetrics
from attendance_tpu.sketch.memory_store import MemorySketchStore
from attendance_tpu.sketch.tpu_store import TpuSketchStore


@pytest.mark.parametrize("store_cls", [TpuSketchStore, MemorySketchStore])
def test_estimated_fpr_tracks_fill(store_cls):
    store = store_cls(Config())
    assert store.estimated_fpr("bf") is None  # absent key
    store.execute_command("BF.RESERVE", "bf", 0.01, 10_000)
    assert store.estimated_fpr("bf") == 0.0  # empty filter
    rng = np.random.default_rng(0)
    keys = rng.choice(1 << 30, size=10_000, replace=False).astype(np.uint32)
    store.bf_add_many("bf", keys[:1_000])
    light = store.estimated_fpr("bf")
    store.bf_add_many("bf", keys[1_000:])
    full = store.estimated_fpr("bf")
    # Estimate grows with occupancy and lands near the configured 1%
    # at declared capacity.
    assert 0.0 < light < full
    assert 0.002 < full < 0.02


def test_estimated_fpr_spans_scalable_chain():
    store = MemorySketchStore(Config())
    store.execute_command("BF.RESERVE", "bf", 0.01, 500)
    keys = np.arange(2_000, dtype=np.uint32) + 7
    store.bf_add_many("bf", keys)  # forces chained sub-filters
    assert len(store._blooms["bf"].filters) > 1
    est = store.estimated_fpr("bf")
    assert 0.0 < est < 0.04  # chain budget is <= 2 * base error


def test_fused_pipeline_estimated_fpr_and_metrics_line(tmp_path):
    from attendance_tpu.pipeline.fast_path import FusedPipeline
    from attendance_tpu.pipeline.loadgen import generate_frames
    from attendance_tpu.transport.memory_broker import (
        MemoryBroker, MemoryClient)

    config = Config(bloom_filter_capacity=5_000)
    client = MemoryClient(MemoryBroker())
    pipe = FusedPipeline(config, client=client, num_banks=8)
    assert pipe.estimated_fpr() == 0.0
    roster, frames = generate_frames(4_096, 2_048, roster_size=5_000,
                                     num_lectures=4)
    pipe.preload(roster)
    est = pipe.estimated_fpr()
    assert 0.001 < est < 0.02
    producer = client.create_producer(config.pulsar_topic)
    for f in frames:
        producer.send(f)
    pipe.run(idle_timeout_s=0.2)
    assert pipe.metrics.events == 4_096
    line = pipe.metrics.summary(pipe.estimated_fpr())
    assert "est. bloom FPR" in line and "%" in line
    assert "4096 events" in line


def test_metrics_summary_handles_missing_fpr():
    m = ProcessorMetrics()
    m.events, m.batches, m.wall_seconds = 10, 1, 1.0
    assert "est. bloom FPR n/a" in m.summary(None)


def test_zero_wall_clock_reports_null_rate_not_zero():
    """wall_seconds == 0 means "no wall clock was measured", not "dead
    pipeline": to_dict must emit null and summary must print n/a so
    downstream consumers cannot mistake an instant run for a stall."""
    m = ProcessorMetrics()
    m.events, m.batches = 10, 1
    assert m.wall_seconds == 0.0
    assert m.to_dict()["events_per_second"] is None
    assert "n/a ev/s" in m.summary(None)
    # A measured clock restores the numeric rate in both surfaces.
    m.wall_seconds = 2.0
    assert m.to_dict()["events_per_second"] == 5.0
    assert "5 ev/s" in m.summary(None)


def test_profile_flag_writes_trace_artifact(tmp_path):
    from attendance_tpu.pipeline.processor import AttendanceProcessor
    from attendance_tpu.pipeline.generator import generate_student_data

    profile_dir = tmp_path / "prof"
    config = Config(sketch_backend="memory", profile_dir=str(profile_dir),
                    batch_timeout_s=0.01)
    processor = AttendanceProcessor(config)
    processor.setup_bloom_filter()
    producer = processor.client.create_producer(config.pulsar_topic)
    report = generate_student_data(
        producer=producer, sketch_store=processor.sketch,
        num_students=20, num_invalid=2, seed=0, keep_events=False)
    processor.process_attendance(max_events=report.message_count,
                                 idle_timeout_s=0.3)
    processor.cleanup()
    # jax.profiler.trace writes a plugins/profile/<run>/ tree with at
    # least one .xplane.pb (or trace.json.gz) artifact.
    artifacts = list(profile_dir.rglob("*"))
    assert any(p.is_file() for p in artifacts), (
        f"no profile artifact under {profile_dir}")


def test_processor_metrics_line_logged(caplog):
    from attendance_tpu.pipeline.processor import AttendanceProcessor
    from attendance_tpu.pipeline.generator import generate_student_data

    config = Config(sketch_backend="memory", batch_timeout_s=0.01)
    processor = AttendanceProcessor(config)
    processor.setup_bloom_filter()
    producer = processor.client.create_producer(config.pulsar_topic)
    report = generate_student_data(
        producer=producer, sketch_store=processor.sketch,
        num_students=20, num_invalid=2, seed=0, keep_events=False)
    with caplog.at_level(logging.INFO,
                         logger="attendance_tpu.pipeline.processor"):
        processor.process_attendance(max_events=report.message_count,
                                     idle_timeout_s=0.3)
    processor.cleanup()
    metrics_lines = [r.getMessage() for r in caplog.records
                     if "est. bloom FPR" in r.getMessage()]
    assert metrics_lines


def test_device_validity_counters_carry_past_32_bits():
    """The (lo, hi) two-lane counters must carry exactly when lo wraps —
    the 64-bit contract TPUs can't express with a native int64."""
    import jax.numpy as jnp

    from attendance_tpu.models.fused import _bump_counts, decode_counts

    near = np.uint32(0xFFFFFFFF - 5)
    counts = jnp.asarray(np.array([[near, 0], [near, 3]], np.uint32))
    counts = _bump_counts(counts, jnp.uint32(10), jnp.uint32(2))
    v, i = decode_counts(counts)
    assert v == int(near) + 10  # crossed 2^32: hi lane carried
    assert i == (3 << 32) + int(near) + 2


def test_validity_counts_survive_snapshot_restore(tmp_path):
    from attendance_tpu.pipeline.fast_path import FusedPipeline
    from attendance_tpu.pipeline.loadgen import generate_frames
    from attendance_tpu.transport.memory_broker import (
        MemoryBroker, MemoryClient)

    config = Config(bloom_filter_capacity=5_000,
                    snapshot_dir=str(tmp_path / "snap"))
    a = FusedPipeline(config, client=MemoryClient(MemoryBroker()),
                      num_banks=8)
    roster, frames = generate_frames(4_096, 2_048, roster_size=5_000,
                                     num_lectures=4)
    a.preload(roster)
    producer = a.client.create_producer(config.pulsar_topic)
    for f in frames:
        producer.send(f)
    a.run(idle_timeout_s=0.2)
    before = a.validity_counts()
    assert sum(before) == 4_096
    a.cleanup()

    b = FusedPipeline(Config(bloom_filter_capacity=5_000,
                             snapshot_dir=str(tmp_path / "snap")),
                      client=MemoryClient(MemoryBroker()), num_banks=8)
    assert b.validity_counts() == before


def test_metrics_line_marks_blocked_layout_fpr_as_lower_bound():
    """The blocked layout's occupancy FPR understates the true rate
    (VERDICT r02 weak #6): its metrics line must print '>=' so the
    number cannot be read as the flat layout's budget-accurate
    estimate."""
    from attendance_tpu.pipeline.processor import ProcessorMetrics

    m = ProcessorMetrics()
    m.events, m.batches, m.wall_seconds = 10, 1, 1.0
    plain = m.summary(0.005)
    bound = m.summary(0.005, fpr_is_lower_bound=True)
    assert "est. bloom FPR 0.5000%" in plain
    assert "est. bloom FPR >= 0.5000%" in bound


def test_metrics_json_sink_appends_one_line_per_run(tmp_path):
    """config.metrics_json: both processors append ONE machine-readable
    JSON line per run — the structured-logging surface the reference's
    README narrates without implementing (SURVEY §5)."""
    import json

    from attendance_tpu.config import Config
    from attendance_tpu.pipeline.fast_path import FusedPipeline
    from attendance_tpu.pipeline.loadgen import generate_frames
    from attendance_tpu.transport.memory_broker import (
        MemoryBroker, MemoryClient)

    path = tmp_path / "metrics.jsonl"
    config = Config(transport_backend="memory",
                    bloom_filter_capacity=10_000,
                    metrics_json=str(path))
    client = MemoryClient(MemoryBroker())
    pipe = FusedPipeline(config, client=client, num_banks=8)
    roster, frames = generate_frames(4096, 1024, roster_size=4_000,
                                     num_lectures=4, seed=9)
    pipe.preload(roster)
    prod = client.create_producer(config.pulsar_topic)
    for f in frames:
        prod.send(f)
    pipe.run(max_events=4096, idle_timeout_s=0.3)
    pipe.run(max_events=0, idle_timeout_s=0.1)  # second run, second line

    lines = [json.loads(x) for x in path.read_text().splitlines()]
    assert len(lines) == 2
    first = lines[0]
    assert first["events"] == 4096
    assert first["events_per_second"] > 0
    assert first["wire_dwell"]  # which wire carried the frames
    assert first["fpr_is_lower_bound"] is True
    assert first["estimated_fpr"] is None  # deferred on the fused path
