"""End-to-end fused pipeline on a multi-device mesh (VERDICT r1 item 3).

Runs hermetically on the 8-virtual-CPU-device mesh from conftest:
broker -> FusedPipeline(sharded ShardedSketchEngine) -> columnar store
-> analyzer, asserted against the loadgen ground-truth oracle — the
competing-consumer scale-out the reference delegates to Pulsar Shared
subscriptions (reference attendance_processor.py:30-34), plus sketch
capacity sharding no single Redis node provides.
"""

import jax
import numpy as np
import pytest

from attendance_tpu.config import Config
from attendance_tpu.pipeline.analyzer import AttendanceAnalyzer
from attendance_tpu.pipeline.fast_path import FusedPipeline
from attendance_tpu.pipeline.loadgen import generate_frames
from attendance_tpu.transport.memory_broker import MemoryBroker, MemoryClient


@pytest.mark.parametrize("sp,dp", [(4, 2), (2, 2), (8, 1)])
def test_sharded_pipeline_end_to_end(sp, dp):
    config = Config(bloom_filter_capacity=50_000,
                    transport_backend="memory",
                    num_shards=sp, num_replicas=dp)
    client = MemoryClient(MemoryBroker())
    pipe = FusedPipeline(config, client=client, num_banks=8)
    assert pipe.sharded
    assert pipe.engine.sp == sp and pipe.engine.dp == dp

    num_events, batch = 20_000, 4_096
    roster, frames = generate_frames(num_events, batch,
                                     roster_size=10_000, num_lectures=8,
                                     invalid_fraction=0.2, seed=13)
    pipe.preload(roster)
    producer = client.create_producer(config.pulsar_topic)
    for f in frames:
        producer.send(f)
    pipe.run(max_events=num_events, idle_timeout_s=0.5)

    assert pipe.metrics.events == num_events
    assert pipe.consumer.backlog() == 0

    df = pipe.store.to_dataframe(deduplicate=False)
    in_roster = np.isin(df.student_id.to_numpy(np.uint32), roster)
    stored_valid = df.is_valid.to_numpy(bool)
    assert stored_valid[in_roster].all()  # no false negatives, ever
    fp = stored_valid[~in_roster].mean() if (~in_roster).any() else 0.0
    assert fp <= 0.02, fp

    # HLL counts vs exact uniques per lecture (valid events only).
    vdf = df[stored_valid]
    for day, group in vdf.groupby("lecture_day"):
        exact = group.student_id.nunique()
        est = pipe.count(int(day))
        assert est == pytest.approx(exact, rel=0.05, abs=3)

    # Analyzer consumes the sharded run's store unchanged.
    insights = AttendanceAnalyzer(pipe.store).generate_insights()
    assert [i["title"] for i in insights][0] == "Habitual Latecomers"


def test_sharded_matches_single_chip_answers():
    """The sharded pipeline computes the exact same validity bits as the
    single-chip fused path on the same stream (mesh shape must never
    change answers — same hash positions, same filter)."""
    num_events, batch = 8_192, 2_048
    roster, frames = generate_frames(num_events, batch, roster_size=5_000,
                                     num_lectures=4, seed=17)
    frames = list(frames)

    results = []
    for sp, dp in ((1, 1), (4, 2)):
        config = Config(bloom_filter_capacity=20_000,
                        transport_backend="memory",
                        num_shards=sp, num_replicas=dp)
        client = MemoryClient(MemoryBroker())
        pipe = FusedPipeline(config, client=client, num_banks=8)
        pipe.preload(roster)
        producer = client.create_producer(config.pulsar_topic)
        for f in frames:
            producer.send(f)
        pipe.run(max_events=num_events, idle_timeout_s=0.5)
        df = pipe.store.to_dataframe(deduplicate=False)
        results.append(df.sort_values(
            ["micros", "student_id"]).is_valid.to_numpy(bool))
    np.testing.assert_array_equal(results[0], results[1])


def test_ten_million_roster_sharded():
    """BASELINE.md bench config #4: a 10M-student roster sharded over the
    mesh — no false negatives on a roster sample, FPR within budget on a
    disjoint sample, and per-shard HBM an 1/sp slice of the packed
    (1-bit-per-bit) filter."""
    from attendance_tpu.parallel.sharded import (
        ShardedSketchEngine, make_mesh)

    capacity = 10_000_000
    mesh = make_mesh(num_shards=4, num_replicas=2)
    engine = ShardedSketchEngine(mesh, capacity=capacity, error_rate=0.01,
                                 num_banks=4, layout="blocked")

    # Packed storage: total bytes = m_alloc bits / 8, sliced 1/sp per
    # device — ~14MB total for 10M keys, not the ~112MB of byte-per-bit.
    assert engine.bits.dtype == np.uint32
    total_bytes = engine.bits.nbytes
    assert total_bytes == engine.m_alloc // 8
    assert total_bytes < 20 * 1024 * 1024
    shard_bytes = {s.data.nbytes for s in engine.bits.addressable_shards}
    assert shard_bytes == {total_bytes // engine.sp}

    # Preload 10M keys in loadgen-sized chunks (the id universe is dense
    # here so membership math stays simple at this scale).
    rng = np.random.default_rng(23)
    roster_lo, roster_hi = 1 << 20, (1 << 20) + capacity
    chunk = 1 << 20
    for start in range(roster_lo, roster_hi, chunk):
        engine.preload(np.arange(start, min(start + chunk, roster_hi),
                                 dtype=np.uint32))

    members = rng.integers(roster_lo, roster_hi, 100_000).astype(np.uint32)
    assert engine.contains(members).all(), "false negatives at 10M scale"

    outsiders = rng.integers(1 << 28, 1 << 29, 100_000).astype(np.uint32)
    fpr = engine.contains(outsiders).mean()
    assert fpr <= 0.013, fpr

    # Device-side fill estimate agrees with the host popcount over the
    # full filter (the one-scalar-D2H replacement for shipping ~14MB).
    from attendance_tpu.models.bloom import bloom_packed_fill_fraction
    words, _ = engine.get_state()
    host_fill = float(bloom_packed_fill_fraction(jax.numpy.asarray(words)))
    assert engine.fill_fraction() == pytest.approx(host_fill, rel=1e-5)

    # count_all sanity at 10M roster scale: count a batch of events
    # into two banks and read every estimate in one device pass.
    n = engine.padded_size(8_192)
    keys = rng.integers(roster_lo, roster_hi, n).astype(np.uint32)
    banks = (keys & 1).astype(np.int32)
    engine.step(keys, banks)
    ests = engine.count_all()
    assert len(ests) == 4
    for b in (0, 1):
        exact = len(np.unique(keys[banks == b]))
        assert ests[b] == pytest.approx(exact, rel=0.05, abs=3)
    assert ests[2] == ests[3] == 0


@pytest.mark.parametrize("wire", ["seg", "delta"])
def test_sharded_narrow_wires_match_word_wire(wire):
    """VERDICT r02 #5: the seg/delta bit-packed wires over the mesh.
    Forced narrow wires must land on the identical store content and
    counts as the default word wire, carry their dwell attribution, and
    keep the device-side validity counters exact."""
    num_events, batch = 8_192, 2_048
    roster, frames = generate_frames(num_events, batch, roster_size=5_000,
                                     num_lectures=6, seed=29)
    frames = list(frames)

    results = []
    for wf in ("auto", wire):
        config = Config(bloom_filter_capacity=20_000,
                        transport_backend="memory",
                        num_shards=2, num_replicas=2, wire_format=wf)
        client = MemoryClient(MemoryBroker())
        pipe = FusedPipeline(config, client=client, num_banks=8)
        pipe.preload(roster)
        producer = client.create_producer(config.pulsar_topic)
        for f in frames:
            producer.send(f)
        pipe.run(max_events=num_events, idle_timeout_s=0.5)
        assert pipe.consumer.backlog() == 0
        df = pipe.store.to_dataframe(deduplicate=False).sort_values(
            ["micros", "student_id"])
        counts = {d: pipe.count(d) for d in pipe.lecture_days()}
        results.append((df, counts, pipe.metrics.wire_dwell,
                        pipe.validity_counts()))

    (df_w, counts_w, _, vc_w), (df_n, counts_n, dwell_n, vc_n) = results
    np.testing.assert_array_equal(df_w.is_valid.to_numpy(bool),
                                  df_n.is_valid.to_numpy(bool))
    np.testing.assert_array_equal(df_w.student_id.to_numpy(np.uint32),
                                  df_n.student_id.to_numpy(np.uint32))
    assert counts_w == counts_n
    assert set(dwell_n) == {wire}  # every frame rode the forced wire
    # Device-side counters (valid, invalid) agree across wires and sum
    # to the event count — the r02 gap was validity_counts() is None
    # when sharded.
    assert vc_w is not None and vc_n is not None
    assert vc_w == vc_n
    assert sum(vc_n) == num_events


@pytest.mark.parametrize("wire", ["seg", "delta"])
def test_sharded_narrow_native_pack_matches_numpy(wire):
    """VERDICT r03 weak #5: the mesh's per-replica seg/delta packs run
    natively (atp_pack_seg / atp_delta_scan + atp_bitpack). The native
    and numpy packs must produce byte-identical per-replica wire
    buffers and the identical store content."""
    from attendance_tpu.native import load as load_native
    if load_native() is None:
        pytest.skip("no C toolchain: native host runtime unavailable")

    num_events, batch = 8_192, 2_048
    roster, frames = generate_frames(num_events, batch, roster_size=5_000,
                                     num_lectures=6, seed=37)
    frames = list(frames)

    results = []
    for force_numpy in (False, True):
        config = Config(bloom_filter_capacity=20_000,
                        transport_backend="memory",
                        num_shards=2, num_replicas=2, wire_format=wire)
        client = MemoryClient(MemoryBroker())
        pipe = FusedPipeline(config, client=client, num_banks=8)
        if force_numpy:
            pipe._native = None
        else:
            assert pipe._native is not None
        # Capture the exact device-bound buffers for the byte compare.
        sent = []
        orig_step_narrow = pipe.engine.step_narrow

        def spy(bufs, mode, width, padded_local, _orig=orig_step_narrow):
            sent.append((bufs.copy(), mode, width, padded_local))
            return _orig(bufs, mode, width, padded_local)

        pipe.engine.step_narrow = spy
        pipe.preload(roster)
        producer = client.create_producer(config.pulsar_topic)
        for f in frames:
            producer.send(f)
        pipe.run(max_events=num_events, idle_timeout_s=0.5)
        df = pipe.store.to_dataframe(deduplicate=False).sort_values(
            ["micros", "student_id"])
        results.append((sent, df, pipe.validity_counts()))

    (sent_nat, df_nat, vc_nat), (sent_np, df_np, vc_np) = results
    assert len(sent_nat) == len(sent_np) > 0
    for (b_nat, m_nat, w_nat, p_nat), (b_np, m_np, w_np, p_np) in zip(
            sent_nat, sent_np):
        assert (m_nat, w_nat, p_nat) == (m_np, w_np, p_np)
        np.testing.assert_array_equal(b_nat, b_np)
    np.testing.assert_array_equal(df_nat.is_valid.to_numpy(bool),
                                  df_np.is_valid.to_numpy(bool))
    assert vc_nat == vc_np


def test_sharded_fill_fraction_matches_host():
    """estimated_fpr's sharded path reads ONE device scalar; it must
    equal the host popcount over get_state's words (and the pipeline
    estimate must match a single-chip pipeline with the same state)."""
    from attendance_tpu.models.bloom import bloom_packed_fill_fraction
    from attendance_tpu.parallel.sharded import (
        ShardedSketchEngine, make_mesh)

    engine = ShardedSketchEngine(make_mesh(num_shards=4, num_replicas=2),
                                 capacity=30_000, error_rate=0.01,
                                 num_banks=4, layout="blocked")
    roster = np.arange(50_000, 80_000, dtype=np.uint32)
    engine.preload(roster)
    words, _ = engine.get_state()
    host_fill = float(bloom_packed_fill_fraction(jax.numpy.asarray(words)))
    assert engine.fill_fraction() == pytest.approx(host_fill, rel=1e-5)
    assert 0.0 < engine.fill_fraction() < 1.0


def test_sharded_validity_counts_and_snapshot_counts(tmp_path):
    """Counters survive sharded snapshots and restore across mesh
    shapes (including to/from single-chip), no longer zeroed."""
    num_events, batch = 4_096, 1_024
    roster, frames = generate_frames(num_events, batch, roster_size=4_000,
                                     num_lectures=4, seed=31)
    config = Config(bloom_filter_capacity=10_000,
                    transport_backend="memory",
                    num_shards=2, num_replicas=2,
                    snapshot_dir=str(tmp_path), snapshot_every_batches=2)
    client = MemoryClient(MemoryBroker())
    pipe = FusedPipeline(config, client=client, num_banks=8)
    pipe.preload(roster)
    producer = client.create_producer(config.pulsar_topic)
    for f in frames:
        producer.send(f)
    pipe.run(max_events=num_events, idle_timeout_s=0.5)
    vc = pipe.validity_counts()
    assert vc is not None and sum(vc) == num_events
    pipe.snapshot()

    # Restore onto a DIFFERENT mesh shape: counters carry over.
    cfg2 = Config(bloom_filter_capacity=10_000,
                  transport_backend="memory",
                  num_shards=4, num_replicas=1,
                  snapshot_dir=str(tmp_path))
    pipe2 = FusedPipeline(cfg2, client=MemoryClient(MemoryBroker()),
                          num_banks=8)
    assert pipe2.validity_counts() == vc

    # And onto the single-chip engine.
    cfg3 = Config(bloom_filter_capacity=10_000,
                  transport_backend="memory",
                  snapshot_dir=str(tmp_path))
    pipe3 = FusedPipeline(cfg3, client=MemoryClient(MemoryBroker()),
                          num_banks=8)
    assert pipe3.validity_counts() == vc


def test_sharded_auto_ladder_dispatches_narrow_under_pressure():
    """The adaptive wire ladder now drives the mesh too: at ladder
    level 1/2 (sustained link backpressure) auto mode dispatches the
    seg/delta wires, with results identical to the word wire."""
    num_events, batch = 4_096, 1_024
    roster, frames = generate_frames(num_events, batch, roster_size=4_000,
                                     num_lectures=4, seed=47)
    frames = list(frames)
    config = Config(bloom_filter_capacity=10_000,
                    transport_backend="memory",
                    num_shards=2, num_replicas=2, wire_format="auto")
    client = MemoryClient(MemoryBroker())
    pipe = FusedPipeline(config, client=client, num_banks=8)
    pipe.preload(roster)
    producer = client.create_producer(config.pulsar_topic)
    for f in frames:
        producer.send(f)
    pipe._auto_level = 1  # as if the climb signal fired
    pipe._auto_pressure = 0
    pipe.run(max_events=num_events, idle_timeout_s=0.4)
    assert pipe.metrics.wire_dwell.get("seg", 0) > 0
    vc = pipe.validity_counts()
    assert sum(vc) == num_events

    # Reference answer on the default (word) wire.
    client2 = MemoryClient(MemoryBroker())
    ref = FusedPipeline(config, client=client2, num_banks=8)
    ref.preload(roster)
    prod2 = client2.create_producer(config.pulsar_topic)
    for f in frames:
        prod2.send(f)
    ref.run(max_events=num_events, idle_timeout_s=0.4)
    assert ref.validity_counts() == vc
    df_a = pipe.store.to_dataframe(deduplicate=False).sort_values(
        ["micros", "student_id"])
    df_b = ref.store.to_dataframe(deduplicate=False).sort_values(
        ["micros", "student_id"])
    np.testing.assert_array_equal(df_a.is_valid.to_numpy(bool),
                                  df_b.is_valid.to_numpy(bool))
