"""Test harness configuration.

Tests run hermetically on the CPU backend with 8 virtual devices so the
multi-chip sharding paths (hash-prefix sharded sketches, OR/max
collectives) are exercised without a TPU pod — SURVEY.md §4. This must run
before the first `import jax` in any test module, hence env mutation at
conftest import time (the axon sitecustomize pins JAX_PLATFORMS=axon, so
we override it here).
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["JAX_PLATFORM_NAME"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()
